"""Server-side realtime ingestion: consuming segments + commit lifecycle.

Parity: pinot-core/.../data/manager/realtime/ —
LLRealtimeSegmentDataManager.java:85-590 (per-partition consumer state
machine: consumeLoop indexes decoded rows into the mutable segment; on end
criteria → segmentConsumed protocol; COMMIT → build immutable segment +
split commit; CATCHUP → consume to the winner's offset; DISCARD/KEEP →
stop and wait for the committed copy) and
RealtimeTableDataManager.java:61 (consuming + completed segments of one
realtime table on one server).

The mutable segment is registered in the server's TableDataManager the
moment consumption starts, so queries see in-flight rows (host execution
path — arrival-order dictionaries don't meet the device kernels' sorted-id
preconditions); the committed immutable segment atomically replaces it via
the regular refcounted swap.
"""
from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Dict, Optional

from pinot_tpu.common import completion as proto
from pinot_tpu.common.table_name import raw_table
from pinot_tpu.ingestion import CompoundTransformer
from pinot_tpu.realtime import converter
from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
from pinot_tpu.realtime.registry import resolve_stream_config
from pinot_tpu.realtime.segment_name import LLCSegmentName
from pinot_tpu.realtime.stream import StreamConfig
from pinot_tpu.segment.loader import ImmutableSegmentLoader

log = logging.getLogger(__name__)

# consumer states (parity: LLRealtimeSegmentDataManager.State)
CONSUMING_STATE = "CONSUMING"
CATCHING_UP = "CATCHING_UP"
HOLDING = "HOLDING"
COMMITTING = "COMMITTING"
COMMITTED = "COMMITTED"
DISCARDED = "DISCARDED"
ERROR_STATE = "ERROR"

_POLL_S = 0.02


class RealtimeSegmentDataManager:
    """One consuming segment: consumer thread + mutable segment."""

    def __init__(self, llc: LLCSegmentName, table: str, schema,
                 table_config, stream_config: StreamConfig,
                 start_offset: int, completion, instance_id: str,
                 table_data_manager, work_dir: str, stats_history=None,
                 upsert=None, upsert_key_fn=None, metrics=None,
                 post_seal=None):
        """`upsert`: the table's PartitionUpsertMetadata for this stream
        partition (realtime/upsert.py) — None for non-upsert tables;
        `upsert_key_fn`: row dict → normalized primary-key tuple;
        `post_seal`: advisory hook run after a successful upsert seal
        (deadness publication for the minion compaction plane)."""
        self.llc = llc
        self.table = table
        self.stream_config = stream_config
        self.completion = completion
        self.instance_id = instance_id
        self.tdm = table_data_manager
        self.work_dir = work_dir
        self.offset = int(start_offset)
        self.state = CONSUMING_STATE
        self.stats_history = stats_history
        self.upsert = upsert
        self.upsert_key_fn = upsert_key_fn
        self.metrics = metrics
        self.post_seal = post_seal
        # how often the build-time lease extender pings the controller
        self.lease_extend_interval_s = 10.0
        # allocation sizing from the table's completed-segment history
        # (parity: RealtimeSegmentStatsHistory.java:49 feedback loop)
        hint = stats_history.estimate(table) if stats_history else None
        self.mutable = MutableSegmentImpl(schema, table_config, llc.name,
                                          stats_hint=hint)
        if self.upsert is not None:
            # reuse the restored bitmap: a restarted consumer re-applies
            # the same (key, doc) assignments onto the same bits
            self.mutable.valid_doc_ids = \
                self.upsert.register_consuming(llc.sequence)
        self.consumer = stream_config.consumer_factory \
            .create_partition_consumer(stream_config, llc.partition)
        self.decoder = stream_config.decoder
        self.transformer = CompoundTransformer(schema)
        self._catchup_target: Optional[int] = None
        self._seal_requested = False
        self._deadline = time.monotonic() + \
            stream_config.flush_threshold_time_ms / 1e3
        self._stop = threading.Event()
        # queryable from the first row (refcounted like any segment)
        self.tdm.add_segment(self.mutable)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"consumer-{llc.name}")
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        # close BEFORE join: close() wakes a long-polling fetch (the
        # stream SPI's blocking read), otherwise the join waits out the
        # fetch timeout
        try:
            self.consumer.close()
        except Exception:  # noqa: BLE001
            pass
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=10)

    # -- consume loop ------------------------------------------------------

    def request_seal(self) -> None:
        """Graceful-drain hook: force the end criteria so the consumer
        reports segmentConsumed on its next loop — with a live
        controller this seals the segment (commit election → build →
        split commit) before the server departs, so a planned restart
        leaves no unsealed rows behind to re-consume."""
        self._seal_requested = True  # tpulint: disable=concurrency -- latched one-way flag; the consumer thread reads one GIL-atomic snapshot per loop

    def _end_criteria_reached(self) -> bool:
        if self._catchup_target is not None:
            return self.offset >= self._catchup_target
        return (self._seal_requested or
                self.mutable.num_docs >=
                self.stream_config.flush_threshold_rows or
                time.monotonic() >= self._deadline)

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                if self._end_criteria_reached():
                    if not self._report_consumed():
                        return
                    continue
                self._consume_batch()
        except Exception as e:  # noqa: BLE001 — keep the server alive
            log.exception("consumer %s died", self.llc.name)
            self._enter_error(f"consumer loop died: {e}")

    def _consume_batch(self) -> None:
        try:
            batch = self.consumer.fetch_messages(
                self.offset, self._catchup_target,
                self.stream_config.fetch_timeout_ms)
        except Exception:  # noqa: BLE001 — flaky stream: back off, retry
            log.warning("fetch failed for %s at offset %d; retrying",
                        self.llc.name, self.offset, exc_info=True)
            self._stop.wait(_POLL_S)
            return
        if not batch.messages:
            self._stop.wait(_POLL_S)
            return
        rows = []
        for msg in batch.messages:
            if msg.offset < self.offset:
                continue
            row = self.decoder.decode(msg.value)
            if row is not None:
                try:
                    row = self.transformer.transform(row)
                except Exception:  # noqa: BLE001 — poison record: drop,
                    row = None     # never kill the partition consumer
            if row is None:
                log.debug("dropping undecodable/untransformable message "
                          "at offset %d", msg.offset)
                continue
            rows.append(row)
        keys = None
        if self.upsert is not None:
            # extract keys BEFORE indexing; rows whose primary key is
            # missing/unconvertible are dropped like any other poison
            # record (never kill the partition consumer, and an
            # unindexed row needs no map entry)
            keys, keyed_rows = [], []
            for row in rows:
                k = self.upsert_key_fn(row)
                if k is None:
                    log.debug("dropping row with missing/invalid "
                              "primary key in %s", self.llc.name)
                    continue
                keys.append(k)
                keyed_rows.append(row)
            rows = keyed_rows
        # batch indexing: one column-at-a-time pass over the fetch batch
        self.mutable.index_rows(rows)
        if self.upsert is not None and rows:
            # fold the batch into the partition key map AFTER indexing
            # (docs default-valid, so queries never under-count in the
            # index→apply window) and journal the deltas for recovery
            base = self.mutable.num_docs - len(rows)
            before_masked = self.upsert.masked_docs
            upserts = self.upsert.apply_batch(
                self.llc.sequence,
                [(k, base + i) for i, k in enumerate(keys)],
                int(batch.next_offset))
            if self.metrics is not None:
                from pinot_tpu.common.metrics import ServerMeter
                if upserts:
                    self.metrics.meter(ServerMeter.UPSERTED_ROWS,
                                       self.table).mark(upserts)
                masked = self.upsert.masked_docs - before_masked
                if masked:
                    self.metrics.meter(ServerMeter.MASKED_DOCS,
                                       self.table).mark(masked)
        self.offset = max(self.offset, batch.next_offset)  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot

    # -- completion protocol (server side) ---------------------------------

    #: backoff between completion-protocol retries while the controller
    #: is unreachable (failover window: the lease must expire and the
    #: standby publish its endpoint before calls can succeed again)
    COMPLETION_RETRY_S = 0.5

    def _completion_call(self, fn, *args):
        """Run a completion-protocol op, riding out controller failover:
        connection-level failures (dead lead controller, standby not yet
        serving) back off and retry — the HTTP client re-resolves the
        ACTIVE controller endpoint from the store between attempts —
        while protocol-level outcomes (HOLD/COMMIT/FAILED...) pass
        through untouched. Returns None when the consumer was stopped
        mid-retry; killing the consumer over a transient controller
        outage would strand the partition until an external repair."""
        while not self._stop.is_set():
            try:
                return fn(*args)
            except (ConnectionError, TimeoutError, OSError) as e:
                log.warning("completion call failed for %s (%s); "
                            "retrying — controller may be failing over",
                            self.llc.name, e)
                self._stop.wait(self.COMPLETION_RETRY_S)
        return None

    def _report_consumed(self) -> bool:
        """segmentConsumed → steer by response. Returns False to exit."""
        self._catchup_target = None  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot
        self.state = HOLDING  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot
        resp = self._completion_call(
            self.completion.segment_consumed,
            self.table, self.llc.name, self.instance_id, self.offset)
        if resp is None:
            return False            # stopped while the controller was away
        if resp.status == proto.HOLD:
            self._stop.wait(_POLL_S)
            return True
        if resp.status == proto.CATCHUP:
            self.state = CATCHING_UP  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot
            self._catchup_target = int(resp.offset)  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot
            return True
        if resp.status == proto.COMMIT:
            self._commit()
            return False
        if resp.status in (proto.KEEP, proto.DISCARD):
            # another replica committed; the ONLINE transition will swap in
            # the committed copy (losers always take the download path)
            self.state = DISCARDED  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot
            return False
        log.warning("unexpected completion status %s for %s", resp.status,
                    self.llc.name)
        self._enter_error(f"unexpected completion status {resp.status}")
        return False

    def _enter_error(self, reason: str) -> None:
        """Report stoppedConsuming so the controller's validation task can
        repair the partition despite this server process staying live."""
        self.state = ERROR_STATE  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot
        try:
            self.completion.stopped_consuming(
                self.table, self.llc.name, self.instance_id, reason)
        except Exception:  # noqa: BLE001 — best effort
            log.exception("stopped_consuming report failed for %s",
                          self.llc.name)

    def _commit(self) -> None:
        self.state = COMMITTING  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot
        # SegmentBuildTimeLeaseExtender parity: ping the controller for
        # the WHOLE commit (build + upload) so a slow build or a long
        # deep-store copy isn't mistaken for a dead winner
        lease_stop = threading.Event()

        def _extend_lease() -> None:
            extend = getattr(self.completion, "extend_build_time", None)
            if extend is None:
                return
            while not lease_stop.wait(self.lease_extend_interval_s):
                try:
                    extend(self.table, self.llc.name, self.instance_id)
                except Exception:  # noqa: BLE001 — advisory; commit_end
                    # is the authoritative outcome
                    log.warning("extendBuildTime failed for %s",
                                self.llc.name, exc_info=True)

        lease_thread = threading.Thread(
            target=_extend_lease, daemon=True,
            name=f"lease-{self.llc.name}")
        lease_thread.start()
        try:
            self._commit_inner()
        finally:
            lease_stop.set()
            lease_thread.join(timeout=5)

    def _commit_inner(self) -> None:
        resp = self._completion_call(
            self.completion.commit_start,
            self.table, self.llc.name, self.instance_id, self.offset)
        if resp is None:
            return                  # stopped mid-retry: nothing committed
        if resp.status != proto.COMMIT_CONTINUE:
            log.warning("commit_start rejected for %s: %s", self.llc.name,
                        resp.status)
            self._enter_error(f"commit_start rejected: {resp.status}")
            return
        out_dir = os.path.join(self.work_dir, self.llc.name)
        try:
            shutil.rmtree(out_dir, ignore_errors=True)
            converter.convert(self.mutable, out_dir, self.llc.name)
        except Exception as e:  # noqa: BLE001 — build failure (disk etc.)
            log.exception("segment build failed for %s", self.llc.name)
            self._enter_error(f"segment build failed: {e}")
            return
        # record stats NOW, before commit_end: the controller creates
        # the SUCCESSOR consuming segment synchronously inside the
        # commit_end call chain, and its allocation hint must see this
        # segment's stats (also: the CONSUMING→ONLINE swap destroys the
        # mutable before commit_end returns). Advisory data — recording
        # before a failed commit is harmless.
        if self.stats_history is not None:
            self.stats_history.add_segment_stats(
                self.table, self.mutable.collect_stats())
        # capture BEFORE commit_end: the CONSUMING→ONLINE swap destroys
        # the mutable before commit_end returns (num_docs survives as an
        # int, but take no chances on ordering)
        sealed_docs = int(self.mutable.num_docs)
        resp = self._completion_call(
            self.completion.commit_end,
            self.table, self.llc.name, self.instance_id, self.offset,
            out_dir)
        if resp is None:
            return                  # stopped mid-retry
        if resp.status != proto.COMMIT_SUCCESS:
            log.warning("commit_end failed for %s: %s", self.llc.name,
                        resp.status)
            self._enter_error(f"commit_end failed: {resp.status}")
            return
        if self.upsert is not None:
            # SEAL: durably snapshot the key map + validDocIds and
            # truncate the journal. Crash-safe at any instruction — a
            # loss here just replays the (longer) journal on restart;
            # IO failures are advisory (the fold path re-derives masks)
            try:
                self.upsert.seal(self.llc.sequence, self.offset,
                                 sealed_docs)
            except OSError:
                log.warning("upsert seal failed for %s", self.llc.name,
                            exc_info=True)
            if self.post_seal is not None:
                try:
                    # advisory: deadness publication for the compaction
                    # plane — a failure must never fail the commit
                    self.post_seal()
                except Exception:  # noqa: BLE001
                    log.warning("post-seal hook failed for %s",
                                self.llc.name, exc_info=True)
        self.state = COMMITTED  # tpulint: disable=concurrency -- consumer-thread single-writer; cross-thread readers (consuming_state) take one GIL-atomic snapshot


class RealtimeTableDataManager:
    """All consuming segments of this server, across realtime tables.

    Parity: RealtimeTableDataManager.java:61 — holds the consuming segment
    managers; completed (immutable) segments live in the regular
    TableDataManager maps alongside offline segments.
    """

    def __init__(self, server, resource_manager, completion,
                 work_dir: str, fetcher=None):
        """`fetcher`: optional (table, segment, download_path,
        expected_crc) -> local_dir callable — the participant's cached,
        CRC-verifying deep-store fetch, so committed realtime segments
        take the same download/verify/quarantine path offline segments
        do (required when downloadPath is a remote URL)."""
        self.server = server
        self.manager = resource_manager
        self.completion = completion
        self.work_dir = work_dir
        self.fetcher = fetcher
        from pinot_tpu.realtime.stats_history import \
            RealtimeSegmentStatsHistory
        self.stats_history = RealtimeSegmentStatsHistory(
            os.path.join(work_dir, "stats_history.json"))
        self._consuming: Dict[str, RealtimeSegmentDataManager] = {}
        # table → TableUpsertMetadataManager (realtime/upsert.py); built
        # lazily from the table config's upsertConfig
        self._upsert: Dict[str, Optional[object]] = {}
        # (table, segment) → last published deadness bitmap version
        self._published_deadness: Dict[tuple, int] = {}
        self._closed = False
        self._lock = threading.Lock()
        # table-wide segment deletion (TTL retention, table drop) must
        # garbage-collect the key-map entries whose winners lived in the
        # deleted segment — watch the durable record removals; replica-
        # local drops (rebalance moves) fire no record removal and keep
        # their entries (the winners still exist in the table)
        self._record_watcher = self._on_segment_record_change
        self.manager.store.watch("/SEGMENTS/", self._record_watcher)

    def _on_segment_record_change(self, path: str, record) -> None:
        if record is not None:
            return                          # only removals drive GC
        parts = path.split("/")
        if len(parts) != 4:                 # /SEGMENTS/<table>/<segment>
            return
        table, segment = parts[2], parts[3]
        with self._lock:
            um = self._upsert.get(table)
            self._published_deadness.pop((table, segment), None)
        if um is not None:
            um.gc_segment_record(segment)

    def _live_llc_seqs(self, table: str, partition: int):
        """Sequences with a live segment record for one stream
        partition — the boot-time upsert GC reconcile's ground truth."""
        out = set()
        for seg in self.manager.segment_names(table):
            try:
                llc = LLCSegmentName.parse(seg)
            except ValueError:
                continue
            if llc.partition == partition:
                out.add(llc.sequence)
        return out

    def publish_deadness(self, table: str) -> int:
        """Publish per-committed-segment deadness (invalid doc ids) to
        the property store for the minion compaction plane. Version-
        skipped: only bitmaps that changed since the last publication
        are rewritten. Advisory — IO failures are logged, never
        propagated."""
        from pinot_tpu.realtime.upsert import deadness_path
        um = self.upsert_manager(table)
        if um is None:
            return 0
        with self._lock:
            already = {name: ver for (t, name), ver in
                       self._published_deadness.items() if t == table}
        published = 0
        for name, info in sorted(um.deadness_reports(already).items()):
            key = (table, name)
            with self._lock:
                if self._published_deadness.get(key) == info["version"]:
                    continue
            try:
                self.manager.store.set(deadness_path(table, name), info)
            except Exception:  # noqa: BLE001 — advisory publication
                log.warning("deadness publish failed for %s/%s", table,
                            name, exc_info=True)
                continue
            with self._lock:
                self._published_deadness[key] = info["version"]
            published += 1
        return published

    def upsert_manager(self, table: str):
        """The table's upsert metadata manager, or None when the table
        config carries no (enabled) upsertConfig. Only REAL managers are
        cached — a transiently missing config (transition racing config
        availability, or a table re-created with upsert enabled) must
        not silently disable dedup for the table's lifetime."""
        with self._lock:
            mgr = self._upsert.get(table)
        if mgr is not None:
            return mgr
        config = self.manager.get_table_config(table)
        uc = getattr(config, "upsert_config", None) if config else None
        if uc is None or not uc.enabled:
            return None
        from pinot_tpu.realtime.upsert import TableUpsertMetadataManager
        schema = self.manager.get_schema(raw_table(table))
        if schema is None:
            raise ValueError(f"missing schema for upsert table {table}")
        mgr = TableUpsertMetadataManager(
            table, uc, schema,
            os.path.join(self.work_dir, "upsert", table),
            live_seqs_fn=lambda p, t=table: self._live_llc_seqs(t, p))
        with self._lock:
            winner = self._upsert.setdefault(table, mgr)
        if winner is mgr:
            # gauge binds only to the instance that WON the setdefault —
            # a racing loser's callable would pin the metric at 0
            metrics = getattr(self.server, "metrics", None)
            if metrics is not None:
                mgr.register_metrics(metrics)
        return winner

    def consuming_state(self, segment: str) -> Optional[str]:
        with self._lock:
            rdm = self._consuming.get(segment)
            return rdm.state if rdm else None

    def start_consuming(self, table: str, segment: str) -> None:
        """OFFLINE→CONSUMING: start the partition consumer.

        Resumes from the durable startOffset in segment metadata — the
        checkpoint/resume story (SURVEY §5.4): consumption always restarts
        from the last committed segment boundary.
        """
        meta = self.manager.segment_metadata(table, segment)
        if meta is None:
            raise ValueError(f"no metadata for {table}/{segment}")
        if meta.get("status") == "DONE" and meta.get("downloadPath"):
            # committed while this server was away (e.g. a controller
            # that crashed between commit and the ideal-state step, now
            # repaired): never re-consume committed rows — serve the
            # committed artifact; the validation task advances the
            # ideal state and successor from the durable record
            self.on_segment_online(table, segment)
            return
        config = self.manager.get_table_config(table)
        schema = self.manager.get_schema(raw_table(table))
        if config is None or schema is None:
            raise ValueError(f"missing config/schema for {table}")
        stream_config = resolve_stream_config(config)
        llc = LLCSegmentName.parse(segment)
        tdm = self.server.data_manager.table(table, create=True)
        um = self.upsert_manager(table)
        upsert_part = um.partition(llc.partition) if um is not None \
            else None
        # construct (which starts the consumer thread) under the lock so a
        # concurrent shutdown() can never miss a just-started consumer
        with self._lock:
            if self._closed or segment in self._consuming:
                return
            self._consuming[segment] = RealtimeSegmentDataManager(
                llc, table, schema, config, stream_config,
                int(meta["startOffset"]), self.completion,
                self.server.instance_id, tdm,
                os.path.join(self.work_dir, table),
                stats_history=self.stats_history,
                upsert=upsert_part,
                upsert_key_fn=um.key_of if um is not None else None,
                metrics=getattr(self.server, "metrics", None),
                post_seal=((lambda t=table: self.publish_deadness(t))
                           if um is not None else None))

    def on_segment_online(self, table: str, segment: str) -> None:
        """CONSUMING→ONLINE (or OFFLINE→ONLINE for a committed LLC
        segment): stop any local consumer and swap in the committed copy
        from the deep store."""
        with self._lock:
            rdm = self._consuming.pop(segment, None)
        if rdm is not None:
            rdm.stop()
        meta = self.manager.segment_metadata(table, segment)
        if meta is None or not meta.get("downloadPath"):
            raise ValueError(f"no committed artifact for {table}/{segment}")
        path = meta["downloadPath"]
        if self.fetcher is not None:
            path = self.fetcher(table, segment, path, meta.get("crc"))
        elif "://" not in path:
            # committed copy is CRC-verified against the durable record
            # before it replaces the consuming segment — a corrupt
            # artifact fails the transition (ERROR) instead of serving
            from pinot_tpu.segment.integrity import verify_segment
            verify_segment(path, meta.get("crc"))
        seg = ImmutableSegmentLoader.load(path)
        um = self.upsert_manager(table)
        if um is not None:
            # attach the partition's validDocIds (or FOLD the segment's
            # primary keys when no durable coverage exists — the loser-
            # download and lost-snapshot convergence path; or REMAP a
            # compacted rewrite) BEFORE the segment becomes queryable
            um.on_committed_segment(segment, seg)
            with self._lock:
                # whatever deadness we last published described the
                # pre-swap artifact — force a fresh publication at the
                # next seal regardless of version collisions
                self._published_deadness.pop((table, segment), None)
        self.server.data_manager.table(table, create=True).add_segment(seg)

    def on_segment_offline(self, table: str, segment: str) -> None:
        with self._lock:
            rdm = self._consuming.pop(segment, None)
        if rdm is not None:
            rdm.stop()
        tdm = self.server.data_manager.table(table)
        if tdm is not None:
            tdm.remove_segment(segment)

    def seal_all(self, timeout_s: float = 20.0) -> bool:
        """Graceful drain: ask every consuming segment with indexed rows
        to seal (commit through the completion protocol) and wait —
        bounded — until each reaches a terminal consumer state. Empty
        consumers are skipped (nothing to lose; the successor record
        already points at their start offset). Returns True when every
        sealable consumer reached COMMITTED/DISCARDED in time."""
        with self._lock:
            rdms = list(self._consuming.values())
        sealing = []
        for rdm in rdms:
            if rdm.mutable.num_docs > 0:
                rdm.request_seal()
                sealing.append(rdm)
        deadline = time.monotonic() + timeout_s
        ok = True
        for rdm in sealing:
            while rdm.state not in (COMMITTED, DISCARDED, ERROR_STATE):
                if time.monotonic() >= deadline:
                    log.warning("drain: %s did not seal within %.1fs "
                                "(state %s); departing unsealed — the "
                                "takeover path re-consumes from the "
                                "last committed offset", rdm.llc.name,
                                timeout_s, rdm.state)
                    ok = False
                    break
                time.sleep(0.02)
            else:
                ok = ok and rdm.state != ERROR_STATE
        return ok

    def shutdown(self) -> None:
        try:
            self.manager.store.unwatch(self._record_watcher)
        except Exception:  # noqa: BLE001 — store may already be closed
            pass
        with self._lock:
            self._closed = True
            rdms = list(self._consuming.values())
            self._consuming.clear()
            upserts = [m for m in self._upsert.values() if m is not None]
            self._upsert.clear()
        for rdm in rdms:
            rdm.stop()
        for um in upserts:
            um.close()
