"""Mutable (consuming) segment: append rows, query concurrently.

Parity: pinot-core/.../indexsegment/mutable/MutableSegmentImpl.java:64-198 —
per-column mutable dictionary (ARRIVAL order: ids must stay stable as values
arrive, so unlike immutable segments the dictionary is unsorted) + growable
fixed-width forward indexes; queries snapshot (num_docs, lanes[:n]) without
blocking the writer. Device serving: a PERIODIC SORTED SNAPSHOT freezes the
row prefix into a standard in-memory ImmutableSegment (sorted dictionaries,
remapped id lanes) so the TPU kernels serve the bulk of a consuming segment,
with only the post-freeze tail on the host executor (see device_view); on
commit RealtimeSegmentConverter re-sorts everything into a standard
immutable segment (RealtimeSegmentConverter.java:85-129).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from pinot_tpu.common.datatype import DataType
from pinot_tpu.common.schema import FieldSpec, Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.segment.metadata import ColumnMetadata, SegmentMetadata


class MutableDictionary:
    """Arrival-order dictionary: id = insertion rank (stable)."""

    is_sorted = False

    def __init__(self, data_type: DataType):
        self.data_type = data_type
        self._values: List = []
        self._index: Dict = {}  # tpulint: disable=cache-bound -- the dictionary IS the data: bounded by the segment-size seal threshold, frozen at commit
        self._np_cache: Optional[np.ndarray] = None

    @property
    def cardinality(self) -> int:
        return len(self._values)

    def __len__(self) -> int:
        return len(self._values)

    @property
    def values(self) -> np.ndarray:
        if self._np_cache is None or len(self._np_cache) != len(self._values):
            dtype = self.data_type.np_dtype if self.data_type.is_numeric \
                else object
            self._np_cache = np.array(self._values, dtype=dtype)
        return self._np_cache

    def index_of(self, value) -> int:
        v = self._coerce(value)
        return self._index.get(v, -1)

    def index_of_many(self, values) -> np.ndarray:
        return np.array([self.index_of(v) for v in values], dtype=np.int32)

    def index_of_or_add(self, value) -> int:
        v = self._coerce(value)
        i = self._index.get(v)
        if i is None:
            i = len(self._values)
            self._values.append(v)
            self._index[v] = i
        return i

    def add_many(self, values, coerced: bool = False) -> np.ndarray:
        """Batch index_of_or_add: one tight loop (no per-value method
        dispatch), int32 ids out — the consuming path's hot loop.
        `coerced=True` skips _coerce for values already normalized by
        FieldSpec.convert (idempotent with _coerce for every type)."""
        out = np.empty(len(values), np.int32)
        idx = self._index
        vals = self._values
        coerce = None if coerced else self._coerce
        for i, v in enumerate(values):
            if coerce is not None:
                v = coerce(v)
            j = idx.get(v)
            if j is None:
                j = len(vals)
                vals.append(v)
                idx[v] = j
            out[i] = j
        return out

    def get(self, dict_id: int):
        return self._values[dict_id]

    def decode(self, dict_ids: np.ndarray) -> np.ndarray:
        return self.values[dict_ids]

    def _coerce(self, value):
        if self.data_type.is_numeric:
            try:
                return int(str(value)) if \
                    self.data_type.np_dtype.kind in "iu" else float(value)
            except ValueError:
                return float(value)
        if self.data_type == DataType.BYTES:
            return value if isinstance(value, bytes) \
                else bytes.fromhex(str(value))
        return str(value)

    @property
    def min_value(self):
        return min(self._values) if self._values else None

    @property
    def max_value(self):
        return max(self._values) if self._values else None


class _GrowableArray:
    """Append-only numpy array with capacity doubling; reads of [:n] are
    stable because growth copies into a NEW buffer (readers keep slicing a
    consistent snapshot)."""

    def __init__(self, dtype, capacity: int = 4096):
        self._arr = np.zeros(capacity, dtype=dtype)
        self.n = 0

    def append(self, v) -> None:
        # direct scalar write: this is the HLC per-row ingest path, so
        # it must not pay extend()'s slice machinery per value — the
        # single-writer invariant is stated in the suppressions instead
        if self.n == len(self._arr):
            bigger = np.zeros(len(self._arr) * 2, dtype=self._arr.dtype)
            bigger[: self.n] = self._arr
            self._arr = bigger  # tpulint: disable=concurrency -- single consumer-thread writer (all call sites run under MutableSegmentImpl._lock); readers slice stable [:n] snapshots of the previous buffer
        self._arr[self.n] = v  # tpulint: disable=concurrency -- same single-writer invariant; the cell is beyond every published snapshot until n moves
        self.n += 1  # tpulint: disable=concurrency -- same single-writer invariant: n publishes AFTER the cell write, readers never observe unwritten rows

    def extend(self, arr) -> None:
        """Vectorized append of a whole batch (same reader contract:
        rows past the published n are never observed; growth copies
        into a new buffer)."""
        need = self.n + len(arr)
        if need > len(self._arr):
            cap = len(self._arr)
            while cap < need:
                cap *= 2
            bigger = np.zeros(cap, dtype=self._arr.dtype)
            bigger[: self.n] = self._arr[: self.n]
            self._arr = bigger  # tpulint: disable=concurrency -- same single-writer invariant as append(): growth publishes a fully-copied buffer
        self._arr[self.n: need] = arr  # tpulint: disable=concurrency -- same single-writer invariant; rows land beyond every published n
        self.n = need  # tpulint: disable=concurrency -- same single-writer invariant: n publishes after the batch write

    def snapshot(self, n: int) -> np.ndarray:
        return self._arr[:n]


class _GrowableMatrix:
    """Append-only [n, dim] float32 matrix with capacity doubling — the
    consuming-side vector forward block. Same reader contract as
    _GrowableArray: growth copies into a NEW buffer, rows land beyond
    every published n, so [:n] snapshots stay stable."""

    def __init__(self, dim: int, capacity: int = 4096):
        self._arr = np.zeros((capacity, dim), np.float32)
        self.n = 0

    def extend(self, rows: np.ndarray) -> None:
        need = self.n + len(rows)
        if need > len(self._arr):
            cap = len(self._arr)
            while cap < need:
                cap *= 2
            bigger = np.zeros((cap, self._arr.shape[1]), np.float32)
            bigger[: self.n] = self._arr[: self.n]
            self._arr = bigger  # tpulint: disable=concurrency -- single consumer-thread writer (same invariant as _GrowableArray): growth publishes a fully-copied buffer
        self._arr[self.n: need] = rows  # tpulint: disable=concurrency -- same single-writer invariant; rows land beyond every published n
        self.n = need  # tpulint: disable=concurrency -- same single-writer invariant: n publishes after the row writes

    def snapshot(self, n: int) -> np.ndarray:
        return self._arr[:n]


class _MutableDataSource:
    """DataSource-compatible column view over mutable storage."""

    def __init__(self, field: FieldSpec, has_dictionary: bool,
                 initial_capacity: int = 4096):
        self.field = field
        self.is_vector = field.data_type == DataType.VECTOR
        self.has_dictionary = has_dictionary and not self.is_vector
        self.dictionary = MutableDictionary(field.data_type) \
            if self.has_dictionary else None
        self.inverted_index = None
        self.bloom_filter = None
        self.sorted_ranges = None
        self._vec: Optional[_GrowableMatrix] = None
        if self.is_vector:
            self._vec = _GrowableMatrix(field.vector_dimension,
                                        capacity=initial_capacity)
            self._sv = None
            self._mv: Optional[List[List[int]]] = None
        elif field.single_value:
            dtype = np.int32 if self.has_dictionary \
                else field.data_type.np_dtype
            self._sv = _GrowableArray(dtype, capacity=initial_capacity)
            self._mv = None
        else:
            self._sv = None
            self._mv = []
        self._snapshot_n = 0
        self._mv_cache: Optional[np.ndarray] = None

    # -- write path --------------------------------------------------------
    def add(self, value) -> None:
        f = self.field
        if self.is_vector:
            self._vec.extend(f.convert(value)[None])
        elif f.single_value:
            v = f.convert(value)
            if self.has_dictionary:
                self._sv.append(self.dictionary.index_of_or_add(v))
            else:
                self._sv.append(v)
        else:
            vs = value if isinstance(value, (list, tuple)) else (
                [] if value is None else [value])
            converted = [f.convert(x) for x in vs] or [f.default_null_value]
            self._mv.append([self.dictionary.index_of_or_add(x)
                             for x in converted])

    def add_many(self, values: list) -> None:
        """Batch write path (one listcomp/array op per column instead of
        per-row python dispatch — the consume loop's 2x)."""
        f = self.field
        if self.is_vector:
            self._vec.extend(np.stack([f.convert(v) for v in values])
                             if values else
                             np.zeros((0, f.vector_dimension), np.float32))
            return
        if not f.single_value:
            for v in values:
                self.add(v)
            return
        if self.has_dictionary:
            conv = f.convert
            self._sv.extend(self.dictionary.add_many(
                [conv(v) for v in values], coerced=True))
        else:
            self._sv.extend(np.asarray(
                [f.convert(v) for v in values],
                dtype=f.data_type.np_dtype))

    # -- read path (snapshot at n docs) ------------------------------------
    def bind(self, n: int) -> "_MutableDataSource":
        self._snapshot_n = n
        return self

    @property
    def metadata(self) -> ColumnMetadata:
        card = self.dictionary.cardinality if self.has_dictionary else \
            self._snapshot_n
        return ColumnMetadata(
            name=self.field.name, data_type=self.field.data_type,
            cardinality=card,
            bits_per_element=max(1, int(np.ceil(np.log2(max(card, 2))))),
            single_value=self.field.single_value, sorted=False,
            has_dictionary=self.has_dictionary,
            min_value=self.dictionary.min_value if self.has_dictionary
            else None,
            max_value=self.dictionary.max_value if self.has_dictionary
            else None,
            total_number_of_entries=self._snapshot_n,
            vector_dimension=self.field.vector_dimension)

    @property
    def dict_ids(self) -> Optional[np.ndarray]:
        if self._sv is None or not self.has_dictionary:
            return None
        return self._sv.snapshot(self._snapshot_n)

    @property
    def raw_values(self) -> Optional[np.ndarray]:
        if self._sv is None or self.has_dictionary:
            return None
        return self._sv.snapshot(self._snapshot_n)

    @property
    def vec_values(self) -> Optional[np.ndarray]:
        if self._vec is None:
            return None
        return self._vec.snapshot(self._snapshot_n)

    @property
    def mv_dict_ids(self) -> Optional[np.ndarray]:
        if self._mv is None:
            return None
        n = self._snapshot_n
        if self._mv_cache is not None and len(self._mv_cache) == n:
            return self._mv_cache
        card = self.dictionary.cardinality
        rows = self._mv[:n]
        width = max((len(r) for r in rows), default=1)
        out = np.full((n, width), card, dtype=np.int32)
        for i, r in enumerate(rows):
            out[i, : len(r)] = r
        self._mv_cache = out
        return out

    def raw_column(self, n: int):
        """Decoded values for the segment converter."""
        if self._vec is not None:
            # 2-D float32 block: the creator's VECTOR branch takes it
            return np.array(self._vec.snapshot(n), copy=True)
        if self._mv is not None:
            return [[self.dictionary.get(i) for i in r]
                    for r in self._mv[:n]]
        arr = self._sv.snapshot(n)
        if self.has_dictionary:
            return list(self.dictionary.decode(arr))
        return list(arr)


class _SnapshotDictionary:
    """Dictionary view pinned at a cardinality: values added after the
    snapshot are invisible (index_of returns -1 for them)."""

    is_sorted = False

    def __init__(self, inner: MutableDictionary, cardinality: int):
        self._inner = inner
        self.cardinality = cardinality
        self.data_type = inner.data_type

    def __len__(self) -> int:
        return self.cardinality

    @property
    def values(self) -> np.ndarray:
        return self._inner.values[: self.cardinality]

    def index_of(self, value) -> int:
        i = self._inner.index_of(value)
        return i if i < self.cardinality else -1

    def index_of_many(self, values) -> np.ndarray:
        return np.array([self.index_of(v) for v in values], dtype=np.int32)

    def get(self, dict_id: int):
        return self._inner.get(dict_id)

    def decode(self, dict_ids: np.ndarray) -> np.ndarray:
        return self.values[dict_ids]

    @property
    def min_value(self):
        vals = self._inner._values[: self.cardinality]
        return min(vals) if vals else None

    @property
    def max_value(self):
        vals = self._inner._values[: self.cardinality]
        return max(vals) if vals else None


class _SnapshotSource:
    """Point-in-time column view: doc count AND dictionary cardinality are
    pinned at snapshot creation, so every access within one query sees the
    same rows (the writer keeps appending concurrently). `start` slices a
    TAIL window [start, n) for the hybrid frozen+tail serving mode."""

    def __init__(self, ds: _MutableDataSource, n: int, start: int = 0):
        self._ds = ds
        self._n = n
        self._start = start
        self.field = ds.field
        self.has_dictionary = ds.has_dictionary
        self.dictionary = _SnapshotDictionary(
            ds.dictionary, ds.dictionary.cardinality) \
            if ds.has_dictionary else None
        self.inverted_index = None
        self.bloom_filter = None
        self.sorted_ranges = None
        self._mv_cache: Optional[np.ndarray] = None

    @property
    def metadata(self) -> ColumnMetadata:
        card = self.dictionary.cardinality if self.has_dictionary \
            else self._n - self._start
        return ColumnMetadata(
            name=self.field.name, data_type=self.field.data_type,
            cardinality=card,
            bits_per_element=max(1, int(np.ceil(np.log2(max(card, 2))))),
            single_value=self.field.single_value, sorted=False,
            has_dictionary=self.has_dictionary,
            min_value=self.dictionary.min_value if self.has_dictionary
            else None,
            max_value=self.dictionary.max_value if self.has_dictionary
            else None,
            total_number_of_entries=self._n - self._start,
            vector_dimension=self.field.vector_dimension)

    @property
    def dict_ids(self) -> Optional[np.ndarray]:
        if self._ds._sv is None or not self.has_dictionary:
            return None
        return self._ds._sv.snapshot(self._n)[self._start:]

    @property
    def raw_values(self) -> Optional[np.ndarray]:
        if self._ds._sv is None or self.has_dictionary:
            return None
        return self._ds._sv.snapshot(self._n)[self._start:]

    @property
    def vec_values(self) -> Optional[np.ndarray]:
        if self._ds._vec is None:
            return None
        return self._ds._vec.snapshot(self._n)[self._start:]

    @property
    def mv_dict_ids(self) -> Optional[np.ndarray]:
        if self._ds._mv is None:
            return None
        if self._mv_cache is None:
            card = self.dictionary.cardinality
            rows = self._ds._mv[self._start: self._n]
            width = max((len(r) for r in rows), default=1)
            out = np.full((len(rows), width), card, dtype=np.int32)
            for i, r in enumerate(rows):
                out[i, : len(r)] = r
            self._mv_cache = out
        return self._mv_cache


class MutableSegmentView:
    """Frozen (num_docs, cardinalities) view of a consuming segment — what
    one query executes against. Parity: the reference snapshots the doc
    count once per query (MutableSegmentImpl readers index up to a captured
    numDocsIndexed); here the whole column view is pinned.

    `start` > 0 makes this a TAIL view (rows [start, num_docs)) — the
    un-snapshotted remainder served host-side next to a frozen device
    snapshot of rows [0, start)."""

    is_mutable = True

    def __init__(self, impl: "MutableSegmentImpl", start: int = 0):
        self._impl = impl
        self.segment_name = impl.segment_name if start == 0 else \
            f"{impl.segment_name}__tail"
        self.schema = impl.schema
        self.start = start
        self.num_docs = impl._num_docs - start
        self._sources: Dict[str, _SnapshotSource] = {}  # tpulint: disable=cache-bound -- bounded by the schema's column count; dies with the snapshot view
        # upsert validDocIds: PIN the liveness mask for this view's rows
        # at snapshot time, so the filter mask and every column lane
        # agree even while the upsert fold keeps invalidating docs
        vd = impl.valid_doc_ids
        self.valid_doc_mask = None if vd is None or not vd.num_invalid \
            else vd.valid_mask(start, start + self.num_docs)

    @property
    def padded_docs(self) -> int:
        from pinot_tpu.segment.loader import padded_size
        return padded_size(max(self.num_docs, 1))

    @property
    def column_names(self) -> List[str]:
        return list(self._impl._sources.keys())

    def has_column(self, column: str) -> bool:
        return column in self._impl._sources

    def data_source(self, column: str) -> _SnapshotSource:
        src = self._sources.get(column)
        if src is None:
            src = _SnapshotSource(self._impl._sources[column],
                                  self.start + self.num_docs,
                                  start=self.start)
            self._sources[column] = src
        return src

    @property
    def metadata(self) -> SegmentMetadata:
        tc = self.schema.time_column
        return SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self._impl.table_config.table_name,
            total_docs=self.num_docs,
            columns={name: self.data_source(name).metadata
                     for name in self.column_names},
            time_column=tc.name if tc else None,
            time_unit=tc.time_unit.name if tc else None,
            start_time=self._impl._start_time,
            end_time=self._impl._end_time,
            creation_time_ms=self._impl.creation_time_ms)


class MutableSegmentImpl:
    """The consuming segment: single writer, many reader snapshots."""

    is_mutable = True

    def __init__(self, schema: Schema, table_config: TableConfig,
                 segment_name: str, stats_hint: Optional[dict] = None):
        """stats_hint: RealtimeSegmentStatsHistory.estimate() output —
        sizes initial row-buffer allocations so steady-state consumption
        skips the growth-copy ladder (parity: the reference sizing
        MutableSegmentImpl allocations from RealtimeSegmentStatsHistory).
        """
        self.schema = schema
        self.table_config = table_config
        self.segment_name = segment_name
        no_dict = set(table_config.indexing_config.no_dictionary_columns)
        est_rows = int((stats_hint or {}).get("rows", 0))
        # next pow2 ≥ estimate, floor 4096, capped so a bad estimate
        # can't allocate unbounded memory up front
        cap = 4096
        while cap < est_rows and cap < (1 << 24):
            cap *= 2
        self._sources = {
            f.name: _MutableDataSource(f, f.name not in no_dict,
                                       initial_capacity=cap)
            for f in schema.fields}
        self._num_docs = 0
        self._lock = threading.Lock()
        self._start_time: Optional[int] = None
        self._end_time: Optional[int] = None
        self._frozen = None                  # sorted device snapshot
        self._freeze_lock = threading.Lock()
        # primary-key upsert liveness bitmap (realtime/upsert.py):
        # attached by the realtime data manager when the table runs
        # upserts; shared with the frozen device snapshot and inherited
        # by the committed immutable segment (docIds survive conversion)
        self.valid_doc_ids = None
        self.creation_time_ms = int(time.time() * 1e3)
        # freshness: when the most recent row was indexed (parity: the
        # lastIndexedTimestamp feeding minConsumingFreshnessTimeMs)
        self.last_indexed_time_ms = self.creation_time_ms

    # -- write -------------------------------------------------------------
    def index_row(self, row: dict) -> bool:
        tc = self.schema.time_column
        with self._lock:
            for name, ds in self._sources.items():
                ds.add(row.get(name))
            if tc is not None:
                try:
                    t = int(row.get(tc.name))
                    self._start_time = t if self._start_time is None \
                        else min(self._start_time, t)
                    self._end_time = t if self._end_time is None \
                        else max(self._end_time, t)
                except (TypeError, ValueError):
                    pass
            self._num_docs += 1
            self.last_indexed_time_ms = int(time.time() * 1e3)
        return True

    def index_rows(self, rows: list) -> int:
        """Batch indexing: column-at-a-time over the whole fetch batch
        (parity outcome: BenchmarkRealtimeConsumptionSpeed-class rates —
        the per-row python dispatch was the consuming bottleneck)."""
        if not rows:
            return 0
        tc = self.schema.time_column
        with self._lock:
            for name, ds in self._sources.items():
                ds.add_many([r.get(name) for r in rows])
            if tc is not None:
                ts = []
                for r in rows:
                    try:
                        ts.append(int(r.get(tc.name)))
                    except (TypeError, ValueError):
                        pass
                if ts:
                    lo, hi = min(ts), max(ts)
                    self._start_time = lo if self._start_time is None \
                        else min(self._start_time, lo)
                    self._end_time = hi if self._end_time is None \
                        else max(self._end_time, hi)
            self._num_docs += len(rows)
            self.last_indexed_time_ms = int(time.time() * 1e3)
        return len(rows)

    def collect_stats(self) -> dict:
        """Completed-segment stats for RealtimeSegmentStatsHistory
        (parity: the stats the reference records at segment completion:
        rows indexed, per-column cardinality, avg MV count)."""
        with self._lock:
            cols = {}
            for name, ds in self._sources.items():
                st = {"cardinality": int(ds.dictionary.cardinality)
                      if ds.dictionary is not None else 0}
                if ds._mv is not None and self._num_docs:
                    st["avgMvCount"] = (sum(len(v) for v in ds._mv) /
                                        self._num_docs)
                cols[name] = st
            return {"numRowsIndexed": int(self._num_docs),
                    "columns": cols}

    # -- query interface (ImmutableSegment-compatible) ---------------------
    def snapshot_view(self, start: int = 0) -> MutableSegmentView:
        """Consistent point-in-time view for one query."""
        return MutableSegmentView(self, start=start)

    # -- device path: periodic sorted snapshot -----------------------------
    #
    # The TPU-first answer to "consuming segments are first-class query
    # targets" (reference: MutableSegmentImpl.java:64-198 serves queries
    # on the same engine): arrival-order dictionaries break the device
    # kernels' sorted-id preconditions, so a background-free PERIODIC
    # SNAPSHOT re-sorts each dictionary, remaps the frozen row prefix
    # into sorted-id space, and materializes a standard in-memory
    # ImmutableSegment — every device kernel (and its jit cache) applies
    # unchanged. Queries then run [frozen device part] + [host tail of
    # rows indexed since the freeze] as two segments and merge through
    # the ordinary combine path. Freeze points double (8192, 16384, ...)
    # so the jit shape set stays logarithmic in segment size and the
    # O(n + card log card) rebuild cost amortizes to O(1)/row.

    FREEZE_MIN_ROWS = 8192

    def device_view(self):
        """(frozen ImmutableSegment | None, tail MutableSegmentView).

        The tail view may be empty (num_docs == 0) when no rows arrived
        since the freeze; callers skip executing it then. Rebuild+swap
        is serialized by _freeze_lock (queries run on a worker pool);
        superseded snapshots are NOT destroyed eagerly — an in-flight
        query may still be executing against one, so their device
        arrays are released by GC when the last reference drops."""
        n = self._num_docs
        snap = self._frozen
        if n >= self.FREEZE_MIN_ROWS and \
                (snap is None or n >= 2 * snap.num_docs):
            with self._freeze_lock:
                snap = self._frozen        # another query may have won
                if snap is None or n >= 2 * snap.num_docs:
                    snap = self._build_frozen(n)
                    self._frozen = snap
        if snap is None:
            return None, self.snapshot_view()
        return snap, self.snapshot_view(start=snap.num_docs)

    def release_device_snapshot(self) -> None:
        """Graceful degradation under HBM pressure (the residency
        manager's pressure hook): drop the frozen device snapshot.
        In-flight queries keep their reference (GC releases the lanes
        when the last drops); new queries serve the full row range
        host-side until the executor's mutable gate re-admits a freeze."""
        with self._freeze_lock:
            self._frozen = None

    def _build_frozen(self, n: int):
        """Rows [0, n) as a sorted-dictionary in-memory ImmutableSegment."""
        from pinot_tpu.segment.dictionary import Dictionary
        from pinot_tpu.segment.loader import DataSource, ImmutableSegment

        tc = self.schema.time_column
        sources: Dict[str, DataSource] = {}
        col_meta: Dict[str, ColumnMetadata] = {}
        for name, ms in self._sources.items():
            f = ms.field
            if ms.is_vector:
                mat = np.array(ms._vec.snapshot(n), copy=True)
                cm = ColumnMetadata(
                    name=name, data_type=f.data_type, cardinality=n,
                    bits_per_element=32, single_value=True,
                    has_dictionary=False, total_number_of_entries=n,
                    vector_dimension=f.vector_dimension)
                ds = DataSource(cm, None)
                ds.vec_values = mat
                sources[name] = ds
                col_meta[name] = cm
                continue
            if not ms.has_dictionary:
                raw = np.array(ms._sv.snapshot(n), copy=True)
                cm = ColumnMetadata(
                    name=name, data_type=f.data_type, cardinality=n,
                    bits_per_element=32, single_value=True,
                    has_dictionary=False,
                    min_value=raw.min() if n else None,
                    max_value=raw.max() if n else None,
                    total_number_of_entries=n)
                ds = DataSource(cm, None)
                ds.raw_values = raw
                sources[name] = ds
                col_meta[name] = cm
                continue
            # pin the cardinality, sort values, invert the permutation
            card = ms.dictionary.cardinality
            dtype = f.data_type.np_dtype if f.data_type.is_numeric \
                else object
            # list slice under the GIL: a consistent copy even while the
            # consumer thread keeps appending new values
            vals = np.array(ms.dictionary._values[:card], dtype=dtype)
            order = np.argsort(vals, kind="stable")
            sorted_vals = vals[order]
            remap = np.empty(card + 1, np.int32)
            remap[order] = np.arange(card, dtype=np.int32)
            remap[card] = card          # MV padding sentinel
            if f.single_value:
                ids = remap[ms._sv.snapshot(n)]
                mv = None
                entries = n
            else:
                rows = ms._mv[:n]
                width = max((len(r) for r in rows), default=1)
                mv = np.full((n, width), card, dtype=np.int32)
                for i, r in enumerate(rows):
                    mv[i, : len(r)] = remap[r]
                ids = None
                entries = int(sum(len(r) for r in rows))
            cm = ColumnMetadata(
                name=name, data_type=f.data_type, cardinality=card,
                bits_per_element=max(
                    1, int(np.ceil(np.log2(max(card, 2))))),
                single_value=f.single_value, sorted=False,
                has_dictionary=True,
                min_value=sorted_vals[0] if card else None,
                max_value=sorted_vals[-1] if card else None,
                max_number_of_multi_values=(0 if mv is None
                                            else mv.shape[1]),
                total_number_of_entries=entries)
            ds = DataSource(cm, None)
            ds.dictionary = Dictionary(f.data_type, sorted_vals)
            ds.dict_ids = ids
            ds.mv_dict_ids = mv
            sources[name] = ds
            col_meta[name] = cm
        meta = SegmentMetadata(
            segment_name=f"{self.segment_name}__frozen",
            table_name=self.table_config.table_name,
            total_docs=n, columns=col_meta,
            time_column=tc.name if tc else None,
            time_unit=tc.time_unit.name if tc else None,
            start_time=self._start_time, end_time=self._end_time,
            creation_time_ms=self.creation_time_ms)
        seg = ImmutableSegment(meta, sources)
        for ds in sources.values():
            ds._segment = seg
        # the frozen prefix shares the LIVE bitmap: rows [0, n) stay
        # maskable when a later (tail/committed) row supersedes them;
        # device lanes refresh via the bitmap version
        seg.valid_doc_ids = self.valid_doc_ids
        return seg

    @property
    def num_docs(self) -> int:
        return self._num_docs

    @property
    def padded_docs(self) -> int:
        from pinot_tpu.segment.loader import padded_size
        return padded_size(max(self._num_docs, 1))

    @property
    def column_names(self) -> List[str]:
        return list(self._sources.keys())

    def has_column(self, column: str) -> bool:
        return column in self._sources

    def data_source(self, column: str) -> _MutableDataSource:
        ds = self._sources[column]
        return ds.bind(self._num_docs)

    @property
    def metadata(self) -> SegmentMetadata:
        tc = self.schema.time_column
        return SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.table_config.table_name,
            total_docs=self._num_docs,
            columns={name: ds.bind(self._num_docs).metadata
                     for name, ds in self._sources.items()},
            time_column=tc.name if tc else None,
            time_unit=tc.time_unit.name if tc else None,
            start_time=self._start_time, end_time=self._end_time,
            creation_time_ms=self.creation_time_ms)

    def columnar_snapshot(self) -> Dict[str, List]:
        """Decoded columns for RealtimeSegmentConverter → SegmentCreator."""
        n = self._num_docs
        return {name: ds.raw_column(n) for name, ds in self._sources.items()}

    def destroy(self) -> None:
        # _freeze_lock orders this against a concurrent device_view()
        # rebuild — without it destroy could null the reference while
        # _build_frozen publishes a fresh snapshot (leaked device arrays)
        with self._freeze_lock:
            if self._frozen is not None:
                self._frozen.destroy()
                self._frozen = None
        self._sources.clear()
