"""Native (C++) segment-build hot loops, compiled on first use.

The compute path is JAX/XLA on the TPU; the segment BUILD is host work
whose hot loops (cube grouping, grouped stats, fixed-bit packing) live in
seglib.cpp, compiled here with g++ -O3 into a cached shared object and
bound via ctypes (no pybind11 in the image). Every entry point has a
numpy fallback so the package works without a compiler — `lib()` returns
None then and callers keep their pure-python path.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(__file__), "seglib.cpp")


def _build_dir() -> str:
    d = os.environ.get("PINOT_TPU_NATIVE_CACHE") or \
        os.path.join(os.path.expanduser("~"), ".cache", "pinot_tpu_native")
    os.makedirs(d, exist_ok=True)
    return d


def lib() -> Optional[ctypes.CDLL]:
    """The compiled library, building it if needed; None when no g++."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("PINOT_TPU_NO_NATIVE") == "1":
            return None
        try:
            with open(_SRC, "rb") as fh:  # tpulint: disable=lock-blocking -- one-time native build memoized under the module lock: double-checked compile, only ever blocks on first use
                tag = hashlib.sha256(fh.read()).hexdigest()[:16]
            so = os.path.join(_build_dir(), f"seglib-{tag}.so")
            if not os.path.exists(so):
                tmp = so + f".tmp{os.getpid()}"
                subprocess.run(  # tpulint: disable=lock-blocking -- same one-time-build invariant: racing builders would compile the same .so twice and corrupt the rename dance
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, so)      # atomic: racing builders agree
            cdll = ctypes.CDLL(so)
            _bind(cdll)
            _LIB = cdll
        except Exception:  # noqa: BLE001 — fallback is pure numpy
            _LIB = None
        return _LIB


def _bind(cdll: ctypes.CDLL) -> None:
    i64, i32, u32, f64, vp = (ctypes.c_int64, ctypes.c_int32,
                              ctypes.c_uint32, ctypes.c_double,
                              ctypes.c_void_p)
    cdll.pack_bits_u32.argtypes = [vp, i64, ctypes.c_int, vp, i64]
    cdll.unpack_bits_u32.argtypes = [vp, i64, ctypes.c_int, i64, vp]
    cdll.group_index_i64.restype = i64
    cdll.group_index_i64.argtypes = [vp, i64, vp, vp]
    cdll.group_counts_i64.argtypes = [vp, i64, i64, vp]
    cdll.group_stats_f64.argtypes = [vp, vp, i64, i64, vp, vp, vp]
    cdll.group_stats_sorted_f64.argtypes = [vp, vp, i64, i64, vp, vp, vp,
                                            vp]
    cdll.packed_key_i64.argtypes = [vp, vp, ctypes.c_int, i64, vp]


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


# ---------------------------------------------------------------------------
# numpy-signature wrappers (None return = caller takes the numpy path)
# ---------------------------------------------------------------------------


def pack_bits(ids: np.ndarray, num_bits: int) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    n = len(ids)
    n_words = (n * num_bits + 31) // 32
    out = np.empty(n_words, np.uint32)
    L.pack_bits_u32(_ptr(ids), n, num_bits, _ptr(out), n_words)
    return out


def unpack_bits(words: np.ndarray, num_bits: int,
                n: int) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.uint32)
    out = np.empty(n, np.int32)
    L.unpack_bits_u32(_ptr(words), len(words), num_bits, n, _ptr(out))
    return out


def group_index(key: np.ndarray):
    """(sorted unique keys, per-row rank int32) or None (no native lib /
    alloc failure)."""
    L = lib()
    if L is None:
        return None
    key = np.ascontiguousarray(key, dtype=np.int64)
    n = len(key)
    uniq = np.empty(n, np.int64)
    rank = np.empty(n, np.int32)
    g = L.group_index_i64(_ptr(key), n, _ptr(uniq), _ptr(rank))
    if g < 0:
        return None
    return uniq[:g].copy(), rank


def group_counts(rank: np.ndarray, g: int) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    rank = np.ascontiguousarray(rank, dtype=np.int32)
    out = np.empty(g, np.int64)
    L.group_counts_i64(_ptr(rank), len(rank), g, _ptr(out))
    return out


def group_stats(rank: np.ndarray, vals: np.ndarray, g: int):
    """(sums, mins, maxs) float64 [g] or None."""
    L = lib()
    if L is None:
        return None
    rank = np.ascontiguousarray(rank, dtype=np.int32)
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    sums = np.empty(g, np.float64)
    mins = np.empty(g, np.float64)
    maxs = np.empty(g, np.float64)
    L.group_stats_f64(_ptr(rank), _ptr(vals), len(rank), g,
                      _ptr(sums), _ptr(mins), _ptr(maxs))
    return sums, mins, maxs


def group_stats_sorted(order: np.ndarray, starts: np.ndarray, n: int,
                       vals: np.ndarray):
    """(sums, mins, maxs) per sorted-key run, gather fused in; None
    when no native lib."""
    L = lib()
    if L is None:
        return None
    order = np.ascontiguousarray(order, dtype=np.int64)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    g = len(starts)
    sums = np.empty(g, np.float64)
    mins = np.empty(g, np.float64)
    maxs = np.empty(g, np.float64)
    L.group_stats_sorted_f64(_ptr(order), _ptr(starts), g, n, _ptr(vals),
                             _ptr(sums), _ptr(mins), _ptr(maxs))
    return sums, mins, maxs


def packed_key(dims, cards) -> Optional[np.ndarray]:
    """Mixed-radix key over int32 dim lanes in one native pass."""
    L = lib()
    if L is None or not dims:
        return None
    arrs = [np.ascontiguousarray(d, dtype=np.int32) for d in dims]
    n = len(arrs[0])
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])
    cards64 = np.asarray(cards, dtype=np.int64)
    out = np.empty(n, np.int64)
    L.packed_key_i64(ptrs, _ptr(cards64), len(arrs), n, _ptr(out))
    return out
