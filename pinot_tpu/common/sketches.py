"""Mergeable sketches: dense HyperLogLog + merging t-digest.

Parity: the reference's intermediate custom objects for approximate
aggregations — com.clearspring HyperLogLog used by DISTINCTCOUNTHLL /
FASTHLL (HllConstants, pinot-common/.../startree/hll) and com.tdunning
TDigest used by PERCENTILETDIGEST (+ QuantileDigest for PERCENTILEEST),
with typed serde entries (core/common/ObjectSerDeUtils.java:55-83).
These are genuinely mergeable across segments/servers with non-shared
dictionaries — the property exact histograms lose once value sets differ.

Vectorized numpy throughout: adds are O(values) with a 6-step exact
bit-length ladder, no per-element Python.
"""
from __future__ import annotations

import hashlib
import struct
from typing import List, Optional, Tuple

import numpy as np

DEFAULT_LOG2M = 12                 # 4096 registers, ~1.6% std error
DEFAULT_COMPRESSION = 100.0

_U64 = np.uint64


def _bit_length_u64(v: np.ndarray) -> np.ndarray:
    """Exact bit length of uint64 values (vectorized, no float loss)."""
    v = v.copy()
    bl = np.zeros(v.shape, dtype=np.int64)
    for s in (32, 16, 8, 4, 2, 1):
        big = v >= (_U64(1) << _U64(s))
        bl[big] += s
        v[big] >>= _U64(s)
    bl[v > 0] += 1
    return bl


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — stable 64-bit hash for numeric values."""
    with np.errstate(over="ignore"):
        x = (x + _U64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)
        return x ^ (x >> _U64(31))


def _hash_values(values) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype.kind in "iu":
        return _mix64(arr.astype(np.int64).view(np.uint64))
    if arr.dtype.kind == "f":
        return _mix64(arr.astype(np.float64).view(np.uint64))
    if arr.dtype.kind == "b":
        return _mix64(arr.astype(np.int64).view(np.uint64))
    # strings / objects: stable 8-byte blake2b per value
    out = np.empty(len(arr), dtype=np.uint64)
    for i, v in enumerate(arr):
        data = v if isinstance(v, bytes) else str(v).encode("utf-8")
        out[i] = int.from_bytes(
            hashlib.blake2b(data, digest_size=8).digest(), "big")
    return out


def hll_tables(values, log2m: int = DEFAULT_LOG2M
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-value (register index, rank) int32 tables for `values`.

    The ONE hashing implementation shared by HyperLogLog.add_values and
    the device HLL kernel's per-dictId precompute (ops/kernels.py agg
    "hll"): a register array built by scatter-maxing rank over idx for
    any subset of `values` is bit-identical to
    HyperLogLog.from_values(that subset) by construction — the
    host/device/sharded register-identity contract.
    """
    if len(values) == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32))
    h = _hash_values(values)
    idx = (h >> _U64(64 - log2m)).astype(np.int32)
    low = h & ((_U64(1) << _U64(64 - log2m)) - _U64(1))
    # rank = (64 - log2m + 1) - bitlength, all values <= 64: int32-exact
    max_rank = 65 - log2m
    bl = _bit_length_u64(low).astype(np.int32)
    rank = np.int32(max_rank) - bl
    return idx, rank


class HyperLogLog:
    """Dense HLL with the standard bias-corrected estimator."""

    def __init__(self, log2m: int = DEFAULT_LOG2M,
                 registers: Optional[np.ndarray] = None):
        self.log2m = log2m
        self.m = 1 << log2m
        self.registers = registers if registers is not None \
            else np.zeros(self.m, dtype=np.uint8)

    @classmethod
    def from_values(cls, values, log2m: int = DEFAULT_LOG2M
                    ) -> "HyperLogLog":
        hll = cls(log2m)
        hll.add_values(values)
        return hll

    def add_values(self, values) -> None:
        if len(values) == 0:
            return
        # delegates to the shared (host+device) hash/rank tables so the
        # device register kernel stays bit-identical by construction
        idx, rank = hll_tables(values, self.log2m)
        np.maximum.at(self.registers, idx, rank.astype(np.uint8))

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        assert self.log2m == other.log2m, "HLL log2m mismatch"
        return HyperLogLog(self.log2m,
                           np.maximum(self.registers, other.registers))

    def cardinality(self) -> float:
        m = float(self.m)
        alpha = 0.7213 / (1 + 1.079 / m)
        inv = np.ldexp(1.0, -self.registers.astype(np.int64))
        est = alpha * m * m / inv.sum()
        if est <= 2.5 * m:
            zeros = int((self.registers == 0).sum())
            if zeros:
                return m * np.log(m / zeros)       # linear counting
        elif est > (2 ** 64) / 30.0:
            est = -(2.0 ** 64) * np.log(1 - est / 2.0 ** 64)
        return float(est)

    def to_bytes(self) -> bytes:
        return struct.pack(">B", self.log2m) + self.registers.tobytes()

    @classmethod
    def from_bytes(cls, b: bytes) -> "HyperLogLog":
        log2m = b[0]
        regs = np.frombuffer(b[1:1 + (1 << log2m)],
                             dtype=np.uint8).copy()
        return cls(log2m, regs)

    def __eq__(self, other) -> bool:
        return isinstance(other, HyperLogLog) and \
            self.log2m == other.log2m and \
            bool(np.array_equal(self.registers, other.registers))


def union_serialized_hlls(hex_values) -> Optional["HyperLogLog"]:
    """Union hex-serialized HLLs (the derived-HLL-column FASTHLL path:
    each dictionary value of a derived column is one sketch). Returns
    None when no sketches matched — a default-log2m empty sketch would
    trip the log2m-mismatch assert when merged with a real segment's
    sketch at a different configured log2m; AggregationFunction.merge
    treats None as the identity."""
    out: Optional[HyperLogLog] = None
    for v in hex_values:
        h = HyperLogLog.from_bytes(bytes.fromhex(str(v)))
        out = h if out is None else out.merge(h)
    return out


class TDigest:
    """Merging t-digest (k1 arcsine scale) over (mean, weight) centroids."""

    def __init__(self, compression: float = DEFAULT_COMPRESSION,
                 means: Optional[np.ndarray] = None,
                 weights: Optional[np.ndarray] = None):
        self.compression = compression
        self.means = means if means is not None \
            else np.zeros(0, dtype=np.float64)
        self.weights = weights if weights is not None \
            else np.zeros(0, dtype=np.float64)

    @classmethod
    def from_values(cls, values, weights=None,
                    compression: float = DEFAULT_COMPRESSION) -> "TDigest":
        td = cls(compression)
        td.add_values(values, weights)
        return td

    def add_values(self, values, weights=None) -> None:
        vals = np.asarray(values, dtype=np.float64)
        if len(vals) == 0:
            return
        w = np.ones(len(vals)) if weights is None \
            else np.asarray(weights, dtype=np.float64)
        self.means = np.concatenate([self.means, vals])
        self.weights = np.concatenate([self.weights, w])
        self._compress()

    def merge(self, other: "TDigest") -> "TDigest":
        out = TDigest(self.compression,
                      np.concatenate([self.means, other.means]),
                      np.concatenate([self.weights, other.weights]))
        out._compress()
        return out

    def _k(self, q: np.ndarray) -> np.ndarray:
        return (self.compression / (2 * np.pi)) * \
            np.arcsin(np.clip(2 * q - 1, -1, 1))

    def _compress(self) -> None:
        """Vectorized k-space binning: centroids whose left-edge quantiles
        fall in the same unit k1-interval merge (weighted mean) — bounded
        bin mass with tiny tail bins, no per-element Python."""
        if len(self.means) <= 1:
            return
        order = np.argsort(self.means, kind="stable")
        means, weights = self.means[order], self.weights[order]
        total = weights.sum()
        q_left = (np.cumsum(weights) - weights) / total
        k = np.floor(self._k(q_left)).astype(np.int64)
        bin_id = np.concatenate([[0], np.cumsum(np.diff(k) != 0)])
        nbins = int(bin_id[-1]) + 1
        new_w = np.zeros(nbins)
        new_mw = np.zeros(nbins)
        np.add.at(new_w, bin_id, weights)
        np.add.at(new_mw, bin_id, means * weights)
        self.means = new_mw / new_w
        self.weights = new_w

    @property
    def total_weight(self) -> float:
        return float(self.weights.sum())

    def quantile(self, q: float) -> float:
        if len(self.means) == 0:
            return float("-inf")
        if len(self.means) == 1:
            return float(self.means[0])
        total = self.weights.sum()
        target = q * total
        cum = np.cumsum(self.weights) - self.weights / 2.0
        if target <= cum[0]:
            return float(self.means[0])
        if target >= cum[-1]:
            return float(self.means[-1])
        i = int(np.searchsorted(cum, target))
        t = (target - cum[i - 1]) / (cum[i] - cum[i - 1])
        return float(self.means[i - 1] +
                     t * (self.means[i] - self.means[i - 1]))

    def to_bytes(self) -> bytes:
        head = struct.pack(">dI", self.compression, len(self.means))
        return head + self.means.tobytes() + self.weights.tobytes()

    @classmethod
    def from_bytes(cls, b: bytes) -> "TDigest":
        compression, n = struct.unpack_from(">dI", b)
        off = struct.calcsize(">dI")
        means = np.frombuffer(b[off:off + 8 * n], dtype=np.float64).copy()
        weights = np.frombuffer(b[off + 8 * n:off + 16 * n],
                                dtype=np.float64).copy()
        return cls(compression, means, weights)

    def __eq__(self, other) -> bool:
        return isinstance(other, TDigest) and \
            self.compression == other.compression and \
            bool(np.array_equal(self.means, other.means)) and \
            bool(np.array_equal(self.weights, other.weights))
