"""Combine per-segment result blocks into one per-server block.

Parity: pinot-core/.../operator/CombineOperator.java (selection/agg merge via
CombineService) and CombineGroupByOperator.java:107-156 (concurrent group map
merge) + AggregationGroupByTrimmingService.java:44 (trim to
max(5·topN, 5000) when the merged map passes 4× that size).

Two merge engines live here:

- the ROW engine (the original, kept as the correctness oracle): dict
  inserts per group, python sorts keyed by `_order_key`/`_Rev` per row;
- the COLUMNAR engine: when every input block carries column blocks
  (zero-copy DataTable v3 decode) and the aggregation functions fold
  with numpy ufuncs, merges run as vectorized folds — group-by via
  factorize + bincount/ufunc.at, selection ordering via ONE stable
  `np.lexsort` over the concatenated key columns instead of a `_Rev`
  key object allocated per row per merge.

Any block or function the columnar engine cannot express falls back to
the row engine for the whole payload, so results are bit-identical by
construction (tests/test_transport_mux.py pins the parity).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.datatable import _col_to_list
from pinot_tpu.common.request import BrokerRequest, SelectionSort
from pinot_tpu.query.aggregation import AggregationFunction, make_functions
from pinot_tpu.query.blocks import IntermediateResultsBlock

# aggregation bases whose intermediates are scalars foldable with a
# numpy reduction (everything else — AVG pairs, sketches, sets,
# percentile maps — merges through the row engine's f.merge)
_NP_FOLD_BASES = ("COUNT", "SUM", "MIN", "MAX")


def trim_size_for(top_n: int) -> int:
    return max(5 * top_n, 5000)


def np_foldable(functions: List[AggregationFunction]) -> bool:
    return all(f.info.base in _NP_FOLD_BASES for f in functions)


def group_map_of(blk: IntermediateResultsBlock
                 ) -> Optional[Dict[Tuple, List]]:
    """The block's group map, materializing a columnar payload lazily
    (the fallback bridge from the columnar engine to the row engine)."""
    if blk.group_map is None and blk.group_cols is not None:
        key_cols, inter_cols = blk.group_cols
        keys = zip(*[_col_to_list(c) for c in key_cols]) if key_cols \
            else iter(())
        inters = zip(*[_col_to_list(c) for c in inter_cols])
        blk.group_map = {k: list(v) for k, v in zip(keys, inters)}
        blk.group_cols = None
    return blk.group_map


def selection_rows_of(blk: IntermediateResultsBlock
                      ) -> Optional[List[tuple]]:
    """Row tuples of a selection block, materializing columnar form."""
    if blk.selection_rows is None and blk.selection_cols is not None:
        cols = blk.selection_cols
        blk.selection_rows = list(zip(*[_col_to_list(c) for c in cols])) \
            if cols else []
        blk.selection_cols = None
    return blk.selection_rows


def combine_blocks(request: BrokerRequest,
                   blocks: List[IntermediateResultsBlock]
                   ) -> IntermediateResultsBlock:
    if not blocks:
        return IntermediateResultsBlock()
    out = blocks[0]
    functions = make_functions(request.aggregations) \
        if request.is_aggregation else []
    for blk in blocks[1:]:
        _merge_into(request, functions, out, blk)
        out.stats.merge(blk.stats)
        out.exceptions.extend(blk.exceptions)
    if request.is_group_by:
        t = trim_size_for(request.group_by.top_n)
        if out.group_cols is not None and _columnar_group(out) and \
                np_foldable(functions):
            inter_cols = out.group_cols[1]
            n_groups = len(inter_cols[0]) if inter_cols else 0
            if n_groups > 4 * t:
                out.group_cols = _trim_group_cols(out.group_cols,
                                                  functions, t)
        else:
            # object-tagged intermediates (AVG pairs, sketches) or a
            # single unfolded columnar block: the row engine trims
            gm = group_map_of(out)
            if gm is not None and len(gm) > 4 * t:
                out.group_map = trim_group_map(gm, functions, t)
    if request.is_selection and (out.selection_rows is not None or
                                 out.selection_cols is not None):
        _trim_selection(request, out)
    return out


def _merge_into(request: BrokerRequest,
                functions: List[AggregationFunction],
                a: IntermediateResultsBlock,
                b: IntermediateResultsBlock) -> None:
    if request.is_group_by:
        _merge_group_by(functions, a, b)
    elif request.is_aggregation:
        if a.agg_intermediates is None:
            a.agg_intermediates = b.agg_intermediates
        elif b.agg_intermediates is not None:
            a.agg_intermediates = [
                f.merge(x, y) for f, x, y in
                zip(functions, a.agg_intermediates, b.agg_intermediates)]
    if request.is_selection:
        _merge_selection(request, a, b)


# ---------------------------------------------------------------------------
# group-by merge
# ---------------------------------------------------------------------------

def _group_empty(blk: IntermediateResultsBlock) -> bool:
    if blk.group_cols is not None:
        inter = blk.group_cols[1]
        return not inter or len(inter[0]) == 0
    return blk.group_map is not None and not blk.group_map


def _merge_group_by(functions: List[AggregationFunction],
                    a: IntermediateResultsBlock,
                    b: IntermediateResultsBlock) -> None:
    if b.group_map is None and b.group_cols is None:
        return
    # empty-side shortcuts FIRST: a zero-row block decodes its columns
    # as untyped lists, and letting it into the type checks below would
    # demote the whole merge to the row engine for nothing
    if a.group_map is None and a.group_cols is None or _group_empty(a):
        a.group_map, a.group_cols = b.group_map, b.group_cols
        return
    if _group_empty(b):
        return
    if _columnar_group(a) and _columnar_group(b) and \
            np_foldable(functions):
        a.group_cols = merge_group_cols(functions,
                                        [a.group_cols, b.group_cols])
        return
    # row engine (oracle): materialize whichever side is columnar
    a_map = group_map_of(a)
    b_map = group_map_of(b)
    for key, inters in b_map.items():
        mine = a_map.get(key)
        if mine is None:
            a_map[key] = inters
        else:
            a_map[key] = [f.merge(x, y) for f, x, y in
                          zip(functions, mine, inters)]


def _columnar_group(blk: IntermediateResultsBlock) -> bool:
    """Columnar AND numerically foldable: every intermediate column is
    a numeric numpy array (an object-tagged column — AVG pairs, Nones —
    cannot fold, and an int column that could overflow an exact int64
    fold must use the row engine's unbounded python ints), and key
    columns are arrays (without NaN, which np.unique would collapse
    across groups while the dict oracle keeps NaN keys distinct) or
    all-string lists."""
    if blk.group_cols is None or blk.group_map is not None:
        return False
    key_cols, inter_cols = blk.group_cols
    for c in inter_cols:
        if not (isinstance(c, np.ndarray) and c.dtype.kind in "if"):
            return False
        if c.dtype.kind == "i" and not _int_fold_safe(c):
            return False
    for c in key_cols:
        if isinstance(c, np.ndarray):
            if c.dtype.kind == "f" and bool(np.isnan(c).any()):
                return False
        elif not _is_str_list(c):
            return False
    return True


def _int_fold_safe(col: np.ndarray) -> bool:
    """Can an exact int64 np.add fold of this column EVER wrap? Bound
    |sum| ≤ n·max|x| in python ints (no wrap in the check itself);
    conservative — epoch-nano magnitudes fall back to the row engine's
    unbounded python-int accumulation."""
    if len(col) == 0:
        return True
    mx = max(abs(int(col.max())), abs(int(col.min())))
    return mx * len(col) < (1 << 62)


def _is_str_list(col) -> bool:
    # EVERY element must be str: an object-tagged column exists exactly
    # because the encoder saw a non-homogeneous column, so a first-
    # element probe would let ('5',) and (5,) cross-type collapse under
    # np.unique's stringification (or crash on None) instead of falling
    # back to the row engine
    return isinstance(col, list) and all(type(v) is str for v in col)


def _concat_cols(parts: List[object]) -> object:
    """Concatenate one column's per-block pieces: ndarray-only parts
    stay an ndarray, anything else flattens to a python list."""
    if all(isinstance(p, np.ndarray) for p in parts):
        return np.concatenate(parts)
    merged: list = []
    for p in parts:
        merged.extend(_col_to_list(p))
    return merged


def _factorize(col) -> Tuple[np.ndarray, int]:
    """→ (codes ascending-by-value, cardinality) for one key column."""
    arr = col if isinstance(col, np.ndarray) else np.asarray(col)
    uniq, inv = np.unique(arr, return_inverse=True)
    return inv.astype(np.int64, copy=False), len(uniq)


def _group_ids(key_cols: List[object]) -> np.ndarray:
    """One int64 id per row, equal iff the full key tuple is equal.
    Pairwise combine + re-compact keeps intermediate products bounded
    by n_rows × cardinality — no overflow at any column count."""
    ids, _ = _factorize(key_cols[0])
    for col in key_cols[1:]:
        codes, card = _factorize(col)
        ids = ids * np.int64(card) + codes
        uniq, inv = np.unique(ids, return_inverse=True)
        ids = inv.astype(np.int64, copy=False)
    return ids


def merge_group_cols(functions: List[AggregationFunction],
                     block_cols: List[Tuple[List, List]]
                     ) -> Tuple[List, List]:
    """Vectorized group merge over columnar blocks: concatenate, group
    by first occurrence (dict-merge insertion-order parity), fold each
    intermediate column with its numpy reduction."""
    n_keys = len(block_cols[0][0])
    key_cols = [_concat_cols([bc[0][ki] for bc in block_cols])
                for ki in range(n_keys)]
    inter_cols = [np.concatenate([bc[1][fi] for bc in block_cols])
                  for fi in range(len(functions))]

    ids = _group_ids(key_cols)
    _uniq, first_idx, inv = np.unique(ids, return_index=True,
                                      return_inverse=True)
    # groups ordered by FIRST OCCURRENCE in the concatenation — exactly
    # the row engine's dict-merge insertion order
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(len(order), dtype=np.int64)
    rank[order] = np.arange(len(order), dtype=np.int64)
    gpos = rank[inv]
    n_groups = len(order)
    sel = first_idx[order]

    out_keys: List[object] = []
    for col in key_cols:
        if isinstance(col, np.ndarray):
            out_keys.append(col[sel])
        else:
            out_keys.append([col[i] for i in sel])
    out_inters: List[object] = []
    for f, col in zip(functions, inter_cols):
        base = f.info.base
        if base in ("COUNT", "SUM"):
            if col.dtype.kind == "i":
                # EXACT int64 accumulation — a float64 bincount would
                # silently round sums past 2^53 (epoch-nanos, big
                # counters) and break row-engine bit-parity
                folded = np.zeros(n_groups, dtype=col.dtype)
                np.add.at(folded, gpos, col)
            else:
                folded = np.bincount(gpos, weights=col,
                                     minlength=n_groups)
            out_inters.append(folded)
        else:
            ufunc = np.minimum if base == "MIN" else np.maximum
            if col.dtype.kind == "i":
                info = np.iinfo(col.dtype)
                init = info.max if base == "MIN" else info.min
            else:
                init = np.inf if base == "MIN" else -np.inf
            folded = np.full(n_groups, init, dtype=col.dtype)
            ufunc.at(folded, gpos, col)
            out_inters.append(folded)
    return out_keys, out_inters


def _trim_group_cols(group_cols: Tuple[List, List],
                     functions: List[AggregationFunction],
                     trim_size: int) -> Tuple[List, List]:
    """Columnar trim: union of per-function top-`trim_size` groups
    (value desc, first-occurrence stable), kept in group order."""
    key_cols, inter_cols = group_cols
    n = len(inter_cols[0])
    keep = np.zeros(n, dtype=bool)
    for f, col in zip(functions, inter_cols):
        top = np.argsort(sortable_desc_key(f, col),
                         kind="stable")[:trim_size]
        keep[top] = True
    idx = np.flatnonzero(keep)
    kept_keys = [c[idx] if isinstance(c, np.ndarray)
                 else [c[i] for i in idx] for c in key_cols]
    kept_inters = [c[idx] for c in inter_cols]
    return kept_keys, kept_inters


def trim_group_map(group_map: Dict[Tuple, List],
                   functions: List[AggregationFunction],
                   trim_size: int) -> Dict[Tuple, List]:
    """Keep the union of per-function top-`trim_size` groups (value desc).

    Parity: AggregationGroupByTrimmingService sorts per function and keeps
    the heads, so a group surviving under ANY function survives the trim.
    """
    keep = set()
    keys = list(group_map.keys())
    for fi, f in enumerate(functions):
        scored = sorted(
            keys, key=lambda k: f.sortable_final(group_map[k][fi]),
            reverse=True)
        keep.update(scored[:trim_size])
    return {k: group_map[k] for k in keep}


# ---------------------------------------------------------------------------
# selection merge
# ---------------------------------------------------------------------------

def _selection_empty(blk: IntermediateResultsBlock) -> bool:
    if blk.selection_cols is not None:
        cols = blk.selection_cols
        return not cols or len(cols[0]) == 0
    return blk.selection_rows is not None and not blk.selection_rows


def _merge_selection(request: BrokerRequest,
                     a: IntermediateResultsBlock,
                     b: IntermediateResultsBlock) -> None:
    if b.selection_rows is None and b.selection_cols is None:
        return
    # adopt-and-skip shortcuts: a zero-row block's columns decode as
    # untyped empty lists, which must not demote the lexsort engine
    if (a.selection_rows is None and a.selection_cols is None) or \
            (_selection_empty(a) and not _selection_empty(b)):
        a.selection_rows = b.selection_rows
        a.selection_cols = b.selection_cols
        a.selection_columns = b.selection_columns
        a.selection_display_cols = b.selection_display_cols
        return
    if _selection_empty(b):
        return
    if a.selection_cols is not None and b.selection_cols is not None and \
            _lexsortable(request, a.selection_columns, a.selection_cols):
        a.selection_cols = merge_selection_cols(
            request, a.selection_columns,
            [a.selection_cols, b.selection_cols])
        return
    rows_b = selection_rows_of(b)
    if rows_b:
        a.selection_rows = merge_selection_rows(
            request, a.selection_columns, selection_rows_of(a), rows_b)
        a.selection_cols = None


def _sort_spec(request: BrokerRequest, columns: List[str]
               ) -> List[Tuple[int, bool]]:
    """[(column index, ascending)] in significance order, covering both
    ORDER BY and the vector-similarity merge order."""
    if request.vector is not None:
        return [(columns.index("$score"), False),
                (columns.index("$segmentName"), True),
                (columns.index("$docId"), True)]
    sel = request.selection
    idx = {c: i for i, c in enumerate(columns)}
    return [(idx[ob.column], ob.ascending) for ob in sel.order_by]


def _lexsortable(request: BrokerRequest, columns: Optional[List[str]],
                 cols: List[object]) -> bool:
    """Every merge-order key column must be a numeric array or a string
    list for the lexsort engine; anything else → row engine."""
    if columns is None:
        return False
    try:
        spec = _sort_spec(request, columns)
    except (ValueError, KeyError):
        return False
    for ci, _asc in spec:
        col = cols[ci]
        if not (isinstance(col, np.ndarray) and col.dtype.kind in "if"
                or _is_str_list(col)):
            return False
    return True


def _desc_key(col: np.ndarray) -> np.ndarray:
    """Ascending sort key that orders `col` DESCENDING, exactly: `~x`
    (= -x-1) is a monotone-decreasing int map with no overflow at
    INT64_MIN, and no float round-trip that would rank distinct int64
    values past 2^53 as ties."""
    if col.dtype.kind == "i":
        return ~col
    return -col


def sortable_desc_key(f: AggregationFunction,
                      col: np.ndarray) -> np.ndarray:
    """Descending group-ranking key that reproduces the row engine's
    `sortable_final` semantics EXACTLY: COUNT finals are python ints
    (exact comparisons — `~x`, overflow-free), everything else ranks by
    its float final, so ties land precisely where the oracle ties."""
    if f.info.base == "COUNT" and col.dtype.kind == "i":
        return ~col
    return -col.astype(np.float64, copy=False)


def _lexsort_keys(cols: List[object],
                  spec: List[Tuple[int, bool]]) -> List[np.ndarray]:
    """np.lexsort keys (least-significant first, per its contract)."""
    keys: List[np.ndarray] = []
    for ci, asc in reversed(spec):
        col = cols[ci]
        if isinstance(col, np.ndarray):
            keys.append(col if asc else _desc_key(col))
        else:
            codes, _card = _factorize(col)
            keys.append(codes if asc else ~codes)
    return keys


def merge_selection_cols(request: BrokerRequest, columns: List[str],
                         block_cols: List[List[object]]
                         ) -> List[object]:
    """Columnar selection merge: concatenate, ONE stable np.lexsort
    over the order-by key columns, slice the top offset+size."""
    sel = request.selection
    limit = sel.offset + sel.size
    n_cols = len(block_cols[0])
    cols: List[object] = []
    for ci in range(n_cols):
        cols.append(_concat_cols([bc[ci] for bc in block_cols]))
    spec = _sort_spec(request, columns)
    if spec:
        idx = np.lexsort(_lexsort_keys(cols, spec))[:limit]
        cols = [c[idx] if isinstance(c, np.ndarray)
                else [c[i] for i in idx] for c in cols]
    else:
        cols = [c[:limit] for c in cols]
    return cols


def vector_order_key(columns: List[str]):
    """Merge order for vector-similarity rows: score desc, then
    (segment, docId) asc — total and deterministic, so every merge
    topology (frozen+tail pair, per-server combine, broker reduce)
    produces the same top-k as one global pass."""
    si = columns.index("$score")
    ni = columns.index("$segmentName")
    di = columns.index("$docId")

    def key(row: tuple):
        return (-row[si], row[ni], row[di])

    return key


def merge_selection_rows(request: BrokerRequest, columns: List[str],
                         rows_a: List[tuple], rows_b: List[tuple]
                         ) -> List[tuple]:
    sel = request.selection
    limit = sel.offset + sel.size
    merged = list(rows_a) + list(rows_b)
    if request.vector is not None:
        merged.sort(key=vector_order_key(columns))
    elif sel.order_by:
        merged.sort(key=_order_key(sel.order_by, columns))
    return merged[:limit]


def _order_key(order_by: List[SelectionSort], columns: List[str]):
    idx = {c: i for i, c in enumerate(columns)}

    def key(row: tuple):
        parts = []
        for ob in order_by:
            v = row[idx[ob.column]]
            parts.append(_Rev(v) if not ob.ascending else v)
        return tuple(parts)

    return key


class _Rev:
    """Reverse-order wrapper for mixed-type sort keys."""
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v


def _trim_selection(request: BrokerRequest,
                    out: IntermediateResultsBlock) -> None:
    sel = request.selection
    limit = sel.offset + sel.size
    if out.selection_cols is not None:
        if _lexsortable(request, out.selection_columns,
                        out.selection_cols):
            out.selection_cols = merge_selection_cols(
                request, out.selection_columns, [out.selection_cols])
            return
        selection_rows_of(out)        # fall through to the row engine
    rows = out.selection_rows
    if not rows:
        out.selection_rows = []
        return
    if request.vector is not None:
        rows = sorted(rows, key=vector_order_key(out.selection_columns))
    elif sel.order_by:
        rows = sorted(rows, key=_order_key(sel.order_by,
                                           out.selection_columns))
    out.selection_rows = rows[:limit]
