"""protocol-invariants / protocol-model: the crash-interleaving gates.

`protocol-invariants` extracts the eight protocol transition systems
(lease/epoch fencing, rebalance add-then-prune, realtime takeover,
upsert seal/snapshot/truncate, graceful drain, compaction/merge
segment swap, exchange publish/ack/fetch/TTL-sweep, tiered-residency
demote/promote swaps — see analysis/protocol.py) from the LIVE source
and exhaustively explores
every interleaving of their steps, environment events, and
crash-at-every-step placements, machine-checking the written
ROBUSTNESS.md invariants:

1. no double-owned partition      (takeover: `no-double-owned`,
                                   plus `no-takeover-stall`)
2. no replica-count regression    (rebalance: `no-replica-regression`)
3. fenced writes                  (lease: `fenced-writes`)
4. drain is errorless             (drain: `drain-errorless`)
   + upsert durability prefix     (upsert-seal: `no-acked-delta-loss`)
5. swap serves exactly-one        (compact-swap: `no-double-serve`,
                                   `routed-implies-artifact`,
                                   `no-swap-loss`)
6. exchange lifecycle             (exchange: `no-half-published-read`,
                                   `no-read-after-sweep`,
                                   `expired-fetch-is-typed`,
                                   `no-spurious-overflow`,
                                   `bytes-conservation`)
7. tiered residency swaps         (residency: `no-read-of-released-lane`,
                                   `promoted-implies-artifact`,
                                   `budget-conservation`)

A violated invariant is reported WITH its counterexample trace (the
ordered step list that reaches the bad state). Per the no-silent-caps
rule, hitting `--max-states` is itself a finding — a truncated
exploration proves nothing. State counts are printed per system so the
"exhaustive" claim is auditable in CI logs.

`protocol-model` diffs the extracted systems against the committed
`protocol-model.json` (regenerate intentionally with
`--write-protocol-model`), so any change to a protocol's step order or
discipline flags is a review-visible artifact diff, exactly like
wire-schema changes.
"""
from __future__ import annotations

import sys
from typing import Iterator, List

from pinot_tpu.analysis.core import Finding, OPTIONS, Rule, register


@register
class ProtocolInvariantsRule(Rule):
    id = "protocol-invariants"
    description = ("exhaustive crash-interleaving model check of the "
                   "extracted lease/rebalance/takeover/upsert-seal/"
                   "drain/compact-swap/exchange/residency protocols "
                   "(protocol tier)")
    tier = "protocol"

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())

    def check_global(self) -> List[Finding]:
        from pinot_tpu.analysis import protocol
        max_states = int(OPTIONS.get("max_states",
                                     protocol.DEFAULT_MAX_STATES))
        result = protocol.check_protocols(max_states=max_states)
        for line in result.summary_lines():
            print(f"tpulint[protocol]: {line}", file=sys.stderr)
        findings: List[Finding] = []
        for system, path, line, msg in result.problems:
            findings.append(Finding(path, line, self.id,
                                    f"[{system}] {msg}"))
        for report in result.reports:
            if report.truncated:
                findings.append(Finding(
                    report.path, report.anchor_line, self.id,
                    f"[{report.system}] exploration TRUNCATED at "
                    f"{report.states} states (--max-states "
                    f"{max_states}) — coverage is incomplete; raise "
                    "the budget or shrink the model"))
            for v in report.violations:
                findings.append(Finding(
                    report.path, report.anchor_line, self.id,
                    f"[{v.system}] invariant `{v.invariant}` violated: "
                    f"{v.message}; {v.render_trace()}"))
        return findings


@register
class ProtocolModelRule(Rule):
    id = "protocol-model"
    description = ("extracted protocol transition systems must match "
                   "the committed protocol-model.json (protocol tier)")
    tier = "protocol"

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())

    def check_global(self) -> List[Finding]:
        from pinot_tpu.analysis import protocol
        return [Finding(path=protocol.PROTOCOL_MODEL_FILE, line=1,
                        rule=self.id, message=d)
                for d in protocol.check_protocol_model()]
