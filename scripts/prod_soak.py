"""Production soak: one multi-process cluster, every subsystem, a
deterministic chaos schedule, SLO-gated end to end.

One 4 broker x 8 server HA cluster (standalone store, lead + standby
controller, minion) serves a weighted production mix — SSB aggregations,
broadcast + co-partitioned joins, window functions, VECTOR_SIMILARITY,
and a 2-tenant quota split — at ~80% of the measured saturation knee
(QPS_r14.json), while realtime upsert ingestion churns a bounded
keyspace over the TCP stream and the minion runs UpsertCompaction on
schedule. A seeded ChaosCoordinator (common/chaos.py) fires mid-run:
transport latency/drop windows armed inside the broker processes,
kill -9 of a serving server, a SIGTERM drain, lead-controller failover
onto the standby lease, and a minion kill — each with a recovery
deadline.

Gates (all must hold, or exit 1):
- ZERO unflagged errors: every exception on every BrokerResponse must
  carry a machine-readable errorCode (obs/slo.py classify_response) —
  "the error rate was zero OR every error was a flagged, classified
  degradation" as an assertion, not a grep.
- Per-class p99 within bounds (SLOTracker).
- Every chaos recovery inside its deadline (replication healed +
  clean query after kill -9; endpoint re-published + /health after
  controller failover).
- Leak gauges FLAT (obs/slo.py GaugeSeries): per-process RSS,
  exchange held-bytes, residency ledger bytes, summed
  upsertKeyMapSize — sampled from every /debug/health rollup.

Writes SOAK_r15.json (timeline + per-class latency ladder + leak-gauge
series + recovery times) at the repo root (override SOAK_ARTIFACT).

Modes: PINOT_TPU_SOAK_SECONDS sets the duration (default 1800). Under
600s the harness runs the scaled-down CI shape — 1 broker x 4 servers,
low rates, one server-kill + one controller-failover — wired into
scripts/check.sh as the short soak gate (120s).
"""
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# serving-plane configuration (inherited by every spawned process) —
# the same rig QPS_r14.json measured, so the knee transfers
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ.setdefault("PINOT_TPU_BROKER_INLINE", "1")
os.environ.setdefault("PINOT_TPU_BROKER_CACHE_OFFLINE", "1")
os.environ.setdefault("PINOT_TPU_SHM_MIN_BYTES", str(256 * 1024))

import numpy as np  # noqa: E402

from pinot_tpu.common.chaos import ChaosCoordinator  # noqa: E402
from pinot_tpu.obs.slo import (GaugeSeries, SLOTracker,  # noqa: E402
                               classify_response)
from pinot_tpu.tools.cluster import MultiprocCluster  # noqa: E402

DURATION_S = float(os.environ.get("PINOT_TPU_SOAK_SECONDS", "1800"))
SHORT = DURATION_S < 600
SEED = int(os.environ.get("SOAK_SEED", "15"))
ARTIFACT = os.environ.get(
    "SOAK_ARTIFACT", os.path.join(REPO, "SOAK_r15.json"))

NUM_BROKERS = 1 if SHORT else 4
NUM_SERVERS = 4 if SHORT else 8
ROWS = int(os.environ.get("SOAK_ROWS", "20000" if SHORT else "500000"))
SEGMENTS = 4
THREADS = int(os.environ.get("SOAK_THREADS", "4" if SHORT else "7"))
INGEST_ROWS_PER_S = float(os.environ.get(
    "SOAK_INGEST_RPS", "40" if SHORT else "150"))
# bounded so the key map SETTLES inside the run (coupon-collector:
# full coverage needs ~K·lnK rows; the short gate publishes ~4.8k)
UPSERT_KEYSPACE = 300 if SHORT else 2000
VEC_DIM = 16

# p99 bounds per query class (ms): generous — the run includes fault
# windows and kill -9 recovery; the load-bearing gates are zero
# unflagged errors, recovery deadlines, and leak flatness
P99_BOUNDS_MS = json.loads(os.environ.get("SOAK_P99_BOUNDS", json.dumps({
    "ssb": 4000.0, "join": 8000.0, "window": 8000.0,
    "vector": 8000.0, "upsert": 4000.0, "tenant": 4000.0,
})))

# the production mix: weight per query class
MIX = [("ssb", 40), ("join", 15), ("window", 10), ("vector", 10),
       ("upsert", 15), ("tenant", 10)]


def _target_qps() -> float:
    if "SOAK_QPS" in os.environ:
        return float(os.environ["SOAK_QPS"])
    if SHORT:
        return 25.0
    try:
        d = json.load(open(os.path.join(REPO, "QPS_r14.json")))
        knee = next(s["saturation_knee_qps"] for s in d["shapes"]
                    if s["brokers"] == 4 and s["servers"] == 8)
        return 0.8 * float(knee)
    except Exception:  # noqa: BLE001 — artifact missing on a fresh rig
        return 320.0


def _http(method, url, body=None, ctype="application/json", timeout=30):
    req = urllib.request.Request(
        url, data=body, method=method,
        headers={"Content-Type": ctype} if body else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


# -- workload ---------------------------------------------------------------

SSB_TEMPLATES = [
    "SELECT SUM(lo_revenue) FROM lineorder WHERE d_year = 1993 AND "
    "lo_discount BETWEEN 1 AND 3 AND lo_quantity < {q}",
    "SELECT SUM(lo_revenue) FROM lineorder WHERE p_category = 'MFGR#12' "
    "AND s_region = 'AMERICA' GROUP BY d_year, p_brand1 TOP 100",
    "SELECT COUNT(*), SUM(lo_revenue) FROM lineorder WHERE "
    "c_region = 'ASIA' AND s_region = 'ASIA' GROUP BY d_year TOP 100",
    "SELECT MAX(lo_revenue), MIN(lo_supplycost) FROM lineorder "
    "WHERE lo_quantity BETWEEN {q} AND 40",
]

JOIN_TEMPLATES = [
    # broadcast probe (dim filtered server-side, fact co-partitioned)
    "SELECT SUM(lineorderj.lo_revenue), COUNT(*) FROM lineorderj "
    "JOIN part ON lineorderj.lo_partkey = part.p_partkey "
    "WHERE part.p_mfgr = 'MFGR#{m}'",
    "SELECT SUM(lineorderj.lo_quantity) FROM lineorderj "
    "JOIN part ON lineorderj.lo_partkey = part.p_partkey "
    "WHERE lineorderj.d_year = {y}",
]

WINDOW_TEMPLATES = [
    "SELECT d_year, lo_revenue, ROW_NUMBER() OVER (PARTITION BY d_year "
    "ORDER BY lo_revenue DESC) FROM lineorderj WHERE d_year = {y} "
    "LIMIT 20",
    "SELECT d_year, SUM(lo_quantity) OVER (PARTITION BY d_year "
    "ORDER BY lo_revenue) FROM lineorderj WHERE d_year = {y} LIMIT 20",
]


def build_query(qclass: str, rng: np.random.Generator) -> str:
    if qclass == "ssb":
        t = SSB_TEMPLATES[int(rng.integers(len(SSB_TEMPLATES)))]
        return t.format(q=int(rng.integers(20, 30)))
    if qclass == "join":
        t = JOIN_TEMPLATES[int(rng.integers(len(JOIN_TEMPLATES)))]
        return t.format(m=int(rng.integers(1, 6)),
                        y=int(rng.integers(1992, 1999)))
    if qclass == "window":
        t = WINDOW_TEMPLATES[int(rng.integers(len(WINDOW_TEMPLATES)))]
        return t.format(y=int(rng.integers(1992, 1999)))
    if qclass == "vector":
        qs = ", ".join(f"{x:.4f}" for x in rng.standard_normal(VEC_DIM))
        # half the class probes the IVF index (mixed stack: one segment
        # indexed, one not — the exact-fallback and NotShardable paths
        # serve continuously, including through minion kill windows)
        ann = ", nprobe=4" if rng.random() < 0.5 else ""
        return (f"SELECT rid, VECTOR_SIMILARITY(emb, [{qs}], 7, "
                f"'COSINE'{ann}) FROM vectab WHERE shard < 2")
    if qclass == "upsert":
        return "SELECT COUNT(*), SUM(value) FROM events"
    if qclass == "tenant":
        tenant = "gold" if rng.random() < 0.7 else "bronze"
        q = int(rng.integers(20, 30))
        return (f"SELECT SUM(lo_revenue) FROM lineorder WHERE "
                f"lo_quantity < {q} OPTION(workload={tenant})")
    raise ValueError(qclass)


class LoadDriver:
    """Open-loop paced query mix against the broker fleet. Each worker
    owns a slot cadence; a query still in flight when its next slot
    arrives counts a missed slot instead of piling up (client-side
    shedding — offered load stays bounded under fault windows)."""

    def __init__(self, cluster, tracker: SLOTracker, qps: float,
                 threads: int, seed: int):
        self.cluster = cluster
        self.tracker = tracker
        self.qps = qps
        self.threads = threads
        self.seed = seed
        self.stop_flag = threading.Event()
        self.missed_slots = 0
        self.transport_errors = 0
        self.issued = 0
        self._lock = threading.Lock()
        self._workers = []

    def _post(self, port: int, pql: str):
        body = json.dumps({"pql": pql}).encode()
        try:
            return _http("POST", f"http://127.0.0.1:{port}/query", body,
                         timeout=30)
        except urllib.error.HTTPError as e:
            # 429/503 carry the BrokerResponse JSON in the error body
            try:
                return json.loads(e.read())
            except Exception:  # noqa: BLE001
                return None
        except Exception:  # noqa: BLE001 — connection-level failure
            return None

    def _worker(self, wid: int):
        rng = np.random.default_rng(self.seed * 1000 + wid)
        ports = self.cluster.broker_ports
        interval = self.threads / self.qps
        nxt = time.monotonic() + rng.random() * interval
        weights = np.array([w for _, w in MIX], dtype=float)
        weights /= weights.sum()
        classes = [c for c, _ in MIX]
        while not self.stop_flag.is_set():
            now = time.monotonic()
            if now < nxt:
                time.sleep(min(nxt - now, 0.2))
                continue
            behind = int((now - nxt) / interval)
            if behind > 0:           # shed the slots we already missed
                with self._lock:
                    self.missed_slots += behind
                nxt += behind * interval
            nxt += interval
            qclass = classes[int(rng.choice(len(classes), p=weights))]
            pql = build_query(qclass, rng)
            port = ports[int(rng.integers(len(ports)))]
            t0 = time.monotonic()
            resp = self._post(port, pql)
            dt_ms = (time.monotonic() - t0) * 1000.0
            with self._lock:
                self.issued += 1
                if resp is None:
                    self.transport_errors += 1
                else:
                    self.tracker.record(qclass, dt_ms, resp)

    def start(self):
        for i in range(self.threads):
            t = threading.Thread(target=self._worker, args=(i,),
                                 daemon=True, name=f"load-{i}")
            t.start()
            self._workers.append(t)

    def stop(self):
        self.stop_flag.set()
        for t in self._workers:
            t.join(timeout=35)


class UpsertIngest:
    """Realtime churn: rows over the TCP stream topic, keys cycling a
    BOUNDED keyspace so upserts dominate — upsertKeyMapSize must go
    FLAT once every key has been seen (the leak-gate signal), while
    superseded rows accumulate deadness for the minion's
    UpsertCompactionTask."""

    def __init__(self, publisher, topic: str, rows_per_s: float,
                 seed: int, partitions: int = 2):
        self.pub = publisher
        self.topic = topic
        self.rows_per_s = rows_per_s
        self.partitions = partitions
        self.rng = np.random.default_rng(seed + 77)
        self.stop_flag = threading.Event()
        self.published = 0
        self._thread = None

    def _run(self):
        interval = 1.0 / self.rows_per_s
        nxt = time.monotonic()
        while not self.stop_flag.is_set():
            now = time.monotonic()
            if now < nxt:
                time.sleep(min(nxt - now, 0.2))
                continue
            nxt = max(nxt + interval, now - 1.0)
            k = int(self.rng.integers(UPSERT_KEYSPACE))
            row = {"key": f"k{k}", "value": int(self.rng.integers(1000)),
                   "ts": 1_700_000_000_000 + self.published}
            try:
                self.pub.publish_row(self.topic, row,
                                     partition=k % self.partitions)
                self.published += 1
            except Exception:  # noqa: BLE001 — topic server restart gap
                time.sleep(0.5)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ingest")
        self._thread.start()

    def stop(self):
        self.stop_flag.set()
        if self._thread:
            self._thread.join(timeout=10)


class LeakSampler:
    """Polls every /debug/health rollup on a cadence into GaugeSeries.
    Per-process RSS / exchange held-bytes / residency bytes, plus the
    cluster-summed upsertKeyMapSize (per-server series would step to
    zero on kill -9; the sum recovers as the replacement rebuilds its
    key map from committed segments)."""

    def __init__(self, cluster, period_s: float = 5.0):
        self.cluster = cluster
        self.period_s = period_s
        self.series = {}
        self.stop_flag = threading.Event()
        self._thread = None
        self._t0 = time.monotonic()

    def _get(self, name: str, **kw) -> GaugeSeries:
        if name not in self.series:
            self.series[name] = GaugeSeries(name, **kw)
        return self.series[name]

    def sample(self):
        t = time.monotonic() - self._t0
        rollups = self.cluster.health_rollups()
        key_map_total = 0.0
        for proc, h in rollups.items():
            self._get(f"{proc}.rssBytes", rel_tol=0.15,
                      abs_tol=96e6).add(t, float(h.get("rssBytes", 0)))
            self._get(f"{proc}.exchangeHeldBytes", abs_tol=4e6).add(
                t, float(h.get("exchangeHeldBytes", 0)))
            res = h.get("residency") or {}
            self._get(f"{proc}.residencyBytes", rel_tol=0.15,
                      abs_tol=64e6).add(
                t, float(res.get("totalDeviceBytesResident", 0)))
            key_map_total += float(
                (h.get("gauges") or {}).get("upsertKeyMapSize") or 0)
        # Bounded mode, not slope: a kill -9 wipes one server's key map
        # and the healed replica rebuilds it, which reads as a positive
        # slope without being a leak. The structural cap is keyspace x
        # replicas-hosting (every server may hold committed copies); a
        # real leak grows with publish churn and crosses it.
        self._get("cluster.upsertKeyMapSize",
                  bound=UPSERT_KEYSPACE * NUM_SERVERS).add(t, key_map_total)

    def _run(self):
        while not self.stop_flag.is_set():
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — mid-failover scrape
                pass
            self.stop_flag.wait(self.period_s)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="leak-sampler")
        self._thread.start()

    def stop(self):
        self.stop_flag.set()
        if self._thread:
            self._thread.join(timeout=10)


# -- chaos schedule ---------------------------------------------------------

def chaos_schedule(duration_s: float):
    """Deterministic fault plan scaled to the run length. Explicit
    targets for kill/drain/restart pairs (the restart must name the
    process the kill took down); net_* targets are seeded-chosen by the
    coordinator from the live pool."""
    if SHORT:
        return [
            {"at_s": 0.25 * duration_s, "kind": "kill_server",
             "target": "Server_2", "recovery_deadline_s": 60.0,
             "note": "kill -9 a serving replica"},
            {"at_s": 0.45 * duration_s, "kind": "start_server",
             "target": "Server_2"},
            {"at_s": 0.60 * duration_s, "kind": "fail_controller",
             "recovery_deadline_s": 30.0,
             "note": "lead lease takeover"},
        ]
    return [
        {"at_s": 300.0, "kind": "net_latency", "duration_s": 60.0,
         "params": {"latency_s": 0.1, "probability": 0.5},
         "note": "100ms on half the dispatches to one server"},
        {"at_s": 480.0, "kind": "kill_server", "target": "Server_3",
         "recovery_deadline_s": 120.0,
         "note": "kill -9 a serving replica mid-load"},
        {"at_s": 780.0, "kind": "start_server", "target": "Server_3"},
        {"at_s": 960.0, "kind": "drain_server", "target": "Server_5",
         "recovery_deadline_s": 90.0,
         "note": "SIGTERM graceful drain (zero-error restart path)"},
        {"at_s": 1080.0, "kind": "start_server", "target": "Server_5"},
        {"at_s": 1140.0, "kind": "fail_controller",
         "recovery_deadline_s": 60.0,
         "note": "kill -9 the ACTIVE lead; standby lease takeover"},
        {"at_s": 1260.0, "kind": "start_controller",
         "target": "Controller_lead",
         "note": "failed lead rejoins as the new standby"},
        {"at_s": 1320.0, "kind": "kill_minion", "target": "Minion_0",
         "note": "kill -9 possibly mid-swap (intent-log recovery)"},
        {"at_s": 1380.0, "kind": "start_minion", "target": "Minion_0"},
        {"at_s": 1500.0, "kind": "net_drop", "duration_s": 30.0,
         "params": {"probability": 0.3},
         "note": "drop 30% of dispatches to one server"},
    ]


# -- data build + table registration ----------------------------------------

def make_vec_segments(base):
    from pinot_tpu.common.schema import (DataType, Schema, dimension,
                                         metric, vector)
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    schema = Schema("vectab", [
        dimension("shard", DataType.INT),
        metric("rid", DataType.INT),
        vector("emb", VEC_DIM),
    ])
    from pinot_tpu.common.table_config import IndexingConfig
    idx = IndexingConfig()
    idx.vector_index_configs = {"emb": {"numCentroids": 32}}
    cfg = TableConfig("vectab", indexing_config=idx)
    # the minion backfills vec_1's missing codebook mid-soak (and the
    # chaos plane may kill it mid-swap — the durable-intent resume path)
    cfg.task_configs = {"IvfRetrainTask": {}}
    # segment 0 seals WITH the IVF codebook; segment 1 is built
    # index-less on purpose, so every probed query in the mix exercises
    # the index-miss exact fallback AND the sharded mixed-stack
    # sequential fallback for the whole run — including minion kill
    # windows, where the IvfRetrainTask backfill for vec_1 may be
    # mid-flight
    plain = TableConfig("vectab")
    rng = np.random.default_rng(SEED + 5)
    dirs = []
    n = 1024 if SHORT else 4096
    for i in range(2):
        cols = {
            "shard": rng.integers(0, 4, n).astype(np.int32),
            "rid": (np.arange(n, dtype=np.int32) + i * n),
            "emb": rng.standard_normal((n, VEC_DIM)).astype(np.float32),
        }
        d = os.path.join(base, f"vec_{i}")
        SegmentCreator(schema, cfg if i == 0 else plain,
                       segment_name=f"vec_{i}").build(cols, d)
        dirs.append(d)
    return schema, cfg, dirs


def events_schema_config(topic_host, topic_port):
    from pinot_tpu.common.schema import (DataType, Schema, dimension,
                                         metric)
    from pinot_tpu.common.table_config import (IndexingConfig,
                                               SegmentsConfig,
                                               TableConfig, TableType,
                                               UpsertConfig)
    schema = Schema("events", [
        dimension("key", DataType.STRING),
        metric("value", DataType.LONG),
        dimension("ts", DataType.LONG),
    ])
    cfg = TableConfig(
        "events", table_type=TableType.REALTIME,
        indexing_config=IndexingConfig(stream_configs={
            "stream.factory.name": "tcp",
            "stream.topic.name": "events",
            "stream.tcp.host": topic_host,
            "stream.tcp.port": str(topic_port),
            "realtime.segment.flush.threshold.size":
                "500" if SHORT else "2000",
            "realtime.segment.flush.threshold.time.ms": "600000000",
        }),
        segments_config=SegmentsConfig(replication=1,
                                       time_column_name="ts"))
    cfg.upsert_config = UpsertConfig(mode="FULL",
                                     primary_key_columns=["key"])
    cfg.task_configs = {"UpsertCompactionTask":
                        {"invalidDocsThresholdPercent": 30,
                         "minInvalidDocs": 50}}
    return schema, cfg


def load_tables(cluster, base):
    import json as _json

    from pinot_tpu.common.table_config import QuotaConfig
    from pinot_tpu.tools.datagen import (build_join_table_dirs,
                                         build_ssb_segment_dirs,
                                         fact_join_schema,
                                         join_table_configs,
                                         part_dim_schema, ssb_schema,
                                         ssb_table_config)

    # 1. lineorder OFFLINE: SSB + window base + 2-tenant quota split
    ssb_dirs, _ids, _sc = build_ssb_segment_dirs(
        os.path.join(base, "ssb"), ROWS, SEGMENTS, seed=SEED,
        star_tree=True)
    cfg = ssb_table_config(star_tree=True)
    cfg.segments_config.replication = 2
    cfg.quota_config = QuotaConfig(max_queries_per_second=10_000.0)
    bronze_qps = 2.0 if SHORT else 8.0
    cfg.custom_config = {"tenantQuotas": _json.dumps(
        {"gold": 5_000.0, "bronze": bronze_qps})}
    cluster.add_schema(ssb_schema())
    cluster.add_table(cfg)
    for d in ssb_dirs:
        cluster.upload_segment("lineorder_OFFLINE", d)

    # 2. join pair, co-partitioned (Modulo) on the join keys
    fact_rows = 5000 if SHORT else 50_000
    fact_dirs, dim_dirs, _dim, _fact = build_join_table_dirs(
        os.path.join(base, "join"), fact_rows, 4, dim_rows=800,
        seed=SEED, num_partitions=4)
    fact_cfg, dim_cfg = join_table_configs(num_partitions=4)
    fact_cfg.segments_config.replication = 2
    cluster.add_schema(fact_join_schema())
    cluster.add_schema(part_dim_schema())
    cluster.add_table(fact_cfg)
    cluster.add_table(dim_cfg)
    for d in fact_dirs:
        cluster.upload_segment("lineorderj_OFFLINE", d)
    for d in dim_dirs:
        cluster.upload_segment("part_OFFLINE", d)

    # 3. vector table
    vschema, vcfg, vdirs = make_vec_segments(os.path.join(base, "vec"))
    cluster.add_schema(vschema)
    cluster.add_table(vcfg)
    for d in vdirs:
        cluster.upload_segment("vectab_OFFLINE", d)
    return ROWS, fact_rows


# -- gating + artifact -------------------------------------------------------

def evaluate_gates(tracker, coordinator, sampler, driver,
                   chaos_excluded):
    failures = []
    unflagged = tracker.unflagged_total()
    if unflagged:
        failures.append(
            f"{unflagged} UNFLAGGED errors (responses whose exceptions "
            f"lack a machine-readable errorCode)")
    failures.extend(tracker.violations())
    for v in coordinator.violations():
        failures.append(f"chaos recovery deadline violated: {v}")
    verdicts = {}
    for name, series in sorted(sampler.series.items()):
        verdict = series.verdict()
        verdicts[name] = verdict
        proc = name.split(".", 1)[0]
        if proc in chaos_excluded and name.endswith("rssBytes"):
            continue    # killed/drained + restarted: RSS series steps
        if not verdict.flat:
            failures.append(f"leak gauge not flat: {name} "
                            f"({verdict.reason})")
    if driver.issued == 0:
        failures.append("load driver issued zero queries")
    return failures, verdicts


def main() -> int:
    t_start = time.time()
    qps = _target_qps()
    base = tempfile.mkdtemp(prefix="pinot_tpu_soak_")
    print(f"soak: {'SHORT' if SHORT else 'FULL'} {DURATION_S:.0f}s, "
          f"{NUM_BROKERS}x{NUM_SERVERS}, target {qps:.0f} QPS, "
          f"base {base}", file=sys.stderr, flush=True)

    from pinot_tpu.realtime.tcp_stream import (TcpTopicClient,
                                               TcpTopicServer)
    topic_srv = TcpTopicServer()
    tport = topic_srv.start()
    topic_srv.create_topic("events", 2)
    publisher = TcpTopicClient("127.0.0.1", tport)

    cluster = MultiprocCluster(
        base, num_brokers=NUM_BROKERS, num_servers=NUM_SERVERS,
        ha=True, minion=True, lease_s=2.0, broker_faults=True)
    tracker = SLOTracker(p99_bounds_ms=P99_BOUNDS_MS)
    sampler = LeakSampler(cluster, period_s=5.0)
    driver = None
    ingest = None
    rc = 1
    try:
        load_tables(cluster, base)
        eschema, ecfg = events_schema_config("127.0.0.1", tport)
        cluster.add_schema(eschema)
        cluster.add_table(ecfg)
        cluster.await_ready("lineorder", ROWS, timeout_s=600)
        print(f"tables ready at t={time.time() - t_start:.0f}s",
              file=sys.stderr, flush=True)

        ingest = UpsertIngest(publisher, "events", INGEST_ROWS_PER_S,
                              SEED)
        ingest.start()
        driver = LoadDriver(cluster, tracker, qps, THREADS, SEED)
        driver.start()
        sampler.start()

        coordinator = ChaosCoordinator(cluster,
                                       chaos_schedule(DURATION_S),
                                       seed=SEED)
        chaos_thread = threading.Thread(target=coordinator.run,
                                        daemon=True, name="chaos")
        t0 = time.monotonic()
        chaos_thread.start()
        while time.monotonic() - t0 < DURATION_S:
            time.sleep(5.0)
            el = time.monotonic() - t0
            snap = tracker.snapshot()
            total = sum(c["count"] for c in snap.values())
            print(f"t={el:.0f}s issued={driver.issued} tracked={total} "
                  f"unflagged={tracker.unflagged_total()} "
                  f"transportErr={driver.transport_errors} "
                  f"missed={driver.missed_slots}",
                  file=sys.stderr, flush=True)
        coordinator.stop()
        chaos_thread.join(timeout=30)

        driver.stop()
        ingest.stop()
        sampler.sample()        # one final point for the verdicts
        sampler.stop()

        chaos_excluded = {f"{ev['target']}"
                          for ev in chaos_schedule(DURATION_S)
                          if ev.get("target") and
                          ev["kind"] in ("kill_server", "drain_server")}
        chaos_excluded.add("controller")
        failures, verdicts = evaluate_gates(
            tracker, coordinator, sampler, driver, chaos_excluded)

        artifact = {
            "artifact": "production_soak",
            "mode": "short" if SHORT else "full",
            "config": {
                "durationS": DURATION_S, "seed": SEED,
                "brokers": NUM_BROKERS, "servers": NUM_SERVERS,
                "ha": True, "minion": True,
                "targetQps": qps, "threads": THREADS,
                "offlineRows": ROWS,
                "ingestRowsPerS": INGEST_ROWS_PER_S,
                "upsertKeyspace": UPSERT_KEYSPACE,
                "mix": dict(MIX),
            },
            "chaos": coordinator.report(),
            "slo": {
                "perClass": tracker.snapshot(),
                "p99BoundsMs": P99_BOUNDS_MS,
                "unflaggedErrors": tracker.unflagged_total(),
                "unflaggedExamples": tracker.unflagged_examples,
                "violations": tracker.violations(),
            },
            "load": {
                "issued": driver.issued,
                "missedSlots": driver.missed_slots,
                "transportErrors": driver.transport_errors,
                "ingestPublished": ingest.published,
            },
            "leakGauges": {
                name: {"verdict": v.to_json(),
                       "series": sampler.series[name].series()}
                for name, v in verdicts.items()
            },
            "gates": {"passed": not failures, "failures": failures},
            "wallClockS": round(time.time() - t_start, 1),
        }
        with open(ARTIFACT, "w") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
        print(f"artifact -> {ARTIFACT}", file=sys.stderr, flush=True)
        if failures:
            print("SOAK GATE FAILURES:", file=sys.stderr)
            for fmsg in failures:
                print(f"  - {fmsg}", file=sys.stderr)
            rc = 1
        else:
            print("SOAK GATES GREEN", file=sys.stderr)
            rc = 0
    finally:
        if driver is not None:
            driver.stop_flag.set()
        if ingest is not None:
            ingest.stop_flag.set()
        sampler.stop_flag.set()
        cluster.stop()
        try:
            publisher.close()
        except Exception:  # noqa: BLE001
            pass
        topic_srv.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
