"""Broker reduce: merge per-server blocks → final BrokerResponse.

Parity: pinot-core/.../query/reduce/BrokerReduceService.java:72-524 —
selection merge, aggregation merge + extractFinalResult, group-by top-N per
function, HAVING post-filter — and CombineService for the two-block case.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      HavingNode)
from pinot_tpu.common.response import (AggregationResult, BrokerResponse,
                                       SelectionResults, exception_entry)
from pinot_tpu.query.aggregation import AggregationFunction, make_functions
from pinot_tpu.query.blocks import IntermediateResultsBlock
from pinot_tpu.query.combine import (combine_blocks, group_map_of,
                                     np_foldable, sortable_desc_key)


class BrokerReduceService:
    def reduce(self, request: BrokerRequest,
               blocks: List[IntermediateResultsBlock],
               num_servers_queried: int = 1,
               num_servers_responded: int = 1) -> BrokerResponse:
        merged = combine_blocks(request, list(blocks))
        resp = BrokerResponse()
        stats = merged.stats
        resp.num_docs_scanned = stats.num_docs_scanned
        resp.num_entries_scanned_in_filter = \
            stats.num_entries_scanned_in_filter
        resp.num_entries_scanned_post_filter = \
            stats.num_entries_scanned_post_filter
        resp.num_segments_processed = stats.num_segments_processed
        resp.num_segments_matched = stats.num_segments_matched
        resp.num_groups_limit_reached = stats.num_groups_limit_reached
        resp.total_docs = stats.total_docs
        resp.num_consuming_segments_queried = \
            stats.num_consuming_segments_processed
        resp.min_consuming_freshness_time_ms = \
            stats.min_consuming_freshness_ms
        resp.num_servers_queried = num_servers_queried
        resp.num_servers_responded = num_servers_responded
        # structured degradation: every per-segment/server exception
        # string carries errorCode + machine cause so clients and the
        # soak's SLO gate never have to string-match message text
        resp.exceptions = [exception_entry(e) for e in merged.exceptions]

        if request.is_group_by:
            self._reduce_group_by(request, merged, resp)
        elif request.is_aggregation:
            functions = make_functions(request.aggregations)
            inters = merged.agg_intermediates or [None] * len(functions)
            resp.aggregation_results = [
                AggregationResult(function=f.result_name,
                                  value=_final_str(f.extract_final(x)))
                for f, x in zip(functions, inters)]
        if request.is_selection:
            sel = request.selection
            columns = merged.selection_columns or sel.columns
            if merged.selection_cols is not None:
                # columnar payload: slice the window first, materialize
                # row lists only for the ≤ size emitted rows
                cols = [c[sel.offset: sel.offset + sel.size]
                        for c in merged.selection_cols]
                rows = list(zip(*[c.tolist()  # tpulint: disable=host-sync -- numpy host array, not a device value
                                  if isinstance(c, np.ndarray) else c
                                  for c in cols])) if cols else []
            else:
                rows = merged.selection_rows or []
                rows = rows[sel.offset: sel.offset + sel.size]
            n = merged.selection_display_cols
            if n is not None and n < len(columns):
                columns = columns[:n]
                rows = [row[:n] for row in rows]
            resp.selection_results = SelectionResults(
                columns=columns,
                results=[[_json_val(v) for v in row] for row in rows])
        return resp

    def _reduce_group_by(self, request: BrokerRequest,
                         merged: IntermediateResultsBlock,
                         resp: BrokerResponse) -> None:
        functions = make_functions(request.aggregations)
        if merged.group_cols is not None and request.having is None and \
                np_foldable(functions) and \
                all(isinstance(c, np.ndarray) and c.dtype.kind in "if"
                    for c in merged.group_cols[1]):
            self._reduce_group_cols(request, merged, resp, functions)
            return
        group_map = group_map_of(merged) or {}
        # final values per group per function
        finals: Dict[Tuple, List] = {
            key: [f.extract_final(x) for f, x in zip(functions, inters)]
            for key, inters in group_map.items()}
        if request.having is not None:
            finals = {k: v for k, v in finals.items()
                      if _eval_having(request.having, functions, v)}
        top_n = request.group_by.top_n
        results = []
        for fi, f in enumerate(functions):
            ordered = sorted(
                finals.items(),
                key=lambda kv: f.sortable_final(group_map[kv[0]][fi],
                                                final=kv[1][fi]),
                reverse=True)[:top_n]
            results.append(AggregationResult(
                function=f.result_name,
                group_by_columns=list(request.group_by.columns),
                group_by_result=[
                    {"group": [_json_val(g) for g in key], "value":
                     _final_str(vals[fi])}
                    for key, vals in ordered]))
        resp.aggregation_results = results

    def _reduce_group_cols(self, request: BrokerRequest,
                           merged: IntermediateResultsBlock,
                           resp: BrokerResponse,
                           functions: List[AggregationFunction]) -> None:
        """Vectorized finals for columnar group payloads: top-N per
        function via ONE stable argsort over the intermediate column —
        no per-group tuple keys, no python sort lambda per row. Bit
        parity with the row path: stable argsort of the negated values
        IS sorted(reverse=True) over first-occurrence group order, and
        per-cell finals go through the same extract_final/_fmt."""
        key_cols, inter_cols = merged.group_cols
        top_n = request.group_by.top_n
        results = []
        for fi, f in enumerate(functions):
            vals = inter_cols[fi]
            # sortable_desc_key reproduces sortable_final's comparison
            # semantics (exact int for COUNT, float for the rest), so
            # top-N ties land exactly where the row oracle's do
            order = np.argsort(sortable_desc_key(f, vals),
                               kind="stable")[:top_n]
            group_by_result = []
            for i in order:
                key = [_json_val(c[i]) if isinstance(c, np.ndarray)
                       else c[i] for c in key_cols]
                group_by_result.append(
                    {"group": key,
                     "value": _final_str(f.extract_final(
                         _json_val(vals[i])))})
            results.append(AggregationResult(
                function=f.result_name,
                group_by_columns=list(request.group_by.columns),
                group_by_result=group_by_result))
        resp.aggregation_results = results


def _eval_having(node: HavingNode, functions: List[AggregationFunction],
                 finals: List) -> bool:
    if node.operator == FilterOperator.AND:
        return all(_eval_having(c, functions, finals) for c in node.children)
    if node.operator == FilterOperator.OR:
        return any(_eval_having(c, functions, finals) for c in node.children)
    # leaf: find the function index matching the agg call
    idx = None
    for i, f in enumerate(functions):
        if f.name == node.agg.function_name.upper() and \
                f.column == node.agg.column:
            idx = i
            break
    if idx is None:
        raise ValueError(
            f"HAVING references {node.agg.call} not present in SELECT")
    v = finals[idx]
    if not isinstance(v, (int, float)):
        raise ValueError("HAVING on non-numeric aggregation result")
    if node.operator == FilterOperator.EQUALITY:
        return v == float(node.values[0])
    if node.operator == FilterOperator.NOT:
        return v != float(node.values[0])
    if node.operator == FilterOperator.IN:
        return any(v == float(x) for x in node.values)
    if node.operator == FilterOperator.RANGE:
        ok = True
        if node.lower is not None:
            lo = float(node.lower)
            ok &= (v >= lo) if node.lower_inclusive else (v > lo)
        if node.upper is not None:
            hi = float(node.upper)
            ok &= (v <= hi) if node.upper_inclusive else (v < hi)
        return ok
    raise ValueError(f"unsupported HAVING operator {node.operator}")


def _final_str(v):
    from pinot_tpu.common.response import _fmt
    return _fmt(v)


def _json_val(v):
    import numpy as np
    if isinstance(v, np.generic):
        return v.item()  # tpulint: disable=host-sync -- np.generic scalar (broker-side reduce is all-numpy)
    if isinstance(v, bytes):
        return v.hex()
    return v
