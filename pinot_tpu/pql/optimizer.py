"""Broker-side filter-tree rewrites.

Parity: pinot-broker/.../requesthandler/
{FlattenNestedPredicatesFilterQueryTreeOptimizer,
MultipleOrEqualitiesToInClauseFilterQueryTreeOptimizer,
RangeMergeOptimizer}.java — flatten nested AND/OR, collapse OR of equalities
on one column into IN, and intersect ANDed ranges on one column.
"""
from __future__ import annotations

from typing import List, Optional

from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      FilterQueryTree)


class BrokerRequestOptimizer:
    def optimize(self, request: BrokerRequest) -> BrokerRequest:
        if request.filter is not None:
            f = flatten(request.filter)
            f = or_eq_to_in(f)
            f = merge_ranges(f)
            request.filter = f
        return request


def flatten(node: FilterQueryTree) -> FilterQueryTree:
    """AND(AND(a,b),c) → AND(a,b,c); same for OR; unwrap single-child nodes."""
    if node.is_leaf():
        return node
    children = [flatten(c) for c in node.children]
    out: List[FilterQueryTree] = []
    for c in children:
        if not c.is_leaf() and c.operator == node.operator:
            out.extend(c.children)
        else:
            out.append(c)
    if len(out) == 1:
        return out[0]
    return FilterQueryTree(node.operator, children=out)


def or_eq_to_in(node: FilterQueryTree) -> FilterQueryTree:
    """OR(col=a, col=b, col IN (c)) → col IN (a,b,c)."""
    if node.is_leaf():
        return node
    children = [or_eq_to_in(c) for c in node.children]
    if node.operator != FilterOperator.OR:
        return FilterQueryTree(node.operator, children=children)
    by_col = {}
    rest: List[FilterQueryTree] = []
    for c in children:
        if c.is_leaf() and c.operator in (FilterOperator.EQUALITY,
                                          FilterOperator.IN):
            by_col.setdefault(c.column, []).extend(c.values)
        else:
            rest.append(c)
    merged: List[FilterQueryTree] = []
    for col, vals in by_col.items():
        uniq = list(dict.fromkeys(vals))
        if len(uniq) == 1:
            merged.append(FilterQueryTree(FilterOperator.EQUALITY, column=col,
                                          values=uniq))
        else:
            merged.append(FilterQueryTree(FilterOperator.IN, column=col,
                                          values=uniq))
    out = merged + rest
    if len(out) == 1:
        return out[0]
    return FilterQueryTree(FilterOperator.OR, children=out)


def merge_ranges(node: FilterQueryTree) -> FilterQueryTree:
    """AND(col>a, col<=b) → single RANGE(a, b]. Numeric bounds only."""
    if node.is_leaf():
        return node
    children = [merge_ranges(c) for c in node.children]
    if node.operator != FilterOperator.AND:
        return FilterQueryTree(node.operator, children=children)
    ranges = {}
    rest: List[FilterQueryTree] = []
    for c in children:
        if c.is_leaf() and c.operator == FilterOperator.RANGE and \
                _is_numeric_range(c):
            if c.column in ranges:
                ranges[c.column] = _intersect(ranges[c.column], c)
            else:
                ranges[c.column] = c
        else:
            rest.append(c)
    out = list(ranges.values()) + rest
    if len(out) == 1:
        return out[0]
    return FilterQueryTree(FilterOperator.AND, children=out)


def _is_numeric_range(n: FilterQueryTree) -> bool:
    for v in (n.lower, n.upper):
        if v is None:
            continue
        try:
            float(v)
        except ValueError:
            return False
    return True


def _intersect(a: FilterQueryTree, b: FilterQueryTree) -> FilterQueryTree:
    lower, lower_inc = a.lower, a.lower_inclusive
    if b.lower is not None:
        if lower is None or float(b.lower) > float(lower) or \
                (float(b.lower) == float(lower) and not b.lower_inclusive):
            lower, lower_inc = b.lower, b.lower_inclusive
    upper, upper_inc = a.upper, a.upper_inclusive
    if b.upper is not None:
        if upper is None or float(b.upper) < float(upper) or \
                (float(b.upper) == float(upper) and not b.upper_inclusive):
            upper, upper_inc = b.upper, b.upper_inclusive
    return FilterQueryTree(FilterOperator.RANGE, column=a.column,
                           lower=lower, upper=upper,
                           lower_inclusive=lower_inc,
                           upper_inclusive=upper_inc)
