"""Broker access control SPI.

Parity: pinot-broker/.../api/AccessControl.java + AccessControlFactory
(BaseBrokerRequestHandler.java:159 calls hasAccess(requesterIdentity,
brokerRequest) before routing; the default factory returns an allow-all
implementation). Identity here is whatever the transport layer attaches —
the HTTP API passes a RequesterIdentity with the client address and any
auth token; in-process callers pass None.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.common.table_name import raw_table


@dataclasses.dataclass
class RequesterIdentity:
    client_address: str = ""
    token: Optional[str] = None


class AccessControl:
    """SPI: decide whether `identity` may run `request`."""

    def has_access(self, identity: Optional[RequesterIdentity],
                   request: BrokerRequest) -> bool:
        raise NotImplementedError

    def allow_workload(self, identity: Optional[RequesterIdentity],
                       workload: str) -> bool:
        """Whether `identity` may tag its queries OPTION(workload=...).

        The tag drives per-tenant quota debit, scheduler grouping and
        admission fair-share, so an unchecked tag lets one principal
        spend another tenant's quota (or inflate its fair-share count).
        Default: allowed — tags are cooperative scheduling hints, as in
        the reference's workloadName option. Deployments that hand
        per-tenant quotas to mutually-untrusting clients should
        override this to bind tags to authenticated principals."""
        return True


class AllowAllAccessControl(AccessControl):
    """The reference's default: everything is allowed."""

    def has_access(self, identity, request) -> bool:
        return True


class TableAclAccessControl(AccessControl):
    """Static per-table token ACL: a table not in the map is open; a table
    in the map requires one of its listed tokens."""

    def __init__(self, table_tokens: Dict[str, list]):
        self.table_tokens = {raw_table(k): set(v)
                             for k, v in table_tokens.items()}

    def has_access(self, identity, request) -> bool:
        allowed = self.table_tokens.get(raw_table(request.table_name))
        if allowed is None:
            return True
        return identity is not None and identity.token in allowed


class AccessControlFactory:
    """Parity: AccessControlFactory.create (class-name keyed registry)."""

    _registry: Dict[str, Callable[..., AccessControl]] = {
        "allowall": AllowAllAccessControl,
        "tableacl": TableAclAccessControl,
    }

    @classmethod
    def register(cls, name: str,
                 ctor: Callable[..., AccessControl]) -> None:
        cls._registry[name.lower()] = ctor

    @classmethod
    def create(cls, name: str = "allowall", **kwargs) -> AccessControl:
        ctor = cls._registry.get(name.lower())
        if ctor is None:
            raise ValueError(f"unknown access control: {name}")
        return ctor(**kwargs)
