"""Segment format conversion: v1 (file-per-index) ↔ v3 (single-file).

Parity: core/segment/store/ (SegmentVersion.java:21-24,
SingleFileIndexDirectory, SegmentV1V2ToV3FormatConverter). v3 packs
every index member into ONE `columns.psf` container; `metadata.json`
stays outside as in the reference (metadata.properties survives the
conversion in place). DEFLATE per member doubles as the chunk
compression layer (ChunkCompressorFactory PASS_THROUGH | compressed).
"""
from __future__ import annotations

import json
import os
import zipfile

from pinot_tpu.segment import format as fmt


class SegmentFormatConverter:
    """Parity: SegmentFormatConverter SPI + the v1→v3 impl."""

    @staticmethod
    def v1_to_v3(seg_dir: str, compress: bool = True) -> str:
        """Pack all index members into columns.psf (in place)."""
        psf = os.path.join(seg_dir, fmt.COLUMNS_PSF)
        if os.path.exists(psf):
            return psf
        members = [n for n in sorted(os.listdir(seg_dir))
                   if n != fmt.METADATA_FILE and
                   not os.path.isdir(os.path.join(seg_dir, n))]
        comp = zipfile.ZIP_DEFLATED if compress else zipfile.ZIP_STORED
        tmp = psf + ".tmp"
        with zipfile.ZipFile(tmp, "w", compression=comp) as z:
            for name in members:
                z.write(os.path.join(seg_dir, name), arcname=name)
        os.replace(tmp, psf)             # container is the commit marker
        for name in members:
            os.remove(os.path.join(seg_dir, name))
        _set_version(seg_dir, fmt.SEGMENT_VERSION_V3)
        return psf

    @staticmethod
    def v3_to_v1(seg_dir: str) -> None:
        """Unpack columns.psf back into file-per-index members."""
        psf = os.path.join(seg_dir, fmt.COLUMNS_PSF)
        if not os.path.exists(psf):
            return
        with zipfile.ZipFile(psf, "r") as z:
            for name in z.namelist():
                if name.startswith("..") or os.path.isabs(name) or \
                        "/" in name or "\\" in name:
                    raise ValueError(f"suspicious member name {name!r}")
                with z.open(name) as src, \
                        open(os.path.join(seg_dir, name), "wb") as dst:
                    dst.write(src.read())
        os.remove(psf)
        _set_version(seg_dir, fmt.SEGMENT_VERSION)


def _set_version(seg_dir: str, version: str) -> None:
    path = os.path.join(seg_dir, fmt.METADATA_FILE)
    with open(path) as f:
        meta = json.load(f)
    meta["segmentVersion"] = version
    with open(path, "w") as f:
        json.dump(meta, f, indent=1, default=str)
