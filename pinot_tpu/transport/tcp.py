"""Data-plane transport: length-framed TCP between broker and servers.

Parity: the reference's Netty data plane — core/transport/ServerChannels.java
(one channel per server, LengthFieldBasedFrameDecoder framing) and
pinot-transport NettyServer — rebuilt on asyncio. Frames are
[4-byte big-endian length][payload]; requests carry a serialized
InstanceRequest, responses carry DataTable bytes (request correlation via
the requestId metadata entry, as in the reference).
"""
from __future__ import annotations

import asyncio
import struct
import threading
from typing import Callable, Dict, Optional

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    n = _LEN.unpack(header)[0]
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return await reader.readexactly(n)


def write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)) + payload)


class QueryServer:
    """Accepts framed requests, hands payloads to a handler, writes replies.

    handler: bytes -> bytes, called on the event loop's default executor so
    device work never blocks the accept loop (parity: Netty worker threads
    handing off to the QueryScheduler).
    """

    def __init__(self, host: str, port: int,
                 handler: Callable[[bytes], bytes]):
        self.host = host
        self.port = port
        self.handler = handler
        self._server: Optional[asyncio.AbstractServer] = None
        self._connections: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # force-close persistent client connections so wait_closed()
            # doesn't wait for brokers that keep their channels open
            for writer in list(self._connections):
                writer.close()
            await self._server.wait_closed()
            self._server = None

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()
        self._connections.add(writer)
        try:
            while True:
                payload = await read_frame(reader)
                reply = await loop.run_in_executor(None, self.handler,
                                                   payload)
                write_frame(writer, reply)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                ConnectionAbortedError):
            pass
        finally:
            self._connections.discard(writer)
            writer.close()


class ServerConnection:
    """One persistent framed connection to a server (broker side).

    Concurrent senders are serialized per connection; responses come back
    in order (the server processes frames sequentially per connection),
    mirroring the single-channel-per-server model of ServerChannels.
    """

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def _ensure(self) -> None:
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port)

    async def request(self, payload: bytes,
                      timeout: Optional[float] = None) -> bytes:
        async with self._lock:
            await self._ensure()
            try:
                write_frame(self._writer, payload)
                await self._writer.drain()
                return await asyncio.wait_for(read_frame(self._reader),
                                              timeout)
            except BaseException:
                # a timeout/cancel mid-frame desynchronizes the stream (a
                # late response would be read as the NEXT query's reply) —
                # drop the connection so the next request reconnects clean
                self._writer.close()
                self._writer = None
                self._reader = None
                raise

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread (for sync call sites)."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self.loop.run_forever,
                                        daemon=True)
        self._thread.start()

    def run(self, coro, timeout: Optional[float] = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def stop(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)
