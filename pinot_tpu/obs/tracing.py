"""Hierarchical distributed tracing: trace-id/span-id spans with parent
links, carried explicitly through the query path.

Parity: pinot-core/.../util/trace/TraceContext.java (request-scoped
trace tree enabled by the query's `trace` option, serialized into
response metadata) upgraded to the Dapper span model (PAPERS.md): every
span carries `spanId` + `parentId`, the broker stamps its dispatch
span's id into the `InstanceRequest`, the server roots its spans under
that id, and the broker reduce step merges every participant's span
list into ONE tree with correct cross-process parent links.

Design notes:

- Spans are plain dicts ``{"name", "ms", "spanId", "parentId"}`` (+
  optional ``"attrs"``) appended to a per-request list under a lock —
  the flat list stays cheap to serialize into DataTable metadata, and
  the tree is assembled once, at the broker, by `build_trace_tree`.
- Parenting is a per-THREAD stack inside the context: the broker path
  is async and the server path fans segments onto a worker pool, so a
  single global stack would interleave spans across threads. Workers
  seed their stack with `attach(parent_id)`.
- `NoopTraceContext` keeps the disabled path allocation- and
  lock-free: `make_trace_context(False)` must add no measurable
  per-query overhead (the acceptance bar for trace=false).

Wire format (DataTable metadata "traceInfo" / InstanceRequest):
``{"traceId": ..., "rootSpanId": ..., "spans": [...]}``; the legacy
flat ``[{"name", "ms"}, ...]`` list still parses (version skew).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional


def _new_id() -> str:
    """A 12-hex-char id, unique enough for one trace's span namespace."""
    return os.urandom(6).hex()


class TraceContext:
    """One request's span collection (broker- or server-side half)."""

    enabled = True

    def __init__(self, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 root_name: str = "query"):
        self.trace_id = trace_id or _new_id()
        # span ids are prefix+counter: one urandom call per context, not
        # per span (spans are created on the hot path)
        self._prefix = _new_id()
        self._counter = itertools.count(1)
        self.spans: List[Dict[str, object]] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.root_span_id = self._next_id()
        self._root = {"name": root_name, "ms": 0.0,
                      "spanId": self.root_span_id,
                      "parentId": parent_span_id}
        self.spans.append(self._root)
        self._t0 = time.perf_counter()

    def _next_id(self) -> str:
        return f"{self._prefix}.{next(self._counter)}"

    # -- parenting stack (per thread) ---------------------------------------
    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def current_span_id(self) -> Optional[str]:
        s = self._stack()
        return s[-1] if s else self.root_span_id

    @contextmanager
    def attach(self, parent_id: Optional[str]):
        """Seed THIS thread's parent stack (worker-pool fan-out: the
        submitting thread captures a span id, the worker attaches it)."""
        s = self._stack()
        s.append(parent_id or self.root_span_id)
        try:
            yield
        finally:
            s.pop()

    # -- span creation ------------------------------------------------------
    def record(self, name: str, ms: float,
               parent_id: Optional[str] = None, **attrs) -> dict:
        """Append a completed span (for durations measured externally,
        e.g. scheduler queue-wait)."""
        span: Dict[str, object] = {
            "name": name, "ms": round(ms, 3), "spanId": self._next_id(),
            "parentId": parent_id or self.current_span_id()}
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            self.spans.append(span)
        return span

    @contextmanager
    def span(self, name: str, parent_id: Optional[str] = None, **attrs):
        """Open a span; children created on this thread nest under it."""
        s: Dict[str, object] = {
            "name": name, "ms": 0.0, "spanId": self._next_id(),
            "parentId": parent_id or self.current_span_id()}
        if attrs:
            s["attrs"] = attrs
        with self._lock:
            self.spans.append(s)
        stack = self._stack()
        stack.append(s["spanId"])
        t0 = time.perf_counter()
        try:
            yield s
        finally:
            s["ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            # pop by value: interleaved async spans on one thread may
            # close out of LIFO order
            if stack and stack[-1] == s["spanId"]:
                stack.pop()
            else:
                try:
                    stack.remove(s["spanId"])
                except ValueError:
                    pass

    def finish_root(self) -> None:
        self._root["ms"] = round((time.perf_counter() - self._t0) * 1e3, 3)

    # -- (de)serialization --------------------------------------------------
    def to_list(self) -> List[Dict[str, object]]:
        with self._lock:
            return list(self.spans)

    def to_json_str(self) -> str:
        self.finish_root()
        return json.dumps({"traceId": self.trace_id,
                           "rootSpanId": self.root_span_id,
                           "spans": self.to_list()})

    @staticmethod
    def from_json_str(s: str) -> "TraceContext":
        d = json.loads(s)
        if isinstance(d, list):
            # legacy flat phase list from a version-skewed peer
            t = TraceContext()
            t.spans = [dict(x) for x in d]
            return t
        t = TraceContext(trace_id=d.get("traceId"))
        t.spans = [dict(x) for x in d.get("spans", [])]
        if d.get("rootSpanId"):
            t.root_span_id = d["rootSpanId"]
        return t


class NoopTraceContext(TraceContext):
    """Zero-cost stand-in when tracing is disabled — no ids, no locks,
    no appends. `bool(ctx.enabled)` is the cheap branch for callers."""

    enabled = False

    def __init__(self, *_a, **_k):  # noqa: D401 — no state at all
        self.trace_id = None
        self.root_span_id = None
        self.spans = []

    def current_span_id(self) -> Optional[str]:
        return None

    @contextmanager
    def attach(self, parent_id: Optional[str]):
        yield

    def record(self, name: str, ms: float,
               parent_id: Optional[str] = None, **attrs) -> dict:
        return {}

    @contextmanager
    def span(self, name: str, parent_id: Optional[str] = None, **attrs):
        yield None

    def finish_root(self) -> None:
        pass

    def to_list(self) -> List[Dict[str, object]]:
        return []

    def to_json_str(self) -> str:
        return "{}"


def make_trace_context(enabled: bool, trace_id: Optional[str] = None,
                       parent_span_id: Optional[str] = None,
                       root_name: str = "query") -> TraceContext:
    if not enabled:
        return NoopTraceContext()
    return TraceContext(trace_id=trace_id, parent_span_id=parent_span_id,
                        root_name=root_name)


def build_trace_tree(spans: List[Dict[str, object]],
                     trace_id: Optional[str] = None) -> Optional[dict]:
    """Assemble one tree from every participant's flat span list.

    Nodes keep their source dict's fields plus ``children``. Spans whose
    parent is unknown (skewed peer, lost dispatch span) attach under the
    root rather than vanishing — a trace must degrade, not lie by
    omission. Returns None when there are no spans at all.
    """
    if not spans:
        return None
    nodes: Dict[str, dict] = {}
    order: List[dict] = []
    for s in spans:
        node = dict(s)
        node["children"] = []
        sid = node.get("spanId")
        if sid is not None:
            nodes[str(sid)] = node
        order.append(node)
    true_roots: List[dict] = []
    orphans: List[dict] = []
    for node in order:
        pid = node.get("parentId")
        parent = nodes.get(str(pid)) if pid is not None else None
        if parent is not None and parent is not node:
            parent["children"].append(node)
        elif pid is None:
            true_roots.append(node)
        else:
            orphans.append(node)
    if len(true_roots) == 1:
        tree = true_roots[0]
        tree["children"].extend(orphans)
    else:
        # zero or several parentless spans: synthesize one wrapper
        roots = true_roots + orphans
        tree = {"name": "trace", "ms": sum(float(r.get("ms", 0))
                                           for r in roots),
                "spanId": None, "parentId": None, "children": roots}
    if trace_id is not None:
        tree["traceId"] = trace_id
    return tree
