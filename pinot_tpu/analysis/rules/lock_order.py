"""lock-order / lock-blocking: the interprocedural lock analyzer.

The serving plane holds locks on scheduler worker threads, consumer
threads, watcher threads and the asyncio loop thread at once, so the two
hazards that matter are the two that lexical rules can't see:

- **lock-order** — an acquisition CYCLE in the per-module lock graph
  (lock B taken while A is held in one code path, A while B is held in
  another). Two threads entering the two paths concurrently deadlock.
  This is the kernel lockdep model: record the acquisition ORDER the
  code exhibits, fail on a cycle, never wait for the deadlock to happen
  in production. Edges follow calls one level interprocedurally
  (`with self._lock: self._flush()` charges _flush's acquisitions to
  the held set).

- **lock-blocking** — a threading lock held across a blocking call
  (`await`, `Future.result()`, `time.sleep`, socket/file IO, spawned
  subprocesses, `jax.device_get`). The holder parks on IO while every
  other thread convoys at the lock; under asyncio an `await` with a
  threading lock held parks it for a whole scheduling round-trip.

Both rules see `with`-statements (incl. multi-item) and explicit
`acquire()`/`release()`; lock identity is `Class.attr` for instance
locks and the bare global name for module-level locks.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from pinot_tpu.analysis import astutil, callgraph
from pinot_tpu.analysis.core import Finding, Rule, register


def _acquisitions(fn: ast.AST, self_locks: Set[str],
                  global_locks: Set[str]) -> List[callgraph.Site]:
    return [s for s in callgraph.walk_with_locks(fn, self_locks,
                                                 global_locks)
            if s.acquires is not None]


def _blocking_sites(fn: ast.AST, aliases) -> List[Tuple[ast.AST, str]]:
    """(node, kind) for every blocking call/await shallow in `fn`."""
    out: List[Tuple[ast.AST, str]] = []
    for node in astutil.walk_shallow(fn):
        if isinstance(node, ast.Await):
            out.append((node, "await"))
            continue
        kind = callgraph.blocking_kind(node, aliases)
        if kind is not None:
            out.append((node, kind))
    return out


class _ModuleLockAnalysis:
    """One file's lock graph + held-across-blocking sites."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.global_locks = callgraph.module_locks(ctx.tree, ctx.aliases)
        # edge (held_lock -> acquired_lock) → example (line, where)
        self.edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
        # (node, held_lock, kind, where) blocking-under-lock hazards
        self.blocked: List[Tuple[ast.AST, str, str, str]] = []
        for model in callgraph.iter_class_models(ctx.tree, ctx.aliases):
            self._scan_class(model)
        self._scan_module_functions()

    # -- scanning -----------------------------------------------------------
    def _qualify(self, cls_name: str, lock: str) -> str:
        return f"{cls_name}.{lock[5:]}" if lock.startswith("self.") \
            else lock

    def _scan_class(self, model: callgraph.ClassModel) -> None:
        cls = model.node.name
        for mname, m in model.methods.items():
            where = f"{cls}.{mname}"
            sites = callgraph.walk_with_locks(m, model.lock_attrs,
                                              self.global_locks)
            for site in sites:
                held = [self._qualify(cls, h) for h in site.held]
                if site.acquires is not None:
                    acq = self._qualify(cls, site.acquires)
                    for h in held:
                        if h != acq:
                            self.edges.setdefault(
                                (h, acq), (site.node.lineno, where))
                    continue
                if not held:
                    # one-level interprocedural: a self-call made while
                    # NO lock is held contributes nothing
                    continue
                kind = None
                if isinstance(site.node, ast.Await):
                    kind = "await"
                else:
                    kind = callgraph.blocking_kind(site.node,
                                                   self.ctx.aliases)
                if kind is not None:
                    for h in held:
                        self.blocked.append((site.node, h, kind, where))
                    continue
                # follow a held self-call one level down
                if isinstance(site.node, ast.Call):
                    callee = model.resolve_call(site.node)
                    if callee is None:
                        continue
                    cname = f"{cls}.{callee.name}"
                    for acq_site in _acquisitions(callee,
                                                  model.lock_attrs,
                                                  self.global_locks):
                        acq = self._qualify(cls, acq_site.acquires)
                        for h in held:
                            if h != acq:
                                self.edges.setdefault(
                                    (h, acq),
                                    (site.node.lineno,
                                     f"{where} → {cname}"))
                    # anchor at the CALLEE's blocking line so one
                    # suppression there covers every held call site
                    for node, kind in _blocking_sites(callee,
                                                      self.ctx.aliases):
                        for h in held:
                            self.blocked.append(
                                (node, h, kind,
                                 f"{where} → {cname}"))

    def _scan_module_functions(self) -> None:
        if not self.global_locks:
            return
        for fn in self.ctx.tree.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            sites = callgraph.walk_with_locks(fn, set(), self.global_locks)
            for site in sites:
                if site.acquires is not None:
                    for h in site.held:
                        if h != site.acquires:
                            self.edges.setdefault(
                                (h, site.acquires),
                                (site.node.lineno, fn.name))
                    continue
                if not site.held:
                    continue
                kind = "await" if isinstance(site.node, ast.Await) else \
                    callgraph.blocking_kind(site.node, self.ctx.aliases)
                if kind is not None:
                    for h in site.held:
                        self.blocked.append((site.node, h, kind, fn.name))

    # -- cycle detection ----------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Distinct simple cycles in the acquisition graph, each
        reported once in canonical rotation (start at min lock)."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        seen: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):]
                    i = cyc.index(min(cyc))
                    canon = tuple(cyc[i:] + cyc[:i])
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(canon))
                    continue
                path.append(nxt)
                on_path.add(nxt)
                dfs(nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

        for start in sorted(graph):
            dfs(start, [start], {start})
        return out


@register
class LockOrderRule(Rule):
    id = "lock-order"
    description = ("cycles in the per-module lock acquisition graph "
                   "(potential deadlocks), lockdep-style")

    def check(self, ctx) -> Iterator[Finding]:
        analysis = getattr(ctx, "_lock_analysis", None)
        if analysis is None:
            analysis = _ModuleLockAnalysis(ctx)
            ctx._lock_analysis = analysis
        for cyc in analysis.cycles():
            ring = cyc + [cyc[0]]
            hops = []
            line = 1
            for a, b in zip(ring, ring[1:]):
                ln, where = analysis.edges[(a, b)]
                hops.append(f"{a} → {b} (`{where}`)")
                line = ln
            node = ast.Module(body=[], type_ignores=[])
            node.lineno = line
            yield ctx.finding(
                self.id, node,
                "potential deadlock: lock acquisition cycle "
                + "; ".join(hops)
                + " — impose one global order or collapse the locks")


@register
class LockBlockingRule(Rule):
    id = "lock-blocking"
    description = ("threading lock held across a blocking call (await, "
                   "Future.result, sleep, socket/file IO, device_get)")

    def check(self, ctx) -> Iterator[Finding]:
        analysis = getattr(ctx, "_lock_analysis", None)
        if analysis is None:
            analysis = _ModuleLockAnalysis(ctx)
            ctx._lock_analysis = analysis
        emitted = set()
        for node, lock, kind, where in analysis.blocked:
            key = (getattr(node, "lineno", 1), lock, kind)
            if key in emitted:
                continue
            emitted.add(key)
            yield ctx.finding(
                self.id, node,
                f"`{where}` holds {lock} across {kind} — the blocked "
                "holder convoys every other thread at this lock; move "
                "the blocking work outside, or state why the hold is "
                "required in a suppression reason")
