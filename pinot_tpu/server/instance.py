"""Server process wiring: data manager + scheduler + executor + transport.

Parity: pinot-server — ServerInstance/ServerBuilder (ServerInstance.java:43:
InstanceDataManager + QueryExecutor + QueryScheduler + NettyServer) and
ScheduledRequestHandler.java:40-66 (bytes → deserialize → schedule →
execute → DataTable bytes).
"""
from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

from pinot_tpu.common.datatable import (DataTable, RESULT_CACHE_HIT_KEY,
                                        amend_metadata_bytes)
from pinot_tpu.common.metrics import (MetricsRegistry, ServerGauge,
                                      ServerMeter, ServerQueryPhase,
                                      ServerTimer)
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.serde import instance_request_from_bytes
from pinot_tpu.server.admission import (AdmissionController,
                                        ServiceTimeEstimator,
                                        busy_datatable)
from pinot_tpu.server.data_manager import InstanceDataManager
from pinot_tpu.server.query_executor import InstanceQueryExecutor
from pinot_tpu.server.result_cache import (ServerResultCache, SingleFlight,
                                           segment_cache_states)
from pinot_tpu.server.scheduler import (BatchGroup, DispatchCoalescer,
                                        QueryScheduler,
                                        SchedulerOutOfCapacityError,
                                        make_scheduler)
from pinot_tpu.transport.tcp import EventLoopThread, QueryServer

#: batching admission window (ms) when neither the constructor nor
#: PINOT_TPU_BATCH_WINDOW_MS says otherwise; 0 disables coalescing
#: entirely (bit-exact pre-coalescer behavior)
DEFAULT_BATCH_WINDOW_MS = 2.0


class _BatchTicket:
    """One coalescer member: the request plus the future its caller is
    already awaiting; resolved by the group runner (or the abandon
    callback) exactly once."""

    __slots__ = ("request", "deser_ms", "future", "t_arrive")

    def __init__(self, request: InstanceRequest, deser_ms: float):
        self.request = request
        self.deser_ms = deser_ms
        self.future: Future = Future()
        self.t_arrive = time.perf_counter()


class ServerInstance:
    """One query server: hosts segments, answers InstanceRequests."""

    def __init__(self, instance_id: str = "server_0",
                 scheduler: str = "fcfs", num_workers: int = 4,
                 mesh=None, use_device: bool = True,
                 max_pending: Optional[int] = None,
                 result_cache_entries: int = 256,
                 device_bytes_budget: Optional[int] = None,
                 batch_window_ms: Optional[float] = None):
        self.instance_id = instance_id
        self.metrics = MetricsRegistry("server")
        from pinot_tpu.obs import residency
        residency.bind_registry(self.metrics)
        self.data_manager = InstanceDataManager()
        # tiered residency: this instance's segments demote HBM → host
        # → disk under the device byte budget (config `deviceBytesBudget`
        # or env PINOT_TPU_DEVICE_BYTES_BUDGET; unset = unbounded, the
        # pre-manager behavior). Per-instance manager: its entries and
        # hooks die with the instance, while admission reads the
        # PROCESS-global ledger so colocated instances see real pressure.
        from pinot_tpu.server.residency_manager import (
            ResidencyManager, budget_from_env, host_budget_from_env)
        self.residency = ResidencyManager(
            device_bytes_budget if device_bytes_budget is not None
            else budget_from_env(), host_budget_from_env())
        self.residency.bind_metrics(self.metrics)
        self.data_manager.add_removal_listener(self.residency.untrack)
        self.scheduler: QueryScheduler = make_scheduler(scheduler,
                                                        num_workers)
        self.executor = InstanceQueryExecutor(
            self.data_manager, mesh=mesh, use_device=use_device,
            metrics=self.metrics,
            segment_executor=self.scheduler.segment_pool,
            residency=self.residency)
        if self.executor.sharded is not None:
            # a demoted segment's stacked twin must drop with it, and
            # the (rebuildable) stack caches are the cheapest HBM to
            # reclaim under pressure
            self.residency.add_release_hook(
                self.executor.sharded.evict_segment)
            self.residency.add_pressure_hook(
                self.executor.sharded.evict_all)
        self.residency.add_pressure_hook(self._release_mutable_snapshots)
        # admission control + CRC-exact result cache (hits bypass the
        # admission queue — the degradation valve under overload)
        self.estimator = ServiceTimeEstimator(self.metrics)
        self.admission = AdmissionController(
            metrics=self.metrics, estimator=self.estimator,
            max_pending=max_pending if max_pending is not None
            else max(16, 16 * num_workers),
            num_workers=num_workers,
            backlog_fn=self.residency.promotion_backlog)
        self.result_cache = ServerResultCache(
            max_entries=result_cache_entries)
        # cold-cache dedup for IDENTICAL concurrent queries: the first
        # executes, the rest await its cache entry (bounded) — the
        # degenerate batch the coalescer never needs to see
        self.single_flight = SingleFlight()
        # cross-query dispatch coalescing: same-plan-shape queries that
        # overlap in flight share one (vmapped) kernel execution after
        # a short admission window (config `batchWindowMs` /
        # PINOT_TPU_BATCH_WINDOW_MS; <= 0 disables, restoring the
        # strictly per-query dispatch path)
        if batch_window_ms is None:
            batch_window_ms = float(os.environ.get(
                "PINOT_TPU_BATCH_WINDOW_MS", DEFAULT_BATCH_WINDOW_MS))
        self.batch_window_ms = float(batch_window_ms)
        self.coalescer: Optional[DispatchCoalescer] = None
        if self.batch_window_ms > 0:
            self.coalescer = DispatchCoalescer(
                self.batch_window_ms / 1e3,
                on_dispatch=self._on_batch_dispatch,
                on_bypass=self._on_batch_bypass)
        # exist at 0 from boot so dashboards see the series immediately
        self.metrics.meter(ServerMeter.BATCHED_DISPATCHES)
        self.metrics.meter(ServerMeter.BATCH_BYPASS)
        self.metrics.meter(ServerMeter.SINGLE_FLIGHT_WAITS)
        self.metrics.timer(ServerTimer.BATCH_OCCUPANCY)
        # exchange plane (multi-stage queries): published stage-1 blocks
        # served to peer servers over XCHG data-plane frames
        from pinot_tpu.query.stages.exchange import ExchangeManager
        self.exchange = ExchangeManager()
        # accepted workload tags (scheduler groups + fair-share keys
        # derive from them) — bounded, because the tag is CLIENT-chosen
        self._tenant_tags: set = set()
        # a replaced/removed segment can change results WITHOUT a CRC
        # change (segment reload re-processes the same artifact against
        # an evolved schema) — any swap clears the cache; swaps are
        # rare (reload, rebalance) so the coarse clear is cheap
        self.data_manager.add_removal_listener(
            lambda _name: self.result_cache.clear())
        self.metrics.gauge(ServerGauge.SEGMENT_COUNT).set_callable(
            self.data_manager.num_segments)
        self.metrics.meter(ServerMeter.QUERIES)   # exists at 0 from boot
        self._loop: Optional[EventLoopThread] = None
        self._server: Optional[QueryServer] = None
        self.port: Optional[int] = None
        # guards the start/stop lifecycle fields (_loop/_server/port):
        # an admin-triggered stop can race a late start on another thread
        self._lifecycle_lock = threading.Lock()

    def _release_mutable_snapshots(self) -> None:
        """Residency pressure hook: drop consuming segments' frozen
        device snapshots (rebuildable caches — in-flight queries keep
        their references; GC releases the lanes)."""
        for table in self.data_manager.table_names():
            tdm = self.data_manager.table(table)
            if tdm is None:
                continue
            sdms, _ = tdm.acquire_segments()
            try:
                for sdm in sdms:
                    release = getattr(sdm.segment,
                                      "release_device_snapshot", None)
                    if release is not None:
                        release()
            finally:
                for sdm in sdms:
                    tdm.release_segment(sdm)

    # -- request path ------------------------------------------------------
    def _deserialize(self, payload: bytes
                     ) -> Tuple[Optional[InstanceRequest], Optional[bytes],
                                float]:
        """(request, None, ms) on success, (None, error reply bytes, ms)
        on a malformed wire payload. The measured milliseconds become
        the query's requestDeserialization span."""
        t0 = time.perf_counter()
        try:
            request = instance_request_from_bytes(payload)
            err = None
        except Exception as e:  # noqa: BLE001 — malformed wire payload
            dt = DataTable()
            dt.exceptions.append(f"RequestDeserializationError: {e}")
            request, err = None, dt.to_bytes()
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.timer(
            ServerQueryPhase.REQUEST_DESERIALIZATION).update(ms)
        self.metrics.meter(ServerMeter.REQUEST_BYTES).mark(len(payload))
        return request, err, ms

    # scheduler groups and admission fair-share counters are permanent
    # once created, and the workload tag that keys them is CLIENT-chosen
    # — past this many distinct tags, new ones fall back to the
    # (config-bounded) per-table group instead of growing the maps and
    # the scheduler's per-pick scan without bound
    MAX_TENANT_TAGS = 256

    def _tenant(self, request: InstanceRequest) -> str:
        """Scheduler group / fair-share key: the broker-stamped tenant
        tag, or the table for untagged traffic (per-table isolation is
        the old behavior and the sensible default). Tags are namespaced
        (``w:``) so OPTION(workload=<table name>) can never join the
        untagged traffic's per-table group.

        Lookup only: a fresh tag's permanent slot is committed by
        ``_register_tenant`` once the request is actually ADMITTED —
        a flood of unique tags that all get shed must not burn the
        tag budget and lock later tenants out of isolation."""
        tag = request.workload
        if not tag:
            return request.query.table_name
        if tag not in self._tenant_tags and \
                len(self._tenant_tags) >= self.MAX_TENANT_TAGS:
            return request.query.table_name
        return f"w:{tag}"

    def _register_tenant(self, tenant: str) -> None:
        """Commit an admitted request's tag slot (no-op for the
        per-table fallback). set.add is atomic under the GIL; a racing
        duplicate add is idempotent and a transient cap overshoot in
        the admit window is harmless."""
        if tenant.startswith("w:"):
            self._tenant_tags.add(tenant[2:])

    # -- result cache -------------------------------------------------------
    def _cache_lookup(self, request: InstanceRequest):
        """→ (fingerprint, cached reply bytes or None, generation,
        full cache key or None). A hit is served WITHOUT touching the
        admission queue or the scheduler. The generation is captured
        BEFORE execution so a segment swap's clear() while the query
        runs invalidates its eventual store instead of racing it. The
        key comes back even on a miss — including the cold (empty)
        cache — because it doubles as the single-flight dedup key; a
        None key means the request is uncacheable (traced, mutable /
        CRC-less segments, missing segments)."""
        gen = self.result_cache.generation
        if request.enable_trace:
            return None, None, gen, None  # traced queries want real spans
        tdm = self.data_manager.table(request.query.table_name)
        if tdm is None:
            return None, None, gen, None
        acquired, missing = tdm.acquire_segments(request.search_segments)
        try:
            if missing:
                return None, None, gen, None
            states = segment_cache_states([s.segment for s in acquired])
        finally:
            for sdm in acquired:
                tdm.release_segment(sdm)
        if states is None:
            # mutable / CRC-less segment in the set
            return None, None, gen, None
        from pinot_tpu.query.fingerprint import query_fingerprint
        fp = query_fingerprint(request.query)
        key = ServerResultCache.key(request.query.table_name, fp, states)
        if len(self.result_cache) == 0:
            # empty-cache fast path: skip the entry probe (the states /
            # fingerprint above still feed the single-flight key)
            self.metrics.meter(ServerMeter.RESULT_CACHE_MISSES).mark()
            return fp, None, gen, key
        payload = self.result_cache.get(key)
        if payload is None:
            self.metrics.meter(ServerMeter.RESULT_CACHE_MISSES).mark()
            return fp, None, gen, key
        self.metrics.meter(ServerMeter.RESULT_CACHE_HITS).mark()
        # splice ONLY the metadata map (fresh bytes per hit, rows
        # byte-identical to the original run): a full serde round-trip
        # just to stamp two keys would burn the CPU the cache exists
        # to save under overload
        reply = amend_metadata_bytes(payload, {
            "requestId": str(request.request_id),
            RESULT_CACHE_HIT_KEY: "1"})
        return fp, reply, gen, key

    def _single_flight_follow(self, request: InstanceRequest,
                              ckey: tuple, ev) -> Optional[bytes]:
        """A leader is executing this exact query: wait (bounded) on
        its event, then re-probe the cache. None → fall through to own
        execution (leader failed / skipped the store / wait expired) —
        correctness never depends on the leader."""
        self.metrics.meter(ServerMeter.SINGLE_FLIGHT_WAITS).mark()
        timeout_s = 1.0
        if request.deadline_budget_ms is not None:
            # never burn more than half the remaining budget waiting
            timeout_s = min(timeout_s,
                            max(0.0, request.deadline_budget_ms / 2e3))
        ev.wait(timeout_s)
        payload = self.result_cache.get(ckey)
        if payload is None:
            return None
        self.metrics.meter(ServerMeter.RESULT_CACHE_HITS).mark()
        return amend_metadata_bytes(payload, {
            "requestId": str(request.request_id),
            RESULT_CACHE_HIT_KEY: "1"})

    def _maybe_cache_store(self, request: InstanceRequest,
                           dt: DataTable, payload: bytes,
                           fingerprint: Optional[str],
                           gen: Optional[int] = None) -> None:
        """Store a fully-successful answer keyed on the EXECUTION-time
        segment states (probe-time states could race a segment swap)."""
        if request.enable_trace or dt.exceptions:
            return
        states = getattr(dt, "cache_states", None)
        if not states:
            return
        if fingerprint is None:
            # the probe was skipped (empty-cache fast path); the
            # execution-time states above already proved cacheability
            from pinot_tpu.query.fingerprint import query_fingerprint
            fingerprint = query_fingerprint(request.query)
        self.result_cache.put(
            ServerResultCache.key(request.query.table_name, fingerprint,
                                  states), payload, gen=gen)

    # -- admission ----------------------------------------------------------
    def _admit(self, request: InstanceRequest):
        """→ (decision, busy reply bytes or None, tenant key). The key
        is computed ONCE here and threaded through scheduling and
        release so the depth accounting debits and credits the same
        counter by construction."""
        tenant = self._tenant(request)
        # a hedged duplicate whose plan shape already has an OPEN batch
        # window here rides the primary's dispatch for (almost) free —
        # shedding it at the low watermark would waste a slot for zero
        # information (hedges are rare, so the extra key hash is cheap)
        batch_join = False
        if request.hedge and self.coalescer is not None and \
                self._batchable(request):
            batch_join = self.coalescer.joinable(self._batch_key(request))
        decision = self.admission.admit(
            request.query.table_name, tenant,
            budget_ms=request.deadline_budget_ms, hedge=request.hedge,
            batch_join=batch_join)
        if not decision:
            return decision, busy_datatable(
                request.request_id, decision.cause,
                decision.retry_after_ms).to_bytes(), tenant
        self._register_tenant(tenant)
        return decision, None, tenant

    # -- dispatch coalescing ------------------------------------------------
    def _batchable(self, request: InstanceRequest) -> bool:
        """Coalescer eligibility: plain single-stage queries only —
        staged requests (join/window/exchange) have per-request side
        channels, and traced queries want their own real spans."""
        return self.coalescer is not None and \
            not request.enable_trace and not self._stage_request(request)

    def _batch_key(self, request: InstanceRequest) -> tuple:
        """Queries coalesce iff they agree on table, plan shape, and
        the segment set the broker routed here."""
        from pinot_tpu.query.fingerprint import plan_shape_key
        shape, _lits = plan_shape_key(request.query)
        return (request.query.table_name, shape,
                tuple(sorted(request.search_segments or ())))

    def _on_batch_dispatch(self, occupancy: int) -> None:
        # every sealed window lands in the occupancy distribution;
        # batchedDispatches counts only executions that served >1 query
        self.metrics.timer(ServerTimer.BATCH_OCCUPANCY).update(
            float(occupancy))
        if occupancy > 1:
            self.metrics.meter(ServerMeter.BATCHED_DISPATCHES).mark()

    def _on_batch_bypass(self) -> None:
        self.metrics.meter(ServerMeter.BATCH_BYPASS).mark()

    @staticmethod
    def _resolve_ticket(ticket: _BatchTicket, dt: Optional[DataTable],
                        exc: Optional[BaseException]) -> None:
        try:
            if exc is not None:
                ticket.future.set_exception(exc)
            else:
                ticket.future.set_result(dt)
        except Exception:  # noqa: BLE001 — already cancelled/resolved
            pass

    #: per-dispatch member cap: groups past this run as consecutive
    #: chunks, which pins the pow2 batch buckets the vmapped kernels
    #: ever compile at to {2, 4, 8} — an unbounded occupancy would keep
    #: minting new bucket sizes (= fresh XLA compiles) exactly when the
    #: server is busiest
    MAX_BATCH_CHUNK = 8

    def _run_batch(self, members: List[_BatchTicket],
                   deadline_s: Optional[float]) -> None:
        """Execute a sealed group and fan results back to every
        member's future (one-member groups take the ordinary execute
        path — same code the solo/bypass states run)."""
        for i in range(0, len(members), self.MAX_BATCH_CHUNK):
            self._run_batch_chunk(members[i:i + self.MAX_BATCH_CHUNK],
                                  deadline_s)

    def _run_batch_chunk(self, members: List[_BatchTicket],
                         deadline_s: Optional[float]) -> None:
        waits = [(time.perf_counter() - m.t_arrive) * 1e3
                 for m in members]
        try:
            if len(members) == 1:
                m = members[0]
                dt = self.executor.execute(
                    m.request, scheduler_wait_ms=waits[0],
                    deadline=deadline_s, deser_ms=m.deser_ms)
                dts = [dt]
            else:
                dts = self.executor.execute_batch(
                    [m.request for m in members], waits, deadline_s)
            for m, dt in zip(members, dts):
                self._resolve_ticket(m, dt, None)
        except BaseException as e:  # noqa: BLE001 — fan the failure out
            for m in members:
                self._resolve_ticket(m, None, e)

    def _abandon_group(self, gfut: Future, group: BatchGroup) -> None:
        """Done-callback on the group runner's scheduler future: if the
        runner never got to seal (queue rejection, deadline trim,
        shutdown), fail every member future so no caller hangs. After a
        NORMAL run the group is already sealed and this is a no-op."""
        if self.coalescer is None:
            return
        members = self.coalescer.seal(group)
        if not members:
            return
        try:
            exc: Optional[BaseException] = None
            try:
                exc = gfut.exception()
            except BaseException as e:  # noqa: BLE001 — cancelled
                exc = e
            if exc is None:
                exc = RuntimeError(
                    "batch group abandoned without executing")
            for m in members:
                self._resolve_ticket(m, None, exc)
        finally:
            self.coalescer.leave(group.key)

    def _coalesced_submit(self, request: InstanceRequest, deser_ms: float,
                          deadline: Optional[float],
                          budget_s: Optional[float],
                          tenant: str) -> Future:
        """Route an eligible query through the dispatch coalescer;
        returns the future its caller awaits (a scheduler future for
        solo/bypass, the member ticket's future for joined/lead)."""
        key = self._batch_key(request)
        ticket = _BatchTicket(request, deser_ms)
        state, group = self.coalescer.arrive(key, ticket, deadline)
        if state in ("solo", "bypass"):
            t_submit = time.perf_counter()

            def run():
                wait_ms = (time.perf_counter() - t_submit) * 1e3
                return self.executor.execute(
                    request, scheduler_wait_ms=wait_ms,
                    deadline=deadline, deser_ms=deser_ms)

            fut = self.scheduler.submit(tenant, run, deadline_s=budget_s)
            fut.add_done_callback(
                lambda _f, k=key: self.coalescer.leave(k))
            return fut
        if state == "joined":
            return ticket.future

        # lead: schedule the window runner under the leader's tenant.
        # It sleeps out the window, seals, and executes the batch under
        # the group deadline (the TIGHTEST member deadline at seal).
        def run_group():
            delay = self.coalescer.remaining_window_s(group)
            if delay > 0:
                time.sleep(delay)
            members = self.coalescer.seal(group)
            if not members:      # abandon callback won the seal race
                return None
            try:
                self._run_batch(members, group.deadline_s)
            finally:
                self.coalescer.leave(key)
            return None

        gfut = self.scheduler.submit(tenant, run_group,
                                     deadline_s=budget_s)
        gfut.add_done_callback(
            lambda f, g=group: self._abandon_group(f, g))
        return ticket.future

    def _schedule(self, request: InstanceRequest, deser_ms: float = 0.0,
                  admission_deadline_s: Optional[float] = None,
                  release_admission: bool = False,
                  tenant: Optional[str] = None):
        """Submit to the scheduler; returns the result Future.

        Broker deadline propagation: the budget is fixed to an absolute
        instant NOW (deserialization time), so queue wait counts against
        it and expired work is dropped, not computed. Under brownout the
        admission controller hands down a TIGHTER absolute deadline so
        execution truncates to a flagged-partial result.
        """
        deadline = None
        budget_s = None
        if request.deadline_budget_ms is not None:
            budget_s = request.deadline_budget_ms / 1e3
            deadline = time.monotonic() + budget_s
        if admission_deadline_s is not None:
            deadline = admission_deadline_s if deadline is None \
                else min(deadline, admission_deadline_s)
            budget_s = max(0.0, deadline - time.monotonic())
        # per-TENANT scheduler group: the token hierarchy isolates CPU
        # between tenants instead of pooling everything per table
        if tenant is None:
            tenant = self._tenant(request)
        if self._batchable(request):
            fut = self._coalesced_submit(request, deser_ms, deadline,
                                         budget_s, tenant)
        else:
            t_submit = time.perf_counter()

            def run():
                wait_ms = (time.perf_counter() - t_submit) * 1e3
                return self.executor.execute(request,
                                             scheduler_wait_ms=wait_ms,
                                             deadline=deadline,
                                             deser_ms=deser_ms)

            fut = self.scheduler.submit(tenant, run, deadline_s=budget_s)
        if release_admission:
            # pairs with the admit() in the request path; a failed
            # future (e.g. OutOfCapacity) completes immediately, so the
            # depth can never leak. Each batch member carries its OWN
            # future, so every member credits its own tenant here.
            fut.add_done_callback(
                lambda _f, t=tenant: self.admission.release(t))
        return fut

    def _serialize(self, request: InstanceRequest, dt: DataTable) -> bytes:
        with self.metrics.timer(
                ServerQueryPhase.RESPONSE_SERIALIZATION).time():
            t0 = time.perf_counter()
            payload = dt.to_bytes()
            ser_ms = (time.perf_counter() - t0) * 1e3
        self.metrics.meter(ServerMeter.RESPONSE_BYTES).mark(len(payload))
        if request.enable_trace and "traceInfo" in dt.metadata:
            # the serde span cannot ride inside the bytes it measures:
            # amend the trace and re-serialize (trace=true only — the
            # untraced path pays a single to_bytes)
            try:
                info = json.loads(dt.metadata["traceInfo"])
            except ValueError:
                return payload
            root = info.get("rootSpanId") if isinstance(info, dict) else None
            if root is not None:
                info["spans"].append({
                    "name": ServerQueryPhase.RESPONSE_SERIALIZATION,
                    "ms": round(ser_ms, 3), "spanId": f"{root}.serde",
                    "parentId": root})
                dt.metadata["traceInfo"] = json.dumps(info)
                payload = dt.to_bytes()
        return payload

    def _capacity_reply(self, request: InstanceRequest) -> bytes:
        """The scheduler's bounded queue rejected the query: same typed
        server-busy surface as an admission shed."""
        self.metrics.meter(ServerMeter.REQUESTS_SHED).mark()
        self.metrics.meter(ServerMeter.REQUESTS_SHED,
                           table="capacity").mark()
        return busy_datatable(request.request_id, "capacity",
                              0.0).to_bytes()

    def _error_reply(self, request: InstanceRequest, e: Exception) -> bytes:
        self.metrics.meter(ServerMeter.QUERY_EXECUTION_EXCEPTIONS).mark()
        dt = DataTable()
        dt.metadata["requestId"] = str(request.request_id)
        dt.exceptions.append(f"QueryExecutionError: {e}")
        return dt.to_bytes()

    # -- multi-stage plumbing ----------------------------------------------
    @staticmethod
    def _stage_request(request: InstanceRequest) -> bool:
        """Multi-stage requests bypass the result cache both ways: the
        fingerprint keys on ONE table's segment states, but a join/
        window answer also depends on the dim/exchanged side (satellite:
        a join result cached under the fact table would survive
        dim-table changes)."""
        return (request.publish_exchange is not None or
                request.exchange_sources is not None or
                request.query.join is not None or
                bool(request.query.windows))

    def _maybe_publish(self, request: InstanceRequest, dt: DataTable,
                       payload: bytes) -> bytes:
        """Stage-1 producer epilogue: store the full serialized result
        in the exchange, answer with a small ack (or a typed stage
        error when the scan was truncated by the selection cap)."""
        from pinot_tpu.query.stages.errors import (ExchangeError,
                                                   stage_error_datatable)
        info = request.publish_exchange
        xid = str(info.get("id", ""))
        if dt.exceptions:
            return payload          # surface the scan failure verbatim
        rows = dt.num_rows()
        matched = int(dt.metadata.get("numDocsScanned", "0"))
        if matched > rows:
            return stage_error_datatable(
                request.request_id, "exchangeCapacity",
                f"stage-1 scan matched {matched} rows but the exchange "
                f"window holds {rows} — narrow the stage's filter"
            ).to_bytes()
        try:
            # lifetime tracks the query: the block only matters until
            # stage 2's deadline passes (+slack for clock skew/retries)
            ttl = None
            if request.deadline_budget_ms is not None:
                ttl = request.deadline_budget_ms / 1e3 + 15.0
            self.exchange.put(xid, payload, ttl_s=ttl)
        except ExchangeError as e:
            return stage_error_datatable(
                request.request_id, "exchangeCapacity",
                str(e)).to_bytes()
        ack = DataTable()
        ack.metadata["requestId"] = str(request.request_id)
        ack.metadata["exchangeId"] = xid
        ack.metadata["exchangeKey"] = self.exchange.xkey
        ack.metadata["exchangeRows"] = str(rows)
        ack.metadata["numDocsScanned"] = dt.metadata.get(
            "numDocsScanned", "0")
        key_col = info.get("keyColumn")
        if key_col:
            tags = self._partition_tags(request, str(key_col))
            if tags is not None:
                fn, n, pids = tags
                import json as _json
                ack.metadata["partitionFunction"] = fn
                ack.metadata["numPartitions"] = str(n)
                ack.metadata["exchangePartitions"] = _json.dumps(
                    sorted(pids))
        return ack.to_bytes()

    def _partition_tags(self, request: InstanceRequest, key_col: str):
        """Partition metadata of the published block's key column across
        the scanned segments (None unless consistently tagged) — the
        co-partitioned dispatch contract (stages/join.py)."""
        from pinot_tpu.query.stages.join import fact_partition_info
        tdm = self.data_manager.table(request.query.table_name)
        if tdm is None:
            return None
        acquired, missing = tdm.acquire_segments(request.search_segments)
        try:
            if missing:
                return None
            return fact_partition_info(
                [s.segment for s in acquired], key_col)
        finally:
            for sdm in acquired:
                tdm.release_segment(sdm)

    # -- in-process path (used by tests and the embedded broker) -----------
    def handle_request_bytes(self, payload: bytes) -> bytes:
        from pinot_tpu.query.stages import exchange as _exchange
        if _exchange.is_exchange_frame(payload):
            # peer-server exchange fetch: a memory lookup, answered
            # inline (never scheduled — stage-2 executors are blocked
            # on it, and admission would deadlock colocated stages)
            return self.exchange.handle_frame(payload)
        request, err, deser_ms = self._deserialize(payload)
        if err is not None:
            return err
        staged = self._stage_request(request)
        if staged:
            fingerprint, cached, gen, ckey = None, None, None, None
        else:
            fingerprint, cached, gen, ckey = self._cache_lookup(request)
        if cached is not None:
            return cached          # bypasses admission AND scheduling
        leader_key = None
        if ckey is not None:
            # single-flight: identical concurrent queries on a cold
            # entry — the first becomes leader, the rest await its
            # store (bounded) and re-probe, falling through on failure
            is_leader, ev = self.single_flight.begin(ckey)
            if is_leader:
                leader_key = ckey
            else:
                reply = self._single_flight_follow(request, ckey, ev)
                if reply is not None:
                    return reply
        try:
            decision, busy, tenant = self._admit(request)
            if busy is not None:
                return busy
            try:
                dt = self._schedule(
                    request, deser_ms,
                    admission_deadline_s=decision.deadline_s,
                    release_admission=True,
                    tenant=tenant).result()
                reply = self._serialize(request, dt)
                if request.publish_exchange is not None:
                    return self._maybe_publish(request, dt, reply)
                if not staged:
                    self._maybe_cache_store(request, dt, reply,
                                            fingerprint, gen)
                return reply
            except SchedulerOutOfCapacityError:
                return self._capacity_reply(request)
            except Exception as e:  # noqa: BLE001 — execution/serde error
                return self._error_reply(request, e)
        finally:
            if leader_key is not None:
                self.single_flight.done(leader_key)

    # -- network path (one coroutine per in-flight frame) ------------------
    async def handle_request_async(self, payload: bytes) -> bytes:
        """The multiplexed QueryServer's handler: dispatches to the
        scheduler and awaits the result WITHOUT pinning a thread per
        in-flight request — only scheduler workers compute; serde runs
        on the executor so the event loop keeps draining frames."""
        loop = asyncio.get_running_loop()
        from pinot_tpu.query.stages import exchange as _exchange
        if _exchange.is_exchange_frame(payload):
            # peer-server exchange fetch: a memory lookup, answered
            # inline off the read loop's dispatch task
            return self.exchange.handle_frame(payload)
        request, err, deser_ms = self._deserialize(payload)
        if err is not None:
            return err
        staged = self._stage_request(request)
        # the cache probe touches segment refcounts and hashes the
        # request — off-loop, like the serde it replaces on a hit. But
        # when the probe is a guaranteed no-op (traced query or stage
        # request) the cheap guards run inline: no per-query threadpool
        # hop just to bounce off _cache_lookup's early returns
        if staged:
            fingerprint, cached, gen, ckey = None, None, None, None
        elif request.enable_trace:
            fingerprint, cached, gen, ckey = self._cache_lookup(request)
        else:
            fingerprint, cached, gen, ckey = await loop.run_in_executor(
                None, self._cache_lookup, request)
        if cached is not None:
            return cached          # bypasses admission AND scheduling
        leader_key = None
        if ckey is not None:
            is_leader, ev = self.single_flight.begin(ckey)
            if is_leader:
                leader_key = ckey
            else:
                # the bounded wait blocks — off-loop like the probe
                reply = await loop.run_in_executor(
                    None, self._single_flight_follow, request, ckey, ev)
                if reply is not None:
                    return reply
        try:
            decision, busy, tenant = self._admit(request)
            if busy is not None:
                return busy
            try:
                dt = await asyncio.wrap_future(self._schedule(
                    request, deser_ms,
                    admission_deadline_s=decision.deadline_s,
                    release_admission=True, tenant=tenant))
                if dt.num_rows() <= 128:
                    # small replies (aggregations, trimmed group-bys)
                    # serialize faster than an executor hop costs
                    reply = self._serialize(request, dt)
                else:
                    reply = await loop.run_in_executor(
                        None, self._serialize, request, dt)
                if request.publish_exchange is not None:
                    return self._maybe_publish(request, dt, reply)
                if not staged:
                    self._maybe_cache_store(request, dt, reply,
                                            fingerprint, gen)
                return reply
            except asyncio.CancelledError:
                raise
            except SchedulerOutOfCapacityError:
                return self._capacity_reply(request)
            except Exception as e:  # noqa: BLE001 — execution/serde error
                return self._error_reply(request, e)
        finally:
            if leader_key is not None:
                self.single_flight.done(leader_key)

    # -- network service ---------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start the TCP query service; returns the bound port."""
        with self._lifecycle_lock:
            self._loop = EventLoopThread()
            self._server = QueryServer(
                host, port, self.handle_request_bytes,
                async_handler=self.handle_request_async)
            self._loop.run(self._server.start())
            self.port = self._server.port
            return self.port

    def stop(self) -> None:
        with self._lifecycle_lock:
            if self._server is not None and self._loop is not None:
                self._loop.run(self._server.stop())
            if self._loop is not None:
                self._loop.stop()
                self._loop = None
        self.scheduler.shutdown()
        self.data_manager.shutdown()
        self.exchange.close()
        self.residency.shutdown()
