"""Query replay perf driver.

Parity: pinot-tools/.../tools/perf/QueryRunner.java:43-90 — replay a
query file against a broker in four modes (singleThread, multiThreads,
targetQPS, increasingQPS) and report latency percentiles/QPS. The
driver measures SERVING throughput (broker + scatter-gather + engine),
complementing bench.py's single-query latency headline.

The target is any callable `query_fn(pql) -> response`; `http_query_fn`
builds one for a broker's HTTP endpoint, and an in-process
BrokerRequestHandler's `.handle` works directly (the embedded-cluster
path the tests use).
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class PerfReport:
    mode: str
    num_queries: int
    num_errors: int
    duration_s: float
    qps: float
    latency_avg_ms: float
    latency_p50_ms: float
    latency_p90_ms: float
    latency_p99_ms: float
    latency_max_ms: float
    # targetQPS modes: dispatch slots that fell behind schedule
    missed_slots: int = 0
    target_qps: Optional[float] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        t = f" target={self.target_qps:g}qps" if self.target_qps else ""
        return (f"[{self.mode}{t}] {self.num_queries} queries "
                f"({self.num_errors} errors) in {self.duration_s:.2f}s = "
                f"{self.qps:.1f} QPS; latency ms avg {self.latency_avg_ms:.2f} "
                f"p50 {self.latency_p50_ms:.2f} p90 {self.latency_p90_ms:.2f} "
                f"p99 {self.latency_p99_ms:.2f} max {self.latency_max_ms:.2f}")


def load_query_file(path: str) -> List[str]:
    """One PQL per line; blank lines and #-comments skipped (the
    reference's query-file format)."""
    out = []
    with open(path) as f:
        for line in f:
            q = line.strip()
            if q and not q.startswith("#"):
                out.append(q)
    return out


def http_query_fn(brokers, timeout: float = 30.0
                  ) -> Callable[[str], dict]:
    """POST {"pql": ...} to http://<broker>/query (pinot-api transport).

    `brokers`: one "host:port" or a list of them — each worker THREAD
    is pinned round-robin to one broker and (via the client library's
    `_HttpEndpoint`, which keeps per-thread keep-alive sockets with
    TCP_NODELAY and one transparent retry on a stale connection) holds
    ONE persistent connection to it, the way real serving clients talk
    to a broker fleet — a fresh TCP handshake per query measures the
    OS, not the serving plane, and a single shared socket serializes
    the offered load."""
    import itertools

    from pinot_tpu.client.connection import _HttpEndpoint

    if isinstance(brokers, str):
        brokers = [brokers]
    endpoints = []
    for b in brokers:
        host, _, port = b.partition(":")
        endpoints.append(_HttpEndpoint(host, int(port or 80),
                                       timeout=timeout))
    assign = itertools.count()
    local = threading.local()
    headers = {"Content-Type": "application/json"}

    def fn(pql: str) -> dict:
        ep = getattr(local, "endpoint", None)
        if ep is None:
            ep = local.endpoint = endpoints[next(assign) % len(endpoints)]
        # read-only query: idempotent → the endpoint may retry once on
        # a stale keep-alive before surfacing
        _status, payload = ep.request(
            "POST", "/query", body=json.dumps({"pql": pql}).encode(),
            headers=headers, idempotent=True)
        return json.loads(payload)
    return fn


class QueryRunner:
    def __init__(self, query_fn: Callable[[str], object],
                 queries: Sequence[str],
                 query_provider: Optional[Callable[[int], str]] = None):
        """`query_provider(slot_index) -> pql` overrides the default
        round-robin replay — benchmark drivers use it to mix replayed
        queries with cache-busting variants at a controlled fraction."""
        if not queries and query_provider is None:
            raise ValueError("empty query list")
        self.query_fn = query_fn
        self.queries = list(queries)
        self.query_provider = query_provider
        # persistent worker pool: threads (and their thread-local
        # keep-alive client connections) survive ACROSS rungs, so a
        # high rung starts with warm sockets instead of a reconnect
        # storm that measures the client, not the serving plane
        self._pool = None

    def _query_for(self, i: int) -> str:
        if self.query_provider is not None:
            return self.query_provider(i)
        return self.queries[i % len(self.queries)]

    def _pool_for(self, num_threads: int):
        import concurrent.futures
        if self._pool is None or \
                self._pool._max_workers < num_threads:
            if self._pool is not None:
                self._pool.shutdown(wait=True)
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=num_threads)
        return self._pool

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- internals ---------------------------------------------------------
    def _run_one(self, pql: str, lat_ms: List[float],
                 errors: List[int], lock: threading.Lock) -> None:
        t0 = time.perf_counter()
        err = 0
        try:
            resp = self.query_fn(pql)
            exc = getattr(resp, "exceptions", None)
            if exc is None and isinstance(resp, dict):
                exc = resp.get("exceptions")
            if exc:
                err = 1
        except Exception:  # noqa: BLE001 — an error IS the measurement
            err = 1
        dt = (time.perf_counter() - t0) * 1e3
        with lock:
            lat_ms.append(dt)
            errors[0] += err

    def _report(self, mode: str, lat_ms: List[float], errors: int,
                duration: float, missed: int = 0,
                target_qps: Optional[float] = None) -> PerfReport:
        a = np.asarray(lat_ms) if lat_ms else np.zeros(1)
        return PerfReport(
            mode=mode, num_queries=len(lat_ms), num_errors=errors,
            duration_s=duration,
            qps=len(lat_ms) / duration if duration > 0 else 0.0,
            latency_avg_ms=float(a.mean()),
            latency_p50_ms=float(np.percentile(a, 50)),
            latency_p90_ms=float(np.percentile(a, 90)),
            latency_p99_ms=float(np.percentile(a, 99)),
            latency_max_ms=float(a.max()),
            missed_slots=missed, target_qps=target_qps)

    # -- modes (QueryRunner.java parity) -----------------------------------
    def single_thread(self, num_times: int = 1) -> PerfReport:
        """Replay the file num_times back-to-back on one thread."""
        lat: List[float] = []
        errors = [0]
        lock = threading.Lock()
        t0 = time.perf_counter()
        for _ in range(num_times):
            for q in self.queries:
                self._run_one(q, lat, errors, lock)
        return self._report("singleThread", lat, errors[0],
                            time.perf_counter() - t0)

    def multi_threads(self, num_threads: int = 4,
                      num_times: int = 1) -> PerfReport:
        """num_threads workers drain the replay list concurrently."""
        work = [q for _ in range(num_times) for q in self.queries]
        idx = [0]
        lat: List[float] = []
        errors = [0]
        lock = threading.Lock()

        def worker() -> None:
            while True:
                with lock:
                    if idx[0] >= len(work):
                        return
                    q = work[idx[0]]
                    idx[0] += 1
                self._run_one(q, lat, errors, lock)

        t0 = time.perf_counter()
        ts = [threading.Thread(target=worker) for _ in range(num_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return self._report(f"multiThreads({num_threads})", lat, errors[0],
                            time.perf_counter() - t0)

    def target_qps(self, qps: float, duration_s: float,
                   num_threads: int = 8) -> PerfReport:
        """Dispatch on a fixed schedule; a pool of workers serves the
        slots. Slots whose dispatch falls behind schedule are counted
        (the reference logs the same backlog signal)."""
        period = 1.0 / qps
        lat: List[float] = []
        errors = [0]
        missed = [0]
        lock = threading.Lock()
        slot = [0]
        t_start = time.perf_counter()
        stop = t_start + duration_s

        def worker() -> None:
            while True:
                with lock:
                    i = slot[0]
                    slot[0] += 1
                due = t_start + i * period
                now = time.perf_counter()
                # slots scheduled beyond the deadline never run —
                # checking only `now` would let early workers sleep
                # PAST the deadline and overrun the window
                if now >= stop or due >= stop:
                    return
                if due > now:
                    time.sleep(due - now)
                elif now - due > period:
                    with lock:
                        missed[0] += 1
                self._run_one(self._query_for(i), lat, errors, lock)

        pool = self._pool_for(num_threads)
        futures = [pool.submit(worker) for _ in range(num_threads)]
        for f in futures:
            f.result()
        return self._report("targetQPS", lat, errors[0],
                            time.perf_counter() - t_start,
                            missed=missed[0], target_qps=qps)

    def increasing_qps(self, start_qps: float, step_qps: float,
                       steps: int, step_duration_s: float,
                       num_threads: int = 8) -> List[PerfReport]:
        """targetQPS ladder (the reference's increasingQPS mode): one
        report per rung so saturation shows as p99 blow-up/missed
        slots."""
        return [self.target_qps(start_qps + i * step_qps, step_duration_s,
                                num_threads)
                for i in range(steps)]
