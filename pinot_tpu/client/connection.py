"""Python client: broker connection + result sets + controller admin.

Parity: pinot-api (org.apache.pinot.client) — Connection.java (execute via
a BrokerSelector over the broker list), ResultSetGroup.java,
AggregationResultSet / GroupByResultSet / SelectionResultSet, and
PinotClientException. The admin half mirrors what the reference's
quickstarts drive against the controller REST API (schema/table create,
segment upload).
"""
from __future__ import annotations

import http.client
import itertools
import json
import random
import urllib.parse
from typing import Dict, List, Optional, Sequence, Tuple


class PinotClientError(Exception):
    pass


class ResultSet:
    """One result table: aggregation value, group-by rows, or selection."""

    def __init__(self, column_names: List[str], rows: List[list],
                 group_key_columns: Optional[List[str]] = None,
                 group_keys: Optional[List[list]] = None):
        self._columns = column_names
        self._rows = rows
        self._group_key_columns = group_key_columns or []
        self._group_keys = group_keys or []

    @property
    def row_count(self) -> int:
        return len(self._rows)

    @property
    def column_count(self) -> int:
        return len(self._columns)

    def column_name(self, i: int) -> str:
        return self._columns[i]

    def get(self, row: int, col: int = 0):
        return self._rows[row][col]

    @property
    def group_key_columns(self) -> List[str]:
        return list(self._group_key_columns)

    def group_key(self, row: int) -> list:
        return self._group_keys[row]

    def rows(self) -> List[list]:
        return [list(r) for r in self._rows]


class ResultSetGroup:
    """All result tables of one query + the response stats."""

    def __init__(self, response: dict):
        self.response = response
        self.exceptions = response.get("exceptions", [])
        self._sets: List[ResultSet] = []
        for agg in response.get("aggregationResults", []):
            if "groupByResult" in agg:
                self._sets.append(ResultSet(
                    column_names=[agg["function"]],
                    rows=[[g["value"]] for g in agg["groupByResult"]],
                    group_key_columns=agg.get("groupByColumns", []),
                    group_keys=[g["group"] for g in agg["groupByResult"]]))
            else:
                self._sets.append(ResultSet(
                    column_names=[agg["function"]],
                    rows=[[agg["value"]]]))
        sel = response.get("selectionResults")
        if sel is not None:
            self._sets.append(ResultSet(column_names=sel["columns"],
                                        rows=sel["results"]))

    @property
    def result_set_count(self) -> int:
        return len(self._sets)

    def result_set(self, i: int = 0) -> ResultSet:
        return self._sets[i]

    @property
    def num_docs_scanned(self) -> int:
        return self.response.get("numDocsScanned", 0)

    @property
    def time_used_ms(self) -> float:
        return self.response.get("timeUsedMs", 0.0)

    @property
    def trace_info(self) -> Optional[dict]:
        return self.response.get("traceInfo")


class _HttpEndpoint:
    """One host:port with persistent keep-alive connections."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    def request(self, method: str, path: str, body: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                idempotent: Optional[bool] = None) -> Tuple[int, bytes]:
        """One retry on a stale kept-alive connection — but only for
        requests that are safe to re-send (the server may already have
        processed a POST whose response was lost)."""
        headers = dict(headers or {})
        if idempotent is None:
            idempotent = method in ("GET", "HEAD", "PUT", "DELETE")
        for attempt in (0, 1):
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout)
            try:
                self._conn.request(method, path, body=body, headers=headers)
                resp = self._conn.getresponse()
                return resp.status, resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                self.close()
                if attempt or not idempotent:
                    raise
        raise PinotClientError("unreachable")  # pragma: no cover

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None


class SimpleBrokerSelector:
    """Round-robin over the broker list (parity: SimpleBrokerSelector)."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]]):
        if not endpoints:
            raise PinotClientError("empty broker list")
        shuffled = list(endpoints)
        random.shuffle(shuffled)
        self._endpoints = [_HttpEndpoint(h, p) for h, p in shuffled]
        self._cycle = itertools.cycle(range(len(self._endpoints)))

    def select(self) -> _HttpEndpoint:
        return self._endpoints[next(self._cycle)]

    def close(self) -> None:
        for e in self._endpoints:
            e.close()


class Connection:
    """Queries one Pinot cluster through its broker(s)."""

    def __init__(self, selector: SimpleBrokerSelector,
                 token: Optional[str] = None):
        self._selector = selector
        self._token = token

    def execute(self, pql: str, trace: bool = False) -> ResultSetGroup:
        body = json.dumps({"pql": pql, "trace": trace}).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        endpoint = self._selector.select()
        try:
            # queries are read-only: safe to retry on a stale connection
            status, payload = endpoint.request("POST", "/query", body,
                                               headers, idempotent=True)
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            raise PinotClientError(f"broker unreachable: {e}") from e
        if status != 200:
            raise PinotClientError(f"broker returned HTTP {status}: "
                                   f"{payload[:200]!r}")
        group = ResultSetGroup(json.loads(payload))
        for exc in group.exceptions:
            msg = exc.get("message", "")
            if "AccessDenied" in msg:
                raise PinotClientError(msg)
        return group

    def close(self) -> None:
        self._selector.close()


def connect(brokers, token: Optional[str] = None) -> Connection:
    """connect("host:port") / connect([("h", p), ...]) → Connection."""
    if isinstance(brokers, str):
        brokers = [brokers]
    endpoints = []
    for b in brokers:
        if isinstance(b, str):
            host, _, port = b.partition(":")
            endpoints.append((host, int(port)))
        else:
            endpoints.append(tuple(b))
    return Connection(SimpleBrokerSelector(endpoints), token=token)


class ControllerClient:
    """Admin client for the controller REST API."""

    def __init__(self, host: str, port: int):
        self._endpoint = _HttpEndpoint(host, port)

    def _json(self, method: str, path: str, body: Optional[bytes] = None,
              content_type: str = "application/json",
              idempotent: Optional[bool] = None) -> dict:
        status, payload = self._endpoint.request(
            method, path, body,
            {"Content-Type": content_type} if body else None,
            idempotent=idempotent)
        data = json.loads(payload) if payload else {}
        if status >= 400:
            raise PinotClientError(
                f"HTTP {status}: {data.get('error', payload[:200])}")
        return data

    def add_schema(self, schema_json: dict) -> dict:
        # schema/table adds are store upserts: retry-safe
        return self._json("POST", "/schemas",
                          json.dumps(schema_json).encode(), idempotent=True)

    def get_schema(self, name: str) -> dict:
        return self._json("GET", f"/schemas/{urllib.parse.quote(name)}")

    def add_table(self, config_json: dict) -> dict:
        return self._json("POST", "/tables",
                          json.dumps(config_json).encode())

    def list_tables(self) -> List[str]:
        return self._json("GET", "/tables")["tables"]

    def get_table(self, name: str) -> dict:
        return self._json("GET", f"/tables/{urllib.parse.quote(name)}")

    def delete_table(self, name: str) -> dict:
        return self._json("DELETE", f"/tables/{urllib.parse.quote(name)}")

    def external_view(self, table: str) -> dict:
        return self._json(
            "GET", f"/tables/{urllib.parse.quote(table)}/externalview")

    def rebalance(self, table: str, dry_run: bool = False) -> dict:
        return self._json(
            "POST", f"/tables/{urllib.parse.quote(table)}/rebalance"
            f"?dryRun={'true' if dry_run else 'false'}")

    def list_segments(self, table: str) -> List[str]:
        return self._json(
            "GET", f"/tables/{urllib.parse.quote(table)}/segments")

    def upload_segment_dir(self, table: str, segment_dir: str) -> dict:
        from pinot_tpu.controller.http_api import pack_segment_dir
        data = pack_segment_dir(segment_dir)
        return self._json(
            "POST", f"/segments/{urllib.parse.quote(table)}", data,
            content_type="application/gzip", idempotent=False)

    def delete_segment(self, table: str, segment: str) -> dict:
        return self._json(
            "DELETE", f"/segments/{urllib.parse.quote(table)}/"
            f"{urllib.parse.quote(segment)}")

    def segment_metadata(self, table: str, segment: str) -> dict:
        return self._json(
            "GET", f"/segments/{urllib.parse.quote(table)}/"
            f"{urllib.parse.quote(segment)}/metadata")

    def close(self) -> None:
        self._endpoint.close()
