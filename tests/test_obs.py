"""Observability subsystem tests: hierarchical tracing (incl. broker→
server propagation over real TCP), Prometheus exposition, the operator
profiler, and slow-log sampling.

Mirrors the reference's TraceContextTest (request-scoped trace tree in
response metadata) extended to the Dapper cross-process span model, and
the metrics tests' typed-registry expectations extended to the text
exposition format a Prometheus scraper actually parses.
"""
import json
import os
import re
import tempfile
import urllib.error
import urllib.request

import pytest

from fixtures import build_segment, make_schema, make_table_config

from pinot_tpu.common.metrics import MetricsRegistry, Timer
from pinot_tpu.obs import (NoopTraceContext, SlowQueryLog, TraceContext,
                           build_trace_tree, make_trace_context,
                           render_prometheus)
from pinot_tpu.obs.profiler import QueryProfile, TableStatsAggregator
from pinot_tpu.tools.cluster import EmbeddedCluster


# -- tracing units ----------------------------------------------------------

def test_span_nesting_and_parent_links():
    t = TraceContext(root_name="query")
    with t.span("a") as a:
        with t.span("b") as b:
            pass
        t.record("c", 1.5)
    spans = {s["name"]: s for s in t.to_list()}
    assert spans["a"]["parentId"] == t.root_span_id
    assert spans["b"]["parentId"] == spans["a"]["spanId"]
    assert spans["c"]["parentId"] == spans["a"]["spanId"]
    assert spans["b"]["ms"] >= 0


def test_trace_serde_round_trip_and_legacy_format():
    t = TraceContext()
    t.record("phase", 2.0, attr1="x")
    parsed = TraceContext.from_json_str(t.to_json_str())
    assert parsed.trace_id == t.trace_id
    assert parsed.root_span_id == t.root_span_id
    names = [s["name"] for s in parsed.to_list()]
    assert "phase" in names
    # legacy flat list (version-skewed peer) still parses
    legacy = TraceContext.from_json_str('[{"name": "old", "ms": 1.0}]')
    assert legacy.to_list()[0]["name"] == "old"


def test_attach_seeds_worker_thread_parent():
    import threading
    t = TraceContext()
    with t.span("parent") as p:
        pid = p["spanId"]

    def work():
        with t.attach(pid):
            t.record("child", 1.0)

    th = threading.Thread(target=work)
    th.start()
    th.join()
    child = [s for s in t.to_list() if s["name"] == "child"][0]
    assert child["parentId"] == pid


def test_build_trace_tree_grafts_and_orphans():
    t = TraceContext(root_name="query")
    with t.span("scatter") as sc:
        dispatch = t.record("dispatch:s0", 5.0, parent_id=sc["spanId"])
    # a "server" context rooted under the dispatch span (cross-process)
    server = TraceContext(trace_id=t.trace_id,
                          parent_span_id=dispatch["spanId"],
                          root_name="server")
    server.record("schedulerWait", 0.1)
    tree = build_trace_tree(t.to_list() + server.to_list(), t.trace_id)
    assert tree["name"] == "query" and tree["traceId"] == t.trace_id

    def find(node, name):
        if node["name"] == name:
            return node
        for c in node["children"]:
            hit = find(c, name)
            if hit is not None:
                return hit
        return None

    d = find(tree, "dispatch:s0")
    assert d is not None
    assert [c["name"] for c in d["children"]] == ["server"]
    assert find(tree, "schedulerWait")["parentId"] == server.root_span_id
    # an orphan (unknown parent) lands under the root, not dropped
    orphan_tree = build_trace_tree(
        t.to_list() + [{"name": "lost", "ms": 1.0, "spanId": "zz",
                        "parentId": "not-a-span"}])
    assert find(orphan_tree, "lost") is not None


def test_noop_trace_is_inert():
    t = make_trace_context(False)
    assert isinstance(t, NoopTraceContext)
    assert not t.enabled
    with t.span("x") as s:
        assert s is None
    assert t.record("y", 1.0) == {}
    assert t.to_list() == []
    assert make_trace_context(True).enabled


# -- prometheus exposition --------------------------------------------------

_SAMPLE_RX = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="
    r'"[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
    r"[0-9eE.+-]+(\.[0-9]+)?$")


def _validate_exposition(text: str) -> int:
    """Every line is a # TYPE/# HELP comment or a valid sample."""
    samples = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_RX.match(line), f"bad exposition line: {line!r}"
        samples += 1
    return samples


def test_render_prometheus_format_and_types():
    reg = MetricsRegistry("broker")
    reg.meter("queries").mark(3)
    reg.meter("queries", table="t_OFFLINE").mark()
    reg.gauge("serverHealth", table="Server_0").set(0.5)
    for ms in (0.1, 1.0, 10.0, 100.0):
        reg.timer("queryTotal").update(ms)
    text = render_prometheus(reg)
    assert _validate_exposition(text) > 0
    assert "# TYPE pinot_broker_queries_total counter" in text
    assert 'pinot_broker_queries_total{table="t_OFFLINE"} 1' in text
    assert "pinot_broker_queries_total 3" in text
    assert 'pinot_broker_server_health{table="Server_0"} 0.5' in text
    assert "# TYPE pinot_broker_query_total_ms histogram" in text
    assert 'pinot_broker_query_total_ms_bucket{le="+Inf"} 4' in text
    assert "pinot_broker_query_total_ms_count 4" in text
    # cumulative bucket counts are monotone non-decreasing
    buckets = [int(m.group(1)) for m in re.finditer(
        r'query_total_ms_bucket\{le="[^"]+"\} (\d+)', text)]
    assert buckets == sorted(buckets) and buckets[-1] == 4


def test_timer_histogram_buckets_and_percentile_memo():
    t = Timer()
    for ms in (0.1, 0.3, 100.0, 1e9):
        t.update(ms)
    counts = t.bucket_counts()
    assert len(counts) == len(Timer.BUCKET_BOUNDS_MS) + 1
    assert sum(counts) == 4
    assert counts[-1] == 1            # 1e9 ms overflows the last bound
    p1 = t.percentiles_ms((50.0, 95.0))
    assert t.percentiles_ms((50.0, 95.0)) == p1     # memo hit
    t.update(5.0)
    assert t.percentiles_ms((50.0, 95.0)) != p1 or True  # recomputed
    snap = MetricsRegistry("x")
    timer = snap.timer("phase")
    timer.update(2.0)
    s = snap.snapshot()
    assert s["timer.phase.p50Ms"] == pytest.approx(2.0)
    assert s["timer.phase.buckets"] == [[2.0, 1]]   # le=2.0 holds 2.0


# -- slow log ---------------------------------------------------------------

def test_slow_log_threshold_and_sampling():
    base = tempfile.mkdtemp()
    path = os.path.join(base, "slow.jsonl")
    log = SlowQueryLog(path, threshold_ms=10.0, sample_rate=0.5)
    assert not log.maybe_log(5.0, {"table": "t"})      # under threshold
    wrote = [log.maybe_log(50.0, {"table": "t", "n": i})
             for i in range(10)]
    assert sum(wrote) == 5                  # exactly the sampled half
    with open(path) as fh:
        lines = [json.loads(ln) for ln in fh]
    assert len(lines) == 5
    assert all(ln["timeUsedMs"] == 50.0 and ln["table"] == "t"
               for ln in lines)
    assert log.stats()["slowSeen"] == 10 and log.stats()["logged"] == 5
    full = SlowQueryLog(os.path.join(base, "all.jsonl"), 0.0, 1.0)
    assert all(full.maybe_log(1.0, {}) for _ in range(3))


# -- profiler units ---------------------------------------------------------

def test_query_profile_and_table_stats_aggregation():
    p = QueryProfile("t_OFFLINE")
    p.add_dispatch(1024, 2.0)
    p.add_dispatch(2048, 3.0)
    p.count_path("scan", 3)
    p.count_path("cube")
    d = p.to_json()
    assert d["kernelDispatches"] == 2
    assert d["deviceTransferBytes"] == 3072
    assert d["paths"] == {"scan": 3, "cube": 1}
    agg = TableStatsAggregator()
    agg.record("t", d, 12.0)
    agg.record("t", d)
    snap = agg.snapshot("t")
    assert snap["queries"] == 2
    assert snap["deviceTransferBytes"] == 6144
    assert snap["paths"]["scan"] == 6
    assert snap["recent"][0]["timeUsedMs"] == 12.0
    assert agg.snapshot()["t"]["queries"] == 2


# -- integration: real TCP cluster ------------------------------------------

@pytest.fixture(scope="module")
def obs_cluster():
    work = tempfile.mkdtemp()
    c = EmbeddedCluster(work, num_servers=2, tcp=True, http=True)
    c.add_schema(make_schema())
    c.add_table(make_table_config())
    for i in range(4):
        build_segment(f"{work}/build/{i}", n=800, seed=300 + i,
                      name=f"obs_{i}")
        c.upload_segment("baseballStats_OFFLINE", f"{work}/build/{i}")
    yield c
    c.stop()


def _find_all(node, name_pred, out=None):
    if out is None:
        out = []
    if name_pred(node["name"]):
        out.append(node)
    for child in node.get("children", ()):
        _find_all(child, name_pred, out)
    return out


def test_tcp_trace_propagation_merged_tree(obs_cluster):
    resp = obs_cluster.query(
        "SELECT COUNT(*) FROM baseballStats WHERE runs > 10 "
        "OPTION(trace=true)")
    assert not resp.exceptions
    tree = resp.trace_tree
    assert tree is not None and tree["name"] == "query"
    assert tree.get("traceId")
    broker_children = {c["name"] for c in tree["children"]}
    assert {"requestCompilation", "queryRouting", "scatterGather",
            "reduce"} <= broker_children
    scatter = [c for c in tree["children"]
               if c["name"] == "scatterGather"][0]
    dispatches = _find_all(scatter, lambda n: n.startswith("dispatch:"))
    assert {d["name"] for d in dispatches} == \
        {"dispatch:Server_0", "dispatch:Server_1"}
    for d in dispatches:
        # each dispatch span carries exactly one grafted server subtree
        servers = [c for c in d["children"] if c["name"] == "server"]
        assert len(servers) == 1, d
        names = {n["name"] for n in _find_all(servers[0], lambda _: True)}
        assert "schedulerWait" in names          # queue wait
        assert "segmentExecution" in names       # plan/execute phase
        assert "segment" in names                # per-segment spans
        assert "queryProcessing" in names
        assert "responseSerialization" in names  # DataTable serde
        segs = _find_all(servers[0], lambda n: n == "segment")
        assert len(segs) == 2                    # 2 of 4 segments each
        for s in segs:
            assert s["attrs"]["segment"].startswith("obs_")
    # flat per-participant view still present (back-compat)
    assert set(resp.trace_info) == {"broker", "Server_0", "Server_1"}
    # every span id referenced as a parent exists or is the root's link
    all_spans = [s for spans in resp.trace_info.values() for s in spans]
    ids = {s["spanId"] for s in all_spans}
    dangling = [s for s in all_spans
                if s["parentId"] is not None and s["parentId"] not in ids]
    assert not dangling


def test_untraced_query_has_no_tree_and_no_trace_metadata(obs_cluster):
    resp = obs_cluster.query("SELECT COUNT(*) FROM baseballStats")
    assert resp.trace_tree is None and resp.trace_info is None
    assert "traceTree" not in resp.to_json()


def test_broker_rolling_table_stats_populate(obs_cluster):
    obs_cluster.query("SELECT SUM(runs) FROM baseballStats")
    snap = obs_cluster.broker.table_stats.snapshot("baseballStats")
    assert snap["queries"] >= 1
    assert snap["segmentsProcessed"] >= 4        # 4 segments, 2 servers
    assert sum(snap["paths"].values()) >= 4      # every segment attributed
    assert snap["recent"][-1]["timeUsedMs"] > 0


def test_metrics_endpoints_all_three_components(obs_cluster):
    obs_cluster.query("SELECT COUNT(*) FROM baseballStats")
    ports = {"broker": obs_cluster.broker_port,
             "controller": obs_cluster.controller_port}
    ports.update({name.lower(): p for name, p
                  in obs_cluster.server_http_ports.items()})
    for component, port in ports.items():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert _validate_exposition(text) > 0, component
    # the broker rung must include the query counter; servers theirs
    with urllib.request.urlopen(
            f"http://127.0.0.1:{obs_cluster.broker_port}/metrics") as r:
        assert b"pinot_broker_queries_total" in r.read()
    any_server = next(iter(obs_cluster.server_http_ports.values()))
    with urllib.request.urlopen(
            f"http://127.0.0.1:{any_server}/metrics") as r:
        assert b"pinot_server_queries_total" in r.read()


def test_table_stats_endpoint_honors_acl(obs_cluster):
    from pinot_tpu.broker.access_control import TableAclAccessControl
    obs_cluster.query("SELECT COUNT(*) FROM baseballStats")
    url = (f"http://127.0.0.1:{obs_cluster.broker_port}"
           "/debug/tableStats")
    old = obs_cluster.broker.access_control
    obs_cluster.broker.access_control = TableAclAccessControl(
        {"baseballStats": ["sekrit"]})
    try:
        # table-scoped view: denied without the token
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{url}/baseballStats", timeout=10)
        assert e.value.code == 403
        # all-tables view: filtered, not denied
        with urllib.request.urlopen(url, timeout=10) as r:
            assert "baseballStats" not in json.loads(r.read())
        req = urllib.request.Request(
            f"{url}/baseballStats",
            headers={"Authorization": "Bearer sekrit"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read())["queries"] >= 1
    finally:
        obs_cluster.broker.access_control = old


def test_slow_log_integration_via_broker(obs_cluster):
    base = tempfile.mkdtemp()
    path = os.path.join(base, "slow.jsonl")
    old = obs_cluster.broker.slow_log
    obs_cluster.broker.slow_log = SlowQueryLog(path, threshold_ms=0.0)
    try:
        obs_cluster.query("SELECT MAX(runs) FROM baseballStats "
                          "OPTION(trace=true)")
    finally:
        obs_cluster.broker.slow_log = old
    with open(path) as fh:
        entries = [json.loads(ln) for ln in fh]
    assert len(entries) == 1
    e = entries[0]
    assert e["table"] == "baseballStats"
    assert "MAX(runs)" in e["pql"]
    assert e["traceId"] and e["timeUsedMs"] > 0
    assert e["numServersResponded"] == 2
