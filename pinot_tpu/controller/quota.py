"""Table storage quota enforcement at segment upload.

Parity: pinot-controller/.../validation/StorageQuotaChecker.java —
invoked from the segment upload path (PinotSegmentUploadRestletResource
→ ZKOperator): estimate the table's post-upload storage footprint and
reject the upload when it would exceed the table config's
``quota.storage``. The reference states the quota per replica and
multiplies both sides by the replication factor; the factors cancel, so
this checker compares the sum of single-copy segment artifact sizes
against the parsed quota directly.
"""
from __future__ import annotations

import os
import re
from typing import Dict, Optional

from pinot_tpu.common.table_config import TableConfig

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([KMGTP]?)B?\s*$", re.I)
_UNITS = {"": 1, "K": 1 << 10, "M": 1 << 20, "G": 1 << 30,
          "T": 1 << 40, "P": 1 << 50}


class StorageQuotaExceededError(ValueError):
    """Raised when a segment upload would push a table past its quota."""


def parse_storage_size(text: str) -> int:
    """'100G' / '1.5M' / '2048' / '64KB' → bytes (binary units, matching
    the reference's DataSize parsing)."""
    m = _SIZE_RE.match(text)
    if not m:
        raise ValueError(f"bad storage size: {text!r}")
    return int(float(m.group(1)) * _UNITS[m.group(2).upper()])


def dir_size_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            total += os.path.getsize(os.path.join(root, f))
    return total


class StorageQuotaChecker:
    """Pre-upload admission check against the table's storage quota."""

    def check_segment_upload(self, config: TableConfig, table: str,
                             existing_sizes: Dict[str, Optional[int]],
                             segment_name: str, segment_bytes: int) -> None:
        """Raise StorageQuotaExceededError if adding (or refreshing)
        ``segment_name`` at ``segment_bytes`` would exceed the quota.

        ``existing_sizes`` maps resident segment names to their recorded
        artifact sizes; a refresh replaces the old artifact, so the
        incumbent's size is excluded. Segments with unknown sizes (None,
        e.g. records written before size tracking) are skipped — the
        reference likewise proceeds on incomplete size reports rather
        than failing closed.
        """
        quota = config.quota_config
        if quota is None or not quota.storage:
            return
        allowed = parse_storage_size(quota.storage)
        resident = sum(sz for name, sz in existing_sizes.items()
                       if sz is not None and name != segment_name)
        estimated = resident + segment_bytes
        if estimated > allowed:
            raise StorageQuotaExceededError(
                f"storage quota exceeded for table {table}: estimated "
                f"{estimated} bytes > quota {quota.storage} "
                f"({allowed} bytes); segment {segment_name} is "
                f"{segment_bytes} bytes on top of {resident} resident")
