"""Per-segment query kernels: filter masks, aggregations, group-by, selection.

This is the TPU replacement for the reference's operator tree
(pinot-core/.../core/operator/ — SURVEY.md §2.2 "primary TPU kernel surface").
Where the Java engine pulls 10k-doc blocks through virtual-call iterators
(DocIdSetOperator → ProjectionOperator → AggregationOperator), we compile the
whole per-segment plan into ONE jitted function over padded, HBM-resident
dictId lanes:

- Filter tree → vectorized boolean mask expression. Predicates are resolved
  host-side into the dictId domain (sorted dictionaries make ranges contiguous
  id intervals), so EQ/RANGE/IN become integer compares on int32 lanes and
  arbitrary dictionary predicates (REGEXP_LIKE, big IN lists) become a
  member-vector gather. Replaces BitmapBasedFilterOperator /
  ScanBasedFilterOperator / SortedInvertedIndexBasedFilterOperator and the
  And/OrDocIdIterator hot loops with pure VPU work.
- Aggregations → masked reductions. SUM/AVG/DISTINCTCOUNT go through a dictId
  histogram (int32 scatter-add) so the device only ever computes exact integer
  counts; the final f64 dot with dictionary values happens host-side. MIN/MAX
  reduce dictIds directly (dictionaries are sorted ⇒ id order == value order).
  Replaces AggregationOperator / DictionaryBasedAggregationOperator.
- Group-by → mixed-radix dictId keys (same math as
  DictionaryBasedGroupKeyGenerator.java:204 `groupId = groupId*card + dictId`)
  aggregated WITHOUT row-scale sorts/scatters/gathers: MXU block stream-
  compaction of matched rows + one-hot matmul group tables (dense layout
  for small key spaces, rank-addressed for wide ones), driven by an
  adaptive two-phase executor (plan.drive_group_execution). Replaces
  DefaultGroupByExecutor + CombineGroupByOperator.
- Selection → jnp.nonzero(size=k) for limit queries, lax.top_k over packed
  order keys for ORDER BY. Replaces SelectionOperator's PriorityQueue.

Kernel specs are hashable tuples of static structure (shapes pow2-bucketed);
predicate constants are dynamic operands — so one compiled executable serves
every query with the same shape, the plan-cache requirement called out in
SURVEY.md §7 "hard parts".
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu import compat

INT32_MAX = np.int32(2**31 - 1)


def pow2_bucket(n: int, floor: int = 8) -> int:
    """Round up to a power of two (shape bucketing for jit-cache reuse)."""
    n = max(n, floor)
    return 1 << int(np.ceil(np.log2(n)))


def sum_dtype():
    """Accumulator dtype for value sums: f64 under x64 (CPU tests), else f32."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# ---------------------------------------------------------------------------
# Filter spec evaluation
#
# spec grammar (hashable tuples):
#   ("and", (child, ...)) | ("or", (child, ...))
#   ("match_all",) | ("empty",)
#   ("pred", kind, col, source, extra)
#     kind ∈ {eq_id, neq_id, in_ids, notin_ids, range_ids, member,
#             eq_raw, neq_raw, in_raw, notin_raw, range_raw}
#     source ∈ {sv, mv, raw}
#     extra: kind-specific static data (bucketed value count, inclusivity)
#   ("pred", "ivf_probe", col, "ivf", (nprobe, metric)) — ANN coarse
#     filter over THREE lanes ({col}.ivfa assignments, {col}.ivfc padded
#     centroids, {col}.ivfv centroid validity); consumes the query
#     vector + norm as params and keeps only rows whose coarse cell is
#     in the on-device top-nprobe probe list
# params: flat tuple of jnp arrays consumed in depth-first pred order.
# ---------------------------------------------------------------------------


def _eval_pred(kind: str, source: str, extra, lane, params: List):
    """lane: int32 [P] (sv ids), int32 [P, W] (mv ids), or raw values [P]."""
    if kind == "eq_id" or kind == "eq_raw":
        v = params.pop(0)
        m = lane == v
    elif kind == "neq_id" or kind == "neq_raw":
        v = params.pop(0)
        m = lane != v
    elif kind == "in_ids" or kind == "in_raw":
        vals = params.pop(0)  # [k]
        m = (lane[..., None] == vals).any(-1)
    elif kind == "notin_ids" or kind == "notin_raw":
        vals = params.pop(0)
        m = ~((lane[..., None] == vals).any(-1))
    elif kind == "range_ids":
        lo, hi = params.pop(0), params.pop(0)  # half-open id interval
        m = (lane >= lo) & (lane < hi)
    elif kind == "range_raw":
        lo, hi = params.pop(0), params.pop(0)
        lo_inc, hi_inc = extra
        ml = (lane >= lo) if lo_inc else (lane > lo)
        mh = (lane <= hi) if hi_inc else (lane < hi)
        m = ml & mh
    elif kind == "member":
        member = params.pop(0)  # bool [card_pad]
        # int32 index: a narrow (int8) id lane cannot address a member
        # table whose size exceeds its own dtype range (jax normalizes
        # the axis size into the INDEX dtype)
        m = member[jnp.clip(lane.astype(jnp.int32), 0,
                            member.shape[0] - 1)]
    elif kind == "vdoc":
        # upsert validDocIds mask: the lane IS the per-doc liveness bool
        # (runtime operand — one compiled executable serves any bitmap);
        # fused into the filter mask so aggregation/group/selection all
        # see only live rows
        m = lane
    elif kind == "join_raw":
        # raw-key inner-join probe: the dim side's key array arrives as
        # a RUNTIME operand (padded by repeating its max key, so padding
        # slots are duplicates of a real key and can never create or
        # destroy a match); the probe structure is BUILT ON DEVICE —
        # lax.sort is the hash-build, searchsorted the probe — so one
        # compiled executable serves every dim table of the same
        # pow2-bucketed size
        keys = params.pop(0)                       # [Dp] fact-key dtype
        sk = jax.lax.sort(keys)
        pos = jnp.clip(jnp.searchsorted(sk, lane), 0, sk.shape[0] - 1)
        m = sk[pos] == lane
    else:
        raise ValueError(f"unknown predicate kind {kind}")
    if source == "mv":
        # Pinot MV semantics: doc matches if ANY entry matches; padding
        # entries carry id == cardinality which only member-vectors could
        # accidentally hit — member vectors are padded False there.
        m = m.any(-1)
    return m


def ivf_select_probes(centroids, cvalid, q, q_norm, metric: str,
                      nprobe: int):
    """Top-nprobe coarse-cell selection for the IVF filter lane.

    centroids: f32 [C_pad, dim_pad] zero-padded codebook; cvalid: bool
    [C_pad] liveness (padding rows and dead cells False — a runtime
    lane, NOT a count param, so sharded execution can share one plan
    across segments with different live counts). Scoring reuses the
    query-metric machinery (same balanced tree, same monotone keys) so
    the numpy twin in index/ivf.py is bit-identical; lax.top_k breaks
    score ties toward the LOWER centroid id, like everywhere else.
    Returns (probe_ids i32 [nprobe], probe_ok bool [nprobe])."""
    cscore = _vector_scores(centroids, q, q_norm, metric)
    ckey = jnp.maximum(_monotone_int32_keys(cscore, True)[0], -INT32_MAX)
    scored = jnp.where(cvalid, ckey, -INT32_MAX - 1)
    _, probe = jax.lax.top_k(scored, nprobe)
    probe_ok = jnp.arange(nprobe, dtype=jnp.int32) < \
        cvalid.sum(dtype=jnp.int32)
    return probe.astype(jnp.int32), probe_ok


def _eval_ivf_probe(extra, assign, centroids, cvalid, params: List):
    """rows whose assigned coarse cell is probed. assign: narrow int [P]
    (padding rows carry the never-live sentinel num_centroids). The
    membership test is the in_ids compare form — [P, nprobe] broadcast
    compare + any — which fuses instead of gathering at row scale."""
    nprobe, metric = extra
    q = params.pop(0)               # f32 [dim_pad] query vector
    q_norm = params.pop(0)          # f32 scalar (tree-norm of q)
    probe, probe_ok = ivf_select_probes(centroids, cvalid, q, q_norm,
                                        metric, nprobe)
    m = (assign.astype(jnp.int32)[..., None] == probe) & probe_ok
    return m.any(-1)


def _eval_filter(spec, cols: Dict[str, jnp.ndarray], params: List, valid):
    op = spec[0]
    if op == "match_all":
        return valid
    if op == "empty":
        return jnp.zeros_like(valid)
    if op in ("and", "or"):
        masks = [_eval_filter(c, cols, params, valid) for c in spec[1]]
        out = masks[0]
        for m in masks[1:]:
            out = (out & m) if op == "and" else (out | m)
        return out
    if op == "pred":
        _, kind, col, source, extra = spec
        if source == "ivf":
            # three-lane predicate (assignments + codebook + validity)
            return _eval_ivf_probe(extra, cols[f"{col}.ivfa"],
                                   cols[f"{col}.ivfc"],
                                   cols[f"{col}.ivfv"], params)
        key = {"sv": f"{col}.ids", "mv": f"{col}.mv", "raw": f"{col}.raw",
               "vdoc": f"{col}.vdoc"}[source]
        return _eval_pred(kind, source, extra, cols[key], params)
    raise ValueError(f"unknown filter node {op}")


# ---------------------------------------------------------------------------
# TPU reduction strategy
#
# Scatter/gather run at ~150M rows/s on TPU (serialized updates) while tree
# reductions and MXU matmuls run at memory/matmul bandwidth (20-200x faster,
# measured on v5e). So the hot aggregation paths NEVER scatter or gather:
#
# - SUM/AVG over integer dictionary columns reads precomputed bit-sliced
#   "part lanes" (int8 [n_parts, P], 7 bits of the offset value per lane,
#   built once at segment load) and does masked tree reductions per part.
#   Per 8192-block a part sum is <= 127*8192 < 2^20, so int32 block partials
#   are exact; the final f64/int64 combine (<< 7k shifts + min_value*count)
#   happens host-side. Exact at any scale without f64 on device.
# - GROUP-BY SUM/AVG one-hot-encodes the mixed-radix group key per block and
#   matmuls [B, G]^T @ [B, n_parts] on the MXU with f32 accumulation (block
#   sums < 2^24 => exact), accumulating int32 across blocks.
# - Histograms (DISTINCTCOUNT/PERCENTILE) are one-hot matmuls too.
# - MIN/MAX reduce dictIds directly (sorted dict => id order == value order);
#   group-by min/max uses blocked masked min over a [B, G] compare tile.
# Scatter remains only as the fallback for huge group tables / cardinalities.
# ---------------------------------------------------------------------------

BLOCK = 8192                 # row block: must divide padded segment length
CBLOCK = 2048                # MXU stream-compaction block (B=2048/r=16 won
#                              the measured race against B=8192 variants)
DENSE_G_LIMIT = 32768        # one-hot matmul group-table cap
DENSE_ROWS_LIMIT = 1 << 24   # carry-accum int32 bound (127 * 2^24 < 2^31)
DENSE_CARD_LIMIT = 32768     # one-hot matmul histogram cap


def _tile_rows(g: int, n: Optional[int] = None) -> int:
    """Row-tile size for [B, G] one-hot tiles.

    B*G <= 2^24 keeps a bf16 tile within ~32MB of VMEM; B is a multiple
    of BLOCK up to 8*BLOCK when the table is narrow (fewer, fatter scan
    steps — per-step loop overhead dominates small-G histograms
    otherwise), constrained to divide n when given.
    """
    cap = max((1 << 24) // max(g, 1), 1 << 9)
    b = 1 << max(9, min(16, int(np.log2(cap))))
    b = min(b, 8 * BLOCK)
    if n is not None:
        while b > BLOCK and (n % b or b > n):
            b //= 2
        b = min(b, n)
    return b


def _part_sums(part_lanes, mask):
    """Masked exact sums of 7-bit part lanes.

    part_lanes: [n_parts, P] int8 array (or a list of [P] lanes, stacked
    cheaply as inputs); returns int32 [T1, n_parts] chunk partials.

    ONE reduce op over ONE elementwise producer — never a stack/concat
    of per-lane sibling reduces. Measured (round 5, v5e, 100M rows):
    XLA does not multi-output-fuse sibling reductions even into a
    single concatenated output, so the per-lane form materialized the
    int32 where() contribs at row scale — 3.4GB accessed vs 0.8GB, the
    whole 4.9ms-vs-0.8ms q1.x gap. The [n_parts, T, BLOCK] reduce keeps
    the mask + parts in one fused loop at HBM-bandwidth rate.
    """
    if isinstance(part_lanes, (list, tuple)):
        part_lanes = jnp.stack(part_lanes)            # input-side stack
    contrib = jnp.where(mask[None, :], part_lanes, 0).astype(jnp.int32)
    n_l = part_lanes.shape[0]
    p = part_lanes.shape[-1]
    if 127 * p < 2**31:
        # FULL reduce to [n_parts]: the only shape XLA's fast reduce
        # emitter takes at bandwidth. ANY output keeping a block axis —
        # [T1, L] chunked, [L, T] partials, either orientation —
        # measured 5.0ms vs 0.79ms at 100M rows. Exact: 7-bit lanes
        # bound the int32 sum by 127 * padded < 2^31 (padded <= 16.9M
        # rows per segment — every sharded stack shard qualifies).
        return contrib.reshape(n_l, -1).sum(axis=-1, dtype=jnp.int32), True
    # oversized single segment: exactness first — [n_parts, T] block
    # partials (< 2^20 each), host combines in int64
    return contrib.reshape(n_l, -1, BLOCK).sum(
        axis=-1, dtype=jnp.int32), False


def _chunked_float_sum(vals, mask):
    """Masked float sum -> [T] per-block partials (f64 under x64).

    Like _part_sums, the partials are the OUTPUT — a second on-device
    reduce stage broke the single-reduce fusion (measured 6x) — and the
    host's f64 sum over T values is both exact-enough and cheaper than
    the old two-stage f32 ladder."""
    acc = sum_dtype()
    contrib = jnp.where(mask, vals.astype(acc), 0)
    return contrib.reshape(-1, BLOCK).sum(axis=1, dtype=acc)


import os as _os
RADIX_G = int(_os.environ.get("PINOT_TPU_RADIX_G", "512"))
# row-scale accumulations (full-segment dense tables / histograms) factor
# above RADIX_G; the COMPACTED slot tables process ~100x fewer rows, so
# the direct [K, g] one-hot stays cheap much longer and radix's per-row
# lo-products only pay off for wide tables (measured: direct wins at 513
# slots by 1.5x, radix wins at 8193 by 1.2x on v5e)
SLOT_RADIX_G = int(_os.environ.get("PINOT_TPU_SLOT_RADIX_G", "8192"))
SLOT_CHUNK = 1 << 17   # slot-table chunk: 127 * 2^17 < 2^24 (f32-exact)
#                  ^ above this, one-hots are factored hi x lo: VPU
                   # compares per row drop from g to g/128 + 128, and the
                   # wide accumulation happens on the MXU instead
RADIX_LO = 128     # lane width: lo one-hot fills exactly one vreg lane dim


def _cmp_onehot(idx, width: int, dtype):
    """one_hot(idx, width) via a NARROW-dtype compare.

    jax.nn.one_hot builds an s32 iota + s32 broadcast before the
    compare; on this XLA those materialize at FULL [rows, width] s32
    scale (measured: 1.6GB apiece inside one compacted q2.1 kernel —
    HLO dump, round 3), which made every one-hot-fed dot
    HBM-bandwidth-bound. Comparing in int8 (width <= 128) / int16
    shrinks the materialized intermediates 4x. idx must already be in
    [0, width): callers clip group keys to the padded table.
    """
    it = jnp.arange(width,
                    dtype=jnp.int8 if width <= 128 else jnp.int16)
    return (idx[..., None].astype(it.dtype) == it).astype(dtype)


def _radix_onehots(idx, g_pad: int, dtype):
    """idx -> (oh_hi [k, g_pad/128], oh_lo [k, 128]) with
    one_hot(idx, g_pad)[k, g] == oh_hi[k, g//128] * oh_lo[k, g%128].

    The factored product is exact in any float dtype (entries are 0/1),
    so S = hi^T @ (lo * v) accumulates the same sums as the direct
    one-hot matmul at 1/40th the VPU comparison work for g ~ 8k.
    """
    g1 = g_pad // RADIX_LO
    oh_hi = _cmp_onehot(idx // RADIX_LO, g1, dtype)
    oh_lo = _cmp_onehot(idx % RADIX_LO, RADIX_LO, dtype)
    return oh_hi, oh_lo


def _radix_pad(g: int) -> int:
    return -(-g // RADIX_LO) * RADIX_LO


def _radix_group_sum(oh_hi, oh_lo, v, g: int, acc):
    """hi^T @ (lo * v) -> [g] per-group sums of v, in `acc` dtype.

    The factored one-hot accumulation (see _radix_onehots): exact
    whenever v's values are exact in the one-hot dtype and the per-call
    accumulation stays within `acc`'s integer range — each call site
    carries its own bound. Counts are the v == mask special case
    (sum m * hi * lo == (hi weighted by m)^T lo)."""
    return jnp.matmul(oh_hi.T, oh_lo * v[:, None],
                      preferred_element_type=acc).reshape(-1)[:g]


def _mxu_histogram(ids, mask, card_pad: int):
    """One-hot histogram: int32 [card_pad], exact.

    Three regimes (all exact — counts are sums of 0/1, every per-call
    f32 accumulation cell <= b < 2^24):
    - card_pad <= 128: fused compare+reduce on the VPU. The [b, card]
      compare tile fuses into the sum (reduces fuse with producers on
      TPU) so NOTHING row-scale materializes — this is what makes the
      adaptive hist scout ~10ms-class at 100M rows.
    - card_pad < RADIX_G: bf16 one-hot matmul.
    - else: hi/lo-factored bf16 one-hots, the MASK folded into the
      NARROW hi factor (counts = (hi*m)^T @ lo, the 2-D histogram) —
      one MXU row-stream pass. (bf16, not s8: s8 dots measured ~1.4x
      slower on this XLA/v5e stack.)"""
    if card_pad <= RADIX_LO:
        # batched (scan-free) fused compare+reduce: per-block partials
        # then an int32 tree-sum — no carry chain to serialize
        t = ids.shape[0] // BLOCK
        hit = (ids.reshape(t, BLOCK)[:, :, None] ==
               jnp.arange(card_pad, dtype=ids.dtype)) & \
            mask.reshape(t, BLOCK)[:, :, None]
        return hit.sum(axis=1, dtype=jnp.int32).sum(axis=0)

    b = _tile_rows(card_pad, ids.shape[0])
    ids_b = ids.reshape(-1, b)
    mask_b = mask.astype(jnp.bfloat16).reshape(-1, b)
    radix = card_pad >= RADIX_G
    gp = _radix_pad(card_pad)

    def body(carry, tb):
        i, m = tb
        if radix:
            oh_hi, oh_lo = _radix_onehots(i, gp, jnp.bfloat16)
            h = jnp.matmul((oh_hi * m[:, None]).T, oh_lo,
                           preferred_element_type=jnp.float32
                           ).reshape(-1)[:card_pad].astype(jnp.int32)
        else:
            onehot = _cmp_onehot(i, card_pad, jnp.bfloat16)
            h = jnp.matmul(m[None, :], onehot,
                           preferred_element_type=jnp.float32
                           )[0].astype(jnp.int32)               # <= b
        return carry + h, None

    out, _ = jax.lax.scan(body, jnp.zeros(card_pad, jnp.int32),
                          (ids_b, mask_b))
    return out


def _dense_group_count(key, mask, g_pad: int):
    """Per-group match counts — a histogram over group keys."""
    return _mxu_histogram(key, mask, g_pad)


def _dense_group_part_sums(part_lanes, key, mask, g_pad: int,
                           with_count: bool = False):
    """Exact per-group sums of 7-bit part lanes via MXU: int32 [n_parts, g].

    part_lanes: list of 1-D [P] lanes — per-lane [T, b] blocking avoids
    any small-extent tile axis. Carry-accumulated int32; planner
    guarantees padded <= DENSE_ROWS_LIMIT so 127 * rows < 2^31.

    with_count=True folds the group COUNT in as one more summed lane
    (the mask itself), sharing the per-chunk one-hot build — at dense
    SSB shapes the one-hots dominate, so count-plus-parts in one scan
    runs ~2x faster than separate histogram + part-sum passes. Returns
    (sums [n_parts, g], counts [g]) then; sums alone otherwise.
    """
    n_parts = len(part_lanes)
    n_l = n_parts + (1 if with_count else 0)
    radix = g_pad >= RADIX_G
    gp = _radix_pad(g_pad)
    g1 = gp // RADIX_LO
    n = key.shape[0]
    # BATCHED per-block partials — no lax.scan — whenever the operand
    # widths allow. Three measured lessons from the v5e dense floor
    # (q3.1 big-synth, 100M rows, round 3):
    # - the scan carry SERIALIZED the per-step dots: 164ms scan vs 98ms
    #   batched at g=512 (and ~10x the compile time);
    # - s8 x s8 -> s32 dots are a SLOW path on this XLA stack (227ms vs
    #   161ms bf16) — bf16 operands + f32 accumulation stay exact for
    #   7-bit values because each per-block cell sums <= 127 * 8192
    #   < 2^24;
    # - per-lane dots paid one full MXU row stream PER LANE (the
    #   g-independent ~390ms round-2 floor); folding every lane into
    #   the narrow hi factor and concatenating into one operand lets
    #   ALL lanes share one stream.
    # The mask multiplies into the one-hot ONCE (ohm), so value lanes
    # need no row-scale where() prep. Cross-block combine is an exact
    # int32 tree-sum (127 * DENSE_ROWS_LIMIT < 2^31). Wide tables
    # (n_l * g1 > 128, e.g. un-remapped g=8192 with 6 lanes) break the
    # batched einsum's compile (the concat operand stops fusing), so
    # they fall back to the scan-with-concat form — the adaptive hist
    # rung exists precisely to remap those into the batched regime.
    if radix and n_l * g1 <= RADIX_LO:
        t = n // BLOCK
        kb = key.reshape(t, BLOCK)
        mb = mask.astype(jnp.bfloat16).reshape(t, BLOCK)
        oh_hi = _cmp_onehot(kb // RADIX_LO, g1, jnp.bfloat16)
        oh_lo = _cmp_onehot(kb % RADIX_LO, RADIX_LO, jnp.bfloat16)
        ohm = oh_hi * mb[:, :, None]                      # [t, B, g1]
        a = jnp.concatenate(
            [ohm * l.reshape(t, BLOCK).astype(jnp.bfloat16)[:, :, None]
             for l in part_lanes] + ([ohm] if with_count else []),
            axis=2)                                       # [t, B, n_l*g1]
        s = jnp.einsum("tbx,tbc->txc", a, oh_lo,
                       preferred_element_type=jnp.float32)
        out = s.astype(jnp.int32).sum(axis=0).reshape(
            n_l, g1 * RADIX_LO)[:, :g_pad]
    elif not radix:
        t = n // BLOCK
        kb = key.reshape(t, BLOCK)
        mb = mask.astype(jnp.bfloat16).reshape(t, BLOCK)
        oh = _cmp_onehot(kb, g_pad, jnp.bfloat16)           # [t, B, g]
        st = jnp.stack(
            [mb * l.reshape(t, BLOCK).astype(jnp.bfloat16)
             for l in part_lanes] + ([mb] if with_count else []),
            axis=1)                                       # [t, n_l, B]
        s = jnp.einsum("tlb,tbg->tlg", st, oh,
                       preferred_element_type=jnp.float32)
        out = s.astype(jnp.int32).sum(axis=0)
    else:
        # wide-table scan fallback: per-step concat dot, f32-exact at
        # b <= 2^17 (127 * 2^17 < 2^24); _tile_rows caps b at 2^16
        b = _tile_rows(max(n_l * g1 // 2, RADIX_LO), n)
        key_b = key.reshape(-1, b)
        mb = mask.astype(jnp.bfloat16).reshape(-1, b)
        lanes_b = tuple(l.reshape(-1, b) for l in part_lanes)

        def body(carry, tb):
            k, m = tb[0], tb[1]
            cs = tb[2:]
            oh_hi, oh_lo = _radix_onehots(k, gp, jnp.bfloat16)
            ohm = oh_hi * m[:, None]
            a = jnp.concatenate(
                [ohm * c.astype(jnp.bfloat16)[:, None] for c in cs]
                + ([ohm] if with_count else []), axis=1)
            s = jnp.matmul(a.T, oh_lo,
                           preferred_element_type=jnp.float32)
            return carry + s.reshape(n_l, g1 * RADIX_LO)[
                :, :g_pad].astype(jnp.int32), None

        out, _ = jax.lax.scan(body,
                              jnp.zeros((n_l, g_pad), jnp.int32),
                              (key_b, mb) + lanes_b)
    if with_count:
        return out[:n_parts], out[n_parts]
    return out


def _dense_group_float_sums(vals, key, mask, g_pad: int):
    """Per-group float sums via MXU (f32 carry; f64 under x64): [g_pad]."""
    acc = sum_dtype()
    mm_dtype = acc if acc == jnp.float64 else jnp.float32
    b = _tile_rows(g_pad, key.shape[0])
    contrib = jnp.where(mask, vals.astype(mm_dtype), 0)
    key_b = key.reshape(-1, b)
    cb = contrib.reshape(-1, b)
    radix = g_pad >= RADIX_G
    gp = _radix_pad(g_pad)

    def body(carry, tb):
        k, c = tb
        if radix:
            oh_hi, oh_lo = _radix_onehots(k, gp, mm_dtype)
            s = _radix_group_sum(oh_hi, oh_lo, c, g_pad, mm_dtype)
        else:
            onehot = _cmp_onehot(k, g_pad, mm_dtype)
            s = jnp.matmul(c[None, :], onehot,
                           preferred_element_type=mm_dtype)[0]
        return carry + s, None

    out, _ = jax.lax.scan(body, jnp.zeros(g_pad, mm_dtype), (key_b, cb))
    return out


def _dense_group_extreme(ids_or_vals, key, mask, g_pad: int, sentinel,
                         is_min: bool):
    """Blocked masked min/max per group over a [b, G] compare tile."""
    b = _tile_rows(g_pad, key.shape[0])
    v_b = ids_or_vals.reshape(-1, b)
    key_b = key.reshape(-1, b)
    mask_b = mask.reshape(-1, b)
    groups = jnp.arange(g_pad, dtype=jnp.int32)
    init = jnp.full(g_pad, sentinel, ids_or_vals.dtype)

    def body(carry, tb):
        k, v, m = tb
        hit = (k[:, None] == groups[None, :]) & m[:, None]
        tile = jnp.where(hit, v[:, None], sentinel)
        ext = tile.min(axis=0) if is_min else tile.max(axis=0)
        return (jnp.minimum(carry, ext) if is_min
                else jnp.maximum(carry, ext)), None

    out, _ = jax.lax.scan(body, init, (key_b, v_b, mask_b))
    return out


# ---------------------------------------------------------------------------
# Aggregation spec evaluation (no group-by)
#
# agg spec: (fname, col, source, extra)
#   fname ∈ {count, sum, min, max, avg, minmaxrange, distinctcount,
#            sumhist, percentile}
# extra encodes the planner-chosen strategy (see plan._agg_device_spec):
#   sv: ("parts", n_parts) | ("vlane",) | ("hist", card_pad)
#       | ("ids", card_pad)
# Emitted outputs are "device partials" — host code (query/execution)
# finishes them exactly (int64 shift-combine, f64 histogram ⋅ dictionary
# dot, id → value decode).
# ---------------------------------------------------------------------------


def _histogram(cols, col: str, card_pad: int, mask):
    ids = cols[f"{col}.ids"]
    if card_pad <= DENSE_CARD_LIMIT:
        return _mxu_histogram(ids, mask, card_pad)
    return jnp.zeros(card_pad, jnp.int32).at[ids].add(mask.astype(jnp.int32))


def _is_parts_agg(spec) -> bool:
    fname, _col, source, extra = spec
    return fname in ("sum", "avg") and source == "sv" and \
        isinstance(extra, tuple) and extra[0] == "parts"


def _agg_outputs(agg_specs: Tuple, cols, mask, num_docs):
    outs = {}
    hists: Dict[Tuple[str, int], jnp.ndarray] = {}
    # ALL part-lane sums ride ONE reduce over ONE concatenated [L, P]
    # operand (see _part_sums: sibling reduces don't fuse on this XLA —
    # q4.x's two SUM columns would otherwise pay the materialized-contrib
    # tax twice)
    parts_aggs = [(i, spec) for i, spec in enumerate(agg_specs)
                  if _is_parts_agg(spec)]
    if parts_aggs:
        arrs = [cols[f"{spec[1]}.parts"] for _i, spec in parts_aggs]
        combined = arrs[0] if len(arrs) == 1 else jnp.concatenate(arrs, 0)
        sums, reduced = _part_sums(combined, mask)   # [L] | [L, T]
        key = "parts" if reduced else "partsT"
        off = 0
        for i, spec in parts_aggs:
            n_p = cols[f"{spec[1]}.parts"].shape[0]
            outs[f"agg{i}.{key}"] = sums[off: off + n_p]
            outs[f"agg{i}.count"] = mask.sum(dtype=jnp.int32)
            off += n_p
    for i, spec in enumerate(agg_specs):
        fname, col, source, extra = spec
        if _is_parts_agg(spec):
            continue                     # emitted by the fused pass above
        if fname == "count":
            outs[f"agg{i}"] = mask.sum(dtype=jnp.int32)
        elif fname in ("sum", "avg") and source == "sv" and \
                isinstance(extra, tuple) and extra[0] == "vlane":
            # float dictionary values: decoded value lane, chunked f32/f64
            outs[f"agg{i}.vsum"] = _chunked_float_sum(cols[f"{col}.vlane"],
                                                      mask)
            outs[f"agg{i}.count"] = mask.sum(dtype=jnp.int32)
        elif fname in ("sum", "avg", "distinctcount", "percentile",
                       "hist") and source == "sv":
            card_pad = extra[1] if isinstance(extra, tuple) else extra
            hk = (col, card_pad)
            if hk not in hists:
                hists[hk] = _histogram(cols, col, card_pad, mask)
            # percentile: host walks the value-count CDF; distinctcount:
            # host needs the value set anyway for cross-segment merge
            outs[f"agg{i}"] = hists[hk]
        elif fname == "hll" and source == "sv":
            # HLL sketch registers ON DEVICE: the dictId histogram's
            # present set drives an O(cardinality) scatter-max of the
            # precomputed per-dictId (register index, rank) tables
            # (hashes shared with the host HyperLogLog twin through
            # sketches.hll_tables) into the [m] register array.
            # Registers merge ASSOCIATIVELY (elementwise max) across
            # segments, shards and servers — rank 0 is the merge
            # identity, so masked/padding ids contribute nothing.
            card_pad, m = extra[1], extra[2]
            hk = (col, card_pad)
            if hk not in hists:
                hists[hk] = _histogram(cols, col, card_pad, mask)
            idx = cols[f"{col}.hllidx"]
            rank = cols[f"{col}.hllrank"]
            present = hists[hk] > 0
            outs[f"agg{i}.hll"] = jnp.zeros(m, jnp.int32).at[idx].max(
                jnp.where(present, rank, 0))
        elif source == "mv":
            card_pad, card = extra
            ids = cols[f"{col}.mv"]
            entry_mask = mask[:, None] & (ids < card)  # drop padding entries
            if fname in ("sum", "avg", "percentile", "distinctcount",
                         "countmv"):
                hk = (col, card_pad, "mv")
                if hk not in hists:
                    hists[hk] = jnp.zeros(card_pad, jnp.int32).at[
                        ids.reshape(-1)].add(
                            entry_mask.reshape(-1).astype(jnp.int32))
                if fname == "countmv":
                    outs[f"agg{i}"] = hists[hk][:card].sum(dtype=jnp.int32)
                else:
                    outs[f"agg{i}"] = hists[hk]
            elif fname in ("min", "max", "minmaxrange"):
                if fname in ("min", "minmaxrange"):
                    outs[f"agg{i}.min"] = jnp.where(entry_mask, ids,
                                                    card_pad).min()
                if fname in ("max", "minmaxrange"):
                    outs[f"agg{i}.max"] = jnp.where(entry_mask, ids, -1).max()
            else:
                raise ValueError(f"unsupported MV aggregation {fname}")
        elif fname in ("min", "max", "minmaxrange") and source == "sv":
            card_pad = extra[1] if isinstance(extra, tuple) else extra
            ids = cols[f"{col}.ids"].astype(jnp.int32)
            if fname in ("min", "minmaxrange"):
                outs[f"agg{i}.min"] = jnp.where(mask, ids, card_pad).min()
            if fname in ("max", "minmaxrange"):
                outs[f"agg{i}.max"] = jnp.where(mask, ids, -1).max()
        elif fname in ("sum", "avg", "min", "max", "minmaxrange") and \
                source == "raw":
            vals = cols[f"{col}.raw"]
            if fname in ("sum", "avg"):
                outs[f"agg{i}.vsum"] = _chunked_float_sum(vals, mask)
                outs[f"agg{i}.count"] = mask.sum(dtype=jnp.int32)
            if fname in ("min", "minmaxrange"):
                outs[f"agg{i}.min"] = jnp.where(mask, vals,
                                                jnp.inf).min()
            if fname in ("max", "minmaxrange"):
                outs[f"agg{i}.max"] = jnp.where(mask, vals,
                                                -jnp.inf).max()
        else:
            raise ValueError(f"unsupported aggregation spec {spec}")
    return outs


# ---------------------------------------------------------------------------
# Group-by
#
# group spec: (cols=((name, kind, off, card), ...), strides=(s1,...), g_pad,
#              aggs=(agg specs), kmax)
# Keys are mixed-radix over dictIds; table arrays are pow2-padded.
#
# kmax > 0 selects the SORT-COMPACTED path for filtered group-bys: sort
# (masked key, iota) so matched rows form a prefix, slice kmax rows, and
# aggregate only those. Measured on v5e this beats both the all-rows one-hot
# matmul (selective filters pay row×G work for nothing) and the all-rows
# scatter (~150M rows/s serialized) by 4-10x at SSB shapes. When more than
# kmax rows match, the kernel raises the `group.overflow` flag and the
# executor re-runs with an escalated kmax (plan.escalate_group_kmax).
# ---------------------------------------------------------------------------


def _group_key(gcols, strides, g_pad, cols, params=None):
    key = None
    for (c, gkind, off, _card), s in zip(gcols, strides):
        if gkind == "rawoff":
            # no-dictionary integer group key: bin by (value - min), the
            # on-the-fly analogue of a dictId (metadata min/max bound the
            # range; the planner verified it fits the group table)
            lane = cols[f"{c}.raw"]
            ids = (lane - lane.dtype.type(off)).astype(jnp.int32)
        elif gkind == "idoff":
            # adaptive dense remap (plan.drive_group_execution): the
            # filter's phase-A scout bounded this column's active dictIds
            # to [off, off+span); re-base so the group table covers only
            # the active subspace. The offset is a RUNTIME operand (and
            # spans are pow2-bucketed by the planner) so one compiled
            # executable serves every literal of the same query template.
            off_op = params.pop(0)
            ids = cols[f"{c}.ids"].astype(jnp.int32) - off_op
        elif gkind == "idrank":
            # adaptive DENSIFYING remap: the scout's per-dim histogram
            # found the PRESENT dictIds (scattered ids — e.g. the five
            # Asian nations in a sorted nation dictionary — make
            # offset spans 4-8x wider than the actual active set); the
            # rank vector (runtime operand, [card_pad] int32) maps
            # id -> rank-among-present, collapsing the key space to the
            # bucketed present counts. Evaluated as a ONE-HOT MATMUL,
            # never a row-scale gather (measured: rank[ids] gathers at
            # ~90M rows/s on v5e — 1.1s/dim at 100M rows — vs ~15ms for
            # the [rows, card_pad<=512] one-hot contraction; exact: the
            # one-hot is 0/1 and ranks < 512 are exact in f32).
            # Unmatched rows map to garbage ranks; their contributions
            # are masked everywhere.
            rank = params.pop(0)
            lane = cols[f"{c}.ids"].astype(jnp.int32)
            oh = _cmp_onehot(lane, rank.shape[0], jnp.bfloat16)
            ids = jnp.matmul(oh, rank.astype(jnp.float32)[:, None],
                             preferred_element_type=jnp.float32
                             )[:, 0].astype(jnp.int32)
        elif gkind == "jcode":
            # dict-keyed join group code: the per-dictId fact-key →
            # dim-group-code translation table (runtime operand,
            # [card_pad] int32, built host-side in O(cardinality) by the
            # join planner). A GATHER, not the idrank one-hot matmul:
            # join translate tables span the FACT key's cardinality
            # (thousands to millions), where an O(rows·card) contraction
            # loses to the O(rows) gather. Unmatched dictIds carry code
            # 0 — masked by the fused join-match predicate everywhere.
            code = params.pop(0)
            lane = cols[f"{c}.ids"].astype(jnp.int32)
            ids = code[jnp.clip(lane, 0, code.shape[0] - 1)]
        elif gkind == "jraw":
            # raw-keyed join group code: device-built sorted probe over
            # the dim (key, code) pair — the group-side twin of the
            # join_raw predicate (XLA CSE shares the sort/searchsorted
            # between them). Padding repeats (max key, its code), so
            # probe hits in the padding run resolve to the right code.
            keys = params.pop(0)                   # [Dp] fact-key dtype
            codes = params.pop(0)                  # [Dp] int32
            sk, sc = jax.lax.sort((keys, codes), num_keys=1)
            lane = cols[f"{c}.raw"]
            pos = jnp.clip(jnp.searchsorted(sk, lane), 0, sk.shape[0] - 1)
            ids = sc[pos]
        else:
            ids = cols[f"{c}.ids"].astype(jnp.int32)
        term = ids * np.int32(s)
        key = term if key is None else key + term
    return jnp.clip(key, 0, g_pad - 1)


PLANE_BITS = 7     # compaction planes carry 7-bit values: <= 127 keeps
#                    every plane s8-exact, so the whole compact pipeline
#                    (block compaction + slot tables) runs s8 x s8 -> s32
#                    on the MXU — 2x the bf16 rate, no f32 2^24 bound


def _planes_for(maxval: int) -> int:
    """7-bit planes needed to carry values in [0, maxval]."""
    b = 1
    while (1 << (PLANE_BITS * b)) <= maxval:
        b += 1
    return b


def _block_compact(mask, int_lanes, f32_lanes, r: int):
    """MXU stream compaction: matched rows of each 8192-row block move to
    r per-block output slots via a fused one-hot matmul (no sorts, no
    row-scale scatters/gathers — random HBM access is the slow primitive
    on TPU, matmul is the fast one). Each (block, slot) output cell has
    exactly ONE contributing row, so the f32 accumulation is exact.

    int_lanes: list of [n] integer lanes with values in [0, 127]
    (7-bit planes — s8-exact; any int dtype). f32_lanes: list of [n]
    float lanes, moved in sum_dtype() (f64 under x64 for host parity,
    f32 on device).
    Returns (ints [K, Pi], floats [K, Pf], valid [K], overflow) with
    K = (n // CBLOCK) * r. Rows past r in an overflowing block are
    dropped; `overflow` flags it and the executor escalates kmax.
    """
    n = mask.shape[0]
    t = n // CBLOCK
    mb = mask.reshape(t, CBLOCK)
    # int16 positions/iota: the [t, B, r] one-hot's compare operands
    # materialize at row scale (HLO-measured GBs in s32), so narrow
    # dtypes are the compact path's bandwidth lever (CBLOCK <= 2^15)
    pos = jnp.cumsum(mb.astype(jnp.int16), axis=1) - 1
    cnt = mb.sum(axis=1, dtype=jnp.int32)
    overflow = (cnt > r).any().astype(jnp.int32)
    oh = (pos[:, :, None] == jnp.arange(r, dtype=jnp.int16)) & \
        mb[:, :, None]                                    # [t, B, r]
    ints = None
    if int_lanes:
        # bf16 x bf16 -> f32: exact (one contributor per output cell,
        # values <= 127). s8 x s8 -> s32 measured ~1.4x SLOWER on this
        # XLA/v5e stack — this einsum IS the compact path's row-scale
        # floor (one full row stream), so its dtype is the hot choice.
        lb = jnp.stack([v.reshape(t, CBLOCK).astype(jnp.bfloat16)
                        for v in int_lanes], axis=-1)
        ints = jnp.einsum("tbr,tbl->trl", oh.astype(jnp.bfloat16), lb,
                          preferred_element_type=jnp.float32
                          ).reshape(t * r, len(int_lanes)).astype(jnp.int32)
    floats = None
    if f32_lanes:
        facc = sum_dtype()
        lf = jnp.stack([v.reshape(t, CBLOCK).astype(facc)
                        for v in f32_lanes], axis=-1)
        floats = jnp.einsum("tbr,tbl->trl", oh.astype(facc), lf,
                            preferred_element_type=facc
                            ).reshape(t * r, len(f32_lanes))
    valid = (jnp.arange(r, dtype=jnp.int32)[None, :] <
             jnp.minimum(cnt, r)[:, None]).reshape(t * r)
    return ints, floats, valid, overflow


def _slot_sum_tables(gslot, t_slots: int, int_vals, f32_vals, count_mask):
    """Per-group sums/counts via chunked one-hot matmuls.

    gslot [K] in [0, t_slots] (t_slots = drop slot). Int lanes carry
    7-bit values (<= 127, _planes_for planes / metric parts): chunks
    of <= SLOT_CHUNK = 2^17 rows keep every bf16-product cell sum
    exact in the f32 accumulator (127 * 2^17 < 2^24; the round-2
    2^16 chunk at K ~ 3M meant 48 scan steps x ~0.7ms fixed overhead
    — the measured ~35ms slot-table floor — so the bound is taken to
    its max); chunks combine in int32 (127 * K < 2^31 for K < 2^24 —
    callers route bigger K through DENSE_ROWS_LIMIT macro-chunking).
    bf16 x bf16 -> f32 dots are deliberate: s8 dots measured ~1.4x
    SLOWER on this XLA/v5e stack, and the one-hot operands here must
    stay un-materialized producer fusions (ranked layouts reach
    t_slots ~ millions — a concatenated/stacked operand would
    materialize at [chunk, t_slots/128] scale and cannot compile).
    Returns (int_tables [Li, t_slots] int32, f32_tables [Lf, t_slots],
    counts [t_slots] int32); any of the value args may be None.
    """
    k = gslot.shape[0]
    n_iv = 0 if int_vals is None else int_vals.shape[1]
    n_l = n_iv + (1 if count_mask is not None else 0)   # dispatched lanes
    gp = _radix_pad(t_slots + 1)
    g1 = gp // RADIX_LO
    if n_l and (t_slots + 1 < RADIX_G or n_l * g1 <= RADIX_LO):
        # NARROW tables (the dense/offset-remapped layouts) route the
        # int lanes + count through the BATCHED dense kernel — at
        # compacted caps of ~3M rows the chunked scan below costs ~24
        # sequential steps x ~0.7ms fixed overhead, the dominant term
        # of q2.1-class compacted group-bys (measured round 3)
        kp = -(-k // BLOCK) * BLOCK
        gs_p = jnp.pad(gslot, (0, kp - k), constant_values=t_slots)
        lanes = [jnp.pad(int_vals[:, p], (0, kp - k))
                 for p in range(n_iv)]
        if count_mask is not None:
            # the count mask rides as one more 0/1 VALUE lane (counts
            # are independent of the int sums — masking the sums by it
            # would break the contract), with an all-true row mask;
            # invalid rows land in the drop slot, which is sliced off
            lanes.append(jnp.pad(count_mask, (0, kp - k)).astype(jnp.int8))
        out = _dense_group_part_sums(lanes, gs_p, jnp.ones(kp, bool),
                                     t_slots + 1)
        tf = None
        if f32_vals is not None:
            tf = _slot_sum_tables(gslot, t_slots, None, f32_vals, None)[1]
        return (None if int_vals is None else out[:n_iv, :t_slots],
                tf,
                None if count_mask is None else out[n_iv, :t_slots])
    ch = min(k, SLOT_CHUNK)
    nch = -(-k // ch)
    pad = nch * ch - k
    gs = jnp.pad(gslot, (0, pad), constant_values=t_slots).reshape(nch, ch)
    acc = sum_dtype()

    iv = None if int_vals is None else jnp.pad(
        int_vals, ((0, pad), (0, 0))).reshape(nch, ch, -1)
    fv = None if f32_vals is None else jnp.pad(
        f32_vals, ((0, pad), (0, 0))).reshape(nch, ch, -1)
    cm = None if count_mask is None else jnp.pad(
        count_mask, (0, pad)).reshape(nch, ch)

    radix = (t_slots + 1) > SLOT_RADIX_G
    gp = _radix_pad(t_slots + 1)

    def body(carry, xs):
        ci, cf, cc = carry
        g = xs[0]
        j = 1
        if radix:
            # factored accumulation: per value lane, one [k, 128]
            # elementwise product + one MXU matmul replaces the [k, g]
            # one-hot build (the VPU cost that dominated group-by at
            # g ~ 8k; see _radix_onehots)
            oh_hi, oh_lo = _radix_onehots(g, gp, jnp.bfloat16)
            if iv is not None:
                v = xs[j].astype(jnp.bfloat16)
                ci = ci + jnp.stack([
                    _radix_group_sum(oh_hi, oh_lo, v[:, p], t_slots + 1,
                                     jnp.float32)
                    for p in range(v.shape[1])]).astype(jnp.int32)
                j += 1
            if fv is not None:
                hi_a, lo_a = oh_hi.astype(acc), oh_lo.astype(acc)
                v = xs[j].astype(acc)
                cf = cf + jnp.stack([
                    _radix_group_sum(hi_a, lo_a, v[:, p], t_slots + 1, acc)
                    for p in range(v.shape[1])])
                j += 1
            if cm is not None:
                m = xs[j].astype(jnp.bfloat16)
                cc = cc + _radix_group_sum(
                    oh_hi, oh_lo, m, t_slots + 1,
                    jnp.float32).astype(jnp.int32)
            return (ci, cf, cc), None
        oh2 = g[:, None] == jnp.arange(t_slots + 1, dtype=jnp.int32)
        if iv is not None:
            ci = ci + jnp.einsum(
                "kg,kl->lg", oh2.astype(jnp.bfloat16),
                xs[j].astype(jnp.bfloat16),
                preferred_element_type=jnp.float32).astype(jnp.int32)
            j += 1
        if fv is not None:
            cf = cf + jnp.einsum(
                "kg,kl->lg", oh2.astype(acc), xs[j].astype(acc),
                preferred_element_type=acc)
            j += 1
        if cm is not None:
            cc = cc + jnp.einsum(
                "kg,k->g", oh2.astype(jnp.bfloat16),
                xs[j].astype(jnp.bfloat16),
                preferred_element_type=jnp.float32).astype(jnp.int32)
        return (ci, cf, cc), None

    init = (
        jnp.zeros((iv.shape[2] if iv is not None else 0, t_slots + 1),
                  jnp.int32),
        jnp.zeros((fv.shape[2] if fv is not None else 0, t_slots + 1), acc),
        jnp.zeros(t_slots + 1, jnp.int32))
    xs = (gs,) + tuple(x for x in (iv, fv, cm) if x is not None)
    (ti, tf, tc), _ = jax.lax.scan(body, init, xs)
    return (None if int_vals is None else ti[:, :t_slots],
            None if f32_vals is None else tf[:, :t_slots],
            None if count_mask is None else tc[:t_slots])


def _group_outputs_compacted_sorted(group_spec, cols, mask, num_docs,
                                    params=None):
    """Terminal fallback for barely-selective compacted group-bys
    (r > 256): full-segment sort compaction + scatters into dense
    [g_pad] tables. Slower than the MXU path but its memory/compute is
    bounded at any escalation rung, where the one-hot einsums would
    build O(rows * r) / O(cap * slots) intermediates."""
    gcols, strides, g_pad, agg_specs, kmax = group_spec
    key = _group_key(gcols, strides, g_pad, cols, params)
    n = mask.shape[0]
    mk = jnp.where(mask, key, jnp.int32(g_pad))      # invalid rows sort last
    iota = jnp.arange(n, dtype=jnp.int32)
    sk, si = jax.lax.sort((mk, iota), num_keys=1)
    k_c, si_c = sk[:kmax], si[:kmax]
    vm = k_c < g_pad
    matched = mask.sum(dtype=jnp.int32)
    outs = {"group.overflow": (matched > kmax).astype(jnp.int32),
            "group.count": jnp.zeros(g_pad + 1, jnp.int32).at[k_c].add(
                vm.astype(jnp.int32))[:g_pad]}
    acc = sum_dtype()
    for i, spec in enumerate(agg_specs):
        fname, col, source, extra = spec
        if fname == "count":
            continue
        strategy = extra[0] if isinstance(extra, tuple) else "vals"
        if fname in ("sum", "avg"):
            if strategy == "psums":
                # part lanes gathered at the compacted rows, int32
                # scatter per part; kmax past DENSE_ROWS_LIMIT is chunked
                # into a leading axis the host recombines in int64
                pv = cols[f"{col}.parts"][:, si_c].astype(jnp.int32)
                pv = jnp.where(vm[None, :], pv, 0)
                n_parts = pv.shape[0]
                if kmax > DENSE_ROWS_LIMIT:
                    n_ch = -(-kmax // DENSE_ROWS_LIMIT)
                    pad = n_ch * DENSE_ROWS_LIMIT - kmax
                    kc = jnp.pad(k_c, (0, pad), constant_values=g_pad
                                 ).reshape(n_ch, -1)
                    pc = jnp.pad(pv, ((0, 0), (0, pad))
                                 ).reshape(n_parts, n_ch, -1)
                    outs[f"gagg{i}.cpsums"] = jax.vmap(
                        lambda k, p: jnp.zeros(
                            (n_parts, g_pad + 1),
                            jnp.int32).at[:, k].add(p)[:, :g_pad],
                        in_axes=(0, 1))(kc, pc)
                else:
                    outs[f"gagg{i}.cpsums"] = jnp.zeros(
                        (n_parts, g_pad + 1),
                        jnp.int32).at[:, k_c].add(pv)[:, :g_pad]
            else:
                lane = cols[f"{col}.vlane" if source == "sv"
                            else f"{col}.raw"]
                lv = jnp.where(vm, lane[si_c].astype(acc), 0)
                outs[f"gagg{i}.sum"] = jnp.zeros(
                    g_pad + 1, acc).at[k_c].add(lv)[:g_pad]
        elif fname in ("min", "max", "minmaxrange"):
            if source == "sv":
                card_pad = extra[1]
                idv = cols[f"{col}.ids"][si_c].astype(jnp.int32)
                if fname in ("min", "minmaxrange"):
                    outs[f"gagg{i}.min"] = jnp.full(
                        g_pad + 1, card_pad, jnp.int32).at[k_c].min(
                        jnp.where(vm, idv, card_pad))[:g_pad]
                if fname in ("max", "minmaxrange"):
                    outs[f"gagg{i}.max"] = jnp.full(
                        g_pad + 1, -1, jnp.int32).at[k_c].max(
                        jnp.where(vm, idv, -1))[:g_pad]
            else:
                vv = cols[f"{col}.raw"][si_c].astype(acc)
                if fname in ("min", "minmaxrange"):
                    outs[f"gagg{i}.min"] = jnp.full(
                        g_pad + 1, jnp.inf, acc).at[k_c].min(
                        jnp.where(vm, vv, jnp.inf))[:g_pad]
                if fname in ("max", "minmaxrange"):
                    outs[f"gagg{i}.max"] = jnp.full(
                        g_pad + 1, -jnp.inf, acc).at[k_c].max(
                        jnp.where(vm, vv, -jnp.inf))[:g_pad]
        else:
            raise ValueError(f"unsupported group-by aggregation {fname}")
    return outs


def _group_outputs_compacted(group_spec, cols, mask, num_docs,
                             params=None):
    """Filtered group-by over MXU-compacted matched rows.

    Every needed lane (mixed-radix key bytes, int8 metric parts, float
    value lanes, dictIds for extrema) is block-compacted by _block_compact
    in ONE fused one-hot matmul, then aggregated into group tables by a
    second one-hot matmul (_slot_sum_tables). Measured ~500x faster than
    the sort- or scatter-based alternatives at SSB shapes on v5e: the
    only row-scale work is elementwise + matmul. Two table layouts:

    - g_pad <= DENSE_G_LIMIT: dense [g_pad] tables addressed by key
      (shared key space → device psum combine across segments).
    - g_pad >  DENSE_G_LIMIT ("ranked"): sort the compacted keys (k-scale
      only), rank-dedup, tables addressed by group RANK + a parallel
      `group.rkeys` lane. Bounded by matched rows, not by the key
      cross-product; host merges per-segment rank spaces by key (the
      CombineGroupByOperator merge, done columnar in numpy).
    """
    gcols, strides, g_pad, agg_specs, kmax = group_spec
    n = mask.shape[0]
    t = n // CBLOCK
    r = min(max(-(-kmax // t), 8), CBLOCK)
    if r > 256:
        # barely-selective escalation rung: the one-hot compaction would
        # cost O(rows * r) — the bounded sort+scatter fallback wins there
        return _group_outputs_compacted_sorted(group_spec, cols, mask,
                                               num_docs, params)
    key = _group_key(gcols, strides, g_pad, cols, params)

    # lane registry: key byte planes + per-agg value planes
    n_kb = _planes_for(g_pad - 1)
    int_lanes = [((key >> (PLANE_BITS * b)) & 0x7F) for b in range(n_kb)]
    f32_lanes = []
    int_slots: Dict[int, Tuple[int, int]] = {}   # agg i → (start, n_planes)
    f32_slots: Dict[int, int] = {}
    id_slots: Dict[int, Tuple[int, int]] = {}    # agg i → ids byte planes
    for i, spec in enumerate(agg_specs):
        fname, col, source, extra = spec
        if fname == "count":
            continue
        strategy = extra[0] if isinstance(extra, tuple) else "vals"
        if fname in ("sum", "avg"):
            if strategy == "psums":
                pl = cols[f"{col}.parts"]
                plist = [pl[p] for p in range(pl.shape[0])]
                int_slots[i] = (len(int_lanes), len(plist))
                int_lanes.extend(plist)   # 7-bit values: bf16-exact
            else:
                lane = cols[f"{col}.vlane" if source == "sv"
                            else f"{col}.raw"]
                f32_slots[i] = len(f32_lanes)
                f32_lanes.append(lane.astype(jnp.float32))
        elif fname in ("min", "max", "minmaxrange"):
            if source == "sv":
                card_pad = extra[1]
                ids = cols[f"{col}.ids"].astype(jnp.int32)
                nb = _planes_for(card_pad - 1)
                id_slots[i] = (len(int_lanes), nb)
                for b in range(nb):
                    int_lanes.append((ids >> (PLANE_BITS * b)) & 0x7F)
            else:
                f32_slots[i] = len(f32_lanes)
                f32_lanes.append(cols[f"{col}.raw"].astype(jnp.float32))
        else:
            raise ValueError(f"unsupported group-by aggregation {fname}")

    ci, cf, valid, overflow = _block_compact(mask, int_lanes, f32_lanes, r)
    cap = t * r
    outs = {"group.overflow": overflow}

    def _reassemble(start, nb):
        v = ci[:, start].astype(jnp.int32)
        for b in range(1, nb):
            v = v + (ci[:, start + b].astype(jnp.int32) << (PLANE_BITS * b))
        return v

    k_c = jnp.where(valid, _reassemble(0, n_kb), jnp.int32(g_pad))
    acc = sum_dtype()

    ranked = g_pad > DENSE_G_LIMIT
    if ranked:
        # sort only the compacted keys (cap-scale), rank-dedup
        sk, order = jax.lax.sort((k_c, jnp.arange(cap, dtype=jnp.int32)),
                                 num_keys=1)
        vs = sk < g_pad
        if ci is not None:
            ci = ci[order]
        if cf is not None:
            cf = cf[order]
        valid = vs
        newg = vs & jnp.concatenate([vs[:1], sk[1:] != sk[:-1]])
        gslot = jnp.where(vs, jnp.cumsum(newg.astype(jnp.int32)) - 1, cap)
        t_slots = cap
        outs["group.rkeys"] = jnp.full(
            cap + 1, g_pad, jnp.int32).at[
            jnp.where(newg, gslot, cap)].set(sk)[:cap]
        sum_key, min_key, max_key, psums_key = ("rsum", "rmin", "rmax",
                                                "rpsums")
    else:
        gslot = jnp.where(valid, k_c, g_pad)
        t_slots = g_pad
        sum_key, min_key, max_key, psums_key = ("sum", "min", "max",
                                                "cpsums")

    # the int value columns actually summed (metric parts)
    part_cols = []
    for i, (start, np_) in int_slots.items():
        part_cols.extend(range(start, start + np_))
    iv = ci[:, part_cols] if part_cols else None
    if iv is not None:
        iv = jnp.where(valid[:, None], iv, 0)
    fvals = cf if f32_slots else None
    if fvals is not None:
        fvals = jnp.where(valid[:, None], fvals, 0)
    if iv is not None and cap > DENSE_ROWS_LIMIT:
        # int32 accumulation bound (127 * 2^24 < 2^31): emit per-macro-
        # chunk tables; the host recombines chunks exactly in int64
        n_mc = -(-cap // DENSE_ROWS_LIMIT)
        ti = jnp.stack([
            _slot_sum_tables(
                gslot[c * DENSE_ROWS_LIMIT: (c + 1) * DENSE_ROWS_LIMIT],
                t_slots,
                iv[c * DENSE_ROWS_LIMIT: (c + 1) * DENSE_ROWS_LIMIT],
                None, None)[0]
            for c in range(n_mc)])                      # [C, L, t_slots]
        _, tf, tc = _slot_sum_tables(gslot, t_slots, None, fvals,
                                     valid)
    else:
        ti, tf, tc = _slot_sum_tables(gslot, t_slots, iv, fvals,
                                      valid)
    if ranked:
        outs["group.rcount"] = tc
    else:
        outs["group.count"] = tc

    # map table rows back to per-agg outputs
    pci = 0
    for i, spec in enumerate(agg_specs):
        fname, col, source, extra = spec
        if fname == "count":
            continue
        strategy = extra[0] if isinstance(extra, tuple) else "vals"
        if fname in ("sum", "avg"):
            if strategy == "psums":
                _, np_ = int_slots[i]
                outs[f"gagg{i}.{psums_key}"] = (
                    ti[:, pci: pci + np_] if ti.ndim == 3
                    else ti[pci: pci + np_])
                pci += np_
            else:
                outs[f"gagg{i}.{sum_key}"] = tf[f32_slots[i]]
        elif fname in ("min", "max", "minmaxrange"):
            if source == "sv":
                card_pad = extra[1]
                start, nb = id_slots[i]
                idv = _reassemble(start, nb)
                if fname in ("min", "minmaxrange"):
                    outs[f"gagg{i}.{min_key}"] = jnp.full(
                        t_slots + 1, card_pad, jnp.int32).at[gslot].min(
                        jnp.where(valid, idv, card_pad))[:t_slots]
                if fname in ("max", "minmaxrange"):
                    outs[f"gagg{i}.{max_key}"] = jnp.full(
                        t_slots + 1, -1, jnp.int32).at[gslot].max(
                        jnp.where(valid, idv, -1))[:t_slots]
            else:
                vv = cf[:, f32_slots[i]].astype(acc)
                if fname in ("min", "minmaxrange"):
                    outs[f"gagg{i}.{min_key}"] = jnp.full(
                        t_slots + 1, jnp.inf, acc).at[gslot].min(
                        jnp.where(valid, vv, jnp.inf))[:t_slots]
                if fname in ("max", "minmaxrange"):
                    outs[f"gagg{i}.{max_key}"] = jnp.full(
                        t_slots + 1, -jnp.inf, acc).at[gslot].max(
                        jnp.where(valid, vv, -jnp.inf))[:t_slots]
    return outs


def _expand_mv_group(group_spec, cols, mask, params=None):
    """Row-space expansion for MV group keys: one row per (doc, entry)
    cross-combination across all MV key columns (reference parity:
    DefaultGroupByExecutor.aggregateGroupByMV — a doc contributes once
    per value combination, and its metrics repeat per combination).

    Returns (group_spec', cols', mask') with every "mvids"/"mvin" gcol
    rewritten to a flattened "ids" lane over rows*W rows (W = product
    of the MV columns' padded entry widths, static from lane shapes);
    padding entries (id == cardinality) mask their rows out, and "mvin"
    dims (valuein group keys) additionally mask entries outside their
    allowed-value member vector — a RUNTIME operand popped from
    `params` in gcol order. Only row-scale lanes the group machinery
    reads are expanded; dictionary value tables pass through. W
    multiplies the row count, so this is reserved for MV group-bys
    (never on the SSB hot path)."""
    gcols, strides, g_pad, agg_specs, kmax = group_spec
    n = mask.shape[0]
    # widths/entry indexes are keyed per GCOL POSITION, not per column
    # name: two group keys over the same MV column (e.g. GROUP BY col,
    # valuein(col, ...)) must each contribute an independent axis of
    # the entry cross-product — the reference expands each key position
    # sequentially (DefaultGroupByExecutor.aggregateGroupByMV), so a
    # name-keyed expansion would produce diagonal (same-entry) pairs
    # only and diverge from the host executor (round-2 advisor finding)
    widths = [(gi, c, cols[f"{c}.mv"].shape[-1])
              for gi, (c, gkind, _o, _card) in enumerate(gcols)
              if gkind in ("mvids", "mvin")]
    total_w = int(np.prod([w for _gi, _c, w in widths], dtype=np.int64))
    # mixed-radix decomposition of the cross index over the mv widths
    entry_idx, stride = {}, 1
    for gi, _c, w in widths:
        entry_idx[gi] = (np.arange(total_w) // stride) % w
        stride *= w

    def rep1(lane):                       # [n] -> [n * total_w]
        return jnp.broadcast_to(lane[:, None],
                                (n, total_w)).reshape(-1)

    cols2, mask2, gcols2 = {}, rep1(mask), []
    for gi, (c, gkind, off, card) in enumerate(gcols):
        if gkind in ("mvids", "mvin"):
            flat = cols[f"{c}.mv"][:, entry_idx[gi]].reshape(-1)
            # alias the expanded lane per position so a repeated column
            # keeps its per-position entry axis
            alias = f"{c}#g{gi}"
            cols2[f"{alias}.ids"] = flat
            mask2 = mask2 & (flat < card)
            if gkind == "mvin":
                member = params.pop(0)     # bool [card_pad], pad False
                mask2 = mask2 & member[
                    jnp.clip(flat, 0, member.shape[0] - 1)]
            gcols2.append((alias, "ids", off, card))
        else:
            gcols2.append((c, gkind, off, card))
    for key, lane in cols.items():
        if key in cols2:
            continue
        if key.endswith(".mv"):
            w = lane.shape[-1]
            cols2[key] = jnp.broadcast_to(
                lane[:, None, :], (n, total_w, w)).reshape(-1, w)
        elif key.endswith(".parts"):      # [n_parts, n]
            cols2[key] = jnp.broadcast_to(
                lane[:, :, None],
                lane.shape + (total_w,)).reshape(lane.shape[0], -1)
        elif key.endswith(".vals"):       # dictionary value table
            cols2[key] = lane
        else:                             # .ids / .raw / .vlane: [n]
            cols2[key] = rep1(lane)
    # compaction capacity scales with the expansion (the escalation
    # ladder still covers skew/overflow)
    kmax2 = min(kmax * total_w, n * total_w) if kmax else 0
    spec2 = (tuple(gcols2), strides, g_pad, agg_specs, kmax2)
    return spec2, cols2, mask2


def _group_outputs(group_spec, cols, mask, num_docs, params=None):
    if any(g[1] in ("mvids", "mvin") for g in group_spec[0]):
        group_spec, cols, mask = _expand_mv_group(group_spec, cols, mask,
                                                  params)
    gcols, strides, g_pad, agg_specs, kmax = group_spec
    if kmax:
        return _group_outputs_compacted(group_spec, cols, mask, num_docs,
                                        params)
    key = _group_key(gcols, strides, g_pad, cols, params)
    dense = g_pad <= DENSE_G_LIMIT and mask.shape[0] <= DENSE_ROWS_LIMIT
    # all part-sum aggregations + the group count share ONE fused scan
    # (one-hot builds dominate at dense shapes; fusing halves the passes)
    psums_specs = [(i, spec) for i, spec in enumerate(agg_specs)
                   if spec[0] in ("sum", "avg") and
                   isinstance(spec[3], tuple) and spec[3][0] == "psums"]
    outs = {}
    if dense and psums_specs:
        lanes, slots, start = [], {}, 0
        for i, spec in psums_specs:
            pl = cols[f"{spec[1]}.parts"]
            n_p = pl.shape[0]
            lanes.extend(pl[p] for p in range(n_p))
            slots[i] = (start, n_p)
            start += n_p
        sums, count = _dense_group_part_sums(lanes, key, mask, g_pad,
                                             with_count=True)
        outs["group.count"] = count
        for i, _spec in psums_specs:
            s0, n_p = slots[i]
            outs[f"gagg{i}.psums"] = sums[s0:s0 + n_p]
    elif dense:
        outs["group.count"] = _dense_group_count(key, mask, g_pad)
    else:
        outs["group.count"] = jnp.zeros(g_pad, jnp.int32).at[key].add(
            mask.astype(jnp.int32))
    acc = sum_dtype()
    for i, spec in enumerate(agg_specs):
        fname, col, source, extra = spec
        if fname == "count":
            continue  # shares group.count
        strategy = extra[0] if isinstance(extra, tuple) else "vals"
        if fname in ("sum", "avg"):
            if strategy == "psums":
                if not dense:
                    # scatter fallback keyed per part lane
                    outs[f"gagg{i}.psums"] = jnp.stack([
                        jnp.zeros(g_pad, jnp.int32).at[key].add(
                            jnp.where(mask, cols[f"{col}.parts"][p]
                                      .astype(jnp.int32), 0))
                        for p in range(cols[f"{col}.parts"].shape[0])])
                # dense: already emitted by the fused pass above
            elif strategy == "csums":
                lane = cols[f"{col}.vlane" if source == "sv"
                            else f"{col}.raw"]
                outs[f"gagg{i}.csums"] = _dense_group_float_sums(
                    lane, key, mask, g_pad)
            else:  # scatter fallback (huge group tables)
                if source == "sv":
                    vals = cols[f"{col}.vals"][cols[f"{col}.ids"]]
                else:
                    vals = cols[f"{col}.raw"]
                contrib = jnp.where(mask, vals.astype(acc), 0)
                outs[f"gagg{i}.sum"] = jnp.zeros(g_pad, acc).at[key].add(
                    contrib)
        if fname in ("min", "max", "minmaxrange"):
            if source == "sv":
                card_pad = extra[1]
                ids = cols[f"{col}.ids"].astype(jnp.int32)
                if fname in ("min", "minmaxrange"):
                    outs[f"gagg{i}.min"] = (
                        _dense_group_extreme(ids, key, mask, g_pad,
                                             np.int32(card_pad), True)
                        if dense else jnp.full(g_pad, card_pad, jnp.int32)
                        .at[key].min(jnp.where(mask, ids, card_pad)))
                if fname in ("max", "minmaxrange"):
                    outs[f"gagg{i}.max"] = (
                        _dense_group_extreme(ids, key, mask, g_pad,
                                             np.int32(-1), False)
                        if dense else jnp.full(g_pad, -1, jnp.int32)
                        .at[key].max(jnp.where(mask, ids, -1)))
            else:
                vals = cols[f"{col}.raw"].astype(acc)
                if fname in ("min", "minmaxrange"):
                    outs[f"gagg{i}.min"] = (
                        _dense_group_extreme(vals, key, mask, g_pad,
                                             acc(np.inf), True)
                        if dense else jnp.full(g_pad, jnp.inf, acc)
                        .at[key].min(jnp.where(mask, vals, jnp.inf)))
                if fname in ("max", "minmaxrange"):
                    outs[f"gagg{i}.max"] = (
                        _dense_group_extreme(vals, key, mask, g_pad,
                                             acc(-np.inf), False)
                        if dense else jnp.full(g_pad, -jnp.inf, acc)
                        .at[key].max(jnp.where(mask, vals, -jnp.inf)))
        if fname not in ("sum", "avg", "min", "max", "minmaxrange"):
            raise ValueError(f"unsupported group-by aggregation {fname}")
    return outs


# ---------------------------------------------------------------------------
# Selection
#
# select spec: (kind, k, order=((col, asc, card_pad, source), ...),
#               gather_cols=((col, source), ...))
#   kind ∈ {"limit",     # no order: first-k matched docids
#           "order",     # all-dict keys packed into one int32 → top_k
#           "ordertk",   # single raw int32/f32 key → monotone-map + top_k
#           "ordermk",   # general multi-key → lax.sort (no packing limit)
#           "vector"}    # batched similarity scores → top_k (order slot
#                        #   carries ((col, metric, dim_pad),); runtime
#                        #   params: query vector f32 [dim_pad] + its
#                        #   f32 norm)
# ---------------------------------------------------------------------------


def vec_tree_sum(x):
    """Balanced pairwise sum over the LAST axis (pow2 width).

    This is the vector subsystem's exactness contract: every backend —
    numpy host oracle, XLA CPU, XLA TPU, per-shard sharded lanes — runs
    the SAME log2(D) sequence of elementwise IEEE f32 adds, so scores
    are bit-identical across all of them by construction. A matmul
    would hit the MXU but leaves the accumulation order (and therefore
    the low bits) implementation-defined; for a [P, 128] @ [128]
    matvec the MXU is row-starved anyway, while this form fuses into
    one VPU row stream at HBM bandwidth. Zero padding lanes are exact
    no-ops (x + 0.0 == x for every x the guards let through).
    """
    while x.shape[-1] > 1:
        x = x[..., 0::2] + x[..., 1::2]
    return x[..., 0]


def _vector_scores(mat, q, q_norm, metric: str):
    """Per-row similarity scores, float32 [P].

    mat: f32 [P, dim_pad] embedding block; q: f32 [dim_pad] query
    (zero-padded); q_norm: f32 scalar — the query's tree-norm, computed
    host-side by the planner with the same balanced tree (only read by
    the cosine metric; the planner rejects zero query vectors there).
    Rows with zero norm score -inf under cosine (they can never rank
    above any real match, exactly like the host twin).
    """
    dot = vec_tree_sum(mat * q[None, :])
    if metric == "cosine":
        denom = jnp.sqrt(vec_tree_sum(mat * mat)) * q_norm
        return jnp.where(denom > 0, dot / denom,
                         jnp.float32(-jnp.inf)).astype(jnp.float32)
    return dot.astype(jnp.float32)


def _monotone_int32_keys(lane, asc: bool) -> list:
    """Numeric lane → 1-2 int32 lanes whose lexicographic order equals the
    value order, exactly (IEEE-754 bit tricks; int64/f64 split hi/lo).
    Descending order is per-lane bitwise NOT (x ↦ -x-1 reverses int32 order
    and distributes over the hi/lo concatenation)."""
    dt = lane.dtype
    if dt in (jnp.int8, jnp.int16, jnp.int32):
        keys = [lane.astype(jnp.int32)]
    elif dt == jnp.float32:
        b = jax.lax.bitcast_convert_type(lane, jnp.int32)
        keys = [b ^ ((b >> 31) & jnp.int32(0x7FFFFFFF))]
    elif dt == jnp.int64:
        # wide_i64: these branches only trace for 64-bit lanes (x64 on
        # — the CPU/host-parity path); the helper asserts that instead
        # of silently narrowing to int32 the way jnp.int64(...) would
        hi = (lane >> 32).astype(jnp.int32)
        lo = ((lane & compat.wide_i64(0xFFFFFFFF)) -
              compat.wide_i64(0x80000000)).astype(jnp.int32)
        keys = [hi, lo]
    elif dt == jnp.float64:
        b = jax.lax.bitcast_convert_type(lane, jnp.int64)
        m = b ^ ((b >> 63) & compat.wide_i64(0x7FFFFFFFFFFFFFFF))
        hi = (m >> 32).astype(jnp.int32)
        lo = ((m & compat.wide_i64(0xFFFFFFFF)) -
              compat.wide_i64(0x80000000)).astype(jnp.int32)
        keys = [hi, lo]
    else:
        raise ValueError(f"unsupported order-by lane dtype {dt}")
    return keys if asc else [~k for k in keys]


def _selection_outputs(select_spec, cols, mask, params=None):
    kind, k, order, gather_cols = select_spec
    extra_outs = {}
    if kind == "vector":
        # batched top-k similarity: scores → monotone int32 keys →
        # lax.top_k (XLA top_k breaks ties toward the LOWER index, so
        # equal scores rank docid-ascending — the host twin's contract)
        (col, metric, _dim_pad), = order
        q = params.pop(0)                   # f32 [dim_pad] query vector
        q_norm = params.pop(0)              # f32 scalar (tree-norm of q)
        score = _vector_scores(cols[f"{col}.vec"], q, q_norm, metric)
        key = _monotone_int32_keys(score, True)[0]
        # reserve INT32_MIN for the masked-row sentinel (cost: the two
        # lowest real keys — NaN-pattern scores our guards never emit —
        # collapse into one rank)
        key = jnp.maximum(key, -INT32_MAX)
        scored = jnp.where(mask, key, -INT32_MAX - 1)
        _, docids = jax.lax.top_k(scored, k)
        n_valid = mask.sum(dtype=jnp.int32)
        valid_k = jnp.arange(k, dtype=jnp.int32) < n_valid
        docids = jnp.where(valid_k, docids, -1)
        extra_outs["sel.scores"] = jnp.where(
            valid_k, score[jnp.maximum(docids, 0)], jnp.float32(0))
    elif kind == "limit":
        docids = jnp.nonzero(mask, size=k, fill_value=-1)[0]
    elif kind == "order":
        # pack dict order columns into one int32 key (planner guarantees
        # the radix product fits in 31 bits, else it emits "ordermk")
        key = jnp.zeros(mask.shape[0], jnp.int32)
        for col, asc, card_pad, source in order:
            ids = cols[f"{col}.ids"]
            term = ids if asc else (np.int32(card_pad - 1) - ids)
            key = key * np.int32(card_pad) + term
        key = jnp.where(mask, key, INT32_MAX)
        neg_vals, docids = jax.lax.top_k(-key, k)
        docids = jnp.where(neg_vals == -INT32_MAX, -1, docids)
    elif kind == "ordertk":
        # single raw int32/f32 order column: monotone int32 key + top_k
        (col, asc, _card_pad, _source), = order
        key = _monotone_int32_keys(cols[f"{col}.raw"], asc)[0]
        # reserve INT32_MAX for the masked-row sentinel so no valid row can
        # tie it and get dropped (cost: values whose keys are INT32_MAX and
        # INT32_MAX-1 — int 2^31-1 vs 2^31-2, or two NaN bit patterns —
        # become order-tied with each other)
        key = jnp.minimum(key, INT32_MAX - 1)
        # top_k is descending; ~key descending == key ascending
        scored = jnp.where(mask, ~key, -INT32_MAX - 1)
        _, docids = jax.lax.top_k(scored, k)
        n_valid = mask.sum(dtype=jnp.int32)
        docids = jnp.where(jnp.arange(k, dtype=jnp.int32) < n_valid,
                           docids, -1)
    else:  # ordermk: general multi-key device sort
        keys = []
        for col, asc, card_pad, source in order:
            if source == "sv":
                ids = cols[f"{col}.ids"].astype(jnp.int32)
                keys.append(ids if asc else ~ids)
            else:
                keys.extend(_monotone_int32_keys(cols[f"{col}.raw"], asc))
        flag = jnp.where(mask, jnp.int32(0), jnp.int32(1))
        iota = jnp.arange(mask.shape[0], dtype=jnp.int32)
        res = jax.lax.sort((flag, *keys, iota), num_keys=1 + len(keys))
        docids = jnp.where(res[0][:k] == 0, res[-1][:k], -1)
    out = {"sel.docids": docids.astype(jnp.int32),
           "sel.count": mask.sum(dtype=jnp.int32)}
    out.update(extra_outs)
    safe = jnp.maximum(docids, 0)
    for col, source in gather_cols:
        lane = {"sv": f"{col}.ids", "raw": f"{col}.raw",
                "mv": f"{col}.mv"}[source]
        out[f"sel.{col}"] = cols[lane][safe]
    return out


# ---------------------------------------------------------------------------
# Window kernel (stage 2 of the multi-stage engine, query/stages/window.py)
#
# Operates on ONE exchanged row block (every server's stage-1 scan,
# concatenated in deterministic source order): lax.sort by (validity,
# partition code, window-order keys, input index) puts each window
# partition contiguous with a deterministic total order — the input
# index tie-break makes the sort equal to the host oracle's stable
# np.lexsort — then ROW_NUMBER is an iota rebased at partition starts
# and SUM(...) OVER is jnp.cumsum rebased the same way. All int32: the
# one accumulation every backend (numpy, XLA CPU, XLA TPU) reproduces
# bit-identically, with the executor rejecting inputs whose running
# sums could wrap (the window exactness contract, docs/QUERYENGINE.md).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=128)
def build_window_kernel(n_pad: int, n_order: int, n_sums: int):
    """Unjitted window kernel: fn(part, orders, sums, num_rows) → outs.

    part: int32 [n_pad] partition codes; orders: tuple of n_order int32
    monotone order-key lanes; sums: tuple of n_sums int32 value lanes;
    num_rows: int32 valid prefix. Outputs (all [n_pad], valid prefix
    num_rows): "win.perm" input row index in window order, "win.rn"
    1-based row number within its partition, "win.sum<j>" running sums.
    """

    def kernel(part, orders, sums, num_rows):
        iota = jnp.arange(n_pad, dtype=jnp.int32)
        invalid = (iota >= num_rows).astype(jnp.int32)
        ops = (invalid, part) + tuple(orders) + (iota,) + tuple(sums)
        res = jax.lax.sort(ops, num_keys=3 + n_order)
        sp = res[1]
        perm = res[2 + n_order]
        svals = res[3 + n_order:]
        new = jnp.concatenate([jnp.ones(1, bool), sp[1:] != sp[:-1]])
        starts = jax.lax.cummax(jnp.where(new, iota, 0), axis=0)
        # all lanes arrive int32 by the window contract, so differences
        # and cumsum stay int32 with no narrowing casts (the executor's
        # host-side bound check guarantees no wrap)
        outs = {"win.perm": perm,
                "win.rn": iota - starts + jnp.int32(1)}
        for j, v in enumerate(svals):
            cs = jnp.cumsum(v, dtype=jnp.int32)
            base = cs[starts] - v[starts]
            outs[f"win.sum{j}"] = cs - base
        return outs

    return kernel


@functools.lru_cache(maxsize=128)
def get_window_kernel(n_pad: int, n_order: int, n_sums: int):
    return jax.jit(build_window_kernel(n_pad, n_order, n_sums))


def run_window_kernel(part, orders, sums, num_rows):
    fn = get_window_kernel(int(part.shape[0]), len(orders), len(sums))
    return fn(part, tuple(orders), tuple(sums), jnp.int32(num_rows))


# ---------------------------------------------------------------------------
# Kernel assembly + jit cache
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1024)
def build_segment_kernel(padded: int, filter_spec, agg_specs, group_spec,
                         select_spec):
    """Unjitted whole-plan kernel closure (vmap/shard_map composable)."""

    def kernel(cols: Dict[str, jnp.ndarray], params: Tuple, num_docs):
        valid = jnp.arange(padded, dtype=jnp.int32) < num_docs
        plist = list(params)
        mask = _eval_filter(filter_spec, cols, plist, valid) & valid
        outs = {"stats.num_docs_matched": mask.sum(dtype=jnp.int32)}
        if group_spec is not None:
            outs.update(_group_outputs(group_spec, cols, mask, num_docs,
                                       plist))
        elif agg_specs:
            outs.update(_agg_outputs(agg_specs, cols, mask, num_docs))
        if select_spec is not None:
            # runtime selection operands (the vector query + its norm)
            # follow the filter/group params in depth-first plan order
            outs.update(_selection_outputs(select_spec, cols, mask,
                                           plist))
        return outs

    return kernel


@functools.lru_cache(maxsize=1024)
def get_segment_kernel(padded: int, filter_spec, agg_specs, group_spec,
                       select_spec):
    """Compile (once per static signature) the whole per-segment plan."""
    return jax.jit(build_segment_kernel(padded, filter_spec, agg_specs,
                                        group_spec, select_spec))


def run_segment_kernel(padded: int, filter_spec, agg_specs, group_spec,
                       select_spec, cols, params, num_docs):
    fn = get_segment_kernel(padded, filter_spec, tuple(agg_specs or ()),
                            group_spec, select_spec)
    return fn(cols, tuple(params), jnp.int32(num_docs))


# ---------------------------------------------------------------------------
# Cross-query batched dispatch: one kernel execution serves N queries
# that share a compiled spec and differ only in runtime literal
# operands. The column lanes are per-segment data shared across the
# batch (in_axes=None — uploaded once, read by every lane of the vmap);
# each param leaf gains a leading query axis. Group specs are excluded:
# adaptive group execution (query/groupby.py) drives value-dependent
# scout phases per query, so stacking its operands would fuse control
# flow that must stay per-member.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def get_batched_segment_kernel(padded: int, filter_spec, agg_specs,
                               select_spec):
    """jit(vmap) of the SAME unjitted closure the sequential path
    compiles — batched and per-query dispatch trace one program, which
    is what makes batched-vs-sequential bit-parity a structural
    property rather than a numerical accident for the integer paths."""
    base = build_segment_kernel(padded, filter_spec, agg_specs, None,
                                select_spec)
    return jax.jit(jax.vmap(base, in_axes=(None, 0, None)))


def stack_param_leaves(params_list):
    """[(p0, p1, ...)] per member → one tuple of [B, ...] leaves.

    Spec equality implies leaf-shape equality (widths are padded from
    the spec); a mismatch here means the caller grouped plans whose
    specs diverged and is a bug, surfaced as ValueError before any
    device work."""
    n = len(params_list[0])
    for ps in params_list:
        if len(ps) != n:
            raise ValueError("batched plans disagree on param arity")
    return tuple(
        jnp.stack([jnp.asarray(ps[i]) for ps in params_list])
        for i in range(n))


def batch_bucket(n: int) -> int:
    """Next power of two ≥ n (min 2): the batch axis is padded to a
    bucket before jit sees it, exactly like the doc-count padding —
    jit specializes on the leading dim, so raw occupancies would
    compile one XLA program PER DISTINCT BATCH SIZE under load (a
    compile storm that inverts the whole point of coalescing).
    Bucketing bounds the compile surface at log2(max occupancy)
    programs per spec."""
    b = 2
    while b < n:
        b <<= 1
    return b


def run_segment_kernel_batched(padded: int, filter_spec, agg_specs,
                               select_spec, cols, params_list, num_docs):
    """One dispatch for N same-spec queries; every output gains a
    leading query axis the caller slices per member (padded bucket
    lanes beyond N are never read). Callers handle the param-free case
    themselves (one unbatched dispatch shared by all members — vmap
    cannot infer a batch size from an empty pytree)."""
    fn = get_batched_segment_kernel(padded, filter_spec,
                                    tuple(agg_specs or ()), select_spec)
    members = [tuple(ps) for ps in params_list]
    # pad to the bucket by repeating the last member: dead lanes cost
    # only vmapped compute, never a fresh compile
    members.extend([members[-1]] * (batch_bucket(len(members))
                                    - len(members)))
    stacked = stack_param_leaves(members)
    return fn(cols, stacked, jnp.int32(num_docs))


# ---------------------------------------------------------------------------
# Kernel contract registry (consumed by analysis/contracts.py --deep)
#
# Every kernel family the planner can emit is registered here as a
# representative (spec, operand-layout) case; the deep analysis tier
# traces each one with jax.make_jaxpr across the shape-bucket grid and
# asserts the jaxpr-level contract: no host callbacks, no 64-bit avals
# under 32-bit mode (silent narrowing), stable retrace (identical jaxpr
# on re-trace, lru_cache hit on equal specs). Adding a kernel path to
# the planner without registering a case here is a review-visible gap:
# the case list IS the kernel surface the gate certifies.
# ---------------------------------------------------------------------------

#: operand layout legend — cols: {lane key: (dtype, shape)}; "P" is the
#: padded doc count, filled per shape bucket. params: depth-first pred /
#: group runtime operands as (dtype, shape).
CONTRACT_SHAPE_BUCKETS = (8192, 16384)


def contract_cases():
    """[(name, filter_spec, agg_specs, group_spec, select_spec, cols,
    params)] — the registered kernel surface."""
    P = "P"
    i8, i16, i32, f32, bl = "int8", "int16", "int32", "float32", "bool"
    cases = []

    def case(name, filt, aggs, group, select, cols, params=()):
        cases.append((name, filt, tuple(aggs), group, select,
                      dict(cols), tuple(params)))

    # scan-only counts
    case("count_match_all", ("match_all",), [("count", "*", "sv", None)],
         None, None, {})
    # the full predicate mix (sv ids, mv any-match, raw ranges, member
    # vectors, upsert vdoc liveness lane)
    case("filter_pred_mix",
         ("and", (
             ("pred", "eq_id", "d0", "sv", None),
             ("or", (("pred", "range_ids", "d1", "sv", None),
                     ("pred", "member", "d2", "sv", 64),
                     ("pred", "notin_ids", "d1", "sv", None))),
             ("pred", "in_ids", "m0", "mv", None),
             ("pred", "range_raw", "r0", "raw", (True, False)),
             ("pred", "vdoc", "$validDocIds", "vdoc", None))),
         [("count", "*", "sv", None)], None, None,
         {"d0.ids": (i32, (P,)), "d1.ids": (i32, (P,)),
          "d2.ids": (i32, (P,)), "m0.mv": (i32, (P, 4)),
          "r0.raw": (f32, (P,)), "$validDocIds.vdoc": (bl, (P,))},
         [(i32, ()), (i32, ()), (i32, ()), (bl, (64,)), (i32, (4,)),
          (i32, (8,)), (f32, ()), (f32, ())])
    # exact integer sums via bit-sliced part lanes (the q1.x hot path)
    case("agg_part_sums", ("match_all",),
         [("sum", "m0", "sv", ("parts", 2)),
          ("avg", "m1", "sv", ("parts", 3)),
          ("count", "*", "sv", None)],
         None, None,
         {"m0.parts": (i8, (2, P)), "m1.parts": (i8, (3, P))})
    # float sums, id extrema, histograms, decoded value lanes
    case("agg_float_hist",
         ("pred", "eq_id", "d0", "sv", None),
         [("sum", "r0", "raw", None), ("min", "r0", "raw", None),
          ("max", "d0", "sv", ("ids", 64)),
          ("distinctcount", "d0", "sv", ("hist", 64)),
          ("sum", "v0", "sv", ("vlane",))],
         None, None,
         {"d0.ids": (i32, (P,)), "r0.raw": (f32, (P,)),
          "v0.vlane": (f32, (P,))},
         [(i32, ())])
    # multi-value aggregation family
    case("agg_mv", ("match_all",),
         [("sum", "m0", "mv", (64, 50)),
          ("min", "m0", "mv", (64, 50)),
          ("countmv", "m0", "mv", (64, 50))],
         None, None, {"m0.mv": (i32, (P, 4))})
    # dense group-by: fused psums + count + id extrema
    case("group_dense",
         ("pred", "range_ids", "d0", "sv", None),
         [],
         ((("d0", "ids", 0, 8), ("d1", "ids", 0, 8)), (8, 1), 64,
          (("sum", "m0", "sv", ("psums", 2)),
           ("count", "*", "sv", None),
           ("min", "d0", "sv", ("ids", 8))), 0),
         None,
         {"d0.ids": (i32, (P,)), "d1.ids": (i32, (P,)),
          "m0.parts": (i8, (2, P))},
         [(i32, ()), (i32, ())])
    # scatter-fallback group-by (huge key space) + dict-decode sums
    case("group_scatter", ("match_all",), [],
         ((("d0", "ids", 0, 512),), (1,), 2 * DENSE_G_LIMIT,
          (("sum", "v0", "sv", ("vals",)),
           ("max", "r0", "raw", None)), 0),
         None,
         {"d0.ids": (i32, (P,)), "v0.ids": (i32, (P,)),
          "v0.vals": (f32, (512,)), "r0.raw": (f32, (P,))})
    # MXU-compacted filtered group-by (kmax > 0), dense tables
    case("group_compacted",
         ("pred", "eq_id", "d0", "sv", None), [],
         ((("d0", "ids", 0, 8), ("d1", "ids", 0, 8)), (8, 1), 64,
          (("sum", "m0", "sv", ("psums", 2)),
           ("min", "d0", "sv", ("ids", 8)),
           ("sum", "v0", "sv", ("vlane",))), 1024),
         None,
         {"d0.ids": (i32, (P,)), "d1.ids": (i32, (P,)),
          "m0.parts": (i8, (2, P)), "v0.vlane": (f32, (P,))},
         [(i32, ())])
    # rank-addressed compacted tables (g_pad above the dense limit)
    case("group_ranked", ("pred", "eq_id", "d0", "sv", None), [],
         ((("d0", "ids", 0, 70000),), (1,), 131072,
          (("sum", "m0", "sv", ("psums", 2)),), 1024),
         None,
         {"d0.ids": (i32, (P,)), "m0.parts": (i8, (2, P))},
         [(i32, ())])
    # adaptive remap group kinds consume runtime operands
    case("group_adaptive", ("match_all",), [],
         ((("d0", "idoff", 0, 8), ("d1", "idrank", 0, 8)), (8, 1), 64,
          (("count", "*", "sv", None),), 0),
         None,
         {"d0.ids": (i32, (P,)), "d1.ids": (i32, (P,))},
         [(i32, ()), (i32, (8,))])
    # selection kernels: limit, packed order, monotone top-k, multi-key
    case("select_limit", ("match_all",), [], None,
         ("limit", 16, (), (("d0", "sv"), ("r0", "raw"))),
         {"d0.ids": (i32, (P,)), "r0.raw": (f32, (P,))})
    case("select_order", ("match_all",), [], None,
         ("order", 16, (("d0", True, 8, "sv"), ("d1", False, 8, "sv")),
          (("d0", "sv"),)),
         {"d0.ids": (i32, (P,)), "d1.ids": (i32, (P,))})
    case("select_ordertk", ("match_all",), [], None,
         ("ordertk", 16, (("r0", True, 0, "raw"),), ()),
         {"r0.raw": (f32, (P,))})
    case("select_ordermk", ("match_all",), [], None,
         ("ordermk", 16, (("d0", True, 8, "sv"), ("r0", False, 0, "raw")),
          (("r0", "raw"),)),
         {"d0.ids": (i32, (P,)), "r0.raw": (f32, (P,))})
    # batched vector similarity top-k: MIPS/dot over the packed [P, dim]
    # embedding block, with a gather column riding along
    case("select_vector_dot", ("match_all",), [], None,
         ("vector", 16, (("e0", "dot", 128),), (("d0", "sv"),)),
         {"e0.vec": (f32, (P, 128)), "d0.ids": (i32, (P,))},
         [(f32, (128,)), (f32, ())])
    # cosine, fused with a filter predicate AND the upsert vdoc lane —
    # the "dead upserted rows can never rank" path
    case("select_vector_cosine_filtered",
         ("and", (("pred", "eq_id", "d0", "sv", None),
                  ("pred", "vdoc", "$validDocIds", "vdoc", None))),
         [], None,
         ("vector", 16, (("e0", "cosine", 128),), ()),
         {"e0.vec": (f32, (P, 128)), "d0.ids": (i32, (P,)),
          "$validDocIds.vdoc": (bl, (P,))},
         [(i32, ()), (f32, (128,)), (f32, ())])
    # IVF-indexed vector top-k: the ANN coarse-probe pred (assignment +
    # codebook + validity lanes, probe list selected ON DEVICE) fused
    # with the upsert vdoc lane ahead of the exact scoring tree — the
    # "score only probed, live rows" path. Params: probe q + norm
    # (filter, depth-first first), then the selection's q + norm.
    case("select_vector_ivf_probed",
         ("and", (("pred", "ivf_probe", "e0", "ivf", (8, "cosine")),
                  ("pred", "vdoc", "$validDocIds", "vdoc", None))),
         [], None,
         ("vector", 16, (("e0", "cosine", 128),), ()),
         {"e0.vec": (f32, (P, 128)), "e0.ivfa": (i16, (P,)),
          "e0.ivfc": (f32, (64, 128)), "e0.ivfv": (bl, (64,)),
          "$validDocIds.vdoc": (bl, (P,))},
         [(f32, (128,)), (f32, ()), (f32, (128,)), (f32, ())])
    # inner-join probe fused into the filter, dict-keyed fact side: the
    # host-translated member vector is the join-match predicate, the
    # jcode gather the dim group code — composed with the upsert vdoc
    # lane so dead upserted rows never reach a join side
    case("join_dict_group",
         ("and", (("pred", "member", "k0", "sv", 64),
                  ("pred", "vdoc", "$validDocIds", "vdoc", None))),
         [],
         ((("k0", "jcode", 0, 8), ("d0", "ids", 0, 8)), (8, 1), 64,
          (("sum", "m0", "sv", ("psums", 2)),
           ("count", "*", "sv", None)), 0),
         None,
         {"k0.ids": (i32, (P,)), "d0.ids": (i32, (P,)),
          "m0.parts": (i8, (2, P)), "$validDocIds.vdoc": (bl, (P,))},
         [(bl, (64,)), (i32, (64,))])
    # raw-keyed fact side: the dim key/code tables ride as runtime
    # operands and the probe structure is BUILT ON DEVICE (lax.sort +
    # searchsorted) — join_raw pred + jraw group code share the build
    case("join_raw_probe",
         ("pred", "join_raw", "k0", "raw", 128),
         [],
         ((("k0", "jraw", 0, 8),), (1,), 8,
          (("count", "*", "sv", None),), 0),
         None,
         {"k0.raw": (i32, (P,))},
         [(i32, (128,)), (i32, (128,)), (i32, (128,))])
    # DISTINCTCOUNTHLL device registers: histogram-present scatter-max
    # of the per-dictId (register index, rank) tables → [m] int32
    # registers that merge associatively (max) on every combine path
    case("agg_hll",
         ("pred", "eq_id", "d0", "sv", None),
         [("hll", "v0", "sv", ("hll", 64, 4096)),
          ("count", "*", "sv", None)],
         None, None,
         {"d0.ids": (i32, (P,)), "v0.ids": (i32, (P,)),
          "v0.hllidx": (i32, (64,)), "v0.hllrank": (i32, (64,))},
         [(i32, ())])
    return cases


#: leading-query-axis sizes the deep tier traces batched cases at —
#: pow2 only, because batch_bucket pads every occupancy to a pow2
#: before jit ever sees the leading dim
BATCH_CONTRACT_SIZES = (2, 4)


def batched_contract_cases():
    """The registered cases the dispatch coalescer can stack, traced by
    the deep tier through get_batched_segment_kernel at each
    BATCH_CONTRACT_SIZES occupancy: group-by cases are excluded (the
    coalescer never batches them — adaptive group execution is
    value-dependent per query) and so are param-free cases (they share
    one unbatched dispatch instead of a vmap)."""
    return [(name, filt, aggs, group, select, cols, params)
            for (name, filt, aggs, group, select, cols, params)
            in contract_cases()
            if group is None and params]


def extra_contract_cases():
    """Non-segment-plan kernel families, traced by the same deep-tier
    gate (analysis/contracts.py): [(name, builder, static_args,
    arg_specs)]. builder(*static_args) must return the unjitted kernel
    (lru-cached — the gate asserts cache identity like
    build_segment_kernel's); arg_specs is a pytree of (dtype, shape)
    leaves mirroring the kernel's positional args, with "P" filled per
    shape bucket in both static_args and shapes."""
    from pinot_tpu.ops import ivf_kernels  # lazy: avoids import cycle
    P = "P"
    i32, f32, bl = "int32", "float32", "bool"
    return [
        ("window_rank", build_window_kernel, (P, 2, 0),
         ((i32, (P,)), ((i32, (P,)), (i32, (P,))), (), (i32, ()))),
        ("window_rank_sum", build_window_kernel, (P, 1, 2),
         ((i32, (P,)), ((i32, (P,)),),
          ((i32, (P,)), (i32, (P,))), (i32, ()))),
        # IVF codebook lifecycle: Lloyd's train step, assign-only (the
        # sample-then-assign sweep), and standalone probe-select
        ("ivf_train_step", ivf_kernels.build_ivf_train_kernel,
         (P, 64, 128),
         ((f32, (P, 128)), (f32, (64, 128)), (i32, ()), (i32, ()))),
        ("ivf_assign", ivf_kernels.build_ivf_assign_kernel,
         (P, 64, 128),
         ((f32, (P, 128)), (f32, (64, 128)), (i32, ()), (i32, ()))),
        ("ivf_probe_select", ivf_kernels.build_ivf_probe_kernel,
         (64, 128, 8, "cosine"),
         ((f32, (64, 128)), (bl, (64,)), (f32, (128,)), (f32, ()))),
    ]
