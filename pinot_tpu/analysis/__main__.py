"""tpulint CLI: `python -m pinot_tpu.analysis [paths...]`.

Exits nonzero on findings NOT covered by the committed baseline (or on
stale baseline entries with --strict-baseline, which CI uses so the
grandfather list only ever shrinks). Run from the repo root so finding
keys match the baseline.
"""
from __future__ import annotations

import argparse
import os
import sys

from pinot_tpu.analysis import core, runner

DEFAULT_BASELINE = "tpulint.baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.analysis",
        description="JAX-aware static analysis for pinot_tpu")
    ap.add_argument("paths", nargs="*", default=["pinot_tpu"],
                    help="files/directories to lint (repo-relative)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline JSON (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this run and exit 0")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="also fail on stale baseline entries (CI mode)")
    ap.add_argument("--rule", action="append", dest="rules", default=None,
                    help="run only this rule id (repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(core.all_rules().items()):
            print(f"{rid:12s} {rule.description}")
        return 0

    known = set(core.all_rules())
    if args.rules and not set(args.rules) <= known:
        bad = sorted(set(args.rules) - known)
        print(f"tpulint: unknown rule id(s) {bad}; known: "
              f"{sorted(known)}", file=sys.stderr)
        return 2

    result = runner.analyze_paths(
        args.paths, rule_ids=set(args.rules) if args.rules else None)
    for err in result.errors:
        print(f"tpulint: error: {err}", file=sys.stderr)

    if args.write_baseline:
        if result.errors:
            print("tpulint: refusing to write a baseline from a run "
                  "with analysis errors", file=sys.stderr)
            return 1
        core.write_baseline(args.baseline, result.findings)
        print(f"tpulint: wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    baseline = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = core.load_baseline(args.baseline)
    new, stale = runner.diff_baseline(result, baseline)

    if args.show_suppressed:
        for f in result.suppressed:
            print(f"suppressed: {f.render()}")
    for f in new:
        print(f.render())
    for key in stale:
        print(f"tpulint: stale baseline entry (code fixed — regenerate "
              f"with --write-baseline): {key}")

    n_grandfathered = len(result.findings) - len(new)
    by_rule = ", ".join(f"{r}={n}" for r, n in
                        sorted(result.by_rule().items())) or "none"
    print(f"tpulint: {len(result.findings)} finding(s) [{by_rule}], "
          f"{len(new)} new, {n_grandfathered} grandfathered, "
          f"{len(result.suppressed)} suppressed, {len(stale)} stale "
          "baseline entr(ies)")
    if new or result.errors or (stale and args.strict_baseline):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
