"""Deep-tier global rules: kernel jaxpr contracts + wire-schema gate.

Unlike the AST families these don't read source — they import the live
modules, trace kernels, and serialize exemplar wire objects (see
analysis/contracts.py). They register here so the CLI's rule registry,
`--rule` filtering and the baseline machinery treat their findings
uniformly; the runner invokes `check_global()` once per run (only in
`--deep` mode — tracing every kernel is deliberately not part of the
default fast lint).
"""
from __future__ import annotations

from typing import Iterator, List

from pinot_tpu.analysis.core import Finding, Rule, register


@register
class KernelContractRule(Rule):
    id = "kernel-contract"
    description = ("jaxpr-level kernel contracts: no host callbacks, "
                   "no 64-bit avals in 32-bit mode, stable retrace "
                   "(deep tier)")
    tier = "deep"

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())

    def check_global(self) -> List[Finding]:
        from pinot_tpu.analysis import contracts
        return [Finding(path="pinot_tpu/ops/kernels.py", line=1,
                        rule=self.id, message=v)
                for v in contracts.check_kernel_contracts()]


@register
class WireSchemaRule(Rule):
    id = "wire-schema"
    description = ("serde wire surface must match the committed "
                   "wire-schema.json (version-skew gate, deep tier)")
    tier = "deep"

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())

    def check_global(self) -> List[Finding]:
        from pinot_tpu.analysis import contracts
        return [Finding(path="pinot_tpu/common/serde.py", line=1,
                        rule=self.id, message=v)
                for v in contracts.check_wire_schema()]
