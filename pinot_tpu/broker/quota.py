"""Per-tenant/per-table QPS quota: token buckets with burst allowance.

Parity: pinot-broker/.../queryquota/HelixExternalViewBasedQueryQuotaManager
— per-table max QPS sourced from the table config
(``quotaConfig.maxQueriesPerSecond``) and divided by the number of live
brokers so the cluster-wide quota converges as brokers join and leave.

The old sliding HitCounter window had two ingress-control bugs the
token bucket removes structurally:

- **check-after-hit**: every request (including a rejected one) counted
  against the window, so a throttled tenant kept re-filling its own
  window and never recovered; a bucket only debits ADMITTED requests.
- **exact-at-limit flap**: traffic at precisely the quota alternated
  allow/deny on bucket-boundary rounding; a bucket at rate r admits a
  sustained r QPS exactly, with `burst` extra requests of headroom for
  dashboard-style synchronized refresh bursts.

Rejections carry the bucket's refill time so the broker can answer
429 with an honest ``Retry-After``.

The ``HitCounter`` survives as the *observed offered load* meter (it
counts attempts, not admissions — exactly what an operator sizing a
quota wants to see) and now takes the injectable ``now_ms`` everywhere
so quota tests never sleep on the wall clock.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

BUCKETS = 10
BUCKET_MS = 100


class HitCounter:
    """Sliding-window attempt counter (100ms buckets over 1s)."""

    def __init__(self):
        self._times = [0] * BUCKETS
        self._counts = [0] * BUCKETS
        self._lock = threading.Lock()

    def hit(self, now_ms: Optional[int] = None) -> None:
        now_ms = int(time.time() * 1e3) if now_ms is None else int(now_ms)
        idx = (now_ms // BUCKET_MS) % BUCKETS
        with self._lock:
            stamp = now_ms // BUCKET_MS
            if self._times[idx] != stamp:
                self._times[idx] = stamp
                self._counts[idx] = 0
            self._counts[idx] += 1

    def hits_in_window(self, now_ms: Optional[int] = None) -> int:
        now_ms = int(time.time() * 1e3) if now_ms is None else int(now_ms)
        lo = now_ms // BUCKET_MS - BUCKETS + 1
        with self._lock:
            return sum(c for t, c in zip(self._times, self._counts)
                       if t >= lo)


class TokenBucket:
    """rate tokens/s, capacity `burst`; only admitted requests debit.

    NOT internally locked — the owning QueryQuotaManager serializes all
    bucket access under one lock so tenant+table admission is atomic
    (a request rejected by the table bucket must not have debited the
    tenant bucket first).
    """

    __slots__ = ("rate", "burst", "tokens", "last_s")

    def __init__(self, rate: float, burst: Optional[float] = None,
                 now_s: float = 0.0):
        self.rate = float(rate)
        # default burst: one second of traffic, never less than one
        # request (a 0.5-qps quota must still admit a single query)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        self.tokens = self.burst          # start full: burst allowance
        self.last_s = now_s

    def _refill(self, now_s: float) -> None:
        dt = now_s - self.last_s
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
        self.last_s = now_s

    def peek(self, now_s: float, n: float = 1.0) -> bool:
        self._refill(now_s)
        return self.tokens >= n

    def commit(self, n: float = 1.0) -> None:
        self.tokens -= n

    def retry_after_s(self, now_s: float, n: float = 1.0) -> float:
        """Seconds until `n` tokens will have refilled."""
        self._refill(now_s)
        missing = n - self.tokens
        if missing <= 0:
            return 0.0
        return missing / self.rate if self.rate > 0 else float("inf")

    def reconfigure(self, rate: float, burst: Optional[float],
                    now_s: Optional[float] = None) -> None:
        """Adjust rate/burst, preserving accumulated tokens (a view
        change must not hand every table a fresh burst allowance)."""
        if now_s is not None:
            # settle the elapsed interval at the OLD rate first —
            # otherwise the next acquire's refill retroactively credits
            # the whole idle gap at the new rate, which on a quota
            # raise IS the fresh-burst grant this method must not give
            self._refill(now_s)
        new_burst = float(burst) if burst is not None \
            else max(1.0, float(rate))
        self.rate = float(rate)
        self.tokens = min(self.tokens, new_burst)
        self.burst = new_burst


class QuotaDecision:
    """acquire() result: truthy on admit; carries the rejection cause
    ("tableQuota" | "tenantQuota") and the bucket refill time that
    becomes the 429 Retry-After."""

    __slots__ = ("allowed", "retry_after_s", "cause")

    def __init__(self, allowed: bool, retry_after_s: float = 0.0,
                 cause: Optional[str] = None):
        self.allowed = allowed
        self.retry_after_s = retry_after_s
        self.cause = cause

    def __bool__(self) -> bool:
        return self.allowed

    def __repr__(self) -> str:
        return (f"QuotaDecision(allowed={self.allowed}, "
                f"retry_after_s={self.retry_after_s:.3f}, "
                f"cause={self.cause})")


_ALLOW = QuotaDecision(True)

# Retry-After ceiling: a zero-rate bucket (operator blocked the table)
# refills never — retry_after_s would be inf, which breaks both the
# JSON body (bare Infinity) and the HTTP header's math.ceil. One hour
# says "much later" without lying about a refill instant.
MAX_RETRY_AFTER_S = 3600.0


class QueryQuotaManager:
    """Per-table + per-(table, tenant) token buckets, one broker's share.

    `acquire(table, tenant)` checks the tenant bucket (when one is
    configured) and the table bucket atomically: tokens are debited
    from BOTH only when BOTH admit, so a rejection never consumes
    headroom anywhere — a throttled tenant recovers the moment its
    bucket refills.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._tables: Dict[str, TokenBucket] = {}
        self._tenants: Dict[str, Dict[str, TokenBucket]] = {}
        self._offered: Dict[str, HitCounter] = {}
        self._lock = threading.Lock()

    # -- configuration ------------------------------------------------------
    def set_qps_quota(self, table: str, max_qps: Optional[float],
                      burst: Optional[float] = None) -> None:
        with self._lock:
            if max_qps is None:
                self._tables.pop(table, None)
                return
            existing = self._tables.get(table)
            if existing is None:
                self._tables[table] = TokenBucket(max_qps, burst,
                                                  self._clock())
            elif existing.rate != float(max_qps) or burst is not None:
                existing.reconfigure(max_qps, burst, self._clock())

    def set_tenant_qps_quota(self, table: str, tenant: str,
                             max_qps: Optional[float],
                             burst: Optional[float] = None) -> None:
        with self._lock:
            per_table = self._tenants.setdefault(table, {})
            if max_qps is None:
                per_table.pop(tenant, None)
                if not per_table:
                    self._tenants.pop(table, None)
                return
            existing = per_table.get(tenant)
            if existing is None:
                per_table[tenant] = TokenBucket(max_qps, burst,
                                                self._clock())
            elif existing.rate != float(max_qps) or burst is not None:
                existing.reconfigure(max_qps, burst, self._clock())

    def configure_table(self, table: str, max_qps: Optional[float],
                        tenant_qps: Optional[Dict[str, float]] = None,
                        num_brokers: int = 1) -> None:
        """Converge this broker's share of the table's quota from the
        table config: the cluster-wide rate is split evenly across live
        brokers (parity: HelixExternalViewBasedQueryQuotaManager
        dividing by the online broker count)."""
        share = max(1, int(num_brokers))
        self.set_qps_quota(
            table, None if max_qps is None else max_qps / share)
        wanted = dict(tenant_qps or {})
        with self._lock:
            stale = [t for t in self._tenants.get(table, {})
                     if t not in wanted]
        for tenant in stale:
            self.set_tenant_qps_quota(table, tenant, None)
        for tenant, qps in wanted.items():
            self.set_tenant_qps_quota(table, tenant, float(qps) / share)
        with self._lock:
            if table not in self._tables and table not in self._tenants:
                # fully unmanaged now (quota removed / table dropped):
                # the offered-load counter goes too
                self._offered.pop(table, None)

    # -- admission ----------------------------------------------------------
    def acquire(self, table: str, tenant: Optional[str] = None,
                now_ms: Optional[float] = None) -> QuotaDecision:
        """Admit-or-reject; truthy result = admitted. `now_ms` is the
        injectable clock instant (tests drive time explicitly)."""
        now_s = (now_ms / 1e3) if now_ms is not None else self._clock()
        with self._lock:
            tb = self._tables.get(table)
            nb = self._tenants.get(table, {}).get(tenant) \
                if tenant is not None else None
            if tb is None and nb is None and \
                    not self._tenants.get(table):
                # unmanaged table: no offered-load counter either —
                # acquire() runs before routing validates the name, so
                # tracking every string offered would grow without
                # bound under a random-table flood
                return _ALLOW
            self._offered.setdefault(table, HitCounter()).hit(
                int(now_s * 1e3))
            if tb is None and nb is None:
                return _ALLOW
            if nb is not None and not nb.peek(now_s):
                return QuotaDecision(
                    False, min(nb.retry_after_s(now_s),
                               MAX_RETRY_AFTER_S), "tenantQuota")
            if tb is not None and not tb.peek(now_s):
                return QuotaDecision(
                    False, min(tb.retry_after_s(now_s),
                               MAX_RETRY_AFTER_S), "tableQuota")
            # both admit: debit both (atomic under the manager lock)
            if nb is not None:
                nb.commit()
            if tb is not None:
                tb.commit()
            return _ALLOW

    # -- observability ------------------------------------------------------
    def observed_qps(self, table: str,
                     now_ms: Optional[float] = None) -> float:
        """Offered load (attempts, admitted or not) over the last 1s.

        The window is read on the SAME clock acquire() stamps hits
        with (the manager's injectable clock, monotonic by default) —
        never HitCounter's wall-clock fallback, whose epoch-scale
        stamps would make every recorded hit look ancient."""
        counter = self._offered.get(table)
        if counter is None:
            return 0.0
        return counter.hits_in_window(
            int(self._clock() * 1e3) if now_ms is None else int(now_ms))

    def stats(self) -> Dict[str, dict]:
        now_s = self._clock()
        with self._lock:
            out: Dict[str, dict] = {}
            for table, tb in self._tables.items():
                tb._refill(now_s)
                out[table] = {"maxQps": tb.rate, "burst": tb.burst,
                              "availableTokens": round(tb.tokens, 3),
                              "tenants": {}}
            for table, per_table in self._tenants.items():
                entry = out.setdefault(
                    table, {"maxQps": None, "burst": None,
                            "availableTokens": None, "tenants": {}})
                for tenant, nb in per_table.items():
                    nb._refill(now_s)
                    entry["tenants"][tenant] = {
                        "maxQps": nb.rate, "burst": nb.burst,
                        "availableTokens": round(nb.tokens, 3)}
        for table, entry in out.items():
            entry["observedQps"] = self.observed_qps(table)
        return out
