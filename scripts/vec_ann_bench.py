#!/usr/bin/env python
"""VEC_r16: exact scan vs IVF ANN probing at the 10M x 128d rung.

Builds a 10M-row (4 x 2.5M segments) VECTOR table with the IVF codebook
trained at seal by the real SegmentCreator (256 centroids/segment),
then drives filtered-less VECTOR_SIMILARITY top-10 queries through the
host engine path: one exact pass per query, then an nprobe sweep. Per
nprobe rung the artifact records recall@10 against the exact answer,
the scanned-row fraction (numDocsScanned — the probed candidate set)
and the wall-clock speedup over the exact scan.

Pass gate (the ISSUE 20 acceptance bar): some rung reaches
recall@10 >= 0.95 while scanning < 15% of the rows.

Embeddings are cluster-structured (draws around 256 shared centers) —
the regime IVF exists for; i.i.d. gaussian data has no coarse structure
for ANY coarse quantizer to exploit.

Env knobs:
  VEC_ANN_ROWS     total rows    (default 10_000_000)
  VEC_ANN_DIM      dimension     (default 128)
  VEC_ANN_QUERIES  query count   (default 5)
  VEC_ANN_ARTIFACT output path   (default VEC_r16.json next to repo root)
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

ROWS = int(os.environ.get("VEC_ANN_ROWS", str(10_000_000)))
DIM = int(os.environ.get("VEC_ANN_DIM", "128"))
N_QUERIES = int(os.environ.get("VEC_ANN_QUERIES", "5"))
N_SEGS = 4
N_CENTROIDS = 256
NPROBES = (1, 2, 4, 8, 16)
K = 10
ARTIFACT = os.environ.get(
    "VEC_ANN_ARTIFACT",
    os.path.join(os.path.dirname(__file__), "..", "VEC_r16.json"))


def main() -> int:
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import Schema, metric, vector
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.engine import QueryEngine
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    rng = np.random.default_rng(2016)
    per = ROWS // N_SEGS
    schema = Schema("vecbench", [
        metric("rid", DataType.INT),
        vector("emb", DIM),
    ])
    idx = IndexingConfig()
    idx.vector_index_configs = {"emb": {"numCentroids": N_CENTROIDS}}
    cfg = TableConfig("vecbench", indexing_config=idx)
    centers = rng.standard_normal((N_CENTROIDS, DIM)).astype(np.float32) * 4

    base = tempfile.mkdtemp(prefix="vec_ann_bench_")
    segs = []
    t_build0 = time.monotonic()
    try:
        for s in range(N_SEGS):
            which = rng.integers(0, N_CENTROIDS, per)
            emb = (centers[which] +
                   rng.standard_normal((per, DIM)).astype(np.float32) * 0.3)
            cols = {"rid": np.arange(per, dtype=np.int32) + s * per,
                    "emb": emb}
            d = os.path.join(base, f"b{s}")
            SegmentCreator(schema, cfg,
                           segment_name=f"b{s}").build(cols, d)
            del emb, cols
            segs.append(ImmutableSegmentLoader.load(d))
            print(f"vec_ann_bench: segment {s + 1}/{N_SEGS} sealed "
                  f"({per} rows, codebook trained) "
                  f"t={time.monotonic() - t_build0:.0f}s", flush=True)
        build_s = time.monotonic() - t_build0

        engine = QueryEngine(segs, use_device=False)
        queries = [(centers[int(rng.integers(N_CENTROIDS))] +
                    rng.standard_normal(DIM).astype(np.float32) * 0.3)
                   for _ in range(N_QUERIES)]

        def run(q, nprobe):
            qs = ", ".join(repr(float(x)) for x in q)
            clause = f", nprobe={nprobe}" if nprobe else ""
            t0 = time.monotonic()
            resp = engine.query(
                f"SELECT rid, VECTOR_SIMILARITY(emb, [{qs}], {K}, "
                f"'COSINE'{clause}) FROM vecbench")
            ms = (time.monotonic() - t0) * 1000.0
            assert not resp.exceptions, resp.exceptions
            rids = [int(r[0]) for r in resp.selection_results.results]
            return rids, resp.num_docs_scanned, ms

        exact = []
        for qi, q in enumerate(queries):
            rids, scanned, ms = run(q, 0)
            exact.append((rids, ms))
            print(f"vec_ann_bench: exact q{qi} {ms:.0f}ms "
                  f"scanned={scanned}", flush=True)
        exact_p50 = float(np.median([ms for _, ms in exact]))

        rungs = {}
        for nprobe in NPROBES:
            recalls, fracs, times = [], [], []
            for qi, q in enumerate(queries):
                rids, scanned, ms = run(q, nprobe)
                want = set(exact[qi][0])
                recalls.append(len(set(rids) & want) / len(want))
                fracs.append(scanned / ROWS)
                times.append(ms)
            p50 = float(np.median(times))
            rungs[f"nprobe={nprobe}"] = {
                "recall_at_10": round(float(np.mean(recalls)), 4),
                "recall_min": round(float(min(recalls)), 4),
                "scanned_fraction": round(float(np.mean(fracs)), 4),
                "p50_ms": round(p50, 2),
                "speedup_vs_exact": round(exact_p50 / p50, 2),
            }
            print(f"vec_ann_bench: nprobe={nprobe} "
                  f"recall@10={np.mean(recalls):.3f} "
                  f"scan={np.mean(fracs):.1%} p50={p50:.0f}ms "
                  f"({exact_p50 / p50:.1f}x)", flush=True)

        ok = any(r["recall_at_10"] >= 0.95 and
                 r["scanned_fraction"] < 0.15 for r in rungs.values())
        out = {
            "metric": "ivf_recall_and_speedup_vs_exact_scan",
            "backend": "cpu",
            "rows": ROWS, "dim": DIM, "segments": N_SEGS,
            "num_centroids_per_segment": N_CENTROIDS,
            "k": K, "queries": N_QUERIES,
            "data": "clustered (256 shared centers, sigma 0.3)",
            "build_s": round(build_s, 1),
            "exact_p50_ms": round(exact_p50, 2),
            "rungs": rungs,
            "gate": "recall@10 >= 0.95 at < 15% rows scanned",
            "pass": bool(ok),
        }
        with open(ARTIFACT, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"vec_ann_bench: artifact -> {ARTIFACT}  "
              f"{'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    finally:
        for seg in segs:
            getattr(seg, "close", lambda: None)()
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
