"""Dependency-free Avro Object Container File reader.

Parity: core/data/readers/AvroRecordReader.java (the reference's primary
batch-ingest format; its integration-test fixtures are all Avro).  The
environment has no avro library, so this is a from-scratch decoder for
the Avro 1.x spec subset Pinot ingests: a top-level record of primitive
fields (null/boolean/int/long/float/double/string/bytes/enum/fixed),
nullable unions, and arrays of primitives (multi-value columns).

Container format: magic "Obj\\x01", file-metadata map carrying
avro.schema (JSON) + avro.codec (null | deflate), 16-byte sync marker,
then data blocks of (record_count, byte_size, payload, sync).
"""
from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterator, Optional

from pinot_tpu.ingestion.record_reader import RecordReader

_MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# Primitive decoders (Avro binary encoding)
# ---------------------------------------------------------------------------

def read_long(buf: BinaryIO) -> int:
    """Zigzag varint."""
    shift, acc = 0, 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated avro varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not (v & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def read_bytes(buf: BinaryIO) -> bytes:
    n = read_long(buf)
    out = buf.read(n)
    if len(out) != n:
        raise EOFError("truncated avro bytes")
    return out


def _read_blocked(buf: BinaryIO, read_item) -> list:
    """Array/map encoding: blocks of (count[, size]) items, 0-terminated."""
    out = []
    while True:
        n = read_long(buf)
        if n == 0:
            return out
        if n < 0:  # negative count ⇒ block byte-size follows (skippable)
            read_long(buf)
            n = -n
        for _ in range(n):
            out.append(read_item(buf))


class _Decoder:
    """Compiled per-schema decode function tree."""

    def __init__(self, schema: Any, named: Optional[Dict[str, Any]] = None):
        self.named = named if named is not None else {}
        self.fn = self._compile(schema)

    def _compile(self, s: Any):
        if isinstance(s, list):  # union: index then value
            branches = [self._compile(b) for b in s]
            return lambda buf: branches[read_long(buf)](buf)
        if isinstance(s, dict):
            t = s["type"]
            if t in ("record", "enum", "fixed"):
                self.named[s["name"]] = s
            if t == "record":
                fields = [(f["name"], self._compile(f["type"]))
                          for f in s["fields"]]
                return lambda buf: {n: fn(buf) for n, fn in fields}
            if t == "array":
                item = self._compile(s["items"])
                return lambda buf: _read_blocked(buf, item)
            if t == "map":
                val = self._compile(s["values"])
                pair = lambda buf: (read_bytes(buf).decode("utf-8"), val(buf))
                return lambda buf: dict(_read_blocked(buf, pair))
            if t == "enum":
                symbols = s["symbols"]
                return lambda buf: symbols[read_long(buf)]
            if t == "fixed":
                n = s["size"]
                return lambda buf: buf.read(n)
            return self._compile(t)  # {"type": "long", ...} wrapper
        if s in self.named:  # named-type reference
            return self._compile(self.named[s])
        if s == "null":
            return lambda buf: None
        if s == "boolean":
            return lambda buf: buf.read(1) == b"\x01"
        if s in ("int", "long"):
            return read_long
        if s == "float":
            return lambda buf: struct.unpack("<f", buf.read(4))[0]
        if s == "double":
            return lambda buf: struct.unpack("<d", buf.read(8))[0]
        if s == "string":
            return lambda buf: read_bytes(buf).decode("utf-8")
        if s == "bytes":
            return read_bytes
        raise ValueError(f"unsupported avro type {s!r}")


# ---------------------------------------------------------------------------
# Container file
# ---------------------------------------------------------------------------

class AvroRecordReader(RecordReader):
    """Avro Object Container File → row dicts.

    Parity: AvroRecordReader.java / AvroUtils.  Codecs: null, deflate
    (raw zlib, per the Avro spec).
    """

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as fh:
            if fh.read(4) != _MAGIC:
                raise ValueError(f"{path}: not an Avro object container file")
            meta_pair = lambda buf: (read_bytes(buf).decode("utf-8"),
                                     read_bytes(buf))
            meta = dict(_read_blocked(fh, meta_pair))
            self.sync = fh.read(16)
            self._data_start = fh.tell()
        self.schema = json.loads(meta["avro.schema"].decode("utf-8"))
        self.codec = meta.get("avro.codec", b"null").decode("utf-8")
        if self.codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {self.codec!r}")
        if not (isinstance(self.schema, dict)
                and self.schema.get("type") == "record"):
            raise ValueError("top-level avro schema must be a record")
        self._decode = _Decoder(self.schema).fn

    def _rows(self) -> Iterator[dict]:
        with open(self.path, "rb") as fh:
            fh.seek(self._data_start)
            while True:
                head = fh.read(1)
                if not head:
                    return
                fh.seek(-1, io.SEEK_CUR)
                count = read_long(fh)
                size = read_long(fh)
                payload = fh.read(size)
                if len(payload) != size:
                    raise EOFError("truncated avro block")
                if fh.read(16) != self.sync:
                    raise ValueError("avro sync marker mismatch")
                if self.codec == "deflate":
                    payload = zlib.decompress(payload, -15)
                buf = io.BytesIO(payload)
                for _ in range(count):
                    yield self._decode(buf)
