"""Unit tests for the radix-factored group accumulation kernels.

The hi/lo one-hot factorization (ops/kernels.py _radix_onehots) must be
bit-exact with the direct one-hot matmul on both sides of the RADIX_G
threshold — these are the primitives every group-by result flows
through (parity: DefaultGroupByExecutor's per-function aggregation,
with exactness guarantees the reference gets from Java longs).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from pinot_tpu.ops import kernels


def _naive_hist(ids, mask, g):
    out = np.zeros(g, dtype=np.int64)
    np.add.at(out, ids[mask], 1)
    return out


@pytest.mark.parametrize("g_pad", [32, 128, 256, 1024, 8192])
def test_mxu_histogram_matches_naive(g_pad):
    """All three regimes: <=128 fused compare+reduce (the adaptive hist
    scout's path), direct bf16 matmul, hi/lo-factored radix."""
    rng = np.random.default_rng(1)
    n = kernels.BLOCK * 2
    ids = rng.integers(0, g_pad, n).astype(np.int32)
    mask = rng.random(n) < 0.3
    out = np.asarray(kernels._mxu_histogram(
        jnp.asarray(ids), jnp.asarray(mask), g_pad))
    np.testing.assert_array_equal(out, _naive_hist(ids, mask, g_pad))


@pytest.mark.parametrize("g_pad,n_parts", [(256, 4), (1024, 4), (4096, 2),
                                           (8192, 5), (32768, 4)])
def test_dense_group_part_sums_exact(g_pad, n_parts):
    """Covers the direct batched path (g < 512), the batched radix
    concat (n_l*g1 <= 128), and the wide-table scan fallback
    (g_pad=8192 with 6 lanes → n_l*g1 = 384; g_pad=32768 → 1280)."""
    rng = np.random.default_rng(2)
    n = kernels.BLOCK * 2
    key = rng.integers(0, g_pad, n).astype(np.int32)
    mask = rng.random(n) < 0.5
    parts = rng.integers(0, 128, (n_parts, n)).astype(np.int8)  # max 127
    out, count = kernels._dense_group_part_sums(
        [jnp.asarray(parts[p]) for p in range(n_parts)],
        jnp.asarray(key), jnp.asarray(mask), g_pad, with_count=True)
    exp = np.zeros((n_parts, g_pad), dtype=np.int64)
    for p in range(n_parts):
        np.add.at(exp[p], key[mask], parts[p][mask].astype(np.int64))
    np.testing.assert_array_equal(np.asarray(out), exp)
    np.testing.assert_array_equal(np.asarray(count),
                                  _naive_hist(key, mask, g_pad))


@pytest.mark.parametrize("g_pad", [256, 2048])
def test_dense_group_float_sums(g_pad):
    rng = np.random.default_rng(3)
    n = 4096 * 2
    key = rng.integers(0, g_pad, n).astype(np.int32)
    mask = rng.random(n) < 0.5
    vals = rng.random(n).astype(np.float64) * 100
    out = np.asarray(kernels._dense_group_float_sums(
        jnp.asarray(vals), jnp.asarray(key), jnp.asarray(mask), g_pad))
    exp = np.zeros(g_pad)
    np.add.at(exp, key[mask], vals[mask])
    np.testing.assert_allclose(out, exp, rtol=1e-9)


@pytest.mark.parametrize("t_slots", [300, 8192, 16384])
def test_slot_sum_tables_radix_and_direct(t_slots):
    """Both sides of the SLOT_RADIX_G threshold, with the drop slot, max
    7-bit plane values (the s8 contract: every int lane <= 127), and a
    non-divisible row count."""
    rng = np.random.default_rng(4)
    k = (1 << 16) + 777          # forces pad + a non-divisible chunk
    gslot = rng.integers(0, t_slots + 1, k).astype(np.int32)  # incl. drop
    int_vals = rng.integers(0, 128, (k, 3)).astype(np.int32)  # max 127
    f32_vals = (rng.random((k, 2)) * 10).astype(np.float64)
    count_mask = rng.random(k) < 0.9
    orig_chunk = kernels.SLOT_CHUNK
    kernels.SLOT_CHUNK = 1 << 16          # cover the multi-chunk scan
    try:
        ti, tf, tc = kernels._slot_sum_tables(
            jnp.asarray(gslot), t_slots, jnp.asarray(int_vals),
            jnp.asarray(f32_vals), jnp.asarray(count_mask))
    finally:
        kernels.SLOT_CHUNK = orig_chunk
    keep = gslot < t_slots
    exp_i = np.zeros((3, t_slots), dtype=np.int64)
    for li in range(3):
        np.add.at(exp_i[li], gslot[keep], int_vals[keep, li])
    np.testing.assert_array_equal(np.asarray(ti), exp_i)
    exp_f = np.zeros((2, t_slots))
    for li in range(2):
        np.add.at(exp_f[li], gslot[keep], f32_vals[keep, li])
    np.testing.assert_allclose(np.asarray(tf), exp_f, rtol=1e-9)
    exp_c = np.zeros(t_slots, dtype=np.int64)
    np.add.at(exp_c, gslot[keep & count_mask], 1)
    np.testing.assert_array_equal(np.asarray(tc), exp_c)


def test_radix_onehots_reconstruct():
    idx = jnp.asarray(np.arange(0, 1024, 7, dtype=np.int32))
    oh_hi, oh_lo = kernels._radix_onehots(idx, 1024, jnp.bfloat16)
    full = np.asarray(oh_hi)[:, :, None] * np.asarray(oh_lo)[:, None, :]
    direct = np.asarray(jnp.squeeze(
        jnp.asarray(np.eye(1024, dtype=np.float32))[idx]))
    np.testing.assert_array_equal(full.reshape(len(idx), 1024), direct)


def test_part_sums_oversized_fallback_exact():
    """_part_sums splits on 127 * padded < 2^31: the fast path fully
    reduces on device ([n_parts]); past ~16.9M padded rows the partsT
    block-partial fallback keeps int32 exact. Both must match an int64
    reference."""
    import numpy as np
    from pinot_tpu.ops.kernels import BLOCK, _part_sums

    rng = np.random.default_rng(5)
    for padded, expect_reduced in ((4 * BLOCK, True),
                                   (2065 * BLOCK, False)):   # >16.9M
        assert (127 * padded < 2**31) == expect_reduced
        lanes = rng.integers(0, 128, (2, padded)).astype(np.int8)
        mask = rng.random(padded) < 0.37
        sums, reduced = _part_sums(jnp.asarray(lanes), jnp.asarray(mask))
        assert reduced is expect_reduced
        got = np.asarray(sums).astype(np.int64)
        if not reduced:
            assert got.shape == (2, padded // BLOCK)
            got = got.sum(axis=1)
        ref = (lanes.astype(np.int64) * mask[None, :]).sum(axis=1)
        assert np.array_equal(got, ref)
