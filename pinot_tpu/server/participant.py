"""Server-side cluster participant: state transitions → segment lifecycle.

Parity: pinot-server/.../starter/helix/SegmentOnlineOfflineStateModelFactory
.java:81-156 (OFFLINE→ONLINE downloads + loads, ONLINE→OFFLINE unloads,
→DROPPED deletes local data) + SegmentFetcherAndLoader (deep-store fetch →
ImmutableSegmentLoader).
"""
from __future__ import annotations

from typing import Optional

from pinot_tpu.controller.manager import ResourceManager
from pinot_tpu.controller.state_machine import StateModel
from pinot_tpu.segment.loader import ImmutableSegmentLoader
from pinot_tpu.server.instance import ServerInstance


class ServerParticipant(StateModel):
    def __init__(self, server: ServerInstance, manager: ResourceManager):
        self.server = server
        self.manager = manager

    def on_become_online(self, table: str, segment: str) -> None:
        meta = self.manager.segment_metadata(table, segment)
        if meta is None:
            raise ValueError(f"no metadata for {table}/{segment}")
        seg = ImmutableSegmentLoader.load(meta["downloadPath"])
        self.server.data_manager.table(table, create=True).add_segment(seg)

    def on_become_offline(self, table: str, segment: str) -> None:
        tdm = self.server.data_manager.table(table)
        if tdm is not None:
            tdm.remove_segment(segment)

    def on_become_dropped(self, table: str, segment: str) -> None:
        pass  # local artifact cleanup is a no-op: segments load from deep store
