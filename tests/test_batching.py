"""Cross-query device batching: the dispatch coalescer.

Same-plan-shape queries that overlap in flight share ONE (vmapped)
kernel execution per segment. The contracts under test:

- coalescer state machine: solo queries pay nothing, overlapping
  same-shape queries lead/join a bounded window, members whose budget
  cannot survive the window bypass, seal() is idempotent;
- batched results are BIT-IDENTICAL to the sequential twin's — on the
  host, device, and mesh-sharded paths, and with an upsert validDocIds
  mask active (the mask rides the cols side, shared across members);
- `batchWindowMs=0` disables coalescing entirely (today's behavior);
- single-flight dedup: N identical concurrent queries on a cold cache
  execute once, the rest are served the leader's cache entry;
- a hedged duplicate that can join an open batch window is admitted
  past the low-watermark hedge shed (it rides the primary's dispatch).
"""
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from fixtures import build_segment

from pinot_tpu.common.datatable import DataTable, RESULT_CACHE_HIT_KEY
from pinot_tpu.common.metrics import ServerMeter, ServerTimer
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.serde import instance_request_to_bytes
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.server import ServerInstance
from pinot_tpu.server.scheduler import DispatchCoalescer


# ---------------------------------------------------------------------------
# Coalescer state machine (fake clock, no server)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_coalescer_solo_costs_nothing():
    clk = FakeClock()
    c = DispatchCoalescer(0.002, clock=clk)
    state, group = c.arrive("k", "m1", None)
    assert state == "solo" and group is None
    c.leave("k")
    # after leave the key is idle again: next arrival is solo too
    assert c.arrive("k", "m2", None)[0] == "solo"


def test_coalescer_lead_join_seal():
    clk = FakeClock()
    occupancies = []
    c = DispatchCoalescer(0.002, clock=clk,
                          on_dispatch=occupancies.append)
    assert c.arrive("k", "solo", None)[0] == "solo"   # in flight now
    state, g = c.arrive("k", "m1", None)
    assert state == "lead" and g is not None
    assert c.joinable("k")
    assert c.arrive("k", "m2", None) == ("joined", g)
    assert c.arrive("k", "m3", None) == ("joined", g)
    # a different key is unaffected
    assert c.arrive("other", "x", None)[0] == "solo"
    clk.t += 0.001
    assert c.remaining_window_s(g) == pytest.approx(0.001)
    members = c.seal(g)
    assert members == ["m1", "m2", "m3"]
    assert occupancies == [3]
    assert not c.joinable("k")
    # idempotent: the abandon callback racing the runner gets []
    assert c.seal(g) == []
    assert occupancies == [3]
    # the sealed group counts as in flight until leave(): a new arrival
    # while the batch (and the original solo) run becomes a fresh lead
    assert c.arrive("k", "m4", None)[0] == "lead"


def test_coalescer_deadline_bypass():
    clk = FakeClock()
    bypasses = []
    c = DispatchCoalescer(0.010, clock=clk,
                          on_bypass=lambda: bypasses.append(1))
    assert c.arrive("k", "solo", None)[0] == "solo"
    # min_slack_windows=2: under 20ms of budget cannot ride a 10ms
    # window and still execute — bypass, executing immediately
    state, _ = c.arrive("k", "tight", clk.t + 0.015)
    assert state == "bypass" and len(bypasses) == 1
    # a comfortable budget leads a window instead
    state, g = c.arrive("k", "roomy", clk.t + 10.0)
    assert state == "lead"
    # the group deadline is the TIGHTEST member's
    c.arrive("k", "tighter", clk.t + 5.0)
    assert g.deadline_s == pytest.approx(clk.t + 5.0)
    c.arrive("k", "looser", clk.t + 8.0)
    assert g.deadline_s == pytest.approx(clk.t + 5.0)


def test_coalescer_leave_accounting_survives_interleaving():
    c = DispatchCoalescer(0.002, clock=FakeClock())
    assert c.arrive("k", "a", None)[0] == "solo"
    _, g = c.arrive("k", "b", None)
    c.seal(g)              # two in flight now: solo + sealed batch
    c.leave("k")           # solo done
    assert c.arrive("k", "c", None)[0] == "lead"   # batch still runs
    c.leave("k")           # batch done


# ---------------------------------------------------------------------------
# End-to-end: batched results are bit-identical to sequential ones
# ---------------------------------------------------------------------------

# same plan shape (COUNT + SUM + filter literal), different literals —
# the coalescer's target workload; integer-exact so "bit-identical"
# is meaningful even across summation orders
BATCH_PQLS = [
    "SELECT COUNT(*), SUM(hits) FROM baseballStats_OFFLINE "
    "WHERE runs > '%d'" % lit for lit in (10, 40, 75, 110, 130)
]


def _request_bytes(pql, request_id=1, **kw):
    return instance_request_to_bytes(InstanceRequest(
        request_id=request_id, query=compile_pql(pql), **kw))


def _payload_of(dt: DataTable):
    # executionPath is provenance, not result content: a mesh twin
    # reports "sharded" while batch members ran the per-segment
    # kernels — the ROWS must still agree bitwise
    meta = {k: v for k, v in dt.metadata.items()
            if k not in ("requestId", RESULT_CACHE_HIT_KEY, "timeUsedMs",
                         "profileInfo", "executionPath")}
    return dt.kind, dt.columns, dt.rows, meta, dt.exceptions


def _server(batch_window_ms, mesh=None, use_device=True,
            num_segments=2, vdoc=False):
    s = ServerInstance("batch0", mesh=mesh, use_device=use_device,
                       batch_window_ms=batch_window_ms)
    for i in range(num_segments):
        seg, _ = build_segment(tempfile.mkdtemp(), n=700, seed=70 + i,
                               name=f"bt_{i}")
        if vdoc:
            from pinot_tpu.realtime.upsert import ValidDocIds
            seg.valid_doc_ids = ValidDocIds()
            for doc in range(0, 700, 7):       # mask 100 rows
                seg.valid_doc_ids.invalidate(doc)
        s.data_manager.table("baseballStats_OFFLINE",
                             create=True).add_segment(seg)
    return s


def _concurrent_replies(server, pqls, window_warm_s=0.0):
    """Fire one request per pql from its own thread, roughly at once."""
    barrier = threading.Barrier(len(pqls))

    def fire(i_pql):
        i, pql = i_pql
        barrier.wait()
        return DataTable.from_bytes(server.handle_request_bytes(
            _request_bytes(pql, 100 + i)))

    with ThreadPoolExecutor(max_workers=len(pqls)) as pool:
        return list(pool.map(fire, enumerate(pqls)))


@pytest.mark.parametrize("path", ["host", "device", "sharded"])
def test_batched_equals_sequential_bitwise(path):
    if path == "sharded":
        from pinot_tpu.parallel.sharded import make_mesh
        batched = _server(250.0, mesh=make_mesh())
        twin = _server(0.0, mesh=make_mesh())
    else:
        batched = _server(250.0, use_device=(path == "device"))
        twin = _server(0.0, use_device=(path == "device"))
    try:
        # sequential twin first: same segments (same seeds → same CRC),
        # strictly per-query dispatch (window 0 → no coalescer at all)
        assert twin.coalescer is None
        expected = [_payload_of(DataTable.from_bytes(
            twin.handle_request_bytes(_request_bytes(p, 10 + i))))
            for i, p in enumerate(BATCH_PQLS)]
        got = _concurrent_replies(batched, BATCH_PQLS)
        for pql, dt, want in zip(BATCH_PQLS, got, expected):
            assert not dt.exceptions, (pql, dt.exceptions)
            assert _payload_of(dt) == want, pql
        # the concurrent run really coalesced: at least one dispatch
        # served >1 query (the first arrival may have gone solo)
        assert batched.metrics.meter(
            ServerMeter.BATCHED_DISPATCHES).count >= 1
        occ = batched.metrics.timer(ServerTimer.BATCH_OCCUPANCY)
        assert occ.count >= 1 and occ.percentile_ms(100) >= 2
    finally:
        batched.stop()
        twin.stop()


def test_batched_equals_sequential_with_vdoc_mask():
    """The upsert validDocIds mask rides the shared cols side of the
    batched dispatch — every member must see the same masked view."""
    batched = _server(250.0, vdoc=True)
    twin = _server(0.0, vdoc=True)
    try:
        expected = [_payload_of(DataTable.from_bytes(
            twin.handle_request_bytes(_request_bytes(p, 10 + i))))
            for i, p in enumerate(BATCH_PQLS)]
        got = _concurrent_replies(batched, BATCH_PQLS)
        for pql, dt, want in zip(BATCH_PQLS, got, expected):
            assert not dt.exceptions, (pql, dt.exceptions)
            assert _payload_of(dt) == want, pql
        assert batched.metrics.meter(
            ServerMeter.BATCHED_DISPATCHES).count >= 1
    finally:
        batched.stop()
        twin.stop()


def test_batch_members_report_batch_size_in_profile():
    import json
    s = _server(250.0)
    try:
        got = _concurrent_replies(s, BATCH_PQLS)
        sizes = [json.loads(dt.metadata["profileInfo"])["batchSize"]
                 for dt in got]
        # at least one member rode a >1 batch; every member reports a
        # positive size, and solo members report exactly 1
        assert max(sizes) >= 2
        assert all(b >= 1 for b in sizes)
    finally:
        s.stop()


def test_window_zero_disables_coalescing():
    s = _server(0.0)
    try:
        assert s.coalescer is None
        got = _concurrent_replies(s, BATCH_PQLS)
        for dt in got:
            assert not dt.exceptions
        assert s.metrics.meter(ServerMeter.BATCHED_DISPATCHES).count == 0
        assert s.metrics.timer(ServerTimer.BATCH_OCCUPANCY).count == 0
    finally:
        s.stop()


def test_sequential_queries_never_wait_for_a_window():
    """An idle server (nothing same-shape in flight) executes every
    query immediately — the window costs an unbatched workload
    nothing, even with a deliberately huge window configured."""
    s = _server(batch_window_ms=10_000.0)
    try:
        t0 = time.perf_counter()
        for i, pql in enumerate(BATCH_PQLS):
            dt = DataTable.from_bytes(s.handle_request_bytes(
                _request_bytes(pql, 10 + i)))
            assert not dt.exceptions
            time.sleep(0.01)    # let the leave() done-callback land
        assert time.perf_counter() - t0 < 5.0   # no 10s sleeps anywhere
        # nothing overlapped → nothing batched
        assert s.metrics.meter(ServerMeter.BATCHED_DISPATCHES).count == 0
    finally:
        s.stop()


def test_group_by_queries_stay_unbatched_but_correct():
    """GROUP BY plans are excluded from the batched dispatch (their
    scout phases are value-dependent) — concurrent same-shape group-bys
    must still answer correctly through the coalescer plumbing."""
    pqls = ["SELECT SUM(hits) FROM baseballStats_OFFLINE "
            "WHERE runs > '%d' GROUP BY teamID TOP 30" % lit
            for lit in (10, 40, 75, 110)]
    batched = _server(250.0)
    twin = _server(0.0)
    try:
        expected = [_payload_of(DataTable.from_bytes(
            twin.handle_request_bytes(_request_bytes(p, 10 + i))))
            for i, p in enumerate(pqls)]
        got = _concurrent_replies(batched, pqls)
        for pql, dt, want in zip(pqls, got, expected):
            assert not dt.exceptions, (pql, dt.exceptions)
            assert _payload_of(dt) == want, pql
    finally:
        batched.stop()
        twin.stop()


# ---------------------------------------------------------------------------
# Single-flight dedup (satellite): identical concurrent queries
# ---------------------------------------------------------------------------


def test_single_flight_dedups_identical_cold_queries():
    s = _server(0.0)    # no coalescer: isolates the single-flight path
    try:
        pql = BATCH_PQLS[0]
        n = 6
        barrier = threading.Barrier(n)

        def fire(i):
            barrier.wait()
            return DataTable.from_bytes(s.handle_request_bytes(
                _request_bytes(pql, 200 + i)))

        with ThreadPoolExecutor(max_workers=n) as pool:
            got = list(pool.map(fire, range(n)))
        rows = {tuple(map(tuple, dt.rows)) for dt in got}
        assert len(rows) == 1       # every reply has the same result rows
        # followers waited on the leader and were served its entry
        waits = s.metrics.meter(ServerMeter.SINGLE_FLIGHT_WAITS).count
        hits = s.metrics.meter(ServerMeter.RESULT_CACHE_HITS).count
        assert waits >= 1 and hits >= 1
        # every reply carries its OWN requestId (fresh DataTable per
        # follower, no shared mutable reply)
        assert {dt.metadata["requestId"] for dt in got} == \
            {str(200 + i) for i in range(n)}
    finally:
        s.stop()


def test_single_flight_follower_falls_through_on_leader_failure():
    from pinot_tpu.server.result_cache import SingleFlight
    sf = SingleFlight()
    is_leader, ev = sf.begin(("k",))
    assert is_leader
    is_leader2, ev2 = sf.begin(("k",))
    assert not is_leader2 and ev2 is ev
    # leader "fails" (stores nothing) — done() still releases waiters
    sf.done(("k",))
    assert ev.wait(0.1)
    # the key is retired: a new arrival leads again
    assert sf.begin(("k",))[0]
    sf.done(("k",))
    # done() on an unknown key is harmless
    sf.done(("nope",))


# ---------------------------------------------------------------------------
# Hedge-join admission carve-out (satellite)
# ---------------------------------------------------------------------------


def test_hedged_duplicate_joins_open_batch_instead_of_shedding():
    """At the low watermark hedges are shed — UNLESS this server holds
    an open batch window for the hedge's plan shape, in which case it
    rides the primary's dispatch. Exercises the real `_admit` gate
    with a hand-opened window (the scheduler never runs here)."""
    s = _server(batch_window_ms=30_000.0, num_segments=1)
    depth = s.admission.low           # sit exactly at the low watermark
    try:
        for i in range(depth):
            assert s.admission.admit("baseballStats_OFFLINE", f"t{i}")
        pql = BATCH_PQLS[0]
        hedge = InstanceRequest(request_id=1, query=compile_pql(pql),
                                hedge=True)
        # no open window: the hedge is shed at the low watermark
        decision, busy, _ = s._admit(hedge)
        assert not decision and decision.cause == "hedge"
        assert busy is not None
        # open a window for that plan shape (a primary is in flight and
        # a same-shape query led a window)
        key = s._batch_key(hedge)
        assert s.coalescer.arrive(key, "primary", None)[0] == "solo"
        _, group = s.coalescer.arrive(key, "leader", None)
        assert s.coalescer.joinable(key)
        # the same hedge is now admitted: it will ride the open batch
        decision2, busy2, tenant2 = s._admit(hedge)
        assert decision2 and busy2 is None
        s.admission.release(tenant2)
        # ...while a hedge with a DIFFERENT plan shape is still shed
        other = InstanceRequest(
            request_id=2, hedge=True,
            query=compile_pql(
                "SELECT MAX(runs) FROM baseballStats_OFFLINE"))
        decision3, busy3, _ = s._admit(other)
        assert not decision3 and decision3.cause == "hedge"
        s.coalescer.seal(group)
    finally:
        for i in range(depth):
            s.admission.release(f"t{i}")
        s.stop()
