"""SLO gate layer for the production soak (ROADMAP item 5).

Three machine-checkable pieces:

- :func:`classify_response` — flagged-vs-unflagged over a
  ``BrokerResponse`` (or its JSON): a degraded response is FLAGGED when
  every exception entry carries a structured ``errorCode`` (and the
  ``partialResponse`` bit covers exception-free truncation); it is
  UNFLAGGED the moment any entry signals degradation only via message
  text. "Zero unflagged errors" is then an assertion over counters, not
  a grep — and an unflagged error is itself the bug report: some path
  forgot `common/response.py`'s ``EXCEPTION_CLASSES``.
- :class:`SLOTracker` — per-query-class latency ladders (p50/p95/p99
  from full sample lists) plus ok/flagged/unflagged counts and a cause
  histogram, with declared p99 bounds checked by :meth:`violations`.
- :class:`GaugeSeries` — leak-flatness detector over a sampled gauge
  (RSS, ``upsertKeyMapSize``, exchange held-bytes, residency ledger):
  drops a settle window (caches fill, pools warm, churn reaches steady
  state — a step there is startup, not a leak), then requires the
  least-squares trend over the remainder to project ~zero growth across
  the observed window. Linear growth fails; step-after-churn-settles
  passes; a 30-minute window is long enough that a real leak cannot
  hide inside the tolerance.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


def _resp_json(resp) -> dict:
    if isinstance(resp, dict):
        return resp
    return resp.to_json()


def classify_response(resp) -> Tuple[str, List[str]]:
    """→ (cls, causes): cls in {"ok", "flagged", "unflagged"}.

    ok: no exceptions, not partial. flagged: every exceptions[] entry
    carries an integer errorCode (cause slugs collected; a partial
    response with no exceptions is flagged as "partial" — the
    partialResponse bit IS its structured marker). unflagged: any entry
    without an errorCode — degradation only a human reading message
    text could detect."""
    d = _resp_json(resp)
    exceptions = d.get("exceptions") or []
    partial = bool(d.get("partialResponse"))
    if not exceptions and not partial:
        return "ok", []
    causes: List[str] = []
    unflagged = False
    for e in exceptions:
        if not isinstance(e.get("errorCode"), int):
            unflagged = True
            causes.append("unclassified")
        else:
            causes.append(e.get("cause") or f"code{e['errorCode']}")
    if partial and not exceptions:
        causes.append("partial")
    return ("unflagged" if unflagged else "flagged"), causes


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q / 100.0 * len(sorted_vals))) - 1))
    return sorted_vals[i]


class SLOTracker:
    """Per-query-class latency ladder + structured error tally.

    ``p99_bounds_ms`` declares the gate: class → p99 upper bound.
    Classes not in the bounds map are tracked but ungated."""

    def __init__(self, p99_bounds_ms: Optional[Dict[str, float]] = None):
        self.p99_bounds_ms = dict(p99_bounds_ms or {})
        self._samples: Dict[str, List[float]] = {}
        self._counts: Dict[str, Dict[str, int]] = {}
        self._causes: Dict[str, Dict[str, int]] = {}
        self.unflagged_examples: List[dict] = []

    def record(self, qclass: str, latency_ms: float, resp=None) -> str:
        """Record one query; returns its classification."""
        self._samples.setdefault(qclass, []).append(float(latency_ms))
        cls, causes = ("ok", []) if resp is None \
            else classify_response(resp)
        counts = self._counts.setdefault(
            qclass, {"ok": 0, "flagged": 0, "unflagged": 0})
        counts[cls] += 1
        ch = self._causes.setdefault(qclass, {})
        for c in causes:
            ch[c] = ch.get(c, 0) + 1
        if cls == "unflagged" and len(self.unflagged_examples) < 20:
            d = _resp_json(resp)
            self.unflagged_examples.append(
                {"class": qclass,
                 "exceptions": d.get("exceptions") or []})
        return cls

    def unflagged_total(self) -> int:
        return sum(c["unflagged"] for c in self._counts.values())

    def snapshot(self) -> dict:
        out: Dict[str, dict] = {}
        for qclass, samples in sorted(self._samples.items()):
            s = sorted(samples)
            counts = self._counts.get(
                qclass, {"ok": 0, "flagged": 0, "unflagged": 0})
            entry = {
                "count": len(s),
                "p50Ms": round(_percentile(s, 50), 3),
                "p95Ms": round(_percentile(s, 95), 3),
                "p99Ms": round(_percentile(s, 99), 3),
                "maxMs": round(s[-1], 3) if s else 0.0,
                **counts,
            }
            if self._causes.get(qclass):
                entry["causes"] = dict(sorted(
                    self._causes[qclass].items()))
            bound = self.p99_bounds_ms.get(qclass)
            if bound is not None:
                entry["p99BoundMs"] = bound
            out[qclass] = entry
        return out

    def violations(self) -> List[str]:
        """Human-readable SLO violations: p99 over bound, or any
        unflagged error anywhere."""
        out: List[str] = []
        snap = self.snapshot()
        for qclass, entry in snap.items():
            bound = entry.get("p99BoundMs")
            if bound is not None and entry["p99Ms"] > bound:
                out.append(f"{qclass}: p99 {entry['p99Ms']}ms > "
                           f"bound {bound}ms")
            if entry["unflagged"]:
                out.append(f"{qclass}: {entry['unflagged']} UNFLAGGED "
                           f"errors (degradation without structured "
                           f"errorCode)")
        return out


@dataclasses.dataclass
class GaugeVerdict:
    name: str
    flat: bool
    reason: str
    samples: int
    window_s: float
    mean: float
    projected_growth: float     # fitted slope × analysed window
    rel_growth: float           # projected growth / max(|mean|, 1)

    def to_json(self) -> dict:
        return {"name": self.name, "flat": self.flat,
                "reason": self.reason, "samples": self.samples,
                "windowS": round(self.window_s, 1),
                "mean": round(self.mean, 2),
                "projectedGrowth": round(self.projected_growth, 2),
                "relGrowth": round(self.rel_growth, 4)}


class GaugeSeries:
    """Leak-flatness detector over one sampled gauge.

    ``settle_frac`` of the time window is discarded before fitting (a
    step while churn settles is startup, not a leak). Over the rest, a
    least-squares line is fit; the series is FLAT when the projected
    growth across the analysed window is within ``abs_tol`` or within
    ``rel_tol`` of the series mean. Linear growth projects its full
    rise and fails; a settled step projects ~zero and passes.

    ``bound`` switches the detector to bounded mode for gauges that are
    structurally capped but wobble under chaos (a replica kill wipes a
    server's upsert key map; the healed replacement rebuilds it, which
    reads as a positive slope without being a leak). In bounded mode
    the series is FLAT iff every post-settle sample stays at or under
    ``bound`` — a real leak grows with churn and crosses any sane cap,
    while legitimate rebuild wobble cannot."""

    def __init__(self, name: str, settle_frac: float = 0.25,
                 rel_tol: float = 0.10, abs_tol: float = 0.0,
                 bound: Optional[float] = None):
        self.name = name
        self.settle_frac = settle_frac
        self.rel_tol = rel_tol
        self.abs_tol = abs_tol
        self.bound = bound
        self._ts: List[float] = []
        self._vs: List[float] = []

    def add(self, t_s: float, value: float) -> None:
        self._ts.append(float(t_s))
        self._vs.append(float(value))

    def series(self) -> List[Tuple[float, float]]:
        return list(zip(self._ts, self._vs))

    def verdict(self) -> GaugeVerdict:
        n = len(self._ts)
        if n < 4:
            return GaugeVerdict(self.name, True, "insufficient samples",
                                n, 0.0, 0.0, 0.0, 0.0)
        t0, t1 = self._ts[0], self._ts[-1]
        window = t1 - t0
        cut = t0 + window * self.settle_frac
        ts = [t for t in self._ts if t >= cut]
        vs = [v for t, v in zip(self._ts, self._vs) if t >= cut]
        if len(ts) < 3 or ts[-1] <= ts[0]:
            return GaugeVerdict(self.name, True, "insufficient samples "
                                "after settle window", n, window,
                                0.0, 0.0, 0.0)
        if self.bound is not None:
            mean_v = sum(vs) / len(vs)
            mx = max(vs)
            flat = mx <= self.bound
            reason = (f"bounded: max {mx:.1f} <= cap {self.bound:.1f}"
                      if flat else
                      f"max {mx:.1f} exceeds cap {self.bound:.1f}")
            return GaugeVerdict(self.name, flat, reason, n, window,
                                mean_v, mx - self.bound, 0.0)
        # least-squares slope, no numpy needed (soak imports stay light)
        m = len(ts)
        mean_t = sum(ts) / m
        mean_v = sum(vs) / m
        den = sum((t - mean_t) ** 2 for t in ts)
        slope = 0.0 if den == 0 else \
            sum((t - mean_t) * (v - mean_v)
                for t, v in zip(ts, vs)) / den
        analysed = ts[-1] - ts[0]
        projected = slope * analysed
        scale = max(abs(mean_v), 1.0)
        rel = abs(projected) / scale
        flat = abs(projected) <= self.abs_tol or rel <= self.rel_tol
        reason = "flat" if flat else (
            f"projects {projected:+.1f} over {analysed:.0f}s "
            f"({rel:.1%} of mean {mean_v:.1f})")
        return GaugeVerdict(self.name, flat, reason, n, window,
                            mean_v, projected, rel)
