"""HBM residency ledger: every device upload on the serving path is
accounted at ONE choke point.

Parity: the reference's PinotDataBuffer global accounting
(segment-spi/.../memory/PinotDataBuffer.java keeps a process-wide map
of every off-heap allocation with owner/context strings so operators
can answer "what is holding native memory"). On this architecture the
native memory is HBM, and the allocations are device uploads: segment
scan lanes, upsert validDocIds lanes, vector ``[n, dim]`` blocks,
sharded stack lanes, stage-2 join probe structures, window/HLL
operands, and exchange-held stage-1 blocks.

Every upload registers ``(owner, table, segment, kind, bytes)`` here —
through the :func:`ledgered_put` / :func:`ledgered_asarray` choke
points for device arrays, or :meth:`ResidencyLedger.register` for
byte-budgeted stores (the exchange plane) — and releases on eviction /
segment drop / sweep. The tpulint ``device-ledger`` rule (lifecycle
tier) proves the coverage: a raw ``jax.device_put`` / ``jnp.asarray``
materialization site on the serving path that bypasses this module is
a finding, so the ledger can never silently under-count. ROADMAP item
1's tiered-residency manager budgets against exactly this metering.

Exposure: ``deviceBytesResident{table,kind}`` gauges on every
component's /metrics (pre-registered at boot so the first scrape
already carries the series), and the ``/debug/residency`` view on the
server admin API.

The ledger is process-global on purpose: HBM is a per-process resource,
so embedded multi-component clusters report one truthful total from
every component's registry rather than a per-component fiction.
"""
from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from pinot_tpu.common.metrics import CommonGauge

#: the accounted upload kinds — also the pre-registered gauge series.
#: scan: immutable/frozen segment column lanes (ids/vals/raw/mv/parts/
#: vlane); vdoc: upsert validDocIds liveness lanes; vector: [n, dim]
#: embedding blocks; hll: per-dictId HLL register tables; stack: the
#: sharded executor's mesh-stacked lanes (incl. its num_docs vector);
#: join: stage-2 probe structures built from exchanged dim blocks;
#: window: stage-2 window operand columns; exchange: published stage-1
#: DataTable bytes held by an ExchangeManager.
KINDS = ("scan", "vdoc", "vector", "hll", "stack", "join", "window",
         "exchange")


class ResidencyLedger:
    """Thread-safe (owner → table/segment/kind/bytes) residency map.

    ``register`` with an owner key that is already present REPLACES the
    entry (re-upload of the same lane — e.g. a vdoc version bump — is a
    replacement, not a leak). Totals are maintained incrementally so
    gauge reads are O(1) dict lookups, never a scan.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # owner → (table, segment, kind, nbytes)
        self._entries: Dict[str, Tuple[str, str, str, int]] = {}
        self._by_kind: Dict[str, int] = {k: 0 for k in KINDS}
        self._by_table_kind: Dict[Tuple[str, str], int] = {}
        self._total = 0
        # sweepers run before exchange-kind reads so expired entries
        # leave the books on scrape, not on the next put/get (the
        # bytes-conservation invariant the protocol model checks)
        self._sweepers: List[Callable[[], int]] = []
        # optional snapshot-entry annotator (the residency manager adds
        # tier + last-access heat so /debug/residency says WHY a byte
        # is resident, not just that it is)
        self._entry_annotator: Optional[Callable[[dict], None]] = None

    # -- accounting --------------------------------------------------------
    def register(self, owner: str, *, table: str, segment: str,
                 kind: str, nbytes: int) -> None:
        assert kind in KINDS, kind
        nbytes = int(nbytes)
        with self._lock:
            self._drop(owner)
            self._entries[owner] = (table, segment, kind, nbytes)
            self._by_kind[kind] = self._by_kind.get(kind, 0) + nbytes
            tk = (table, kind)
            self._by_table_kind[tk] = \
                self._by_table_kind.get(tk, 0) + nbytes
            self._total += nbytes
        if table:
            _ensure_table_gauge(table, kind)

    def release(self, owner: str) -> int:
        """Release one owner's entry; returns the bytes released."""
        with self._lock:
            return self._drop(owner)

    def release_prefix(self, prefix: str) -> int:
        """Release every entry whose owner starts with `prefix` (one
        segment's lanes, one stack's lanes, one manager's blocks)."""
        with self._lock:
            owners = [o for o in self._entries if o.startswith(prefix)]
            return sum(self._drop(o) for o in owners)

    def _drop(self, owner: str) -> int:
        # caller holds the lock
        entry = self._entries.pop(owner, None)
        if entry is None:
            return 0
        table, _segment, kind, nbytes = entry
        self._by_kind[kind] -= nbytes
        tk = (table, kind)
        left = self._by_table_kind.get(tk, 0) - nbytes
        if left:
            self._by_table_kind[tk] = left
        else:
            self._by_table_kind.pop(tk, None)
        self._total -= nbytes
        return nbytes

    # -- reads -------------------------------------------------------------
    def total_bytes(self) -> int:
        return self._total

    def kind_bytes(self, kind: str) -> int:
        if kind == "exchange":
            self.run_sweepers()
        return self._by_kind.get(kind, 0)

    def table_kind_bytes(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._by_table_kind)

    def snapshot(self, max_entries: int = 512) -> dict:
        """JSON-able view for /debug/residency: totals by table/kind
        plus the largest individual entries."""
        self.run_sweepers()
        with self._lock:
            tables: Dict[str, Dict[str, int]] = {}
            for (table, kind), n in self._by_table_kind.items():
                tables.setdefault(table or "", {})[kind] = n
            largest = sorted(self._entries.items(),
                             key=lambda kv: -kv[1][3])[:max_entries]
            snap = {
                "totalDeviceBytesResident": self._total,
                "byKind": {k: v for k, v in sorted(self._by_kind.items())
                           if v},
                "tables": {t: dict(sorted(ks.items()))
                           for t, ks in sorted(tables.items())},
                "entries": [
                    {"owner": o, "table": t, "segment": s, "kind": k,
                     "bytes": n}
                    for o, (t, s, k, n) in largest],
                "entryCount": len(self._entries),
            }
            annot = self._entry_annotator
        if annot is not None:
            for entry in snap["entries"]:
                annot(entry)
        return snap

    def set_entry_annotator(self, fn: Callable[[dict], None]) -> None:
        """Install (or clear, with None) a per-entry snapshot annotator.
        The residency manager uses this to stamp `tier` and `heat`
        columns onto entries it tracks — annotation runs OUTSIDE the
        ledger lock, on the already-built entry dicts."""
        with self._lock:
            self._entry_annotator = fn

    # -- sweep hooks (exchange TTL) ----------------------------------------
    def add_sweeper(self, fn: Callable[[], int]) -> None:
        with self._lock:
            self._sweepers.append(fn)

    def remove_sweeper(self, fn: Callable[[], int]) -> None:
        with self._lock:
            try:
                self._sweepers.remove(fn)
            except ValueError:
                pass

    def run_sweepers(self) -> int:
        """TTL-sweep every registered byte-budgeted store (exchange
        managers) so expired entries release NOW — scraping /metrics or
        /debug/residency must observe quiescent held-bytes at zero, not
        whenever the next put/get happens to sweep."""
        with self._lock:
            sweepers = list(self._sweepers)
        return sum(fn() for fn in sweepers)


#: the process-global ledger every upload site and gauge reads
LEDGER = ResidencyLedger()

#: the declared metric name (common/metrics.py is the naming contract)
DEVICE_BYTES_RESIDENT = CommonGauge.DEVICE_BYTES_RESIDENT


# ---------------------------------------------------------------------------
# Upload choke points
# ---------------------------------------------------------------------------


def ledgered_put(host, *, owner: str, table: str, segment: str,
                 kind: str, sharding=None):
    """``jax.device_put`` with ledger registration — THE accountable
    upload path for explicitly-placed (possibly mesh-sharded) arrays.
    `owner` must be unique per resident array and stable across
    re-uploads of the same logical lane (replacement semantics)."""
    import jax
    arr = jax.device_put(host, sharding) if sharding is not None \
        else jax.device_put(host)
    LEDGER.register(owner, table=table, segment=segment, kind=kind,
                    nbytes=int(arr.nbytes))
    return arr


def ledgered_asarray(host, *, owner: str, table: str, segment: str,
                     kind: str):
    """``jnp.asarray`` with ledger registration (dtype canonicalization
    preserved — segment lanes rely on jax's x64-mode downcast)."""
    import jax.numpy as jnp
    arr = jnp.asarray(host)
    LEDGER.register(owner, table=table, segment=segment, kind=kind,
                    nbytes=int(arr.nbytes))
    return arr


# ---------------------------------------------------------------------------
# Boot-time gauge wiring
# ---------------------------------------------------------------------------


#: registries bound at boot (weakly — embedded test clusters churn
#: registries); new (table, kind) pairs register their per-table gauge
#: on every live bound registry as uploads appear
_BOUND: List["weakref.ref"] = []
_BOUND_LOCK = threading.Lock()
_TABLE_GAUGES: set = set()


def _live_bound() -> List[object]:
    # caller holds _BOUND_LOCK; prunes dead refs in place
    live, refs = [], []
    for ref in _BOUND:
        m = ref()
        if m is not None:
            live.append(m)
            refs.append(ref)
    _BOUND[:] = refs
    return live


def bind_registry(metrics) -> None:
    """Pre-register every residency gauge on a component registry at
    boot: the bare process total plus one per-kind series (the
    ``kind`` label rides the registry's table-suffix convention as
    ``|<kind>``; obs/prometheus.py splits it back into labels). The
    first scrape therefore already carries `deviceBytesResident` —
    empty-registry exposition was a real PR 5 bug class. Per-table
    twins (``<table>|<kind>`` suffix) register as uploads appear."""
    metrics.gauge(DEVICE_BYTES_RESIDENT).set_callable(LEDGER.total_bytes)
    for kind in KINDS:
        metrics.gauge(DEVICE_BYTES_RESIDENT,
                      table=f"|{kind}").set_callable(
            lambda k=kind: LEDGER.kind_bytes(k))
    with _BOUND_LOCK:
        if not any(m is metrics for m in _live_bound()):
            _BOUND.append(weakref.ref(metrics))
        pairs = list(_TABLE_GAUGES)
    for table, kind in pairs:
        metrics.gauge(DEVICE_BYTES_RESIDENT,
                      table=f"{table}|{kind}").set_callable(
            lambda t=table, k=kind:
            LEDGER.table_kind_bytes().get((t, k), 0))


def _ensure_table_gauge(table: str, kind: str) -> None:
    with _BOUND_LOCK:
        if (table, kind) in _TABLE_GAUGES:
            return
        _TABLE_GAUGES.add((table, kind))
        bound = _live_bound()
    for metrics in bound:
        metrics.gauge(DEVICE_BYTES_RESIDENT,
                      table=f"{table}|{kind}").set_callable(
            lambda t=table, k=kind:
            LEDGER.table_kind_bytes().get((t, k), 0))
