"""Stream factory/decoder registries + table-config → StreamConfig.

Parity: the reference instantiates StreamConsumerFactory and
StreamMessageDecoder by class name from the table's streamConfigs map
(StreamConfig.java / StreamConsumerFactoryProvider). Class-name reflection
becomes a process-local registry: connectors (or tests) register factory
instances under a name, and table configs reference them with
``stream.factory.name``.
"""
from __future__ import annotations

from typing import Dict, Optional

from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.realtime.stream import (JsonMessageDecoder, SMALLEST_OFFSET,
                                       StreamConfig, StreamConsumerFactory,
                                       StreamMessageDecoder)

_factories: Dict[str, StreamConsumerFactory] = {}
_decoders: Dict[str, type] = {"json": JsonMessageDecoder}


def register_stream_factory(name: str, factory: StreamConsumerFactory
                            ) -> None:
    _factories[name] = factory


def unregister_stream_factory(name: str) -> None:
    _factories.pop(name, None)


def _tcp_provider(stream_configs: Dict[str, str]) -> StreamConsumerFactory:
    """Built-in cross-process connector: the factory is constructed from
    the table config alone (stream.tcp.host/port), so a REMOTE server
    process needs no pre-registered in-process object — the property
    that makes realtime work across OS processes (parity: the Kafka
    connector's broker-list-in-config construction,
    KafkaPartitionLevelConsumer.java)."""
    from pinot_tpu.realtime.tcp_stream import TcpStreamConsumerFactory
    return TcpStreamConsumerFactory(
        stream_configs.get("stream.tcp.host", "127.0.0.1"),
        int(stream_configs["stream.tcp.port"]))


# factory PROVIDERS build a factory from the streamConfigs map itself;
# instance registrations (register_stream_factory) take precedence
_providers = {"tcp": _tcp_provider}


def get_stream_factory(name: str, stream_configs: Optional[Dict[str, str]]
                       = None) -> StreamConsumerFactory:
    if name in _factories:
        return _factories[name]
    if name in _providers and stream_configs is not None:
        return _providers[name](stream_configs)
    raise KeyError(f"no stream factory registered under {name!r}")


def register_decoder(name: str, decoder_cls: type) -> None:
    _decoders[name] = decoder_cls


def resolve_stream_config(table_config: TableConfig) -> StreamConfig:
    """streamConfigs map → StreamConfig (factory/decoder resolved here).

    Recognized keys (parity: CommonConstants.Helix.DataSource.Realtime /
    realtime.segment.flush.*):
      stream.factory.name            registry key (required)
      stream.topic.name              topic (required)
      stream.decoder.name            decoder registry key (default "json")
      stream.offset.criteria         smallest|largest (default smallest)
      realtime.segment.flush.threshold.size     rows per segment
      realtime.segment.flush.threshold.time.ms  ms per segment
      stream.fetch.timeout.ms
    """
    sc = table_config.indexing_config.stream_configs or {}
    factory = get_stream_factory(sc["stream.factory.name"], sc)
    decoder_cls = _decoders[sc.get("stream.decoder.name", "json")]
    kw = {}
    if "realtime.segment.flush.threshold.size" in sc:
        kw["flush_threshold_rows"] = int(
            sc["realtime.segment.flush.threshold.size"])
    if "realtime.segment.flush.threshold.time.ms" in sc:
        kw["flush_threshold_time_ms"] = int(
            sc["realtime.segment.flush.threshold.time.ms"])
    if "stream.fetch.timeout.ms" in sc:
        kw["fetch_timeout_ms"] = int(sc["stream.fetch.timeout.ms"])
    return StreamConfig(
        topic=sc["stream.topic.name"],
        consumer_factory=factory,
        decoder=decoder_cls(),
        offset_criteria=sc.get("stream.offset.criteria", SMALLEST_OFFSET),
        **kw)
