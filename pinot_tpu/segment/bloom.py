"""Bloom filter for segment pruning on equality predicates.

Parity: pinot-core/.../segment/creator/impl/bloom/BloomFilterCreator.java and
index/readers/BloomFilterReader.java (guava BloomFilter underneath). Same use:
the ColumnValueSegmentPruner rejects segments whose bloom filter definitely
does not contain the EQ value (SURVEY.md §2.4).
"""
from __future__ import annotations

import hashlib
import math
import os

import numpy as np

from pinot_tpu.segment import format as fmt

DEFAULT_FPP = 0.05
MAX_BITS = 1 << 20  # cap per column, mirrors reference's 1MB default cap


def _hashes(value: str, num_hashes: int, num_bits: int) -> np.ndarray:
    digest = hashlib.md5(value.encode("utf-8")).digest()
    h1 = int.from_bytes(digest[:8], "little")
    h2 = int.from_bytes(digest[8:], "little") | 1
    return np.array([(h1 + i * h2) % num_bits for i in range(num_hashes)],
                    dtype=np.int64)


class BloomFilter:
    def __init__(self, num_bits: int, num_hashes: int,
                 bits: np.ndarray | None = None):
        self.num_bits = num_bits
        self.num_hashes = num_hashes
        self.bits = bits if bits is not None else np.zeros(
            (num_bits + 63) // 64, dtype=np.uint64)

    @classmethod
    def with_capacity(cls, n_items: int, fpp: float = DEFAULT_FPP
                      ) -> "BloomFilter":
        n_items = max(n_items, 1)
        m = int(-n_items * math.log(fpp) / (math.log(2) ** 2))
        m = max(64, min(m, MAX_BITS))
        k = max(1, round(m / n_items * math.log(2)))
        return cls(m, k)

    def add(self, value) -> None:
        idx = _hashes(str(value), self.num_hashes, self.num_bits)
        np.bitwise_or.at(self.bits, idx // 64,
                         np.uint64(1) << (idx % 64).astype(np.uint64))

    def might_contain(self, value) -> bool:
        idx = _hashes(str(value), self.num_hashes, self.num_bits)
        got = (self.bits[idx // 64] >> (idx % 64).astype(np.uint64)) & np.uint64(1)
        return bool(got.all())

    # -- serde -------------------------------------------------------------
    def save(self, seg_dir: str, col: str) -> None:
        header = np.array([self.num_bits, self.num_hashes], dtype=np.uint64)
        np.save(os.path.join(seg_dir, fmt.BLOOM.format(col=col)),
                np.concatenate([header, self.bits]))

    @classmethod
    def load(cls, seg_dir, col: str) -> "BloomFilter":
        arr = np.asarray(fmt.open_dir(seg_dir).load_array(
            fmt.BLOOM.format(col=col)))
        num_bits, num_hashes = int(arr[0]), int(arr[1])
        return cls(num_bits, num_hashes, arr[2:].copy())
