"""CreateSegment tool: input file → immutable segment directory.

Parity: pinot-tools CreateSegmentCommand + the Hadoop
SegmentCreationJob mapper body (read file → transform records →
SegmentIndexCreationDriverImpl.build). The batch multi-file variant
(one segment per input file + controller push) lives in
tools/batch_ingest.py.
"""
from __future__ import annotations

from typing import Dict, Optional

from pinot_tpu.common.schema import Schema, TimeUnit
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.ingestion import CompoundTransformer, make_record_reader
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.metadata import SegmentMetadata


def create_segment_from_file(
        input_path: str, fmt: str, schema: Schema, out_dir: str,
        table_config: Optional[TableConfig] = None,
        segment_name: Optional[str] = None,
        expressions: Optional[Dict[str, str]] = None,
        incoming_time_unit: Optional[TimeUnit] = None,
        **reader_kw) -> SegmentMetadata:
    """Read `input_path` (csv/json), run the record-transformer chain,
    build one immutable segment into `out_dir`."""
    transformer = CompoundTransformer(schema, expressions,
                                      incoming_time_unit)
    reader = make_record_reader(input_path, fmt, schema, **reader_kw)
    with reader:
        rows = (r for r in (transformer.transform(dict(raw))
                            for raw in reader) if r is not None)
        creator = SegmentCreator(schema, table_config,
                                 segment_name=segment_name)
        return creator.build(rows, out_dir)
