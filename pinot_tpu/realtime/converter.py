"""Consuming → immutable segment conversion (the commit build).

Parity: pinot-core/.../realtime/converter/RealtimeSegmentConverter.java:85-129
— drain the mutable segment's rows and run the standard immutable build
(re-sorting dictionaries, re-packing forward indexes, rebuilding inverted/
bloom indexes per the table's indexing config). The TPU build's creator
takes the mutable segment's decoded columnar snapshot directly.
"""
from __future__ import annotations

from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.metadata import SegmentMetadata


def convert(mutable: MutableSegmentImpl, out_dir: str,
            segment_name: str) -> SegmentMetadata:
    """Build a standard immutable segment directory from a consuming
    segment's rows; returns the sealed metadata."""
    columns = mutable.columnar_snapshot()
    creator = SegmentCreator(mutable.schema, mutable.table_config,
                             segment_name=segment_name)
    return creator.build(columns, out_dir)
