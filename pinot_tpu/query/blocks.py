"""Result blocks and execution statistics.

Parity: pinot-core/.../operator/blocks/IntermediateResultsBlock.java and
core/operator/ExecutionStatistics.java — the per-segment (and per-server,
after combine) result container carried up to the broker reduce.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class ExecutionStats:
    num_docs_scanned: int = 0
    num_entries_scanned_in_filter: int = 0
    num_entries_scanned_post_filter: int = 0
    num_segments_processed: int = 0
    num_segments_matched: int = 0
    num_segments_pruned: int = 0
    total_docs: int = 0
    num_groups_limit_reached: bool = False
    time_used_ms: float = 0.0
    # realtime freshness (parity: ServerQueryExecutorV1Impl's
    # minConsumingFreshnessTimeMs + numConsumingSegmentsProcessed);
    # BrokerResponse.to_json emits the pair only when consuming
    # segments were queried
    num_consuming_segments_processed: int = 0
    min_consuming_freshness_ms: int = 0

    def merge(self, other: "ExecutionStats") -> None:
        self.num_docs_scanned += other.num_docs_scanned
        self.num_entries_scanned_in_filter += other.num_entries_scanned_in_filter
        self.num_entries_scanned_post_filter += \
            other.num_entries_scanned_post_filter
        self.num_segments_processed += other.num_segments_processed
        self.num_segments_matched += other.num_segments_matched
        self.num_segments_pruned += other.num_segments_pruned
        self.total_docs += other.total_docs
        self.num_groups_limit_reached |= other.num_groups_limit_reached
        self.num_consuming_segments_processed += \
            other.num_consuming_segments_processed
        if other.min_consuming_freshness_ms:
            self.min_consuming_freshness_ms = \
                min(self.min_consuming_freshness_ms,
                    other.min_consuming_freshness_ms) \
                if self.min_consuming_freshness_ms else \
                other.min_consuming_freshness_ms

    def to_metadata(self) -> Dict[str, str]:
        return {
            "numDocsScanned": str(self.num_docs_scanned),
            "numEntriesScannedInFilter": str(self.num_entries_scanned_in_filter),
            "numEntriesScannedPostFilter":
                str(self.num_entries_scanned_post_filter),
            "numSegmentsProcessed": str(self.num_segments_processed),
            "numSegmentsMatched": str(self.num_segments_matched),
            "totalDocs": str(self.total_docs),
            "numGroupsLimitReached": str(self.num_groups_limit_reached).lower(),
            "numConsumingSegmentsProcessed":
                str(self.num_consuming_segments_processed),
            "minConsumingFreshnessTimeMs":
                str(self.min_consuming_freshness_ms),
        }


@dataclasses.dataclass
class IntermediateResultsBlock:
    """Intermediate (mergeable) results of one segment / one server.

    Exactly one of agg_intermediates / group_map / selection_rows is the
    payload, mirroring the reference's block contents.
    """
    # aggregation-only: one intermediate object per aggregation function
    agg_intermediates: Optional[List[object]] = None
    # group-by: group key values tuple → list of intermediates
    group_map: Optional[Dict[Tuple, List[object]]] = None
    # group-by, COLUMNAR form (zero-copy DataTable v3 decode): a
    # (key_cols, inter_cols) pair of per-column blocks — each a numpy
    # array (i64/f64) or list (str/object). Exactly one of group_map /
    # group_cols is set; combine materializes group_map lazily only
    # when a merge cannot run as a vectorized fold.
    group_cols: Optional[Tuple[List[object], List[object]]] = None
    # selection: row tuples (decoded values) + total matched count
    selection_rows: Optional[List[tuple]] = None
    # selection, COLUMNAR form: one block per column (numpy array or
    # list), same exactly-one-of contract vs selection_rows
    selection_cols: Optional[List[object]] = None
    selection_columns: Optional[List[str]] = None
    # rows may carry trailing ORDER-BY-only columns (needed to re-sort in
    # cross-segment merges); the reducer trims to the first N display cols
    selection_display_cols: Optional[int] = None
    stats: ExecutionStats = dataclasses.field(default_factory=ExecutionStats)
    exceptions: List[str] = dataclasses.field(default_factory=list)
    # which instance-level path served this block: "sharded" (mesh ICI
    # combine) or "sequential" (per-segment + host merge); None when the
    # block came from a layer that doesn't choose (e.g. per-segment)
    execution_path: Optional[str] = None
