"""Controller-plane + full-cluster integration tests.

Mirrors the reference's OfflineClusterIntegrationTest /
MultiNodesOfflineClusterIntegrationTest: a real embedded cluster
(controller + servers + broker) where segments become queryable through
the ideal-state → transition → external-view → routing pipeline, plus
unit tiers for the property store, assignment strategies, retention and
rebalance.
"""
import os
import tempfile

import numpy as np
import pytest

from fixtures import build_segment, make_schema, make_table_config
from oracle import Oracle

from pinot_tpu.common.cluster_state import ONLINE
from pinot_tpu.controller import (BalancedNumSegmentAssignment,
                                  ClusterCoordinator, PropertyStore,
                                  ReplicaGroupSegmentAssignment,
                                  RetentionManager, SegmentStatusChecker)
from pinot_tpu.controller.state_machine import DROPPED, StateModel
from pinot_tpu.tools.cluster import EmbeddedCluster


# -- property store ---------------------------------------------------------

def test_property_store_watch_and_children():
    store = PropertyStore()
    events = []
    store.watch("/EXTERNALVIEW/", lambda p, r: events.append((p, r)))
    store.set("/EXTERNALVIEW/t1", {"a": 1})
    store.set("/CONFIGS/TABLE/t1", {"b": 2})      # not watched
    store.remove("/EXTERNALVIEW/t1")
    assert events == [("/EXTERNALVIEW/t1", {"a": 1}),
                      ("/EXTERNALVIEW/t1", None)]
    store.set("/SEGMENTS/t1/s1", {})
    store.set("/SEGMENTS/t1/s2", {})
    assert store.children("/SEGMENTS/t1") == ["s1", "s2"]


def test_property_store_update_atomic():
    store = PropertyStore()
    store.set("/x", {"n": 1})
    rec = store.update("/x", lambda old: {"n": (old or {}).get("n", 0) + 1})
    assert rec == {"n": 2}
    assert store.get("/x") == {"n": 2}


def test_property_store_watchers_get_defensive_copies():
    """Watchers receive a deep-copied snapshot — neither the caller
    mutating its record afterwards nor a watcher mutating what it was
    handed can corrupt the stored state (get() already copies)."""
    store = PropertyStore()
    received = []

    import json as _json

    def cb(path, rec):
        received.append(_json.loads(_json.dumps(rec)))
        if rec is not None:
            rec["mutated-by-watcher"] = True

    store.watch("/SEGMENTS/", cb)
    record = {"crc": "1", "nested": {"a": [1, 2]}}
    store.set("/SEGMENTS/t/s0", record)
    record["nested"]["a"].append(99)          # caller mutates after set
    assert received[0] == {"crc": "1", "nested": {"a": [1, 2]}}
    assert store.get("/SEGMENTS/t/s0") == \
        {"crc": "1", "nested": {"a": [1, 2]}}
    store.update("/SEGMENTS/t/s0", lambda old: {"crc": "2"})
    assert received[1] == {"crc": "2"}
    assert store.get("/SEGMENTS/t/s0") == {"crc": "2"}
    assert store.cas("/SEGMENTS/t/s0", {"crc": "2"}, {"crc": "3"})
    assert received[2] == {"crc": "3"}
    assert store.get("/SEGMENTS/t/s0") == {"crc": "3"}


# -- leadership -------------------------------------------------------------

def test_leadership_expired_lease_takeover_single_winner():
    """Two controllers racing one expired lease: the takeover is a CAS
    against the exact record each read, so the second claimant's write
    must LOSE — it can never overwrite the winner and believe it won."""
    import json as _json

    from pinot_tpu.controller.leadership import (LEADER_PATH,
                                                 ControllerLeadershipManager)
    store = PropertyStore()
    now = {"t": 100.0}
    store.set(LEADER_PATH, {"instance": "dead", "leaseUntil": 50.0})
    stale = store.get(LEADER_PATH)

    class StaleFirstRead:
        """Simulates the race: c2's first read happened BEFORE c1's
        claim landed (both saw the same expired lease)."""

        def __init__(self, inner):
            self.inner = inner
            self._pending = True

        def get(self, path):
            if self._pending and path == LEADER_PATH:
                self._pending = False
                return _json.loads(_json.dumps(stale))
            return self.inner.get(path)

        def __getattr__(self, name):
            return getattr(self.inner, name)

    c1 = ControllerLeadershipManager(store, "c1", clock=lambda: now["t"])
    c2 = ControllerLeadershipManager(StaleFirstRead(store), "c2",
                                     clock=lambda: now["t"])
    assert c1.try_acquire() is True
    assert c2.try_acquire() is False
    assert store.get(LEADER_PATH)["instance"] == "c1"
    assert c1.is_leader() and not c2.is_leader()
    # after c1's lease expires, c2 takes over cleanly
    now["t"] = 200.0
    assert c2.try_acquire() is True
    assert store.get(LEADER_PATH)["instance"] == "c2"


# -- assignment -------------------------------------------------------------

def test_balanced_assignment_spreads_load():
    strat = BalancedNumSegmentAssignment()
    current = {}
    for i in range(9):
        assigned = strat.assign(f"s{i}", ["a", "b", "c"], 1, current)
        current[f"s{i}"] = {inst: ONLINE for inst in assigned}
    counts = {}
    for m in current.values():
        for inst in m:
            counts[inst] = counts.get(inst, 0) + 1
    assert counts == {"a": 3, "b": 3, "c": 3}


def test_replica_group_assignment():
    strat = ReplicaGroupSegmentAssignment()
    current = {}
    for i in range(4):
        assigned = strat.assign(f"s{i}", ["a", "b", "c", "d"], 2, current)
        current[f"s{i}"] = {inst: ONLINE for inst in assigned}
        assert len(assigned) == 2
        # one from each replica group {a,c} and {b,d}
        assert len({x in ("a", "c") for x in assigned}) == 2


# -- state machine ----------------------------------------------------------

class RecordingModel(StateModel):
    def __init__(self):
        self.events = []

    def on_become_online(self, table, segment):
        self.events.append(("online", table, segment))

    def on_become_offline(self, table, segment):
        self.events.append(("offline", table, segment))

    def on_become_dropped(self, table, segment):
        self.events.append(("dropped", table, segment))


def test_state_machine_transitions_and_view():
    coord = ClusterCoordinator()
    m1, m2 = RecordingModel(), RecordingModel()
    coord.register_participant("i1", m1)
    coord.register_participant("i2", m2)
    coord.set_ideal_state("t", {"s1": {"i1": ONLINE, "i2": ONLINE},
                                "s2": {"i1": ONLINE}})
    assert ("online", "t", "s1") in m1.events
    assert ("online", "t", "s2") in m1.events
    assert m2.events == [("online", "t", "s1")]
    view = coord.external_view("t")
    assert view.servers_for("s1") == ["i1", "i2"]
    assert view.servers_for("s2") == ["i1"]

    # drop s2
    coord.set_ideal_state("t", {"s1": {"i1": ONLINE, "i2": ONLINE},
                                "s2": {"i1": DROPPED}})
    assert ("offline", "t", "s2") in m1.events
    assert ("dropped", "t", "s2") in m1.events
    assert coord.external_view("t").servers_for("s2") == []

    # instance death: view excludes it immediately
    coord.deregister_participant("i2")
    assert coord.external_view("t").servers_for("s1") == ["i1"]


def test_state_machine_failed_transition_marks_error():
    class Failing(StateModel):
        def on_become_online(self, table, segment):
            raise RuntimeError("disk full")

    coord = ClusterCoordinator()
    coord.register_participant("bad", Failing())
    coord.set_ideal_state("t", {"s1": {"bad": ONLINE}})
    view = coord.external_view("t")
    assert view.segment_states["s1"]["bad"] == "ERROR"
    assert view.servers_for("s1") == []       # ERROR is not routable


# -- full cluster -----------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    work = tempfile.mkdtemp()
    c = EmbeddedCluster(work, num_servers=2)
    c.add_schema(make_schema())
    c.add_table(make_table_config())
    segs_dir = os.path.join(work, "build")
    all_cols = []
    for i in range(4):
        _, cols = build_segment(f"{segs_dir}/{i}", n=1500, seed=200 + i,
                                name=f"cl_{i}")
        c.upload_segment("baseballStats_OFFLINE", f"{segs_dir}/{i}")
        all_cols.append(cols)
    merged = {k: (np.concatenate([col[k] for col in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((col[k] for col in all_cols), []))
              for k in all_cols[0]}
    yield c, Oracle(merged)
    c.stop()


def test_cluster_upload_to_queryable(cluster):
    c, oracle = cluster
    m = oracle.mask(lambda r: r["yearID"] > 2000)
    resp = c.query("SELECT COUNT(*) FROM baseballStats WHERE yearID > 2000")
    assert resp.aggregation_results[0].value == str(oracle.count(m))
    assert resp.num_servers_queried == 2
    assert resp.total_docs == 6000


def test_cluster_assignment_balanced(cluster):
    c, _ = cluster
    ideal = c.controller.coordinator.ideal_state("baseballStats_OFFLINE")
    counts = {}
    for seg, m in ideal.items():
        for inst in m:
            counts[inst] = counts.get(inst, 0) + 1
    assert counts == {"Server_0": 2, "Server_1": 2}


def test_cluster_segment_replace_same_name(cluster):
    c, oracle = cluster
    # re-upload cl_0 with different content; count must change accordingly
    work = tempfile.mkdtemp()
    _, cols = build_segment(f"{work}/new", n=700, seed=999, name="cl_0")
    c.upload_segment("baseballStats_OFFLINE", f"{work}/new")
    resp = c.query("SELECT COUNT(*) FROM baseballStats")
    assert resp.aggregation_results[0].value == str(4500 + 700)
    # restore for other tests
    base = tempfile.mkdtemp()
    _, cols0 = build_segment(f"{base}/orig", n=1500, seed=200, name="cl_0")
    c.upload_segment("baseballStats_OFFLINE", f"{base}/orig")


def test_cluster_status_checker(cluster):
    c, _ = cluster
    checker = SegmentStatusChecker()
    checker.run(c.controller.manager)
    report = checker.last_report["baseballStats_OFFLINE"]
    assert report["segments"] == 4
    assert report["missing"] == []


def test_cluster_server_death_and_rebalance(cluster):
    c, oracle = cluster
    m = oracle.mask(lambda r: True)
    # kill Server_1: external view loses its segments, queries go partial
    c.controller.coordinator.deregister_participant("Server_1")
    resp = c.query("SELECT COUNT(*) FROM baseballStats")
    assert resp.num_servers_queried == 1
    assert int(resp.aggregation_results[0].value) < oracle.count(m)

    # rebalance onto the survivor: full results again
    c.controller.manager.rebalance_table("baseballStats_OFFLINE")
    resp = c.query("SELECT COUNT(*) FROM baseballStats")
    assert resp.aggregation_results[0].value == str(oracle.count(m))

    # revive Server_1 and rebalance back
    from pinot_tpu.server.participant import ServerParticipant
    c.controller.coordinator.register_participant(
        "Server_1", ServerParticipant(c.servers["Server_1"],
                                      c.controller.manager))
    c.controller.manager.rebalance_table("baseballStats_OFFLINE")
    resp = c.query("SELECT COUNT(*) FROM baseballStats")
    assert resp.aggregation_results[0].value == str(oracle.count(m))
    assert resp.num_servers_queried == 2


def test_retention_deletes_expired_segments():
    work = tempfile.mkdtemp()
    c = EmbeddedCluster(work, num_servers=1)
    c.add_schema(make_schema())
    cfg = make_table_config()
    cfg.segments_config.retention_time_unit = "DAYS"
    cfg.segments_config.retention_time_value = 365 * 5
    c.add_table(cfg)
    _, cols = build_segment(f"{work}/seg", n=800, seed=5, name="ret_0")
    c.upload_segment("baseballStats_OFFLINE", f"{work}/seg")
    assert c.query("SELECT COUNT(*) FROM baseballStats"
                   ).aggregation_results[0].value == "800"

    # yearID is the DAYS time column with values ~1990-2019: far past
    # any 5-year retention from "now"
    ret = RetentionManager()
    ret.run(c.controller.manager)
    assert c.controller.manager.segment_names(
        "baseballStats_OFFLINE") == []
    resp = c.query("SELECT COUNT(*) FROM baseballStats")
    assert resp.exceptions or resp.aggregation_results[0].value == "0"
    c.stop()


def test_order_by_unselected_column_over_tcp(tmp_path):
    """The display-column split must survive the DataTable wire format:
    ORDER BY on a non-selected column returns only the selected columns
    after the broker's cross-server merge."""
    from fixtures import make_shared_columns
    from pinot_tpu.segment.creator import SegmentCreator

    cluster = EmbeddedCluster(str(tmp_path / "c"), num_servers=2, tcp=True)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        for i in range(2):
            d = str(tmp_path / f"s{i}")
            SegmentCreator(make_schema(), make_table_config(),
                           segment_name=f"s{i}").build(
                make_shared_columns(1024, i), d)
            cluster.upload_segment("baseballStats_OFFLINE", d)
        r = cluster.query("SELECT teamID FROM baseballStats "
                          "ORDER BY yearID LIMIT 5")
        assert r.selection_results.columns == ["teamID"]
        assert len(r.selection_results.results) == 5
        assert all(len(row) == 1 for row in r.selection_results.results)
    finally:
        cluster.stop()


def test_controller_leader_election():
    """Lease-based leader election (parity: ControllerLeadershipManager):
    one leader at a time, takeover on resign and on lease expiry, and
    periodic tasks gated on leadership."""
    from pinot_tpu.controller.leadership import ControllerLeadershipManager
    from pinot_tpu.controller.periodic import (PeriodicTask,
                                               PeriodicTaskScheduler)

    store = PropertyStore()
    clock = [1000.0]
    a = ControllerLeadershipManager(store, "ctrl_a", lease_s=10,
                                    clock=lambda: clock[0])
    b = ControllerLeadershipManager(store, "ctrl_b", lease_s=10,
                                    clock=lambda: clock[0])
    events = []
    a.add_listener(lambda lead: events.append(("a", lead)))
    assert a.try_acquire() is True
    assert b.try_acquire() is False
    assert a.is_leader() and not b.is_leader()
    assert events == [("a", True)]
    # resign → b takes over
    a.resign()
    assert events == [("a", True), ("a", False)]
    assert b.try_acquire() is True and not a.is_leader()
    # lease expiry → a can reclaim without b resigning
    clock[0] += 11
    assert not b.is_leader()
    assert a.try_acquire() is True

    # periodic tasks run only on the leader
    ran = []

    class Probe(PeriodicTask):
        name = "probe"
        interval_s = 1

        def run(self, manager):
            ran.append(1)

    sched_b = PeriodicTaskScheduler(manager=None, tasks=[Probe()],
                                    leadership=b)
    sched_b.run_once()
    assert ran == []                     # b is not the leader
    sched_a = PeriodicTaskScheduler(manager=None, tasks=[Probe()],
                                    leadership=a)
    sched_a.run_once()
    assert ran == [1]


def test_query_console_served(tmp_path):
    import urllib.request
    cluster = EmbeddedCluster(str(tmp_path / "c"), num_servers=1,
                              http=True)
    try:
        html = urllib.request.urlopen(
            f"http://127.0.0.1:{cluster.controller_port}/?broker=x:1",
            timeout=10).read().decode()
        assert "query console" in html and 'value="x:1"' in html
    finally:
        cluster.stop()


def test_schema_evolution_via_reload(tmp_path):
    """Add a column to the schema, reload the segment: servers re-load
    it with a synthesized default column (SegmentPreProcessor parity)."""
    from fixtures import make_shared_columns
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import FieldSpec, FieldType, Schema
    from pinot_tpu.segment.creator import SegmentCreator

    cluster = EmbeddedCluster(str(tmp_path / "c"), num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        d = str(tmp_path / "seg")
        SegmentCreator(make_schema(), make_table_config(),
                       segment_name="evo_0").build(
            make_shared_columns(1024, 3), d)
        cluster.upload_segment("baseballStats_OFFLINE", d)
        # before evolution the column doesn't exist
        r = cluster.query("SELECT COUNT(*) FROM baseballStats "
                          "WHERE country = 'USA'")
        assert r.exceptions or r.num_segments_processed == 0
        evolved = Schema("baseballStats", make_schema().fields + [
            FieldSpec("country", DataType.STRING, FieldType.DIMENSION,
                      default_null_value="USA")])
        cluster.add_schema(evolved)
        cluster.controller.manager.reload_table("baseballStats_OFFLINE")
        r2 = cluster.query("SELECT COUNT(*) FROM baseballStats "
                           "WHERE country = 'USA'")
        assert int(r2.aggregation_results[0].value) == 1024
    finally:
        cluster.stop()


# -- storage quota ----------------------------------------------------------

def test_parse_storage_size():
    from pinot_tpu.controller.quota import parse_storage_size
    assert parse_storage_size("2048") == 2048
    assert parse_storage_size("4K") == 4096
    assert parse_storage_size("1.5M") == 1536 * 1024
    assert parse_storage_size("100G") == 100 << 30
    assert parse_storage_size("64KB") == 64 << 10
    with pytest.raises(ValueError):
        parse_storage_size("lots")


def test_storage_quota_rejects_upload(tmp_path):
    """Parity: StorageQuotaChecker — a table whose quota.storage fits one
    segment accepts the first upload, rejects the second (HTTP path maps
    it to 403), still allows a same-name refresh (the incumbent's size is
    replaced, not added), and accepts again after the quota is raised."""
    from pinot_tpu.common.table_config import QuotaConfig
    from pinot_tpu.controller.quota import (StorageQuotaExceededError,
                                            dir_size_bytes)

    cluster = EmbeddedCluster(str(tmp_path / "c"), num_servers=1)
    try:
        cluster.add_schema(make_schema())
        d0 = str(tmp_path / "s0")
        build_segment(d0, n=1200, seed=1, name="q_0")
        size = dir_size_bytes(d0)
        cluster.add_table(make_table_config(
            quota_config=QuotaConfig(storage=str(size + size // 2))))
        table = "baseballStats_OFFLINE"
        cluster.upload_segment(table, d0)

        d1 = str(tmp_path / "s1")
        build_segment(d1, n=1200, seed=2, name="q_1")
        with pytest.raises(StorageQuotaExceededError, match="quota"):
            cluster.upload_segment(table, d1)
        assert cluster.controller.manager.segment_names(table) == ["q_0"]

        # refresh of the resident segment: replaced, not double-counted
        d0b = str(tmp_path / "s0b")
        build_segment(d0b, n=1200, seed=3, name="q_0")
        cluster.upload_segment(table, d0b)

        # raising the quota admits the second segment
        cfg = cluster.controller.manager.get_table_config(table)
        cfg.quota_config = QuotaConfig(storage="1G")
        cluster.controller.manager.update_table_config(cfg)
        cluster.upload_segment(table, d1)
        meta = cluster.controller.manager.segment_metadata(table, "q_1")
        assert meta["sizeBytes"] > 0
    finally:
        cluster.stop()


def test_no_downtime_rebalance_under_query_load(tmp_path):
    """VERDICT done-condition: rebalance a 2-replica table while a query
    loop runs — zero failed queries, and every intermediate ideal-state
    write keeps >=1 previously-serving replica per segment
    (TableRebalancer.java:82-97 make-before-break parity)."""
    import threading

    from fixtures import make_columns
    from pinot_tpu.segment.creator import SegmentCreator

    c = EmbeddedCluster(str(tmp_path), num_servers=2)
    try:
        cfg = make_table_config()
        cfg.segments_config.replication = 2
        c.add_schema(make_schema())
        c.add_table(cfg)
        table = cfg.table_name_with_type
        total = 0
        for i in range(4):
            d = os.path.join(str(tmp_path), f"seg{i}")
            cols = make_columns(2000, seed=60 + i)
            SegmentCreator(make_schema(), make_table_config(),
                           segment_name=f"seg{i}").build(cols, d)
            c.upload_segment(table, d)
            total += 2000

        # record every intermediate ideal-state write during rebalance
        states = []
        c.controller.coordinator.store.watch(
            f"/IDEALSTATES/{table}",
            lambda p, rec: states.append(
                {s: dict(m) for s, m in (rec or {}).get("segments",
                                                        {}).items()}))

        # register two new servers mid-flight -> rebalance must move load
        from pinot_tpu.server.instance import ServerInstance
        from pinot_tpu.server.participant import ServerParticipant
        for i in (2, 3):
            name = f"Server_{i}"
            srv = ServerInstance(name)
            part = ServerParticipant(
                srv, c.controller.manager,
                completion=c.controller.realtime,
                work_dir=os.path.join(str(tmp_path), "work", name))
            c.servers[name] = srv
            c.participants[name] = part
            c.controller.coordinator.register_participant(name, part)
            # (c.servers IS the InProcessTransport's dict — already wired)

        failures = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    resp = c.query("SELECT COUNT(*) FROM baseballStats")
                    if int(resp.aggregation_results[0].value) != total or \
                            resp.num_servers_responded < \
                            resp.num_servers_queried:
                        failures.append(resp.to_json())
                except Exception as e:  # noqa: BLE001
                    failures.append(repr(e))

        t = threading.Thread(target=hammer)
        t.start()
        try:
            target = c.controller.manager.rebalance_table(
                table, batch_size=1)
        finally:
            stop.set()
            t.join()
        assert not failures, failures[:3]

        # rebalance actually moved something onto the new servers
        placed = {i for m in target.values() for i in m}
        assert placed & {"Server_2", "Server_3"}, target

        # make-before-break invariant on every intermediate write
        prev = None
        for st in states:
            if prev is not None:
                for seg, insts in st.items():
                    if seg in prev:
                        kept = set(prev[seg]) & set(insts)
                        assert kept, (seg, prev[seg], insts)
            prev = st

        # and the final state serves correct answers
        resp = c.query("SELECT COUNT(*) FROM baseballStats")
        assert int(resp.aggregation_results[0].value) == total
    finally:
        c.stop()


def test_rebalance_downtime_flag_one_shot(tmp_path):
    c = EmbeddedCluster(str(tmp_path), num_servers=3)
    try:
        cfg = make_table_config()
        cfg.segments_config.replication = 1
        c.add_schema(make_schema())
        c.add_table(cfg)
        table = cfg.table_name_with_type
        from fixtures import make_columns
        from pinot_tpu.segment.creator import SegmentCreator
        d = os.path.join(str(tmp_path), "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "seg0").build(make_columns(1000, seed=70), d)
        c.upload_segment(table, d)
        writes = []
        c.controller.coordinator.store.watch(
            f"/IDEALSTATES/{table}", lambda p, rec: writes.append(1))
        c.controller.manager.rebalance_table(table, downtime=True)
        assert len(writes) == 1          # one-shot write, no stepping
        resp = c.query("SELECT COUNT(*) FROM baseballStats")
        assert int(resp.aggregation_results[0].value) == 1000
    finally:
        c.stop()
