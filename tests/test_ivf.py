"""IVF ANN vector index (ISSUE 20).

Covers the coarse-quantizer lifecycle end to end:

1. **Config surface** — vectorIndexConfigs validation (table create
   rejects bad type / counts / non-VECTOR columns; IvfRetrainTask
   config requires an index to retrain), PQL ``nprobe=N`` parse +
   serde round-trip + fingerprint keying.
2. **Training degeneracy** — fewer rows than centroids (k clamps),
   all-identical embeddings (one live centroid, ~0 baseline → drift
   undefined, probe still serves), NaN/Inf rejected at ingest AND at
   train (a poisoned minion input must never mint a codebook).
3. **Probe exactness** — host oracle, device kernel and sharded paths
   agree BIT-IDENTICALLY on the probed candidate set; recall@10 vs the
   exact scan on clustered data while scanning a small fraction;
   exact-scan fallback for index-less segments and mixed stacks.
4. **Lifecycle** — index artifacts + drift stats stamped at seal,
   compaction priors carry the trained baseline, minion backfill +
   drift-triggered retrain through the real queue/worker, upsert
   freshness unchanged under nprobe.
"""
import os
import tempfile

import numpy as np
import pytest

from pinot_tpu.common.datatype import DataType
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.schema import Schema, dimension, metric, vector
from pinot_tpu.common.serde import (instance_request_from_bytes,
                                    instance_request_to_bytes,
                                    request_from_json, request_to_json)
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine import QueryEngine
from pinot_tpu.index import ivf
from pinot_tpu.pql.lexer import PqlSyntaxError
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.fingerprint import query_fingerprint
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegmentLoader

DIM = 16
N_CENTROIDS = 16


def ivf_schema(dim=DIM, name="vectab"):
    return Schema(name, [
        dimension("shard", DataType.INT),
        metric("rid", DataType.INT),
        vector("emb", dim),
    ])


def ivf_table_config(num_centroids=N_CENTROIDS, indexed=True, **extra):
    idx = IndexingConfig()
    if indexed:
        idx.vector_index_configs = {
            "emb": {"numCentroids": num_centroids, **extra}}
    return TableConfig("vectab", indexing_config=idx)


def clustered_columns(n, seed=0, dim=DIM, rid_base=0, n_clusters=8):
    """Embeddings drawn tightly around a few cluster centers, so the
    coarse quantizer's partition is meaningful and recall measurable."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_clusters, dim)).astype(np.float32) * 4
    which = rng.integers(0, n_clusters, n)
    emb = centers[which] + \
        rng.standard_normal((n, dim)).astype(np.float32) * 0.3
    return {
        "shard": rng.integers(0, 4, n).astype(np.int32),
        "rid": (np.arange(n, dtype=np.int32) + rid_base),
        "emb": emb.astype(np.float32),
    }


def build_ivf_segments(base, n_segs=2, n=2048, dim=DIM, seed=3,
                       indexed=True):
    segs, cols_list = [], []
    cfg = ivf_table_config(indexed=indexed)
    for s in range(n_segs):
        cols = clustered_columns(n, seed=seed + s, dim=dim, rid_base=s * n)
        d = os.path.join(base, f"v{s}")
        SegmentCreator(ivf_schema(dim), cfg,
                       segment_name=f"v{s}").build(cols, d)
        segs.append(ImmutableSegmentLoader.load(d))
        cols_list.append(cols)
    return segs, cols_list


def pql_for(q, k=7, metric="COSINE", where="WHERE shard < 2",
            nprobe=0):
    qs = ", ".join(repr(float(x)) for x in q)
    np_clause = f", nprobe={nprobe}" if nprobe else ""
    return (f"SELECT rid, VECTOR_SIMILARITY(emb, [{qs}], {k}, "
            f"'{metric}'{np_clause}) FROM vectab {where}").strip()


def result_rows(resp):
    assert not resp.exceptions, resp.exceptions
    return [tuple(r) for r in resp.selection_results.results]


# ---------------------------------------------------------------------------
# tier 1: config surface
# ---------------------------------------------------------------------------


def test_validate_config_rejects_bad_knobs():
    ivf.validate_config(dict(ivf.DEFAULT_CONFIG), "emb")     # fine
    with pytest.raises(ValueError, match="unknown type"):
        ivf.validate_config({"type": "HNSW"}, "emb")
    for key in ("numCentroids", "trainIterations", "trainSampleSize"):
        with pytest.raises(ValueError, match=key):
            ivf.validate_config({**ivf.DEFAULT_CONFIG, key: 0}, "emb")


def test_column_config_merges_defaults():
    cfg = ivf.column_config(ivf_table_config(num_centroids=9), "emb")
    assert cfg["numCentroids"] == 9
    assert cfg["trainIterations"] == ivf.DEFAULT_CONFIG["trainIterations"]
    assert ivf.column_config(ivf_table_config(), "other") is None
    assert ivf.column_config(ivf_table_config(indexed=False), "emb") is None


def test_controller_rejects_bad_ivf_configs(tmp_path):
    from pinot_tpu.controller.manager import InvalidTableConfigError
    from pinot_tpu.tools.cluster import EmbeddedCluster
    cluster = EmbeddedCluster(str(tmp_path), num_servers=1)
    try:
        cluster.add_schema(ivf_schema())
        with pytest.raises(InvalidTableConfigError, match="unknown type"):
            cluster.add_table(ivf_table_config(type="HNSW"))
        with pytest.raises(InvalidTableConfigError, match="numCentroids"):
            cluster.add_table(ivf_table_config(num_centroids=0))
        bad = ivf_table_config()
        bad.indexing_config.vector_index_configs = {"rid": {}}
        with pytest.raises(InvalidTableConfigError, match="non-VECTOR"):
            cluster.add_table(bad)
        # retrain task without any index configured: nothing to retrain
        no_idx = ivf_table_config(indexed=False)
        no_idx.task_configs = {"IvfRetrainTask": {}}
        with pytest.raises(InvalidTableConfigError,
                           match="vectorIndexConfigs"):
            cluster.add_table(no_idx)
    finally:
        cluster.stop()


def test_pql_nprobe_parse_serde_fingerprint():
    q = [0.5] * DIM
    req = compile_pql(pql_for(q, nprobe=8))
    assert req.vector.nprobe == 8
    exact = compile_pql(pql_for(q))
    assert exact.vector.nprobe == 0
    with pytest.raises(PqlSyntaxError, match="nprobe"):
        compile_pql(pql_for(q).replace("'COSINE'", "'COSINE', nprobe=0"))
    # serde round-trips (broker JSON and server wire)
    again = request_from_json(request_to_json(req))
    assert again.vector.nprobe == 8
    ir = InstanceRequest(request_id=1, broker_id="b", query=req,
                         search_segments=["v0"])
    wire = instance_request_from_bytes(instance_request_to_bytes(ir))
    assert wire.query.vector.nprobe == 8
    # ANN and exact plans must never share a fingerprint (the result
    # cache and batch coalescer key on it)
    assert query_fingerprint(req) != query_fingerprint(exact)
    assert query_fingerprint(req) != \
        query_fingerprint(compile_pql(pql_for(q, nprobe=4)))


# ---------------------------------------------------------------------------
# tier 2: training degeneracy
# ---------------------------------------------------------------------------


def test_train_clamps_k_to_rows():
    rng = np.random.default_rng(0)
    mat = rng.standard_normal((5, DIM)).astype(np.float32)
    index = ivf.train(mat, num_centroids=64, iterations=4, seed=0,
                      sample_size=65536)
    assert index.num_centroids == 5
    assert index.assignments.shape == (5,)
    assert (index.assignments >= 0).all()
    assert (index.assignments < 5).all()


def test_train_identical_embeddings():
    mat = np.ones((128, DIM), np.float32)
    index = ivf.train(mat, num_centroids=8, iterations=4, seed=0,
                      sample_size=65536)
    # every row lands on one centroid at distance ~0 → the drift ratio
    # is undefined (division by ~0) and must read as None, not inf
    assert index.meta["baselineMeanDist"] < 1e-6
    custom = {}
    ivf.stamp_custom(custom, "emb", index.meta)
    assert ivf.drift_from_custom(custom, "emb") is None
    live = np.unique(index.assignments)
    assert len(live) == 1


def test_identical_embeddings_probe_still_serves(tmp_path):
    """Degenerate codebook (one live centroid) must still answer."""
    n = 64
    cols = {"shard": np.zeros(n, np.int32),
            "rid": np.arange(n, dtype=np.int32),
            "emb": np.ones((n, DIM), np.float32)}
    d = os.path.join(str(tmp_path), "ident")
    SegmentCreator(ivf_schema(), ivf_table_config(num_centroids=8),
                   segment_name="ident").build(cols, d)
    seg = ImmutableSegmentLoader.load(d)
    pql = pql_for(np.ones(DIM), k=5, metric="DOT", where="", nprobe=2)
    rh = result_rows(QueryEngine([seg], use_device=False).query(pql))
    rd = result_rows(QueryEngine([seg]).query(pql))
    assert rh == rd and len(rh) == 5


def test_nan_inf_rejected_everywhere():
    mat = np.ones((16, DIM), np.float32)
    mat[3, 2] = np.nan
    with pytest.raises(ValueError, match="NaN/Inf"):
        ivf.train(mat, num_centroids=4, iterations=2, seed=0,
                  sample_size=100)
    mat[3, 2] = np.inf
    with pytest.raises(ValueError, match="NaN/Inf"):
        ivf.train(mat, num_centroids=4, iterations=2, seed=0,
                  sample_size=100)
    # ingest: FieldSpec.convert already refuses non-finite rows
    f = ivf_schema().field("emb")
    with pytest.raises(ValueError, match="NaN/Inf"):
        f.convert([float("nan")] + [0.0] * (DIM - 1))
    # seal: the creator refuses to mint an index (or a forward block)
    # from a poisoned matrix that bypassed ingest
    cols = {"shard": np.zeros(16, np.int32),
            "rid": np.arange(16, dtype=np.int32), "emb": mat}
    with pytest.raises(ValueError, match="finite|NaN/Inf"):
        SegmentCreator(ivf_schema(), ivf_table_config(num_centroids=4),
                       segment_name="bad").build(
            cols, tempfile.mkdtemp())


# ---------------------------------------------------------------------------
# tier 3: probe exactness + fallbacks
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ivf_setup():
    base = tempfile.mkdtemp()
    segs, cols_list = build_ivf_segments(base, n_segs=2, n=2048)
    rng = np.random.default_rng(99)
    q = rng.standard_normal(DIM).astype(np.float32)
    return segs, cols_list, q


@pytest.mark.parametrize("metric", ["COSINE", "DOT"])
def test_probed_topk_bit_identical(ivf_setup, metric):
    from pinot_tpu.parallel import make_mesh
    segs, _cols, q = ivf_setup
    pql = pql_for(q, k=9, metric=metric, nprobe=4)
    rh = result_rows(QueryEngine(segs, use_device=False).query(pql))
    rd = result_rows(QueryEngine(segs).query(pql))
    rs = result_rows(QueryEngine(segs, mesh=make_mesh()).query(pql))
    assert rh == rd == rs
    assert len(rh) == 9


def test_probe_scans_fraction_and_recall(ivf_setup):
    segs, _cols, q = ivf_setup
    exact = QueryEngine(segs, use_device=False).query(
        pql_for(q, k=10, where=""))
    probed = QueryEngine(segs, use_device=False).query(
        pql_for(q, k=10, where="", nprobe=3))
    total = sum(s.num_docs for s in segs)
    assert probed.num_docs_scanned < 0.5 * total
    assert probed.num_docs_scanned < exact.num_docs_scanned
    got = {r[:3] for r in result_rows(probed)}
    want = {r[:3] for r in result_rows(exact)}
    recall = len(got & want) / len(want)
    assert recall >= 0.9, (recall, got, want)


def test_nprobe_on_indexless_segments_is_exact(tmp_path):
    """ANN is best-effort: no index → silently exact, never an error."""
    segs, _cols = build_ivf_segments(str(tmp_path), n_segs=2, n=512,
                                     indexed=False)
    q = np.random.default_rng(7).standard_normal(DIM).astype(np.float32)
    exact = result_rows(QueryEngine(segs, use_device=False).query(
        pql_for(q, k=6)))
    for engine in (QueryEngine(segs, use_device=False),
                   QueryEngine(segs)):
        assert result_rows(engine.query(pql_for(q, k=6, nprobe=4))) == exact


def test_mixed_stack_falls_back_to_sequential(tmp_path):
    """One indexed + one index-less segment: the sharded path must not
    stack them (probe/exact divergence) — it falls back and stays
    bit-identical to the sequential paths."""
    from pinot_tpu.parallel import make_mesh
    from pinot_tpu.parallel.sharded import NotShardable, StackedSegments
    seg_i, _ = build_ivf_segments(os.path.join(str(tmp_path), "i"),
                                  n_segs=1, n=512, indexed=True)
    seg_x, _ = build_ivf_segments(os.path.join(str(tmp_path), "x"),
                                  n_segs=1, n=512, seed=11, indexed=False)
    segs = [seg_i[0], seg_x[0]]
    q = np.random.default_rng(13).standard_normal(DIM).astype(np.float32)
    pql = pql_for(q, k=6, where="", nprobe=4)
    rh = result_rows(QueryEngine(segs, use_device=False).query(pql))
    rd = result_rows(QueryEngine(segs).query(pql))
    rs = result_rows(QueryEngine(segs, mesh=make_mesh()).query(pql))
    assert rh == rd == rs and len(rh) == 6


def test_probe_mask_np_matches_device_selection(ivf_setup):
    """The host oracle's probe mask and the device probe-select kernel
    pick the SAME centroid lists (identical tie-breaks)."""
    segs, _cols, q = ivf_setup
    ds = segs[0].data_source("emb")
    cents = ds.ivf_centroids
    nprobe = 3
    q_pad = np.zeros(ivf.pad_dim(DIM), np.float32)
    q_pad[:DIM] = q
    q_norm = np.float32(np.sqrt((q_pad * q_pad).sum()))
    cpad = ds.host_operand("ivfc")
    cvalid = ds.host_operand("ivfv")
    probes, ok = ivf.select_probes_np(cpad, cvalid, q_pad, q_norm,
                                      "COSINE", nprobe)
    from pinot_tpu.ops import kernels
    dev_probes, dev_ok = kernels.ivf_select_probes(
        cpad, cvalid.astype(bool), q_pad, q_norm, "COSINE", nprobe)
    assert np.array_equal(probes, np.asarray(dev_probes))
    assert np.array_equal(ok, np.asarray(dev_ok))
    assert (np.asarray(probes)[np.asarray(ok)] <
            ivf.pad_centroids(cents.shape[0])).all()


# ---------------------------------------------------------------------------
# tier 4: lifecycle — seal stamps, priors, minion retrain, upsert
# ---------------------------------------------------------------------------


def test_seal_writes_index_and_stamps_custom(tmp_path):
    cols = clustered_columns(512, seed=1)
    d = os.path.join(str(tmp_path), "s0")
    SegmentCreator(ivf_schema(), ivf_table_config(),
                   segment_name="s0").build(cols, d)
    index = ivf.load_index(d, "emb")
    assert index is not None
    assert index.num_centroids == N_CENTROIDS
    assert index.assignments.shape == (512,)
    seg = ImmutableSegmentLoader.load(d)
    custom = seg.metadata.custom
    assert ivf.CUSTOM_CENTROIDS.format(col="emb") in custom
    drift = ivf.drift_from_custom(custom, "emb")
    assert drift is not None and abs(drift) < 1e-9     # fresh train
    # deterministic: same rows + seed → identical artifacts
    d2 = os.path.join(str(tmp_path), "s1")
    SegmentCreator(ivf_schema(), ivf_table_config(),
                   segment_name="s1").build(cols, d2)
    again = ivf.load_index(d2, "emb")
    assert np.array_equal(index.centroids, again.centroids)
    assert np.array_equal(index.assignments, again.assignments)


def test_priors_carry_baseline_fresh_train_resets():
    rng = np.random.default_rng(3)
    mat = rng.standard_normal((600, DIM)).astype(np.float32)
    cfg = dict(ivf.DEFAULT_CONFIG, numCentroids=8)
    trained = ivf.build_for_column(mat, cfg)
    base = trained.meta["baselineMeanDist"]
    # drifted survivors reassigned under the OLD codebook (compaction):
    # meanDist grows, the baseline is CARRIED → positive drift
    drifted = mat * 1.8
    rebuilt = ivf.build_for_column(drifted, cfg, priors=trained)
    assert rebuilt.meta["baselineMeanDist"] == base
    assert rebuilt.meta["meanDist"] > base
    assert np.array_equal(rebuilt.centroids, trained.centroids)
    custom = {}
    ivf.stamp_custom(custom, "emb", rebuilt.meta)
    assert ivf.drift_from_custom(custom, "emb") > 0.3
    # a fresh train over the drifted rows RESETS the baseline
    fresh = ivf.build_for_column(drifted, cfg)
    assert fresh.meta["baselineMeanDist"] == fresh.meta["meanDist"]


def test_minion_backfill_and_drift_retrain(tmp_path):
    """End to end through the real queue: a segment sealed BEFORE the
    index existed gets a backfill task; a drifted segment gets exactly
    one retrain that resets its drift to ~0; idle afterwards."""
    from pinot_tpu.controller.manager import SEGMENTS
    from pinot_tpu.minion import MinionWorker, PinotTaskManager
    from pinot_tpu.tools.cluster import EmbeddedCluster
    base = str(tmp_path)
    cluster = EmbeddedCluster(os.path.join(base, "cluster"),
                              num_servers=1)
    try:
        cluster.add_schema(ivf_schema())
        cfg = ivf_table_config()
        cfg.task_configs = {"IvfRetrainTask": {
            "retrainDriftThreshold": "0.2"}}
        cluster.add_table(cfg)
        # built WITHOUT the index config → sealed pre-index
        cols = clustered_columns(600, seed=5)
        d = os.path.join(base, "old")
        SegmentCreator(ivf_schema(), ivf_table_config(indexed=False),
                       segment_name="old").build(cols, d)
        cluster.upload_segment("vectab_OFFLINE", d)

        manager = cluster.controller.manager
        tm = PinotTaskManager(manager)
        ids = tm.schedule_tasks()
        assert len(ids) == 1                  # backfill scheduled once
        assert tm.schedule_tasks() == []      # deduped while open
        worker = MinionWorker(manager,
                              work_dir=os.path.join(base, "minion"))
        assert sorted(worker.drain()) == sorted(ids)
        meta = manager.segment_metadata("vectab_OFFLINE", "old")
        custom = meta.get("customMap") or {}
        assert ivf.CUSTOM_CENTROIDS.format(col="emb") in custom
        assert abs(ivf.drift_from_custom(custom, "emb")) < 1e-9
        assert tm.schedule_tasks() == []      # fresh index: idle

        # simulate embedding churn: bump the published meanDist 1.5x
        path = f"{SEGMENTS}/vectab_OFFLINE/old"
        rec = manager.store.get(path)
        cm = dict(rec["customMap"])
        key = ivf.CUSTOM_MEAN.format(col="emb")
        cm[key] = repr(float(cm[key]) * 1.5)
        manager.store.set(path, {**rec, "customMap": cm})
        ids2 = tm.schedule_tasks()
        assert len(ids2) == 1                 # drift over threshold
        assert sorted(worker.drain()) == sorted(ids2)
        meta2 = manager.segment_metadata("vectab_OFFLINE", "old")
        drift = ivf.drift_from_custom(meta2["customMap"], "emb")
        assert abs(drift) < 1e-9              # retrain reset baseline
        assert tm.schedule_tasks() == []
        # the retrained segment still serves ANN queries
        q = np.random.default_rng(9).standard_normal(DIM)
        resp = cluster.query(pql_for(q, k=5, where="", nprobe=4))
        assert len(result_rows(resp)) == 5
    finally:
        cluster.stop()


def test_upsert_freshness_unchanged_under_nprobe():
    """Consuming segments carry no IVF index: nprobe falls back to the
    exact scan, so an upsert published mid-run still ranks FIRST on the
    immediately following query — freshness is never traded away."""
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.reduce import BrokerReduceService
    from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
    from pinot_tpu.realtime.upsert import ValidDocIds
    impl = MutableSegmentImpl(ivf_schema(), ivf_table_config(),
                              "vectab__0__0")
    impl.valid_doc_ids = ValidDocIds()
    rng = np.random.default_rng(17)
    impl.index_rows([
        {"shard": int(i % 4), "rid": i,
         "emb": [float(x) for x in
                 rng.standard_normal(DIM).astype(np.float32)]}
        for i in range(400)])
    q = rng.standard_normal(DIM).astype(np.float32)
    unit = (q / np.linalg.norm(q)).astype(np.float32)
    req = compile_pql(pql_for(q, k=5, where="", nprobe=4))

    def run(executor):
        blk = executor.execute(req, [impl])
        return result_rows(BrokerReduceService().reduce(req, [blk]))

    dev, host = ServerQueryExecutor(), ServerQueryExecutor(use_device=False)
    assert run(dev) == run(host)
    new_doc = impl.num_docs
    impl.index_rows([{"shard": 0, "rid": 777_000,
                      "emb": [float(x) for x in unit]}])
    impl.valid_doc_ids.invalidate(10)
    r_dev, r_host = run(dev), run(host)
    assert r_dev == r_host
    assert r_dev[0][:2] == (777_000, new_doc)
    assert all(row[1] != 10 for row in r_dev)
