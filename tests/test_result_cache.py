"""CRC-exact result caching: server per-(segment CRC, fingerprint)
cache + broker freshness-bounded cache.

The exactness contract under test: a cached answer is BIT-IDENTICAL to
the uncached answer on every execution path (host numpy, device scan
kernels, mesh-sharded), and every way the underlying data can change
— new segment CRC, upsert validDocIds version bump, segment
replacement — makes the stale entry unreachable.
"""
import tempfile

import pytest

from fixtures import build_segment

from pinot_tpu.broker.result_cache import BrokerResultCache
from pinot_tpu.common.datatable import DataTable, RESULT_CACHE_HIT_KEY
from pinot_tpu.common.metrics import ServerMeter
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.common.serde import instance_request_to_bytes
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.fingerprint import query_fingerprint
from pinot_tpu.server import ServerInstance
from pinot_tpu.server.result_cache import segment_cache_states

QUERIES = [
    "SELECT COUNT(*) FROM baseballStats_OFFLINE",
    "SELECT SUM(hits), AVG(average) FROM baseballStats_OFFLINE "
    "WHERE league = 'NL'",
    "SELECT SUM(salary) FROM baseballStats_OFFLINE GROUP BY teamID TOP 50",
    "SELECT runs, hits FROM baseballStats_OFFLINE "
    "ORDER BY hits DESC LIMIT 7",
]


def _request(pql, request_id=1, **kw):
    return instance_request_to_bytes(InstanceRequest(
        request_id=request_id, query=compile_pql(pql), **kw))


def _payload_of(dt: DataTable):
    """The result payload, metadata that may legitimately differ on a
    cache hit (requestId, cache marker, timings) excluded."""
    meta = {k: v for k, v in dt.metadata.items()
            if k not in ("requestId", RESULT_CACHE_HIT_KEY, "timeUsedMs",
                         "profileInfo")}
    return dt.kind, dt.columns, dt.rows, meta, dt.exceptions


def _server(mesh=None, use_device=True, num_segments=2):
    s = ServerInstance("cache0", mesh=mesh, use_device=use_device)
    for i in range(num_segments):
        seg, _ = build_segment(tempfile.mkdtemp(), n=700, seed=40 + i,
                               name=f"rc_{i}")
        s.data_manager.table("baseballStats_OFFLINE",
                             create=True).add_segment(seg)
    return s


# ---------------------------------------------------------------------------
# Fingerprint canonicalization
# ---------------------------------------------------------------------------


def test_fingerprint_merges_only_equivalent_queries():
    a = compile_pql("SELECT COUNT(*) FROM t WHERE x IN ('b', 'a') "
                    "AND y = '1'")
    b = compile_pql("SELECT COUNT(*) FROM t WHERE y = '1' "
                    "AND x IN ('a', 'b')")
    assert query_fingerprint(a) == query_fingerprint(b)
    c = compile_pql("SELECT COUNT(*) FROM t WHERE x IN ('a', 'c') "
                    "AND y = '1'")
    assert query_fingerprint(a) != query_fingerprint(c)
    # trace/timeout shape metadata, not results: same fingerprint
    d = compile_pql("SELECT COUNT(*) FROM t WHERE x IN ('a', 'b') "
                    "AND y = '1' OPTION(trace=true, timeoutMs=50)")
    assert query_fingerprint(a) == query_fingerprint(d)
    # a different table is a different result space
    e = compile_pql("SELECT COUNT(*) FROM u WHERE x IN ('a', 'b') "
                    "AND y = '1'")
    assert query_fingerprint(a) != query_fingerprint(e)


# ---------------------------------------------------------------------------
# Bit-identical cached results on every execution path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("path", ["host", "device", "sharded"])
def test_cached_equals_uncached_bitwise(path):
    if path == "sharded":
        from pinot_tpu.parallel.sharded import make_mesh
        s = _server(mesh=make_mesh())
    else:
        s = _server(use_device=(path == "device"))
    try:
        for i, pql in enumerate(QUERIES):
            cold = DataTable.from_bytes(
                s.handle_request_bytes(_request(pql, 10 + i)))
            assert not cold.exceptions, (pql, cold.exceptions)
            warm = DataTable.from_bytes(
                s.handle_request_bytes(_request(pql, 100 + i)))
            assert warm.metadata.get(RESULT_CACHE_HIT_KEY) == "1", pql
            assert _payload_of(warm) == _payload_of(cold), pql
        assert s.metrics.meter(ServerMeter.RESULT_CACHE_HITS).count == \
            len(QUERIES)
    finally:
        s.stop()


def test_trace_and_errors_never_cached():
    s = _server()
    try:
        pql = QUERIES[0]
        traced = DataTable.from_bytes(s.handle_request_bytes(
            _request(pql, 1, enable_trace=True)))
        assert "traceInfo" in traced.metadata
        # the traced run neither stored nor read the cache
        assert s.result_cache.stats()["entries"] == 0
        again = DataTable.from_bytes(s.handle_request_bytes(
            _request(pql, 2, enable_trace=True)))
        assert RESULT_CACHE_HIT_KEY not in again.metadata
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# Invalidation: new CRC, vdoc version bump, segment replacement
# ---------------------------------------------------------------------------


def test_cache_states_key_on_crc_and_vdoc_version():
    d1, d2 = tempfile.mkdtemp(), tempfile.mkdtemp()
    seg1, _ = build_segment(d1, n=300, seed=1, name="k_0")
    seg2, _ = build_segment(d2, n=300, seed=2, name="k_0")  # same name!
    s1 = segment_cache_states([seg1])
    s2 = segment_cache_states([seg2])
    assert s1 is not None and s2 is not None
    assert s1 != s2                         # different content → new CRC
    # a validDocIds version bump changes the key too
    from pinot_tpu.realtime.upsert import ValidDocIds
    seg1.valid_doc_ids = ValidDocIds()
    before = segment_cache_states([seg1])
    assert seg1.valid_doc_ids.invalidate(5)
    after = segment_cache_states([seg1])
    assert before != after
    # mutable / CRC-less segments are uncacheable
    class FakeMutable:
        is_mutable = True
        segment_name = "m"
    assert segment_cache_states([seg1, FakeMutable()]) is None


def test_upsert_vdoc_bump_invalidates_end_to_end():
    from pinot_tpu.realtime.upsert import ValidDocIds
    s = ServerInstance("vd0")
    d = tempfile.mkdtemp()
    seg, _ = build_segment(d, n=400, seed=9, name="vd_0")
    seg.valid_doc_ids = ValidDocIds()
    s.data_manager.table("baseballStats_OFFLINE",
                         create=True).add_segment(seg)
    try:
        pql = "SELECT COUNT(*) FROM baseballStats_OFFLINE"
        full = DataTable.from_bytes(s.handle_request_bytes(_request(pql)))
        assert full.rows[0][0] == 400
        hit = DataTable.from_bytes(s.handle_request_bytes(_request(pql, 2)))
        assert hit.metadata.get(RESULT_CACHE_HIT_KEY) == "1"
        # two rows get superseded → version bump → the stale 400 must
        # be unreachable
        seg.valid_doc_ids.invalidate(0)
        seg.valid_doc_ids.invalidate(1)
        masked = DataTable.from_bytes(
            s.handle_request_bytes(_request(pql, 3)))
        assert RESULT_CACHE_HIT_KEY not in masked.metadata
        assert masked.rows[0][0] == 398
        # and the masked result caches under ITS OWN key
        again = DataTable.from_bytes(s.handle_request_bytes(
            _request(pql, 4)))
        assert again.metadata.get(RESULT_CACHE_HIT_KEY) == "1"
        assert again.rows[0][0] == 398
    finally:
        s.stop()


def test_segment_replacement_invalidates_end_to_end():
    s = ServerInstance("cr0")
    d1, d2 = tempfile.mkdtemp(), tempfile.mkdtemp()
    seg1, _ = build_segment(d1, n=250, seed=1, name="swap_0")
    seg2, _ = build_segment(d2, n=350, seed=2, name="swap_0")
    tdm = s.data_manager.table("baseballStats_OFFLINE", create=True)
    tdm.add_segment(seg1)
    try:
        pql = "SELECT COUNT(*) FROM baseballStats_OFFLINE"
        first = DataTable.from_bytes(s.handle_request_bytes(_request(pql)))
        assert first.rows[0][0] == 250
        assert DataTable.from_bytes(
            s.handle_request_bytes(_request(pql, 2))).metadata.get(
                RESULT_CACHE_HIT_KEY) == "1"
        tdm.add_segment(seg2)            # same name, new CRC
        fresh = DataTable.from_bytes(s.handle_request_bytes(
            _request(pql, 3)))
        assert RESULT_CACHE_HIT_KEY not in fresh.metadata
        assert fresh.rows[0][0] == 350
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# Broker-level freshness-bounded cache (hybrid tables)
# ---------------------------------------------------------------------------


class FakeBrokerClock:
    def __init__(self):
        self.t = 50.0

    def __call__(self):
        return self.t


def test_broker_cache_freshness_bound_hit_and_miss():
    clk = FakeBrokerClock()
    cache = BrokerResultCache(clock=clk)
    resp = BrokerResponse(total_docs=10)
    resp.min_consuming_freshness_time_ms = 123456
    cache.put("fp", resp)
    clk.t += 0.2                            # 200ms later
    hit = cache.get("fp", max_age_ms=1000)
    assert hit is not None and hit.total_docs == 10
    # the absolute freshness timestamp travels unchanged
    assert hit.min_consuming_freshness_time_ms == 123456
    # a tighter bound on the SAME entry: miss, entry retained
    assert cache.get("fp", max_age_ms=100) is None
    assert cache.get("fp", max_age_ms=1000) is not None
    # hits are isolated copies: mutating one never corrupts the entry
    hit.exceptions.append({"boom": 1})
    assert not cache.get("fp", max_age_ms=1000).exceptions


def test_broker_cache_refuses_partial_and_excepted():
    cache = BrokerResultCache(clock=lambda: 0.0)
    partial = BrokerResponse(partial_response=True)
    cache.put("a", partial)
    excepted = BrokerResponse(exceptions=[{"errorCode": 425}])
    cache.put("b", excepted)
    assert cache.stats()["entries"] == 0


def test_broker_cache_end_to_end_hybrid_gate():
    """Handler-level: only tables with a realtime part are broker-
    cached, under the minConsumingFreshnessTimeMs bound."""
    from pinot_tpu.broker import (BrokerRequestHandler,
                                  InProcessTransport, RoutingManager)
    from pinot_tpu.common.cluster_state import ONLINE, TableView
    from pinot_tpu.common.metrics import BrokerMeter

    servers = {"S": ServerInstance("S")}
    seg, _ = build_segment(tempfile.mkdtemp(), n=600, seed=21,
                           name="rt_0")
    servers["S"].data_manager.table("baseballStats_REALTIME",
                                    create=True).add_segment(seg)
    routing = RoutingManager()
    routing.update_view(TableView("baseballStats_REALTIME",
                                  {"rt_0": {"S": ONLINE}}))
    handler = BrokerRequestHandler(routing, InProcessTransport(servers),
                                   cache_freshness_ms=60_000.0)
    try:
        pql = "SELECT SUM(runs) FROM baseballStats"
        cold = handler.handle(pql)
        assert not cold.exceptions
        warm = handler.handle(pql)
        assert handler.metrics.meter(
            BrokerMeter.RESULT_CACHE_HITS).count == 1
        assert warm.aggregation_results[0].value == \
            cold.aggregation_results[0].value
        # an explicit zero freshness bound refuses any cached entry
        strict = handler.handle(
            "SELECT SUM(runs) FROM baseballStats "
            "OPTION(minConsumingFreshnessTimeMs=0)")
        assert strict.aggregation_results[0].value == \
            cold.aggregation_results[0].value
        assert handler.metrics.meter(
            BrokerMeter.RESULT_CACHE_HITS).count == 1   # still one hit
    finally:
        servers["S"].stop()
        handler.close()


def test_segment_swap_clear_wins_over_inflight_store():
    """A segment swap clears the cache; an execution that was already
    in flight over the PRE-swap segment must not re-insert its stale
    bytes afterwards — a same-CRC reload (evolved schema) constructs
    the identical key forever, so the raced entry would never age out."""
    from pinot_tpu.server.result_cache import ServerResultCache
    c = ServerResultCache()
    key = ("t", "fp", (("s", "crc", -1),))
    gen = c.generation             # captured before "execution"
    c.clear()                      # the swap races the running query
    c.put(key, b"stale", gen=gen)
    assert c.get(key) is None      # stale insert dropped
    c.put(key, b"fresh", gen=c.generation)
    assert c.get(key) == b"fresh"
    c.clear()
    c.put(key, b"ungenned")        # gen-less puts still work
    assert c.get(key) == b"ungenned"


def test_server_cache_hits_do_not_refold_profiles():
    """A server cache hit replays the ORIGINAL execution's profileInfo
    bytes; the broker must not fold that phantom copy into the rolling
    per-table operator stats on every hit."""
    from pinot_tpu.broker import (BrokerRequestHandler,
                                  InProcessTransport, RoutingManager)
    from pinot_tpu.common.cluster_state import ONLINE, TableView

    servers = {"S": ServerInstance("S")}
    seg, _ = build_segment(tempfile.mkdtemp(), n=600, seed=29,
                           name="off_0")
    servers["S"].data_manager.table("baseballStats_OFFLINE",
                                    create=True).add_segment(seg)
    routing = RoutingManager()
    routing.update_view(TableView("baseballStats_OFFLINE",
                                  {"off_0": {"S": ONLINE}}))
    handler = BrokerRequestHandler(routing, InProcessTransport(servers))
    try:
        pql = "SELECT SUM(runs) FROM baseballStats"
        cold = handler.handle(pql)
        assert not cold.exceptions
        for _ in range(3):                  # server-side cache hits
            assert not handler.handle(pql).exceptions
        assert servers["S"].metrics.meter(
            ServerMeter.RESULT_CACHE_HITS).count == 3
        stats = handler.table_stats.snapshot("baseballStats")
        # only the real execution was folded; 3 hits of ~0 server work
        # added no phantom operator timings
        assert stats["queries"] == 1
    finally:
        servers["S"].stop()
        handler.close()


def test_broker_cache_bypassed_for_traced_queries():
    """A trace=true query must not be served a cached reply (it would
    carry no spans) and must not overwrite the cache either."""
    from pinot_tpu.broker import (BrokerRequestHandler,
                                  InProcessTransport, RoutingManager)
    from pinot_tpu.common.cluster_state import ONLINE, TableView
    from pinot_tpu.common.metrics import BrokerMeter

    servers = {"S": ServerInstance("S")}
    seg, _ = build_segment(tempfile.mkdtemp(), n=600, seed=23,
                           name="rt_0")
    servers["S"].data_manager.table("baseballStats_REALTIME",
                                    create=True).add_segment(seg)
    routing = RoutingManager()
    routing.update_view(TableView("baseballStats_REALTIME",
                                  {"rt_0": {"S": ONLINE}}))
    handler = BrokerRequestHandler(routing, InProcessTransport(servers),
                                   cache_freshness_ms=60_000.0)
    try:
        pql = "SELECT SUM(runs) FROM baseballStats"
        cold = handler.handle(pql)          # populates the cache
        assert not cold.exceptions
        traced = handler.handle(pql + " OPTION(trace=true)")
        # not a cache hit: the traced execution really ran and returned
        # its trace tree
        assert handler.metrics.meter(
            BrokerMeter.RESULT_CACHE_HITS).count == 0
        assert traced.trace_tree
        assert traced.aggregation_results[0].value == \
            cold.aggregation_results[0].value
        # the untraced entry is still served afterwards
        warm = handler.handle(pql)
        assert handler.metrics.meter(
            BrokerMeter.RESULT_CACHE_HITS).count == 1
        assert not warm.trace_tree
    finally:
        servers["S"].stop()
        handler.close()


def test_broker_cache_size_cap_refuses_large_payloads():
    """MB-scale selections never cache: they are poor cache citizens
    (memory) and their defensive put-copies taxed every complete
    query on the reduce path."""
    from pinot_tpu.common.response import SelectionResults

    cache = BrokerResultCache(max_cells=100)
    big = BrokerResponse(selection_results=SelectionResults(
        columns=["a", "b"], results=[[1, 2]] * 51))       # 102 cells
    cache.put("big", big)
    assert cache.get("big", max_age_ms=1e9) is None
    small = BrokerResponse(selection_results=SelectionResults(
        columns=["a", "b"], results=[[1, 2]] * 50))       # 100 cells
    cache.put("small", small)
    assert cache.get("small", max_age_ms=1e9) is not None
    # group-by results count per group; plain aggregations are 1 cell
    from pinot_tpu.common.response import AggregationResult
    grouped = BrokerResponse(aggregation_results=[AggregationResult(
        "sum(x)", group_by_columns=["g"],
        group_by_result=[{"group": [i], "value": i} for i in range(101)])])
    cache.put("grouped", grouped)
    assert cache.get("grouped", max_age_ms=1e9) is None


def test_broker_cache_put_does_not_alias_callers_response():
    """put() stores a private copy: an embedding caller mutating the
    response handle() returned must not poison later hits."""
    resp = BrokerResponse(total_docs=7)
    cache = BrokerResultCache()
    cache.put("fp", resp)
    resp.exceptions.append({"boom": 1})     # caller mutates ITS object
    hit = cache.get("fp", max_age_ms=1e9)
    assert hit is not None and not hit.exceptions


def test_broker_cache_cleared_on_external_view_change():
    """The freshness bound covers consuming-ingestion staleness only —
    an OFFLINE backfill/replacement must flush the broker cache, so
    the cluster watcher clears registered caches on EVERY view
    change (segment lifecycle rate, so the clear is cheap)."""
    from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
    from pinot_tpu.common.cluster_state import ONLINE, TableView

    class _Coord:
        def watch_external_views(self, fn):
            self.on_view = fn

        def tables(self):
            return []

    class _Mgr:
        def get_table_config(self, table):
            return None

        def get_schema(self, table):
            return None

    coord = _Coord()
    w = BrokerClusterWatcher(coord, _Mgr())
    cache = BrokerResultCache()
    w.register_result_cache(cache)
    # ordering matters: the clear (generation bump) must land AFTER
    # routing.update_view — a query racing the handler must not
    # capture the fresh generation while routing on the stale view,
    # or its pre-backfill put would be accepted (round-9 regression)
    events = []
    real_update, real_clear = w.routing.update_view, cache.clear
    real_tb = w._update_time_boundary
    w.routing.update_view = \
        lambda v: (events.append("route"), real_update(v))[1]
    w._update_time_boundary = \
        lambda v: (events.append("boundary"), real_tb(v))[1]
    cache.clear = lambda: (events.append("clear"), real_clear())[1]
    cache.put("fp", BrokerResponse(total_docs=3))
    assert cache.get("fp", max_age_ms=1e9) is not None
    # a segment upload/replacement fires an external-view change
    coord.on_view(TableView("t_OFFLINE", {"seg_0": {"S": ONLINE}}))
    assert cache.get("fp", max_age_ms=1e9) is None
    # the clear lands only after the view change has FULLY landed
    # (routing AND time boundary) — clearing earlier lets a racing
    # query capture the fresh put-guard generation while executing
    # against the pre-change view/boundary
    assert events == ["route", "boundary", "clear"]
    # ...and so does a table drop (empty view)
    cache.put("fp2", BrokerResponse(total_docs=4))
    coord.on_view(TableView("t_OFFLINE", {}))
    assert cache.get("fp2", max_age_ms=1e9) is None


def test_broker_cache_put_after_clear_is_dropped():
    """An OFFLINE backfill's view change clear()s the cache while a
    query is in flight; the query's _finish-time put (generation
    captured at probe time, pre-execution) must not re-populate the
    cache with the pre-backfill result."""
    cache = BrokerResultCache()
    gen = cache.generation            # captured at probe time
    cache.clear()                     # the backfill races the query
    cache.put("fp", BrokerResponse(total_docs=1), gen=gen)
    assert cache.get("fp", max_age_ms=1e9) is None   # stale insert dropped
    cache.put("fp", BrokerResponse(total_docs=2), gen=cache.generation)
    assert cache.get("fp", max_age_ms=1e9).total_docs == 2
    cache.clear()
    cache.put("fp", BrokerResponse(total_docs=3))    # gen-less puts work
    assert cache.get("fp", max_age_ms=1e9).total_docs == 3
