"""Observability smoke gate for CI: the quickstart-shaped cluster must
serve Prometheus text exposition from /metrics on ALL THREE component
APIs (broker, every server, controller), and a trace=true query over
HTTP must return a non-empty merged trace tree with per-server
subtrees.

A wiring canary, not a benchmark: it catches a /metrics route dropped
from one component, an exposition-format regression a scraper would
reject, or a broken broker→server trace-context propagation in
seconds.
"""
import json
import os
import re
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROWS = int(os.environ.get("OBS_SMOKE_ROWS", 4000))
SEGMENTS = int(os.environ.get("OBS_SMOKE_SEGMENTS", 2))

_SAMPLE_RX = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="
    r'"[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
    r"[0-9eE.+-]+(\.[0-9]+)?$")


def check_exposition(name: str, port: int) -> int:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        assert r.status == 200, f"{name}: /metrics -> {r.status}"
        ctype = r.headers.get("Content-Type", "")
        assert ctype.startswith("text/plain"), f"{name}: {ctype}"
        text = r.read().decode()
    samples = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_RX.match(line), \
            f"{name}: invalid exposition line {line!r}"
        samples += 1
    assert samples > 0, f"{name}: /metrics served an empty exposition"
    # the residency ledger's gauges are pre-registered at boot on every
    # component, so "is HBM accounted" is scrapeable before (and after)
    # any upload happens — a dashboard never sees a missing series
    assert "device_bytes_resident" in text, \
        f"{name}: /metrics has no deviceBytesResident series"
    return samples


def check_residency(name: str, port: int) -> dict:
    """The /debug/residency view must agree with a live ledger: after
    segments are uploaded and a query has warmed the scan lanes, the
    serving process holds accounted device bytes."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/residency", timeout=10) as r:
        assert r.status == 200, f"{name}: /debug/residency -> {r.status}"
        view = json.loads(r.read())
    for key in ("totalDeviceBytesResident", "byKind", "tables",
                "entryCount"):
        assert key in view, f"{name}: /debug/residency missing {key!r}"
    assert view["totalDeviceBytesResident"] > 0, \
        f"{name}: no resident bytes after a warmed query: {view}"
    assert view["byKind"].get("scan", 0) > 0, \
        f"{name}: scan lanes not accounted: {view['byKind']}"
    return view


def tree_names(node, out):
    out.add(node["name"])
    for c in node.get("children", ()):
        tree_names(c, out)
    return out


def main() -> int:
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.tools.datagen import (build_ssb_segment_dirs,
                                         ssb_schema, ssb_table_config)

    base = tempfile.mkdtemp()
    dirs, _ids, _sc = build_ssb_segment_dirs(
        os.path.join(base, "segs"), ROWS, SEGMENTS, seed=7)
    cluster = EmbeddedCluster(os.path.join(base, "cluster"),
                              num_servers=2, tcp=True, http=True)
    try:
        cluster.add_schema(ssb_schema())
        cluster.add_table(ssb_table_config())
        for d in dirs:
            cluster.upload_segment("lineorder_OFFLINE", d)

        counts = {"broker": check_exposition("broker",
                                             cluster.broker_port),
                  "controller": check_exposition(
                      "controller", cluster.controller_port)}
        for name, port in cluster.server_http_ports.items():
            counts[name] = check_exposition(name, port)

        # trace=true through the REAL HTTP + TCP path, merged at reduce
        body = json.dumps({
            "pql": "SELECT SUM(lo_revenue) FROM lineorder "
                   "WHERE lo_quantity < 25", "trace": True}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{cluster.broker_port}/query", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            resp = json.loads(r.read())
        assert not resp.get("exceptions"), resp.get("exceptions")
        tree = resp.get("traceTree")
        assert tree and tree.get("children"), \
            "trace=true returned no merged trace tree"
        names = tree_names(tree, set())
        for expected in ("query", "scatterGather", "server",
                         "schedulerWait", "segmentExecution", "reduce"):
            assert expected in names, \
                f"merged trace tree is missing {expected!r}: {names}"
        dispatches = {n for n in names if n.startswith("dispatch:")}
        assert len(dispatches) == 2, \
            f"expected per-server dispatch spans, got {dispatches}"

        # the query warmed the scan lanes: at least one server must now
        # report ledgered resident bytes, and its re-scraped exposition
        # must carry a nonzero per-table deviceBytesResident sample
        resident = 0
        for name, port in cluster.server_http_ports.items():
            view = check_residency(name, port)
            resident += view["totalDeviceBytesResident"]
        assert resident > 0, "no server holds ledgered device bytes"
        print(json.dumps({"metricsSamples": counts,
                          "traceSpans": len(names),
                          "dispatchSpans": sorted(dispatches),
                          "residentBytes": resident}, indent=1))
        print("obs smoke: OK")
        return 0
    finally:
        cluster.stop()


if __name__ == "__main__":
    sys.exit(main())
