"""Record readers: files → row dicts for segment building.

Parity: pinot-core/.../core/data/readers/ — RecordReader SPI (init/next/
rewind/close), CSVRecordReader (configurable delimiter + ';' multi-value
split), JSONRecordReader (objects), GenericRowRecordReader (in-memory
rows), PinotSegmentRecordReader (re-read an existing segment — the
minion/rollup input path).
"""
from __future__ import annotations

import csv
import json
from typing import Dict, Iterator, List, Optional

from pinot_tpu.common.schema import Schema


class RecordReader:
    """Iterate row dicts; re-iterable via rewind()."""

    def __iter__(self) -> Iterator[dict]:
        self.rewind()
        return self._rows()

    def _rows(self) -> Iterator[dict]:
        raise NotImplementedError

    def rewind(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "RecordReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class GenericRowRecordReader(RecordReader):
    def __init__(self, rows: List[dict]):
        self.rows = rows

    def _rows(self) -> Iterator[dict]:
        return iter(self.rows)


class CSVRecordReader(RecordReader):
    """Header-row CSV; multi-value cells split on `mv_delimiter`.

    Parity: CSVRecordReader + CSVRecordReaderConfig (delimiter,
    multiValueDelimiter ';').
    """

    def __init__(self, path: str, schema: Optional[Schema] = None,
                 delimiter: str = ",", mv_delimiter: str = ";"):
        self.path = path
        self.schema = schema
        self.delimiter = delimiter
        self.mv_delimiter = mv_delimiter

    def _rows(self) -> Iterator[dict]:
        with open(self.path, newline="") as fh:
            for rec in csv.DictReader(fh, delimiter=self.delimiter):
                yield self._convert(rec)

    def _convert(self, rec: Dict[str, str]) -> dict:
        row = {}
        for k, v in rec.items():
            if v == "" or v is None:
                row[k] = None
                continue
            if self.schema is not None and self.schema.has_column(k) and \
                    not self.schema.field(k).single_value:
                row[k] = v.split(self.mv_delimiter)
            elif self.mv_delimiter in v and (
                    self.schema is None or not self.schema.has_column(k)):
                row[k] = v.split(self.mv_delimiter)
            else:
                row[k] = v
        return row


class JSONRecordReader(RecordReader):
    """JSON-lines file, or a single top-level JSON array of objects."""

    def __init__(self, path: str):
        self.path = path

    def _rows(self) -> Iterator[dict]:
        with open(self.path) as fh:
            first = fh.read(1)
            fh.seek(0)
            if first == "[":
                for row in json.load(fh):
                    yield row
            else:
                for line in fh:
                    line = line.strip()
                    if line:
                        yield json.loads(line)


class SegmentRecordReader(RecordReader):
    """Re-read rows from a loaded immutable segment (minion/rollup input).

    Parity: PinotSegmentRecordReader.
    """

    def __init__(self, segment):
        self.segment = segment

    def _rows(self) -> Iterator[dict]:
        seg = self.segment
        cols = {}
        for name in seg.column_names:
            ds = seg.data_source(name)
            cm = ds.metadata
            if cm.data_type.name == "VECTOR":
                cols[name] = ds.vec_values       # [n, dim] f32 rows
            elif not cm.has_dictionary:
                cols[name] = ds.raw_values
            elif cm.single_value:
                cols[name] = ds.dictionary.values[ds.dict_ids]
            else:
                card = cm.cardinality
                mv = ds.mv_dict_ids
                cols[name] = [
                    [ds.dictionary.get(i) for i in row if i < card]
                    for row in mv]
        for r in range(seg.num_docs):
            yield {name: _plain(vals[r]) for name, vals in cols.items()}


def _plain(v):
    import numpy as np
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):        # embedding row → float list
        return v.tolist()
    return v


class _ArrowTableRecordReader(RecordReader):
    """Shared row iteration over a pyarrow Table (columnar → row dicts).

    List-typed arrow columns become Python lists (multi-value columns);
    everything else becomes plain scalars via .as_py().
    """

    def __init__(self, table):
        self._table = table
        self._pylist = None

    def _rows(self) -> Iterator[dict]:
        if self._pylist is None:  # convert once; readers are re-iterable
            self._pylist = self._table.to_pylist()
        for row in self._pylist:
            yield dict(row)


class ParquetRecordReader(_ArrowTableRecordReader):
    """Parquet files → rows, via pyarrow.

    Parity: pinot-parquet/.../ParquetRecordReader.java (a pluggable
    RecordReader over the Parquet columnar format; the reference uses
    parquet-avro, here arrow is the host-side columnar substrate).
    """

    def __init__(self, path: str):
        try:
            import pyarrow.parquet as pq
        except ImportError as e:  # pragma: no cover
            raise ImportError("ParquetRecordReader requires pyarrow") from e
        super().__init__(pq.read_table(path))


class ORCRecordReader(_ArrowTableRecordReader):
    """ORC files → rows, via pyarrow.

    Parity: pinot-orc/.../ORCRecordReader.java.
    """

    def __init__(self, path: str):
        try:
            import pyarrow.orc as orc
        except ImportError as e:  # pragma: no cover
            raise ImportError("ORCRecordReader requires pyarrow") from e
        super().__init__(orc.read_table(path))


def make_record_reader(path: str, fmt: str,
                       schema: Optional[Schema] = None,
                       **kw) -> RecordReader:
    fmt = fmt.lower()
    if fmt == "csv":
        return CSVRecordReader(path, schema, **kw)
    if fmt == "json":
        return JSONRecordReader(path)
    if fmt == "parquet":
        return ParquetRecordReader(path)
    if fmt == "orc":
        return ORCRecordReader(path)
    if fmt == "avro":
        from pinot_tpu.ingestion.avro import AvroRecordReader
        return AvroRecordReader(path)
    if fmt == "thrift":
        from pinot_tpu.ingestion.thrift import (ThriftRecordReader,
                                                ThriftRecordReaderConfig)
        cfg = kw.pop("config", None)
        if cfg is None:
            fields = kw.pop("fields", None)
            if fields is None:
                raise ValueError("thrift reader needs config= or fields=")
            cfg = ThriftRecordReaderConfig(fields)
        return ThriftRecordReader(path, cfg, schema)
    raise ValueError(
        f"unsupported input format {fmt!r} "
        "(csv, json, avro, parquet, orc, thrift)")
