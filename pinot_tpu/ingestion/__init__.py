from pinot_tpu.ingestion.avro import AvroRecordReader
from pinot_tpu.ingestion.record_reader import (CSVRecordReader,
                                               GenericRowRecordReader,
                                               JSONRecordReader,
                                               ORCRecordReader,
                                               ParquetRecordReader,
                                               RecordReader,
                                               SegmentRecordReader,
                                               make_record_reader)
from pinot_tpu.ingestion.transformer import (CompoundTransformer,
                                             DataTypeTransformer,
                                             ExpressionTransformer,
                                             NullValueTransformer,
                                             RecordTransformer,
                                             SanitationTransformer,
                                             TimeTransformer)

__all__ = [
    "RecordReader", "CSVRecordReader", "JSONRecordReader",
    "AvroRecordReader", "ParquetRecordReader", "ORCRecordReader",
    "GenericRowRecordReader", "SegmentRecordReader", "make_record_reader",
    "RecordTransformer", "CompoundTransformer", "ExpressionTransformer",
    "TimeTransformer", "DataTypeTransformer", "NullValueTransformer",
    "SanitationTransformer",
]
