"""tpulint runner: walk files, run rules, apply suppressions + baseline."""
from __future__ import annotations

import ast
import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pinot_tpu.analysis import astutil
from pinot_tpu.analysis.core import (AnalysisConfig, Finding, all_rules,
                                     is_suppressed, parse_suppressions,
                                     split_by_baseline)


class FileContext:
    """Everything a rule needs about one file (parsed once, shared)."""

    def __init__(self, path: str, source: str,
                 config: Optional[AnalysisConfig] = None):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.config = config or AnalysisConfig()
        self.tree = ast.parse(source, filename=path)
        self.aliases = astutil.collect_aliases(self.tree)

    def in_prefixes(self, prefixes: Sequence[str]) -> bool:
        return any(self.path.startswith(p) or f"/{p}" in f"/{self.path}"
                   for p in prefixes)

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(path=self.path, line=getattr(node, "lineno", 1),
                       rule=rule, message=message)


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]                 # kept (not suppressed)
    suppressed: List[Finding]
    errors: List[str]                       # unparseable files etc.
    # tier → wall seconds (per-file tiers accumulate across files)
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def analyze_source(source: str, path: str,
                   config: Optional[AnalysisConfig] = None,
                   rule_ids: Optional[Set[str]] = None,
                   tiers: Sequence[str] = ("ast",)) -> AnalysisResult:
    """Analyze one file's source under a (possibly virtual) repo path.

    `tiers`: which PER-FILE tiers run ("ast" always in practice;
    "lifecycle" under --lifecycle). Global tiers (deep/protocol) never
    run here — they have no per-file check()."""
    try:
        ctx = FileContext(path, source, config)
    except SyntaxError as e:
        return AnalysisResult([], [], [f"{path}: syntax error: {e}"])
    per_line, per_file = parse_suppressions(source)
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    timings: Dict[str, float] = {}
    for rule_id, rule in sorted(all_rules().items()):
        if rule_ids is not None and rule_id not in rule_ids:
            continue
        if rule.tier not in tiers:
            continue
        t0 = time.perf_counter()
        for f in rule.check(ctx):
            (suppressed if is_suppressed(f, per_line, per_file)
             else kept).append(f)
        timings[rule.tier] = timings.get(rule.tier, 0.0) + \
            (time.perf_counter() - t0)
    kept.sort()
    suppressed.sort()
    return AnalysisResult(kept, suppressed, [], timings)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return sorted(set(out))


def analyze_paths(paths: Sequence[str],
                  config: Optional[AnalysisConfig] = None,
                  rule_ids: Optional[Set[str]] = None,
                  deep: bool = False,
                  protocol: bool = False,
                  lifecycle: bool = False) -> AnalysisResult:
    """Analyze every .py file under `paths` (files or directories).

    Paths should be given relative to the repo root so finding keys
    match the committed baseline. `lifecycle=True` additionally runs
    the per-file lifecycle tier (device-upload ledger routing, cache
    bounds); `deep=True` the global deep-tier rules (kernel jaxpr
    contracts, wire schema); `protocol=True` the protocol tier
    (durability ordering, crash coverage, metrics contract,
    crash-interleaving model checker). The global tiers are
    path-independent — run them from the repo root only.
    """
    total = AnalysisResult([], [], [])
    file_tiers = ("ast",) + (("lifecycle",) if lifecycle else ())
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            total.errors.append(f"{path}: {e}")
            continue
        res = analyze_source(source, os.path.relpath(path), config,
                             rule_ids, tiers=file_tiers)
        total.findings.extend(res.findings)
        total.suppressed.extend(res.suppressed)
        total.errors.extend(res.errors)
        for tier, secs in res.timings.items():
            total.timings[tier] = total.timings.get(tier, 0.0) + secs
    tiers = (["deep"] if deep else []) + (["protocol"] if protocol
                                          else [])
    for tier in tiers:
        t0 = time.perf_counter()
        for rule_id, rule in sorted(all_rules().items()):
            if rule.tier != tier:
                continue
            if rule_ids is not None and rule_id not in rule_ids:
                continue
            try:
                total.findings.extend(rule.check_global())
            except Exception as e:  # noqa: BLE001 — a crashed checker
                total.errors.append(    # must fail the gate loudly
                    f"{tier} rule {rule_id} crashed: "
                    f"{type(e).__name__}: {e}")
        total.timings[tier] = total.timings.get(tier, 0.0) + \
            (time.perf_counter() - t0)
    total.findings.sort()
    total.suppressed.sort()
    return total


def diff_baseline(result: AnalysisResult, baseline: Dict[str, int]
                  ) -> Tuple[List[Finding], List[str]]:
    """(new findings, stale baseline keys) for a finished run."""
    return split_by_baseline(result.findings, baseline)
