"""TCP topic stream: a cross-process stream connector over framed TCP.

Parity: the reference proves its stream SPI with an out-of-process
connector (pinot-connectors/pinot-connector-kafka-0.9/.../
KafkaPartitionLevelConsumer.java:1 — SimpleConsumer fetches over the
network; KafkaStreamLevelConsumer for the HLC group path). This module is
that connector for an environment without Kafka: `TcpTopicServer` plays
the broker (partitioned append-only logs served over the same 4-byte
length-framed JSON protocol as the property store), and
`TcpStreamConsumerFactory` implements the full consumer SPI —
PartitionLevelConsumer (LLC), StreamMetadataProvider, and
StreamLevelConsumer (HLC) — from any process.

Registered as the built-in `stream.factory.name = "tcp"` provider:
a table's streamConfigs map carries `stream.tcp.host` / `stream.tcp.port`
so a remote server process can construct the consumer from the table
config alone — no in-process object sharing (the MemoryStream
limitation this connector exists to remove).

Message payloads ride base64 inside the JSON frames; the rows-per-second
this serves (test/quickstart scale) is far below the framing overhead
mattering, and the protocol stays debuggable.
"""
from __future__ import annotations

import asyncio
import base64
import json
import socket
import struct
import threading
from typing import Dict, List, Optional

from pinot_tpu.realtime.stream import (MessageBatch, PartitionLevelConsumer,
                                       SMALLEST_OFFSET, StreamConfig,
                                       StreamConsumerFactory,
                                       StreamLevelConsumer, StreamMessage,
                                       StreamMetadataProvider)
from pinot_tpu.transport.tcp import read_frame, write_frame


class TcpTopicServer:
    """Partitioned append-only logs served over framed TCP.

    Ops (JSON frames, `id` echoed):
      create     {topic, partitions}        (idempotent)
      publish    {topic, partition|null, payloads: [b64...]}
      read       {topic, partition, start, max} -> {messages: [[off,b64]..]}
      latest     {topic, partition} -> {offset}
      partitions {topic} -> {count}
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._topics: Dict[str, List[List[bytes]]] = {}  # tpulint: disable=cache-bound -- keyed by topic name: bounded by configured topics (test harness scale)
        self._lock = threading.Lock()
        self.loop = asyncio.new_event_loop()
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    # -- log ops (thread-safe; also usable in-process) ---------------------
    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = [[] for _ in range(partitions)]

    def publish(self, topic: str, payload: bytes,
                partition: Optional[int] = None) -> int:
        with self._lock:
            parts = self._topics[topic]
            if partition is None:
                sizes = [len(p) for p in parts]
                partition = sizes.index(min(sizes))
            parts[partition].append(payload)
            return len(parts[partition]) - 1

    def _read(self, topic: str, partition: int, start: int,
              max_count: int) -> List[tuple]:
        with self._lock:
            log_part = self._topics[topic][partition]
            end = min(len(log_part), start + max(max_count, 0))
            return [(i, log_part[i]) for i in range(max(start, 0), end)]

    def _latest(self, topic: str, partition: int) -> int:
        with self._lock:
            return len(self._topics[topic][partition])

    def _partition_count(self, topic: str) -> int:
        with self._lock:
            return len(self._topics[topic])

    # -- server lifecycle (same daemon event-loop pattern as the
    #    property store server) -------------------------------------------
    def start(self) -> int:
        started = threading.Event()
        boot: dict = {"err": None}

        def run() -> None:
            asyncio.set_event_loop(self.loop)
            try:
                self._server = self.loop.run_until_complete(  # tpulint: disable=concurrency -- boot handshake: started.wait() orders this write before any reader
                    asyncio.start_server(self._serve, self.host, self.port))
            except BaseException as e:  # noqa: BLE001 — surface bind errors
                boot["err"] = e
                started.set()
                return
            self.port = self._server.sockets[0].getsockname()[1]  # tpulint: disable=concurrency -- boot handshake: started.wait() orders this write before any reader
            started.set()
            self.loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)  # tpulint: disable=concurrency -- single lifecycle thread creates the worker before exposing the object
        self._thread.start()
        started.wait()
        if boot["err"] is not None:
            raise OSError(f"topic server cannot bind {self.host}:"
                          f"{self.port}: {boot['err']}") from boot["err"]
        return self.port

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        # capture the task handle HERE: after stop() cancels us and
        # halts the loop, the finally block runs without a running
        # event loop, where asyncio.current_task() raises
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        try:
            while True:
                try:
                    frame = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                req = None
                try:
                    req = json.loads(frame)
                    resp = self._handle(req)
                except Exception as e:  # noqa: BLE001 — report, keep serving
                    resp = {"id": req.get("id") if isinstance(req, dict)
                            else None, "ok": False, "error": str(e)}
                write_frame(writer, json.dumps(resp).encode("utf-8"))
                await writer.drain()
        finally:
            self._conn_tasks.discard(task)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    def _handle(self, req: dict) -> dict:
        op = req["op"]
        ok = {"id": req.get("id"), "ok": True}
        if op == "ping":
            return ok
        if op == "create":
            self.create_topic(req["topic"], int(req.get("partitions", 1)))
            return ok
        if op == "publish":
            offs = [self.publish(req["topic"],
                                 base64.b64decode(p), req.get("partition"))
                    for p in req["payloads"]]
            return {**ok, "offsets": offs}
        if op == "read":
            msgs = self._read(req["topic"], int(req["partition"]),
                              int(req["start"]), int(req["max"]))
            return {**ok, "messages": [
                [off, base64.b64encode(payload).decode("ascii")]
                for off, payload in msgs]}
        if op == "latest":
            return {**ok,
                    "offset": self._latest(req["topic"],
                                           int(req["partition"]))}
        if op == "partitions":
            return {**ok, "count": self._partition_count(req["topic"])}
        raise ValueError(f"unknown op {op!r}")

    def stop(self) -> None:
        async def shutdown() -> None:
            if self._server is not None:
                self._server.close()
            tasks = list(self._conn_tasks)
            for t in tasks:
                t.cancel()
            # wait for the cancelled connection tasks to unwind before
            # halting the loop (destroyed-pending task otherwise)
            await asyncio.gather(*tasks, return_exceptions=True)
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(shutdown(), self.loop)
        except RuntimeError:
            return
        if self._thread is not None:
            self._thread.join(timeout=5)
        if not self.loop.is_running() and not self.loop.is_closed():
            self.loop.close()


class TcpTopicClient:
    """Blocking framed-JSON client (one socket, lock-serialized)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        # RLock: close() locks too, and call() invokes it with the lock
        # already held on transport errors (tpulint concurrency)
        self._lock = threading.RLock()
        self._next_id = 0

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),  # tpulint: disable=lock-blocking -- lock-serialized single-socket client BY DESIGN (class docstring): the lock IS the request pipeline, timeouts bound every hold
                                         timeout=self.timeout)
            self._sock = s  # tpulint: disable=concurrency -- sole caller call() holds self._lock
        return self._sock

    def call(self, **req) -> dict:
        with self._lock:
            self._next_id += 1
            req["id"] = self._next_id
            try:
                s = self._connect()
                data = json.dumps(req).encode("utf-8")
                s.sendall(struct.pack(">I", len(data)) + data)  # tpulint: disable=lock-blocking -- same lock-serialized client design: one request-reply in flight per socket
                hdr = self._recv_exact(s, 4)
                (n,) = struct.unpack(">I", hdr)
                resp = json.loads(self._recv_exact(s, n))
            except (OSError, ConnectionError):
                self.close()
                raise
        if not resp.get("ok"):
            raise RuntimeError(f"topic server error: {resp.get('error')}")
        return resp

    def _recv_exact(self, s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))  # tpulint: disable=lock-blocking -- same lock-serialized client design; socket timeout bounds the hold
            if not chunk:
                raise ConnectionError("topic server closed connection")
            buf += chunk
        return buf

    def publish_row(self, topic: str, row: dict,
                    partition: Optional[int] = None) -> None:
        self.publish_bytes(topic, json.dumps(row).encode("utf-8"), partition)

    def publish_bytes(self, topic: str, payload: bytes,
                      partition: Optional[int] = None) -> None:
        self.call(op="publish", topic=topic, partition=partition,
                  payloads=[base64.b64encode(payload).decode("ascii")])

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class TcpStreamConsumerFactory(StreamConsumerFactory):
    """Consumer SPI over a TcpTopicServer — constructible in any process
    from (host, port) alone."""

    def __init__(self, host: str, port: int, batch_size: int = 1000):
        self.host = host
        self.port = port
        self.batch_size = batch_size

    def _client(self) -> TcpTopicClient:
        return TcpTopicClient(self.host, self.port)

    def create_partition_consumer(self, config: StreamConfig,
                                  partition: int) -> PartitionLevelConsumer:
        return _TcpPartitionConsumer(self._client(), config.topic,
                                     partition, self.batch_size)

    def create_metadata_provider(self, config: StreamConfig
                                 ) -> StreamMetadataProvider:
        return _TcpMetadataProvider(self._client(), config.topic)

    def create_stream_consumer(self, config: StreamConfig,
                               checkpoint: Optional[Dict[int, int]] = None
                               ) -> StreamLevelConsumer:
        return _TcpStreamLevelConsumer(self._client(), config, checkpoint,
                                       self.batch_size)


class _TcpPartitionConsumer(PartitionLevelConsumer):
    def __init__(self, client: TcpTopicClient, topic: str, partition: int,
                 batch_size: int):
        self.client = client
        self.topic = topic
        self.partition = partition
        self.batch_size = batch_size

    def fetch_messages(self, start_offset: int, end_offset: Optional[int],
                       timeout_ms: int) -> MessageBatch:
        limit = self.batch_size if end_offset is None else \
            min(self.batch_size, end_offset - start_offset)
        resp = self.client.call(op="read", topic=self.topic,
                                partition=self.partition,
                                start=start_offset, max=max(limit, 0))
        msgs = [StreamMessage(off, base64.b64decode(b64))
                for off, b64 in resp["messages"]]
        next_off = msgs[-1].offset + 1 if msgs else start_offset
        return MessageBatch(msgs, next_off)

    def close(self) -> None:
        self.client.close()


class _TcpMetadataProvider(StreamMetadataProvider):
    def __init__(self, client: TcpTopicClient, topic: str):
        self.client = client
        self.topic = topic

    def partition_count(self) -> int:
        return int(self.client.call(op="partitions",
                                    topic=self.topic)["count"])

    def fetch_offset(self, partition: int, criteria: str) -> int:
        if criteria == SMALLEST_OFFSET:
            return 0
        return int(self.client.call(op="latest", topic=self.topic,
                                    partition=partition)["offset"])


class _TcpStreamLevelConsumer(StreamLevelConsumer):
    """Round-robin HLC group consumer over the TCP topic."""

    def __init__(self, client: TcpTopicClient, config: StreamConfig,
                 checkpoint: Optional[Dict[int, int]], batch_size: int):
        self.client = client
        self.topic = config.topic
        self.batch_size = batch_size
        parts = int(client.call(op="partitions", topic=self.topic)["count"])
        self._pos: Dict[int, int] = {}
        for p in range(parts):
            if checkpoint and p in checkpoint:
                self._pos[p] = int(checkpoint[p])
            elif config.offset_criteria == SMALLEST_OFFSET:
                self._pos[p] = 0
            else:
                self._pos[p] = int(client.call(
                    op="latest", topic=self.topic, partition=p)["offset"])
        self._next_part = 0

    def next_messages(self, max_count: int) -> List[StreamMessage]:
        out: List[StreamMessage] = []
        parts = len(self._pos)
        for _ in range(parts):
            if len(out) >= max_count:
                break
            p = self._next_part
            self._next_part = (self._next_part + 1) % parts
            resp = self.client.call(
                op="read", topic=self.topic, partition=p,
                start=self._pos[p],
                max=min(self.batch_size, max_count - len(out)))
            msgs = [StreamMessage(off, base64.b64decode(b64))
                    for off, b64 in resp["messages"]]
            if msgs:
                self._pos[p] = msgs[-1].offset + 1
                out.extend(msgs)
        return out

    def checkpoint(self) -> Dict[int, int]:
        return dict(self._pos)

    def close(self) -> None:
        self.client.close()
