"""Throughput curve: SSB queries through a real controller + broker +
2-server cluster (HTTP broker endpoint, TCP data plane), driven by the
QueryRunner perf harness in increasingQPS mode.

Parity: pinot-tools/.../perf/QueryRunner.java targetQPS/increasingQPS and
contrib/pinot-druid-benchmark PinotThroughput — the reference's benchmark
culture records p50/p99 vs offered QPS and the saturation knee, not just
single-query latency. Writes QPS_r05.json at the repo root.

Runs on the CPU backend (the serving plane under test is broker routing +
scatter/gather + scheduler + reduce; bench.py covers the chip plane), on
purpose at a row count small enough that per-query work doesn't mask the
serving-path costs.
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# HARD override: the serving-plane benchmark must not pay the test
# harness's TPU relay RTT (~90ms/dispatch) per query — that measures the
# relay, not the broker path. bench.py owns the chip-plane numbers.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

ROWS = int(os.environ.get("QPS_ROWS", 2_000_000))
SEGMENTS = int(os.environ.get("QPS_SEGMENTS", 4))
STEP_S = float(os.environ.get("QPS_STEP_S", 3.0))


def main() -> None:
    from bench import SSB_PQLS
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.tools.datagen import (build_ssb_segment_dirs,
                                         ssb_schema, ssb_table_config)
    from pinot_tpu.tools.perf import QueryRunner, http_query_fn

    t0 = time.time()
    base = tempfile.mkdtemp()
    print(f"building {ROWS} rows / {SEGMENTS} segments...",
          file=sys.stderr, flush=True)
    dirs, _ids, _sc = build_ssb_segment_dirs(
        os.path.join(base, "segs"), ROWS, SEGMENTS, seed=7, star_tree=True)

    cluster = EmbeddedCluster(os.path.join(base, "cluster"),
                              num_servers=2, tcp=True, http=True)
    try:
        cluster.add_schema(ssb_schema())
        cluster.add_table(ssb_table_config(star_tree=True))
        for d in dirs:
            cluster.upload_segment("lineorder_OFFLINE", d)

        queries = list(SSB_PQLS.values())
        fn = http_query_fn(f"127.0.0.1:{cluster.broker_port}")
        runner = QueryRunner(fn, queries)

        # warm every query's plan/kernel caches
        warm = runner.single_thread(num_times=2)
        print(f"warm: {warm}", file=sys.stderr, flush=True)

        rungs = []
        qps = 25.0
        knee = None
        while qps <= 800:
            r = runner.target_qps(qps=qps, duration_s=STEP_S,
                                  num_threads=16)
            print(str(r), file=sys.stderr, flush=True)
            rungs.append(r.to_json())
            achieved = r.qps
            if knee is None and (achieved < 0.9 * qps or
                                 r.missed_slots > r.num_queries // 2):
                knee = qps
            qps *= 2
        out = {
            "artifact": "ssb13_throughput_curve",
            "rows": ROWS, "segments": SEGMENTS,
            "cluster": "controller + broker(http) + 2 servers over TCP",
            "backend": "cpu (serving-plane benchmark; chip plane is "
                       "bench.py)",
            "mode": "increasingQPS (QueryRunner.java parity)",
            "step_duration_s": STEP_S,
            "warmup": warm.to_json(),
            "rungs": rungs,
            "saturation_knee_qps": knee,
            "wall_s": round(time.time() - t0, 1),
        }
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "QPS_r05.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps({"artifact": path,
                          "saturation_knee_qps": knee,
                          "max_achieved_qps": max(r["qps"]
                                                  for r in rungs)}))
    finally:
        cluster.stop()


if __name__ == "__main__":
    main()
