"""Controller realtime plane: LLC segment lifecycle + completion FSM.

Parity: pinot-controller/.../helix/core/realtime/ —
PinotLLCRealtimeSegmentManager (setupNewTable :198 creates per-partition
IN_PROGRESS segment metadata + CONSUMING ideal state; commitSegmentMetadata
:389-462 flips IN_PROGRESS→DONE, creates the next sequence, steps the ideal
state old CONSUMING→ONLINE / new →CONSUMING; ensureAllPartitionsConsuming
:891-1133 repairs dead partitions) and SegmentCompletionManager.java:55-475
(per-segment FSM: HOLDING → committer election by max offset →
COMMITTER_NOTIFIED → COMMITTING → COMMITTED; losers HOLD/CATCHUP/DISCARD).

The FSM rebuilds from the property store on restart (SURVEY §5.4): segment
status/offsets are durable, the in-memory FSM is only an election cache.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from pinot_tpu.common import completion as proto
from pinot_tpu.common.faults import crash_points
from pinot_tpu.common.cluster_state import CONSUMING, OFFLINE, ONLINE
from pinot_tpu.common.completion import CompletionResponse
from pinot_tpu.common.table_config import TableConfig, TableType
from pinot_tpu.controller.assignment import make_assignment
from pinot_tpu.controller.manager import SEGMENTS, ResourceManager
from pinot_tpu.realtime.registry import resolve_stream_config
from pinot_tpu.realtime.segment_name import LLCSegmentName
from pinot_tpu.segment.metadata import SegmentMetadata

log = logging.getLogger(__name__)

IN_PROGRESS = "IN_PROGRESS"
DONE = "DONE"


class _CompletionFSM:
    """Election state for one committing segment."""

    def __init__(self, replicas: List[str]):
        self.replicas = list(replicas)
        self.offsets: Dict[str, int] = {}
        self.report_order: List[str] = []
        self.first_report_ms: Optional[float] = None
        self.winner: Optional[str] = None
        self.target: Optional[int] = None
        # commit lease: the winner must finish (or extend) within this
        # deadline or the next replica report re-elects (parity:
        # SegmentCompletionManager's commit-time lease +
        # SegmentBuildTimeLeaseExtender extensions)
        self.lease_deadline_ms: Optional[float] = None


class RealtimeSegmentManager:
    def __init__(self, manager: ResourceManager,
                 election_wait_ms: float = 2_000.0,
                 commit_lease_ms: float = 60_000.0,
                 metrics=None):
        """`metrics`: optional controller registry — consuming-partition
        reassignments off dead/stopped owners mark `partitionTakeovers`."""
        self.manager = manager
        self.coordinator = manager.coordinator
        self.store = manager.store
        self.election_wait_ms = election_wait_ms
        self.commit_lease_ms = commit_lease_ms
        self.metrics = metrics
        self._fsm: Dict[str, _CompletionFSM] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # Table setup + repair
    # ------------------------------------------------------------------

    def setup_table(self, config: TableConfig,
                    assignment: str = "balanced") -> str:
        """Create the realtime table and its partition-0 consuming segments.

        Parity: PinotLLCRealtimeSegmentManager.setupNewTable:198.
        """
        assert config.table_type == TableType.REALTIME
        table = self.manager.add_table(config, assignment=assignment)
        self.ensure_all_partitions_consuming(table)
        return table

    def ensure_all_partitions_consuming(self,
                                        table: Optional[str] = None) -> None:
        """Create/repair consuming segments so every stream partition has a
        live CONSUMING replica set.

        Parity: ensureAllPartitionsConsuming:891-1133 — also the
        consuming-partition repair path after server death.
        """
        tables = [table] if table else [
            t for t in self.manager.table_names() if t.endswith("_REALTIME")]
        for t in tables:
            config = self.manager.get_table_config(t)
            if config is None or \
                    not config.indexing_config.stream_configs:
                continue
            try:
                stream = resolve_stream_config(config)
            except KeyError as e:
                log.warning("table %s: unresolvable stream config (%s)", t, e)
                continue
            meta_provider = stream.consumer_factory.create_metadata_provider(
                stream)
            n_parts = meta_provider.partition_count()
            for p in range(n_parts):
                self._ensure_partition_consuming(t, config, stream,
                                                 meta_provider, p)

    def _latest_segment(self, table: str, partition: int
                        ) -> Optional[LLCSegmentName]:
        latest = None
        for name in self.manager.segment_names(table):
            if not LLCSegmentName.is_llc(name):
                continue
            llc = LLCSegmentName.parse(name)
            if llc.partition != partition:
                continue
            if latest is None or llc.sequence > latest.sequence:
                latest = llc
        return latest

    def _ensure_partition_consuming(self, table, config, stream,
                                    meta_provider, partition: int) -> None:
        raw = config.table_name
        latest = self._latest_segment(table, partition)
        if latest is None:
            start = meta_provider.fetch_offset(partition,
                                               stream.offset_criteria)
            self._create_consuming_segment(
                table, config, LLCSegmentName(raw, partition, 0), start)
            return
        meta = self.manager.segment_metadata(table, latest.name) or {}
        if meta.get("status") == DONE:
            # last segment committed but no successor (e.g. controller died
            # mid-commit): flip its replicas to the committed copy and
            # create the next sequence from its end offset
            ideal = self.coordinator.ideal_state(table)
            stale = sorted(ideal.get(latest.name, {}))
            if stale and set(ideal[latest.name].values()) != {ONLINE}:

                def flip(segments):
                    segments[latest.name] = {i: ONLINE for i in stale}
                    return segments

                self.coordinator.update_ideal_state(table, flip)
            self._create_consuming_segment(table, config, latest.next(),
                                           int(meta["endOffset"]))
            return
        # IN_PROGRESS: make sure a live, non-errored replica is consuming.
        # The guard is STATE-aware, not just membership-aware: a crash at
        # takeover.pre_resume leaves the partition's owners parked
        # OFFLINE (bounced but never reassigned) — live OFFLINE owners
        # must re-enter the repair, or the partition stalls forever.
        ideal = self.coordinator.ideal_state(table)
        live = set(self.coordinator.live_instances())
        states = ideal.get(latest.name, {})
        assigned = set(states)
        stopped = set(meta.get("stoppedInstances", []))
        if any(st == CONSUMING and inst in live and inst not in stopped
               for inst, st in states.items()):
            return
        servers = self.manager.server_instances_for(config)
        if not servers:
            return
        replicas = config.segments_config.replication
        strategy = self.manager._assignments.setdefault(
            table, make_assignment("balanced"))
        if assigned:
            # bounce through OFFLINE so a reassignment landing on the same
            # instance still fires a fresh OFFLINE→CONSUMING transition
            # (the state machine skips same-state targets)
            def offline(segments):
                segments[latest.name] = {i: OFFLINE for i in
                                         sorted(assigned)}
                return segments

            self.coordinator.update_ideal_state(table, offline)
        chosen = strategy.assign(latest.name, servers, replicas,
                                 self.coordinator.ideal_state(table))
        log.info("repair: reassigning consuming %s/%s -> %s", table,
                 latest.name, chosen)
        with self._lock:
            self._fsm.pop(latest.name, None)   # stale election state
        if stopped:
            self.store.update(
                f"{SEGMENTS}/{table}/{latest.name}",
                lambda old: {k: v for k, v in (old or {}).items()
                             if k != "stoppedInstances"})

        # seeded crash point: the dead owners were bounced OFFLINE but
        # the new CONSUMING assignment is not yet written — the
        # partition has no consumer. Recovery: the next monitor /
        # validation run re-enters this path (assigned ∩ live empty or
        # all-OFFLINE) and finishes the takeover; the new owner resumes
        # from the durable startOffset, so nothing is lost or doubled.
        crash_points.hit("takeover.pre_resume")

        def reassign(segments):
            segments[latest.name] = {inst: CONSUMING for inst in chosen}
            return segments

        self.coordinator.update_ideal_state(table, reassign)
        if self.metrics is not None:
            from pinot_tpu.common.metrics import ControllerMeter
            self.metrics.meter(ControllerMeter.PARTITION_TAKEOVERS).mark()

    def _create_consuming_segment(self, table: str, config: TableConfig,
                                  llc: LLCSegmentName,
                                  start_offset: int) -> None:
        self.store.set(f"{SEGMENTS}/{table}/{llc.name}", {
            "segmentName": llc.name,
            "partition": llc.partition,
            "sequence": llc.sequence,
            "status": IN_PROGRESS,
            "startOffset": int(start_offset),
            "creationTimeMs": int(time.time() * 1e3),
        })
        servers = self.manager.server_instances_for(config)
        replicas = config.segments_config.replication
        strategy = self.manager._assignments.setdefault(
            table, make_assignment("balanced"))
        ideal = self.coordinator.ideal_state(table)
        chosen = strategy.assign(llc.name, servers, replicas, ideal) \
            if servers else []

        def add(segments):
            segments[llc.name] = {inst: CONSUMING for inst in chosen}
            return segments

        self.coordinator.update_ideal_state(table, add)

    # ------------------------------------------------------------------
    # Completion protocol (controller side)
    # ------------------------------------------------------------------

    def segment_consumed(self, table: str, segment: str, instance: str,
                         offset: int) -> CompletionResponse:
        """A replica reached its end criteria at `offset`.

        Parity: SegmentCompletionManager FSM :321-475 — first reports HOLD
        until every replica reported (or the election window passed), then
        the max-offset replica gets COMMIT, laggards get CATCHUP, and
        late reporters on a committed segment get KEEP/DISCARD.
        """
        meta = self.manager.segment_metadata(table, segment) or {}
        if meta.get("status") == DONE:
            end = int(meta.get("endOffset", -1))
            if offset == end:
                return CompletionResponse(proto.KEEP, end)
            return CompletionResponse(proto.DISCARD, end)
        with self._lock:
            fsm = self._fsm.get(segment)
            if fsm is None:
                replicas = sorted(
                    self.coordinator.ideal_state(table).get(segment, {}))
                fsm = self._fsm[segment] = _CompletionFSM(replicas or
                                                          [instance])
            if instance not in fsm.offsets:
                fsm.report_order.append(instance)
            fsm.offsets[instance] = int(offset)
            now = time.monotonic() * 1e3
            if fsm.first_report_ms is None:
                fsm.first_report_ms = now
            if fsm.winner is None:
                all_reported = set(fsm.replicas) <= set(fsm.offsets)
                window_passed = (now - fsm.first_report_ms
                                 ) >= self.election_wait_ms
                if all_reported or window_passed:
                    self._elect(fsm, now)
            if fsm.winner is None:
                return CompletionResponse(proto.HOLD)
            # lease expiry: a winner that went silent past its commit
            # lease forfeits; re-elect among CURRENT reporters so the
            # partition doesn't stall until the periodic repair task
            if fsm.winner != instance and \
                    fsm.lease_deadline_ms is not None and \
                    now > fsm.lease_deadline_ms:
                # the silent winner forfeits: re-elect among the OTHER
                # reporters (the reporting instance is already recorded)
                expired = fsm.winner
                if any(i != expired for i in fsm.offsets):
                    log.warning("commit lease expired for %s/%s (winner "
                                "%s); re-electing", table, segment,
                                expired)
                    self._elect(fsm, now, exclude=expired)
            if instance == fsm.winner:
                if offset < fsm.target:
                    return CompletionResponse(proto.CATCHUP, fsm.target)
                return CompletionResponse(proto.COMMIT, fsm.target)
            # losers catch up to the winner's offset (so their rows match
            # the committed end — parity with the reference's CATCHUP),
            # then hold until the winner commits → KEEP/DISCARD above
            if offset < fsm.target:
                return CompletionResponse(proto.CATCHUP, fsm.target)
            return CompletionResponse(proto.HOLD)

    def _elect(self, fsm: "_CompletionFSM", now: float,
               exclude: Optional[str] = None) -> None:
        """Pick the max-offset reporter (first in report order breaks
        ties) and start its commit lease AT ELECTION — a winner that
        dies before ever polling must still be time-bounded."""
        candidates = {i: o for i, o in fsm.offsets.items()
                      if i != exclude}
        best = max(candidates.values())
        fsm.winner = next(i for i in fsm.report_order
                          if i != exclude and fsm.offsets[i] == best)
        fsm.target = best
        fsm.lease_deadline_ms = now + self.commit_lease_ms

    def extend_build_time(self, table: str, segment: str, instance: str,
                          extra_ms: float = 60_000.0
                          ) -> CompletionResponse:
        """The committing winner asks for more build time (parity:
        SegmentCompletionProtocol.extendBuildTime, driven by the
        server's SegmentBuildTimeLeaseExtender during long builds)."""
        with self._lock:
            fsm = self._fsm.get(segment)
            if fsm is None or fsm.winner != instance:
                return CompletionResponse(proto.FAILED)
            now = time.monotonic() * 1e3
            if fsm.lease_deadline_ms is not None and \
                    now > fsm.lease_deadline_ms:
                return CompletionResponse(proto.FAILED)   # already lost
            fsm.lease_deadline_ms = now + float(extra_ms)
            return CompletionResponse(proto.PROCESSED)

    def stopped_consuming(self, table: str, segment: str, instance: str,
                          reason: str = "") -> None:
        """A replica's consumer died (build/commit failure, fatal stream
        error). Recorded durably so the validation task can repair the
        partition even though the server process itself is still live.

        Parity: SegmentCompletionProtocol.stoppedConsuming +
        RealtimeSegmentValidationManager picking it up.
        """
        log.warning("stoppedConsuming %s/%s on %s: %s", table, segment,
                    instance, reason)

        def mark(old):
            rec = dict(old or {})
            stopped = set(rec.get("stoppedInstances", []))
            stopped.add(instance)
            rec["stoppedInstances"] = sorted(stopped)
            return rec

        self.store.update(f"{SEGMENTS}/{table}/{segment}", mark)

    def commit_start(self, table: str, segment: str, instance: str,
                     offset: int) -> CompletionResponse:
        with self._lock:
            fsm = self._fsm.get(segment)
            if fsm is None or fsm.winner != instance or \
                    offset != fsm.target:
                return CompletionResponse(proto.FAILED)
        return CompletionResponse(proto.COMMIT_CONTINUE, offset)

    def commit_end(self, table: str, segment: str, instance: str,
                   offset: int, segment_dir: str) -> CompletionResponse:
        """Winner uploaded its built segment: persist + step the cluster.

        Parity: commitSegmentMetadata:389-462 (split-commit end): deep-store
        the artifact, IN_PROGRESS→DONE with endOffset, create next sequence
        IN_PROGRESS, ideal state old→ONLINE / new→CONSUMING.
        """
        with self._lock:
            fsm = self._fsm.get(segment)
            if fsm is None or fsm.winner != instance or \
                    offset != fsm.target:
                return CompletionResponse(proto.FAILED)
        config = self.manager.get_table_config(table)
        if config is None:
            return CompletionResponse(proto.FAILED)
        built = SegmentMetadata.load(segment_dir)
        dest = os.path.join(self.manager.deep_store_dir, table, segment)
        if os.path.abspath(segment_dir) != os.path.abspath(dest):
            # stage per-attempt, swap in only after the post-copy winner
            # re-verify: a forfeited winner's still-running copy must
            # never clobber the re-elected winner's committed artifact
            stage = f"{dest}.staging.{instance}"
            self.manager.fs.delete(stage)
            self.manager.fs.copy(segment_dir, stage)
            if built.crc is not None:
                # a torn deep-store copy must never become the committed
                # artifact (verified before the swap, outside the lock)
                from pinot_tpu.segment.integrity import (
                    SegmentIntegrityError, verify_segment)
                try:
                    verify_segment(stage, built.crc)
                except SegmentIntegrityError:
                    self.manager.fs.delete(stage)
                    return CompletionResponse(proto.FAILED)
            with self._lock:
                fsm = self._fsm.get(segment)
                if fsm is None or fsm.winner != instance or \
                        offset != fsm.target:
                    self.manager.fs.delete(stage)
                    return CompletionResponse(proto.FAILED)
                # swap while still holding the lock: a lease-expiry
                # re-election between re-verify and rename could otherwise
                # let this (now forfeited) winner clobber the re-elected
                # winner's artifact; both ops are fast local-fs calls
                self.manager.fs.delete(dest)
                self.manager.fs.move(stage, dest)
        else:
            with self._lock:
                fsm = self._fsm.get(segment)
                if fsm is None or fsm.winner != instance or \
                        offset != fsm.target:
                    return CompletionResponse(proto.FAILED)

        # seeded crash point: controller dies after the artifact landed in
        # the deep store but BEFORE the metadata flips DONE — the segment
        # stays IN_PROGRESS, replicas re-elect and re-commit after restart
        crash_points.hit("controller.commit_pre_done")

        def finish(old: Optional[dict]) -> dict:
            rec = dict(old or {})
            rec.update({
                "status": DONE,
                "endOffset": int(offset),
                "downloadPath": self.manager.advertised_download_path(
                    table, segment),
                "startTime": built.start_time,
                "endTime": built.end_time,
                "timeUnit": built.time_unit,
                "totalDocs": built.total_docs,
                "pushTimeMs": int(time.time() * 1e3),
                "crc": built.crc,
                # seal-time custom stats (IVF drift baseline) for the
                # minion task generators
                "customMap": dict(built.custom or {}),
            })
            return rec

        self.store.update(f"{SEGMENTS}/{table}/{segment}", finish)
        # seeded crash point: controller dies mid-commit — DONE recorded
        # but no successor created and the ideal state not stepped; the
        # validation task's DONE-without-successor repair must finish the
        # job from the durable store after restart
        crash_points.hit("controller.commit_pre_successor")
        llc = LLCSegmentName.parse(segment)
        nxt = llc.next()
        self.store.set(f"{SEGMENTS}/{table}/{nxt.name}", {
            "segmentName": nxt.name,
            "partition": nxt.partition,
            "sequence": nxt.sequence,
            "status": IN_PROGRESS,
            "startOffset": int(offset),
            "creationTimeMs": int(time.time() * 1e3),
        })
        ideal = self.coordinator.ideal_state(table)
        committed_replicas = sorted(ideal.get(segment, {})) or [instance]

        def step(segments):
            segments[segment] = {i: ONLINE for i in committed_replicas}
            segments[nxt.name] = {i: CONSUMING for i in committed_replicas}
            return segments

        with self._lock:
            self._fsm.pop(segment, None)
        self.coordinator.update_ideal_state(table, step)
        return CompletionResponse(proto.COMMIT_SUCCESS, offset)
