"""Python client API (parity: pinot-api / org.apache.pinot.client)."""
from pinot_tpu.client.connection import (Connection, ControllerClient,
                                         PinotClientError, ResultSet,
                                         ResultSetGroup,
                                         SimpleBrokerSelector, connect)

__all__ = ["Connection", "ControllerClient", "PinotClientError",
           "ResultSet", "ResultSetGroup", "SimpleBrokerSelector", "connect"]
