"""Segment v1 on-disk format constants.

Parity: pinot-core/.../segment/creator/impl/V1Constants.java — file-per-index
layout. We keep the same logical content (dictionary, forward index, inverted
index, bloom, metadata) with numpy-native containers:

    <segment_dir>/
      metadata.json              segment + per-column metadata
      creation.meta.json         build info
      <col>.dict.npy             numeric dictionary (sorted values)
      <col>.dict.bytes / .offsets.npy   string/bytes dictionary
      <col>.sv.fwd.npy           bit-packed dictId forward index (uint32 words)
      <col>.sv.sorted.fwd.npy    sorted column: [cardinality, 2] doc-id ranges
      <col>.mv.fwd.npy / <col>.mv.offsets.npy   multi-value forward index
      <col>.sv.raw.fwd.npy       raw (no-dictionary) values
      <col>.inv.docids.npy / <col>.inv.offsets.npy  CSR inverted index
      <col>.bloom.npy            bloom filter bit array
"""

METADATA_FILE = "metadata.json"
CREATION_META_FILE = "creation.meta.json"

DICT_NUMERIC = "{col}.dict.npy"
DICT_BYTES = "{col}.dict.bytes"
DICT_OFFSETS = "{col}.dict.offsets.npy"

SV_FWD = "{col}.sv.fwd.npy"
SV_SORTED_FWD = "{col}.sv.sorted.fwd.npy"
SV_RAW_FWD = "{col}.sv.raw.fwd.npy"
MV_FWD = "{col}.mv.fwd.npy"
MV_OFFSETS = "{col}.mv.offsets.npy"

INV_DOCIDS = "{col}.inv.docids.npy"
INV_OFFSETS = "{col}.inv.offsets.npy"

BLOOM = "{col}.bloom.npy"

SEGMENT_VERSION = "v1"
