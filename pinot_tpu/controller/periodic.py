"""Controller periodic tasks: retention, validation, status checking.

Parity: pinot-controller/.../helix/core/periodictask/ControllerPeriodicTask
+ core/periodictask/PeriodicTaskScheduler — tables loop on an interval;
RetentionManager.java:50-81 (delete segments past time retention);
OfflineSegmentIntervalChecker / BrokerResourceValidationManager (replica
health). run_once() executes synchronously for tests; start() runs on a
daemon thread.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from pinot_tpu.common.timeutils import unit_ms
from pinot_tpu.controller.manager import ResourceManager

log = logging.getLogger(__name__)


class PeriodicTask:
    name = "task"
    interval_s = 3600.0

    def run(self, manager: ResourceManager) -> None:
        raise NotImplementedError


class RetentionManager(PeriodicTask):
    """Deletes segments whose time range is past the table's retention."""

    name = "RetentionManager"
    interval_s = 6 * 3600.0

    def __init__(self, now_ms_fn=None):
        self._now_ms = now_ms_fn or (lambda: int(time.time() * 1e3))

    def run(self, manager: ResourceManager) -> None:
        for table in manager.table_names():
            config = manager.get_table_config(table)
            sc = config.segments_config if config else None
            if sc is None or not sc.retention_time_unit or \
                    not sc.retention_time_value:
                continue
            retention_ms = sc.retention_time_value * unit_ms(
                sc.retention_time_unit)
            cutoff_ms = self._now_ms() - retention_ms
            for seg in manager.segment_names(table):
                meta = manager.segment_metadata(table, seg) or {}
                end, unit = meta.get("endTime"), meta.get("timeUnit")
                if end is None:
                    continue
                end_ms = int(end) * unit_ms(unit)
                if end_ms < cutoff_ms:
                    log.info("retention: deleting %s/%s (end %s < cutoff)",
                             table, seg, end_ms)
                    manager.delete_segment(table, seg)


class SegmentStatusChecker(PeriodicTask):
    """Reports replica health per table (parity: SegmentStatusChecker /
    OfflineSegmentIntervalChecker metrics). Returns its findings so
    callers/tests can assert on them."""

    name = "SegmentStatusChecker"
    interval_s = 300.0

    def __init__(self):
        self.last_report: Dict[str, Dict] = {}

    def run(self, manager: ResourceManager) -> None:
        report: Dict[str, Dict] = {}
        for table in manager.coordinator.tables():
            ideal = manager.coordinator.ideal_state(table)
            view = manager.coordinator.external_view(table)
            missing, under = [], []
            for seg, wanted in ideal.items():
                live = view.servers_for(seg)
                if not live:
                    missing.append(seg)
                elif len(live) < len(wanted):
                    under.append(seg)
            report[table] = {"segments": len(ideal),
                             "missing": sorted(missing),
                             "underReplicated": sorted(under)}
        self.last_report = report


class RealtimeSegmentValidationManager(PeriodicTask):
    """Repairs realtime consumption: every stream partition must have a
    live consuming segment (parity: RealtimeSegmentValidationManager →
    PinotLLCRealtimeSegmentManager.ensureAllPartitionsConsuming:891)."""

    name = "RealtimeSegmentValidationManager"
    interval_s = 60.0

    def __init__(self, realtime_manager):
        self.realtime_manager = realtime_manager

    def run(self, manager: ResourceManager) -> None:
        self.realtime_manager.ensure_all_partitions_consuming()


class PeriodicTaskScheduler:
    def __init__(self, manager: ResourceManager,
                 tasks: Optional[List[PeriodicTask]] = None,
                 leadership=None):
        self.manager = manager
        self.tasks = tasks if tasks is not None else [
            RetentionManager(), SegmentStatusChecker()]
        # parity: ControllerPeriodicTask lead-controller gating — with
        # multiple controllers, only the lease holder runs the tasks
        self.leadership = leadership
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def run_once(self) -> None:
        if self.leadership is not None and \
                not self.leadership.try_acquire():
            return
        for task in self.tasks:
            try:
                task.run(self.manager)
            except Exception:  # noqa: BLE001 — one task must not kill others
                log.exception("periodic task %s failed", task.name)

    def start(self) -> None:
        for task in self.tasks:
            t = threading.Thread(target=self._loop, args=(task,),
                                 daemon=True, name=f"periodic-{task.name}")
            t.start()
            self._threads.append(t)

    def _loop(self, task: PeriodicTask) -> None:
        while not self._stop.wait(task.interval_s):
            if self.leadership is not None and \
                    not self.leadership.try_acquire():
                continue
            try:
                task.run(self.manager)
            except Exception:  # noqa: BLE001
                log.exception("periodic task %s failed", task.name)

    def stop(self) -> None:
        self._stop.set()
