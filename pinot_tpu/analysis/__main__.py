"""tpulint CLI: `python -m pinot_tpu.analysis [paths...]`.

Exits nonzero on findings NOT covered by the committed baseline (or on
stale baseline entries with --strict-baseline, which CI uses so the
grandfather list only ever shrinks). Run from the repo root so finding
keys match the baseline.

`--deep` additionally runs the global deep tier: jaxpr-level kernel
contracts over the registered kernel surface and the wire-schema gate
against the committed `wire-schema.json` (regenerate the latter
INTENTIONALLY with `--write-wire-schema`).

`--lifecycle` runs the resource-lifecycle tier per file: `device-ledger`
(every device upload on the serving path must route through
obs/residency.py) and `cache-bound` (every query-path cache must carry a
structural bound).

`--protocol` runs the protocol tier: durability-ordering and
crash-coverage over the durable writers, the metrics exposition
contract, and the exhaustive crash-interleaving model checker over the
extracted lease/rebalance/takeover/upsert-seal/drain transition systems
(state budget via `--max-states`; the extracted systems are committed
as `protocol-model.json`, regenerated INTENTIONALLY with
`--write-protocol-model`). `--sarif out.sarif` exports every finding —
new, grandfathered, and suppressed — as SARIF 2.1.0 for CI annotation.
"""
from __future__ import annotations

import argparse
import os
import sys

from pinot_tpu.analysis import core, runner

DEFAULT_BASELINE = "tpulint.baseline.json"

#: per-rule remediation guidance for the failure summary — the diff a
#: CI user sees should say what to DO, not just what fired
FIX_HINTS = {
    "host-sync": "batch into one jax.device_get per dispatch",
    "retrace": "hoist jit out of loops; pass hashable statics",
    "dtype-drift": "keep 64-bit math host-side (compat.wide_i64 for "
                   "genuine 64-bit lanes)",
    "concurrency": "guard both write paths with one lock, or make one "
                   "path the sole writer",
    "api-compat": "route version-sensitive symbols through "
                  "pinot_tpu.compat",
    "lock-order": "impose one global acquisition order or collapse "
                  "the locks",
    "lock-blocking": "move the blocking call outside the lock "
                     "(snapshot under the lock, work outside)",
    "async-blocking": "await the async form, or offload with "
                      "loop.run_in_executor",
    "cross-loop": "create_task from coroutines; "
                  "run_coroutine_threadsafe from other threads",
    "kernel-contract": "fix the kernel (or its contract_cases entry) "
                       "until the jaxpr is callback-free, 32-bit clean "
                       "and retrace-stable",
    "wire-schema": "restore the field, or regenerate wire-schema.json "
                   "with --write-wire-schema and flag the PR as a "
                   "wire-compatibility change",
    "durability-order": "stage to .tmp, fsync per policy, os.replace, "
                        "and only then truncate/publish",
    "crash-coverage": "add a crash_points.hit at the mutation and arm "
                      "it in a kill-restart test",
    "metrics-contract": "declare the name in common/metrics.py; put "
                        "balancing gauge writes in a finally block",
    "protocol-invariants": "follow the counterexample trace; restore "
                           "the step order/guard the model extracted",
    "protocol-model": "restore the protocol shape, or regenerate "
                      "protocol-model.json with --write-protocol-model "
                      "and flag the PR as a crash-protocol change",
    "device-ledger": "route the upload through obs/residency.py "
                     "(ledgered_put / ledgered_asarray) so the bytes "
                     "are accounted",
    "cache-bound": "cap the cache (LRU/size check), key it by a "
                   "version that invalidates, or make it single-slot; "
                   "state a genuinely extrinsic bound in a suppression",
}


def _print_failure_summary(new, errors) -> None:
    """Grouped rule-id → count/guidance block printed on a failed gate."""
    by_rule = {}
    for f in new:
        by_rule.setdefault(f.rule, []).append(f)
    print("tpulint: FAILING — new findings by rule:", file=sys.stderr)
    for rule_id in sorted(by_rule):
        fs = by_rule[rule_id]
        print(f"  {rule_id} ({len(fs)}): fix → "
              f"{FIX_HINTS.get(rule_id, 'see docs/ANALYSIS.md')}",
              file=sys.stderr)
        for f in fs[:5]:
            print(f"    {f.path}:{f.line}", file=sys.stderr)
        if len(fs) > 5:
            print(f"    ... and {len(fs) - 5} more", file=sys.stderr)
    if errors:
        print(f"  plus {len(errors)} analysis error(s)", file=sys.stderr)
    print("  suppress only with a verified invariant: "
          "`# tpulint: disable=<rule> -- <why it is safe>`",
          file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pinot_tpu.analysis",
        description="JAX-aware static analysis for pinot_tpu")
    ap.add_argument("paths", nargs="*", default=["pinot_tpu"],
                    help="files/directories to lint (repo-relative)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline JSON (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this run and exit 0")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="also fail on stale baseline entries (CI mode)")
    ap.add_argument("--lifecycle", action="store_true",
                    help="also run the resource-lifecycle tier: every "
                         "device upload routed through the residency "
                         "ledger, every query-path cache structurally "
                         "bounded")
    ap.add_argument("--deep", action="store_true",
                    help="also run the deep tier: jaxpr kernel contracts "
                         "+ wire-schema gate")
    ap.add_argument("--protocol", action="store_true",
                    help="also run the protocol tier: durability order, "
                         "crash coverage, metrics contract, and the "
                         "crash-interleaving model checker")
    ap.add_argument("--max-states", type=int, default=200_000,
                    help="model-checker state budget per system "
                         "(hitting it is a FINDING, never a silent "
                         "truncation; default 200000)")
    ap.add_argument("--sarif", metavar="PATH",
                    help="also write every finding (new, grandfathered, "
                         "suppressed) as SARIF 2.1.0 to PATH")
    ap.add_argument("--write-wire-schema", action="store_true",
                    help="regenerate wire-schema.json from the live "
                         "serde surface and exit")
    ap.add_argument("--write-protocol-model", action="store_true",
                    help="regenerate protocol-model.json from the live "
                         "protocol sources and exit")
    ap.add_argument("--rule", action="append", dest="rules", default=None,
                    help="run only this rule id (repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(core.all_rules().items()):
            tier = f" [{rule.tier}]" if rule.tier != "ast" else ""
            print(f"{rid:20s}{tier} {rule.description}")
        return 0

    if args.write_wire_schema:
        from pinot_tpu.analysis import contracts
        contracts.write_wire_schema()
        print(f"tpulint: wrote {contracts.WIRE_SCHEMA_FILE} — commit it "
              "and call out the wire-compatibility change in review")
        return 0

    if args.write_protocol_model:
        from pinot_tpu.analysis import protocol
        protocol.write_protocol_model()
        print(f"tpulint: wrote {protocol.PROTOCOL_MODEL_FILE} — commit "
              "it and call out the crash-protocol change in review")
        return 0

    core.OPTIONS["max_states"] = args.max_states

    known = core.all_rules()
    if args.rules and not set(args.rules) <= set(known):
        bad = sorted(set(args.rules) - set(known))
        print(f"tpulint: unknown rule id(s) {bad}; known: "
              f"{sorted(known)}", file=sys.stderr)
        return 2
    if args.rules and not args.deep and \
            any(known[r].tier == "deep" for r in args.rules):
        # asking for a deep rule IS asking for the deep tier — without
        # this the run would silently skip the rule and report green
        args.deep = True
    if args.rules and not args.protocol and \
            any(known[r].tier == "protocol" for r in args.rules):
        args.protocol = True        # same contract for the third tier
    if args.rules and not args.lifecycle and \
            any(known[r].tier == "lifecycle" for r in args.rules):
        args.lifecycle = True       # and the fourth

    result = runner.analyze_paths(
        args.paths, rule_ids=set(args.rules) if args.rules else None,
        deep=args.deep, protocol=args.protocol,
        lifecycle=args.lifecycle)
    for err in result.errors:
        print(f"tpulint: error: {err}", file=sys.stderr)

    if args.write_baseline:
        if result.errors:
            print("tpulint: refusing to write a baseline from a run "
                  "with analysis errors", file=sys.stderr)
            return 1
        pruned, reduced = [], []
        if os.path.exists(args.baseline):
            old = core.load_baseline(args.baseline)
            fresh = core.count_keys(result.findings)
            # "pruned" = the key left the baseline entirely; a count
            # that merely shrank is still grandfathered — reporting it
            # as pruned would tell the operator a live finding is gone
            pruned = [k for k in sorted(old) if fresh.get(k, 0) == 0]
            reduced = [(k, old[k], fresh[k]) for k in sorted(old)
                       if 0 < fresh.get(k, 0) < old[k]]
        core.write_baseline(args.baseline, result.findings)
        print(f"tpulint: wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        if args.sarif:
            # a baseline write grandfathers everything it records, so
            # the paired SARIF reflects that: all "unchanged" (silently
            # skipping --sarif here left CI annotation steps reading a
            # missing or stale file)
            from pinot_tpu.analysis import sarif
            sarif.write_sarif(args.sarif, result.findings,
                              result.suppressed,
                              core.count_keys(result.findings))
            print(f"tpulint: wrote SARIF to {args.sarif}")
        for key in pruned:
            print(f"tpulint: pruned stale baseline entry: {key}")
        for key, was, now in reduced:
            print(f"tpulint: reduced baseline entry {was} → {now}: "
                  f"{key}")
        return 0

    baseline = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = core.load_baseline(args.baseline)
    new, stale = runner.diff_baseline(result, baseline)

    if args.sarif:
        from pinot_tpu.analysis import sarif
        sarif.write_sarif(args.sarif, result.findings,
                          result.suppressed, baseline)
        print(f"tpulint: wrote SARIF to {args.sarif}")

    if args.show_suppressed:
        for f in result.suppressed:
            print(f"suppressed: {f.render()}")
    for f in new:
        print(f.render())
    for key in stale:
        print(f"tpulint: stale baseline entry (code fixed — regenerate "
              f"with --write-baseline): {key}")

    n_grandfathered = len(result.findings) - len(new)
    by_rule = ", ".join(f"{r}={n}" for r, n in
                        sorted(result.by_rule().items())) or "none"
    tier = "+".join(["fast"] +
                    (["lifecycle"] if args.lifecycle else []) +
                    (["deep"] if args.deep else []) +
                    (["protocol"] if args.protocol else []))
    print(f"tpulint[{tier}]: {len(result.findings)} finding(s) "
          f"[{by_rule}], {len(new)} new, {n_grandfathered} "
          f"grandfathered, {len(result.suppressed)} suppressed, "
          f"{len(stale)} stale baseline entr(ies)")
    if result.timings:
        shown = {"ast": "fast"}
        print("tpulint: tier wall time: " +
              " ".join(f"{shown.get(t, t)}={s:.2f}s"
                       for t, s in sorted(result.timings.items())))
    if new or result.errors or (stale and args.strict_baseline):
        if new:
            _print_failure_summary(new, result.errors)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
