"""Broker-side cluster spectator: external views → routing + time boundary.

Parity: HelixBrokerStarter's spectator role —
HelixExternalViewBasedRouting.processExternalViewChange (:418) rebuilds
routing tables, and HelixExternalViewBasedTimeBoundaryService recomputes
hybrid boundaries from offline segment metadata.
"""
from __future__ import annotations

from typing import Optional

from pinot_tpu.broker.quota import QueryQuotaManager
from pinot_tpu.broker.routing import RoutingManager
from pinot_tpu.broker.time_boundary import TimeBoundaryService
from pinot_tpu.common.cluster_state import ONLINE, TableView
from pinot_tpu.common.table_name import (offline_table, raw_table,
                                         realtime_table, table_type)
from pinot_tpu.controller.manager import ResourceManager
from pinot_tpu.controller.state_machine import ClusterCoordinator


class BrokerClusterWatcher:
    def __init__(self, coordinator: ClusterCoordinator,
                 manager: ResourceManager,
                 routing: Optional[RoutingManager] = None,
                 time_boundary: Optional[TimeBoundaryService] = None,
                 quota: Optional[QueryQuotaManager] = None,
                 num_brokers_fn=None):
        self.coordinator = coordinator
        self.manager = manager
        self.routing = routing or RoutingManager()
        self.time_boundary = time_boundary or TimeBoundaryService()
        # per-table/per-tenant QPS quotas converge here: every external-
        # view change re-reads the table config and re-divides the
        # cluster-wide rate by the live broker count (parity:
        # HelixExternalViewBasedQueryQuotaManager's processQueryQuota-
        # ChangeInternal on EV / instance-config change)
        self.quota = quota
        self._num_brokers_fn = num_brokers_fn or (lambda: 1)
        # broker result caches registered for segment-lifecycle
        # invalidation (register_result_cache): the freshness bound
        # covers consuming-ingestion staleness only — an OFFLINE
        # backfill/replacement rewrites rows that were wrong at every
        # point in time, and a drop-and-recreate changes the table's
        # identity, so any external-view change flushes the cache.
        # View changes are segment-lifecycle-rate (commits, uploads,
        # rebalances), so a full clear costs hit rate, never much CPU.
        self._result_caches: list = []
        self._fault_tolerance = None
        self._live_ft_watcher = None
        self.partition_pruner = PartitionZKMetadataPruner(manager)
        coordinator.watch_external_views(self._on_view)
        for table in coordinator.tables():
            self._on_view(coordinator.external_view(table))

    def register_result_cache(self, cache) -> None:
        """Clear `cache` on every external-view change (any object
        with a ``clear()``)."""
        self._result_caches.append(cache)

    def attach_fault_tolerance(self, fault_tolerance) -> None:
        """Forget a deregistered server's health/breaker accounting in
        the SAME watch event that removes its live-instance record
        (`FaultToleranceManager.forget`), so it leaves the candidate
        ranking at once and a later reincarnation on the same host:port
        starts with a clean breaker. Deliberately does NOT touch the
        data-plane channel: a DRAINING server deregisters while still
        serving its in-flight window, and severing its connection here
        would turn a planned, errorless departure into dispatch
        failures — a genuinely dead server's channel fails fast on its
        own, and a reincarnation's fresh endpoint record overwrites the
        stale one (`set_endpoint` closes the old channel)."""
        from pinot_tpu.controller.state_machine import LIVE
        self._fault_tolerance = fault_tolerance

        def on_live(path: str, record, _prefix_len=len(LIVE) + 1) -> None:
            if record is not None:
                return
            if self._fault_tolerance is not None:
                self._fault_tolerance.forget(path[_prefix_len:])

        self._live_ft_watcher = on_live
        self.coordinator.store.watch(LIVE + "/", on_live)

    def close(self) -> None:
        if self._live_ft_watcher is not None:
            try:
                self.coordinator.store.unwatch(self._live_ft_watcher)
            except Exception:  # noqa: BLE001 — store may be closed
                pass
            self._live_ft_watcher = None

    def _on_view(self, view: TableView) -> None:
        self.partition_pruner.invalidate(view.table_name)
        if not view.segment_states:
            self.routing.remove_table(view.table_name)
            # caches flush AFTER the routing change lands (see below)
            for cache in self._result_caches:
                cache.clear()
            # re-converge quotas too: if the OTHER type still exists
            # its config wins; if the table is fully gone its buckets
            # (and offered-load counter) are cleared
            self._apply_quota_config(view.table_name)
            return
        self._apply_routing_config(view.table_name)
        # routing FIRST: every store read below (table configs, broker
        # count) delays this thread, and until update_view lands the
        # broker routes on the PREVIOUS view — under reload/rebalance
        # bounces a widened window turns into real misroutes on
        # just-unloaded replicas. Quota convergence tolerates the lag.
        self.routing.update_view(view)
        if table_type(view.table_name) == "OFFLINE":
            self._update_time_boundary(view)
        # cache flush strictly AFTER the view change has fully landed
        # (update_view AND the time boundary — both steer what a hybrid
        # query executes against): the clear bumps the put-guard
        # generation, and a query racing this handler must not capture
        # the FRESH generation while still routing on the PRE-change
        # view or boundary — its pre-backfill result would be accepted
        # by put() and served for the whole freshness bound. Cleared
        # after, any in-window query holds the stale generation and
        # its put is dropped.
        for cache in self._result_caches:
            cache.clear()
        self._apply_quota_config(view.table_name)

    def reapply_quotas(self) -> None:
        """Re-divide every table's cluster-wide quota by the CURRENT
        live broker count. Broker membership changes (join/leave/death)
        change each broker's share but fire no external-view event —
        without this hook a joining broker would enforce its smaller
        share while incumbents keep the old one until unrelated segment
        churn, over-admitting cluster-wide (and survivors of a broker
        death would under-admit symmetrically)."""
        if self.quota is None:
            return
        # dedupe to RAW names: _apply_quota_config reads BOTH typed
        # configs per call, so iterating t_OFFLINE and t_REALTIME of a
        # hybrid table would double the store reads on the watch-
        # dispatch thread (which must stay fast — routing rides on it)
        for raw in {raw_table(t) for t in self.coordinator.tables()}:
            self._apply_quota_config(raw)

    def _apply_quota_config(self, table: str) -> None:
        """quotaConfig.maxQueriesPerSecond → this broker's token-bucket
        share; per-tenant rates ride in customConfigs["tenantQuotas"]
        as a JSON object {tenant: qps}.

        The broker enforces at the RAW table name (one admission per
        logical query), so a hybrid table's effective quota is merged
        across BOTH typed configs — each type's allowance sums, and a
        view change on the type WITHOUT a quotaConfig must not clobber
        the other type's limits."""
        if self.quota is None:
            return
        raw = raw_table(table)
        quotas = []
        tenant_qps: dict = {}
        found = False
        for typed in (offline_table(raw), realtime_table(raw)):
            config = self.manager.get_table_config(typed)
            if config is None:
                continue
            found = True
            if config.quota_config is not None and \
                    config.quota_config.max_queries_per_second is not None:
                quotas.append(config.quota_config.max_queries_per_second)
            for tenant, qps in self._tenant_quotas(config).items():
                tenant_qps[tenant] = tenant_qps.get(tenant, 0.0) + qps
        if not found:
            # no typed config survives: the table is gone — clear any
            # buckets so a re-created table doesn't inherit old limits
            self.quota.configure_table(raw, None, {})
            return
        max_qps = sum(quotas) if quotas else None
        try:
            num_brokers = max(1, int(self._num_brokers_fn()))
        except Exception:  # noqa: BLE001 — a broken counter never
            num_brokers = 1   # disables quota convergence entirely
        self.quota.configure_table(raw, max_qps, tenant_qps,
                                   num_brokers=num_brokers)

    @staticmethod
    def _tenant_quotas(config) -> dict:
        raw_tenants = (config.custom_config or {}).get("tenantQuotas")
        if not raw_tenants:
            return {}
        import json
        try:
            parsed = json.loads(raw_tenants)
            if isinstance(parsed, dict):
                return {str(k): float(v) for k, v in parsed.items()}
        except (ValueError, TypeError):
            pass          # malformed tenant quotas: fail open (no limit)
        return {}

    def _apply_routing_config(self, table: str) -> None:
        """Honor the table's routingTableBuilderName (parity:
        HelixExternalViewBasedRouting reading RoutingConfig)."""
        from pinot_tpu.broker.routing import make_routing_builder
        config = self.manager.get_table_config(table)
        if config is None:
            return
        rc = config.routing_config

        def partition_lookup(segment: str, _t=table):
            """Segment -> recorded partition-id union across partitioned
            columns (the PartitionAware builder's grouping key)."""
            return self.partition_pruner.segment_partitions(_t, segment)

        builder = make_routing_builder(rc.builder_name, rc.options,
                                       partition_lookup=partition_lookup)
        target = builder if builder is not None else self.routing.builder
        # builder-kind comparison: re-applying the same kind would only
        # churn (option-only changes take effect on broker restart)
        if type(target) is not type(self.routing.table_builder(table)):
            # the caller pushes the fresh view right after: no rebuild
            self.routing.set_table_builder(table, builder, rebuild=False)

    def _update_time_boundary(self, view: TableView) -> None:
        offline_table = view.table_name
        schema = self.manager.get_schema(raw_table(offline_table))
        if schema is None:
            return
        tc = schema.time_column
        if tc is None:
            return
        # Only segments actually served (at least one ONLINE replica in the
        # external view — matching what RoutingManager will route to) may
        # advance the boundary, and non-positive end times are skipped —
        # parity: HelixExternalViewBasedTimeBoundaryService filters to the EV
        # and ignores endTime <= 0. With an async coordinator the property
        # store can hold segments no server serves yet; advancing past them
        # would silently drop rows from hybrid results.
        served = {seg for seg, states in view.segment_states.items()
                  if ONLINE in states.values()}
        ends, unit = [], None
        for seg in self.manager.segment_names(offline_table):
            if seg not in served:
                continue
            meta = self.manager.segment_metadata(offline_table, seg) or {}
            end = meta.get("endTime")
            if end is not None and end > 0:
                ends.append(end)
                unit = meta.get("timeUnit") or unit
        if ends:
            self.time_boundary.update_from_segments(
                offline_table, tc.name, unit or "DAYS", ends)


class PartitionZKMetadataPruner:
    """Broker-side partition pruning from segment ZK records.

    Parity: pinot-broker/.../pruner/PartitionZKMetadataPruner — before
    scatter, EQ predicates on partitioned columns eliminate segments
    whose recorded partition-id sets cannot match, cutting server
    fan-out (the functional outcome of the reference's partition-aware
    routing builders). Partition metadata and schemas are cached per
    table; BrokerClusterWatcher invalidates the cache on external-view
    changes, keeping the query hot path free of property-store reads.
    Any malformed metadata fails OPEN (segment kept, never dropped).
    """

    def __init__(self, manager: ResourceManager):
        self.manager = manager
        self._meta: dict = {}      # table → {segment: partitionMetadata}
        self._schemas: dict = {}   # table → Schema | None

    def invalidate(self, table: str) -> None:
        self._meta.pop(table, None)
        self._schemas.pop(table, None)

    def _table_meta(self, table: str) -> dict:
        cached = self._meta.get(table)
        if cached is None:
            cached = {}
            for seg in self.manager.segment_names(table):
                rec = self.manager.segment_metadata(table, seg) or {}
                pm = rec.get("partitionMetadata") or {}
                if pm:
                    cached[seg] = pm
            self._meta[table] = cached
        return cached

    def _schema(self, table: str):
        if table not in self._schemas:
            self._schemas[table] = self.manager.get_schema(
                raw_table(table))
        return self._schemas[table]

    def segment_partitions(self, table: str, segment: str):
        """Recorded partition-id union across a segment's partitioned
        columns, or None — the public lookup the partition-aware routing
        builder groups by (same cache the pruner reads)."""
        pm = self._table_meta(table).get(segment)
        if not pm:
            return None
        ids = set()
        for info in pm.values():
            ids.update(info.get("partitions") or ())
        return ids or None

    def prune(self, request, table: str, segments):
        try:
            meta = self._table_meta(table)
            if not meta:
                return list(segments)
            schema = self._schema(table)
            memo: dict = {}
            kept = []
            for seg in segments:
                pm = meta.get(seg)
                if pm and self._pruned(request.filter, pm, schema, memo):
                    continue
                kept.append(seg)
            return kept
        except Exception:  # noqa: BLE001 — pruning is an optimization:
            return list(segments)      # fail open on any metadata issue

    def _pruned(self, node, pm, schema, memo) -> bool:
        from pinot_tpu.common.request import FilterOperator
        if node is None:
            return False
        if node.operator == FilterOperator.AND:
            return any(self._pruned(c, pm, schema, memo)
                       for c in node.children)
        if node.operator == FilterOperator.OR:
            return all(self._pruned(c, pm, schema, memo)
                       for c in node.children)
        if node.operator != FilterOperator.EQUALITY:
            return False
        info = pm.get(node.column)
        if not info or not info.get("partitions"):
            return False
        from pinot_tpu.common.partition import partition_of_value
        key = (node.column, info["functionName"],
               int(info["numPartitions"]), node.values[0])
        p = memo.get(key)
        if p is None:
            dt = None
            if schema is not None and schema.has_column(node.column):
                dt = schema.field(node.column).data_type.np_dtype
            try:
                p = partition_of_value(info["functionName"],
                                       int(info["numPartitions"]),
                                       dt, node.values[0])
            except Exception:  # noqa: BLE001 — unknown function: keep
                p = -1
            memo[key] = p
        return p >= 0 and p not in set(info["partitions"])
