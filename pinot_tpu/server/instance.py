"""Server process wiring: data manager + scheduler + executor + transport.

Parity: pinot-server — ServerInstance/ServerBuilder (ServerInstance.java:43:
InstanceDataManager + QueryExecutor + QueryScheduler + NettyServer) and
ScheduledRequestHandler.java:40-66 (bytes → deserialize → schedule →
execute → DataTable bytes).
"""
from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Optional, Tuple

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.common.metrics import (MetricsRegistry, ServerGauge,
                                      ServerMeter, ServerQueryPhase)
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.serde import instance_request_from_bytes
from pinot_tpu.server.data_manager import InstanceDataManager
from pinot_tpu.server.query_executor import InstanceQueryExecutor
from pinot_tpu.server.scheduler import QueryScheduler, make_scheduler
from pinot_tpu.transport.tcp import EventLoopThread, QueryServer


class ServerInstance:
    """One query server: hosts segments, answers InstanceRequests."""

    def __init__(self, instance_id: str = "server_0",
                 scheduler: str = "fcfs", num_workers: int = 4,
                 mesh=None, use_device: bool = True):
        self.instance_id = instance_id
        self.metrics = MetricsRegistry("server")
        self.data_manager = InstanceDataManager()
        self.scheduler: QueryScheduler = make_scheduler(scheduler,
                                                        num_workers)
        self.executor = InstanceQueryExecutor(
            self.data_manager, mesh=mesh, use_device=use_device,
            metrics=self.metrics,
            segment_executor=self.scheduler.segment_pool)
        self.metrics.gauge(ServerGauge.SEGMENT_COUNT).set_callable(
            self.data_manager.num_segments)
        self.metrics.meter(ServerMeter.QUERIES)   # exists at 0 from boot
        self._loop: Optional[EventLoopThread] = None
        self._server: Optional[QueryServer] = None
        self.port: Optional[int] = None
        # guards the start/stop lifecycle fields (_loop/_server/port):
        # an admin-triggered stop can race a late start on another thread
        self._lifecycle_lock = threading.Lock()

    # -- request path ------------------------------------------------------
    def _deserialize(self, payload: bytes
                     ) -> Tuple[Optional[InstanceRequest], Optional[bytes],
                                float]:
        """(request, None, ms) on success, (None, error reply bytes, ms)
        on a malformed wire payload. The measured milliseconds become
        the query's requestDeserialization span."""
        t0 = time.perf_counter()
        try:
            request = instance_request_from_bytes(payload)
            err = None
        except Exception as e:  # noqa: BLE001 — malformed wire payload
            dt = DataTable()
            dt.exceptions.append(f"RequestDeserializationError: {e}")
            request, err = None, dt.to_bytes()
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.timer(
            ServerQueryPhase.REQUEST_DESERIALIZATION).update(ms)
        return request, err, ms

    def _schedule(self, request: InstanceRequest, deser_ms: float = 0.0):
        """Submit to the scheduler; returns the result Future.

        Broker deadline propagation: the budget is fixed to an absolute
        instant NOW (deserialization time), so queue wait counts against
        it and expired work is dropped, not computed.
        """
        deadline = None
        budget_s = None
        if request.deadline_budget_ms is not None:
            budget_s = request.deadline_budget_ms / 1e3
            deadline = time.monotonic() + budget_s
        t_submit = time.perf_counter()

        def run():
            wait_ms = (time.perf_counter() - t_submit) * 1e3
            return self.executor.execute(request, scheduler_wait_ms=wait_ms,
                                         deadline=deadline,
                                         deser_ms=deser_ms)

        return self.scheduler.submit(request.query.table_name, run,
                                     deadline_s=budget_s)

    def _serialize(self, request: InstanceRequest, dt: DataTable) -> bytes:
        with self.metrics.timer(
                ServerQueryPhase.RESPONSE_SERIALIZATION).time():
            t0 = time.perf_counter()
            payload = dt.to_bytes()
            ser_ms = (time.perf_counter() - t0) * 1e3
        if request.enable_trace and "traceInfo" in dt.metadata:
            # the serde span cannot ride inside the bytes it measures:
            # amend the trace and re-serialize (trace=true only — the
            # untraced path pays a single to_bytes)
            try:
                info = json.loads(dt.metadata["traceInfo"])
            except ValueError:
                return payload
            root = info.get("rootSpanId") if isinstance(info, dict) else None
            if root is not None:
                info["spans"].append({
                    "name": ServerQueryPhase.RESPONSE_SERIALIZATION,
                    "ms": round(ser_ms, 3), "spanId": f"{root}.serde",
                    "parentId": root})
                dt.metadata["traceInfo"] = json.dumps(info)
                payload = dt.to_bytes()
        return payload

    def _error_reply(self, request: InstanceRequest, e: Exception) -> bytes:
        self.metrics.meter(ServerMeter.QUERY_EXECUTION_EXCEPTIONS).mark()
        dt = DataTable()
        dt.metadata["requestId"] = str(request.request_id)
        dt.exceptions.append(f"QueryExecutionError: {e}")
        return dt.to_bytes()

    # -- in-process path (used by tests and the embedded broker) -----------
    def handle_request_bytes(self, payload: bytes) -> bytes:
        request, err, deser_ms = self._deserialize(payload)
        if err is not None:
            return err
        try:
            dt = self._schedule(request, deser_ms).result()
            return self._serialize(request, dt)
        except Exception as e:  # noqa: BLE001 — execution or serde error
            return self._error_reply(request, e)

    # -- network path (one coroutine per in-flight frame) ------------------
    async def handle_request_async(self, payload: bytes) -> bytes:
        """The multiplexed QueryServer's handler: dispatches to the
        scheduler and awaits the result WITHOUT pinning a thread per
        in-flight request — only scheduler workers compute; serde runs
        on the executor so the event loop keeps draining frames."""
        loop = asyncio.get_running_loop()
        request, err, deser_ms = self._deserialize(payload)
        if err is not None:
            return err
        try:
            dt = await asyncio.wrap_future(self._schedule(request,
                                                          deser_ms))
            if len(dt.rows) <= 128:
                # small replies (aggregations, trimmed group-bys)
                # serialize faster than an executor hop costs
                return self._serialize(request, dt)
            return await loop.run_in_executor(
                None, self._serialize, request, dt)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — execution or serde error
            return self._error_reply(request, e)

    # -- network service ---------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start the TCP query service; returns the bound port."""
        with self._lifecycle_lock:
            self._loop = EventLoopThread()
            self._server = QueryServer(
                host, port, self.handle_request_bytes,
                async_handler=self.handle_request_async)
            self._loop.run(self._server.start())
            self.port = self._server.port
            return self.port

    def stop(self) -> None:
        with self._lifecycle_lock:
            if self._server is not None and self._loop is not None:
                self._loop.run(self._server.stop())
            if self._loop is not None:
                self._loop.stop()
                self._loop = None
        self.scheduler.shutdown()
        self.data_manager.shutdown()
