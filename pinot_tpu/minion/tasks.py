"""Minion task model + property-store-backed task queue.

Parity: the Helix Task Framework usage in
pinot-controller/.../helix/core/minion/PinotHelixTaskResourceManager.java
(task queues per task type, task states) and
pinot-common PinotTaskConfig. The TPU build replaces the Helix task
state machine with atomic claim/complete updates on the cluster
property store — the same single-writer CAS discipline the ideal-state
updates use.

Claim leases: an ``IN_PROGRESS`` task whose minion was kill -9'd must
not stay stranded forever. Every claim stamps ``claimTimeMs`` and bumps
``attempts``; ``requeue_expired`` (driven by the controller's periodic
minion scheduler) moves expired claims back to ``GENERATED`` — or to
``ERROR`` once the attempt budget is exhausted — and ``finish`` rejects
a completion from a worker whose claim was requeued from under it (the
zombie-minion fencing analogue of the leadership epoch check).
"""
from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Dict, List, Optional

from pinot_tpu.controller.property_store import PropertyStore

TASKS_ROOT = "/TASKS"

# task states (parity: TaskState in the Helix task framework)
GENERATED = "GENERATED"
IN_PROGRESS = "IN_PROGRESS"
COMPLETED = "COMPLETED"
ERROR = "ERROR"


@dataclasses.dataclass
class PinotTaskConfig:
    """Parity: pinot-common PinotTaskConfig — task type + string configs."""
    task_type: str
    configs: Dict[str, str] = dataclasses.field(default_factory=dict)
    task_id: str = ""

    def __post_init__(self):
        if not self.task_id:
            self.task_id = (f"Task_{self.task_type}_"
                            f"{uuid.uuid4().hex[:12]}")

    def to_json(self) -> dict:
        return {"taskType": self.task_type, "taskId": self.task_id,
                "configs": dict(self.configs)}

    @classmethod
    def from_json(cls, d: dict) -> "PinotTaskConfig":
        return cls(task_type=d["taskType"], configs=dict(d.get("configs", {})),
                   task_id=d["taskId"])


# common config keys (parity: core/common/MinionConstants.java)
TABLE_NAME_KEY = "tableName"
SEGMENT_NAME_KEY = "segmentName"
DOWNLOAD_URL_KEY = "downloadURL"
COLUMNS_TO_CONVERT_KEY = "columnsToConvert"
MERGED_SEGMENTS_KEY = "segmentNames"          # comma-separated, merge tasks


class TaskQueue:
    """Task lifecycle on the property store.

    /TASKS/<taskType>/<taskId> → {"config": ..., "state": ...,
    "worker": ..., "info": ..., "claimTimeMs": ..., "attempts": ...}.
    Claiming is an atomic read-modify-write so concurrent minions never
    double-run a task; the claim carries a lease (`lease_s`, injectable
    `clock`) so a claimer's death requeues the task instead of
    stranding it.
    """

    #: how long a claim stays valid before requeue (a task exceeding
    #: this should extend via re-claim semantics — not supported; size
    #: the lease for the slowest expected segment rewrite)
    DEFAULT_LEASE_S = 300.0
    #: claims per task before the queue gives up and marks ERROR
    DEFAULT_MAX_ATTEMPTS = 3
    #: how long COMPLETED/ERROR records stay queryable before the
    #: periodic sweep prunes them — without pruning, /TASKS grows
    #: without bound and every requeue/dedup scan pays for the whole
    #: task HISTORY of the cluster
    DEFAULT_TERMINAL_RETENTION_S = 6 * 3600.0

    def __init__(self, store: PropertyStore, clock=time.time,
                 lease_s: float = DEFAULT_LEASE_S,
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 metrics=None):
        self.store = store
        self._clock = clock
        self.lease_s = lease_s
        self.max_attempts = max_attempts
        self.metrics = metrics

    def _now_ms(self) -> int:
        return int(self._clock() * 1e3)

    def submit(self, task: PinotTaskConfig) -> str:
        self.store.set(f"{TASKS_ROOT}/{task.task_type}/{task.task_id}", {
            "config": task.to_json(), "state": GENERATED,
            "attempts": 0,
            "submitTimeMs": self._now_ms()})
        return task.task_id

    def task_types(self) -> List[str]:
        return self.store.children(TASKS_ROOT)

    def claim(self, worker_id: str, task_types: List[str]
              ) -> Optional[PinotTaskConfig]:
        """Atomically move one GENERATED task to IN_PROGRESS, stamping
        the claim lease and attempt count."""
        for ttype in task_types:
            for task_id in self.store.children(f"{TASKS_ROOT}/{ttype}"):
                path = f"{TASKS_ROOT}/{ttype}/{task_id}"
                claimed = {}

                def try_claim(rec):
                    if rec and rec.get("state") == GENERATED:
                        rec = dict(rec)
                        rec["state"] = IN_PROGRESS
                        rec["worker"] = worker_id
                        rec["claimTimeMs"] = self._now_ms()
                        rec["attempts"] = int(rec.get("attempts", 0)) + 1
                        claimed["config"] = rec["config"]
                    return rec or {}

                self.store.update(path, try_claim)
                if claimed:
                    return PinotTaskConfig.from_json(claimed["config"])
        return None

    def finish(self, task: PinotTaskConfig, state: str,
               info: str = "", worker_id: Optional[str] = None) -> bool:
        """Record a terminal state. When `worker_id` is given, the
        completion is FENCED: it lands only if the task is still
        IN_PROGRESS under that worker's claim — a worker whose lease
        expired and whose task was requeued (possibly already re-run by
        another minion) must not clobber the newer outcome. Returns
        whether the write landed."""
        path = f"{TASKS_ROOT}/{task.task_type}/{task.task_id}"
        accepted = {}

        def done(rec):
            rec = dict(rec or {})
            if worker_id is not None and (
                    rec.get("state") != IN_PROGRESS or
                    rec.get("worker") != worker_id):
                return rec                  # stale claim: reject
            accepted["ok"] = True
            rec["state"] = state
            rec["info"] = info
            rec["endTimeMs"] = self._now_ms()
            return rec

        self.store.update(path, done)
        return bool(accepted)

    def requeue_expired(self, task_types: Optional[List[str]] = None
                        ) -> List[str]:
        """Requeue IN_PROGRESS tasks whose claim lease expired (the
        claiming minion is presumed dead). A task that exhausted its
        attempt budget goes ERROR instead. Atomic per task via the
        store's read-modify-write. Returns the affected task ids."""
        from pinot_tpu.common.metrics import MinionMeter
        now = self._now_ms()
        cutoff = now - int(self.lease_s * 1e3)
        touched: List[str] = []
        for ttype in (task_types if task_types is not None
                      else self.task_types()):
            for task_id in self.store.children(f"{TASKS_ROOT}/{ttype}"):
                path = f"{TASKS_ROOT}/{ttype}/{task_id}"
                outcome = {}

                def sweep(rec):
                    if not rec or rec.get("state") != IN_PROGRESS:
                        return rec or {}
                    if int(rec.get("claimTimeMs", now)) > cutoff:
                        return rec          # lease still live
                    rec = dict(rec)
                    if int(rec.get("attempts", 1)) >= self.max_attempts:
                        rec["state"] = ERROR
                        rec["info"] = (
                            f"claim lease expired after "
                            f"{rec.get('attempts')} attempt(s); worker "
                            f"{rec.get('worker')!r} presumed dead")
                        outcome["state"] = ERROR
                    else:
                        rec["state"] = GENERATED
                        outcome["state"] = GENERATED
                    rec.pop("worker", None)
                    rec.pop("claimTimeMs", None)
                    return rec

                self.store.update(path, sweep)
                if outcome:
                    touched.append(task_id)
                    if self.metrics is not None:
                        name = MinionMeter.TASK_REQUEUES \
                            if outcome["state"] == GENERATED \
                            else MinionMeter.TASK_ATTEMPTS_EXHAUSTED
                        self.metrics.meter(name).mark()
        return touched

    def prune_terminal(self, retention_s: Optional[float] = None
                       ) -> List[str]:
        """Remove COMPLETED/ERROR records older than `retention_s`
        (default DEFAULT_TERMINAL_RETENTION_S) so the queue's scans
        stay O(open tasks), not O(cluster lifetime). Returns pruned
        ids."""
        if retention_s is None:
            retention_s = self.DEFAULT_TERMINAL_RETENTION_S
        cutoff = self._now_ms() - int(retention_s * 1e3)
        pruned: List[str] = []
        for ttype in self.task_types():
            for task_id in self.store.children(f"{TASKS_ROOT}/{ttype}"):
                path = f"{TASKS_ROOT}/{ttype}/{task_id}"
                rec = self.store.get(path)
                if not rec or rec.get("state") not in (COMPLETED, ERROR):
                    continue
                if int(rec.get("endTimeMs", cutoff + 1)) > cutoff:
                    continue
                self.store.remove(path)
                pruned.append(task_id)
        return pruned

    def task_states(self, task_type: str) -> Dict[str, str]:
        out = {}
        for task_id in self.store.children(f"{TASKS_ROOT}/{task_type}"):
            rec = self.store.get(f"{TASKS_ROOT}/{task_type}/{task_id}")
            if rec:
                out[task_id] = rec.get("state", "?")
        return out

    def tasks_for_segment(self, task_type: str, table: str,
                          segment: str) -> List[str]:
        """Open (non-terminal) tasks already covering a segment — used by
        generators to avoid duplicate scheduling."""
        out = []
        for task_id in self.store.children(f"{TASKS_ROOT}/{task_type}"):
            rec = self.store.get(f"{TASKS_ROOT}/{task_type}/{task_id}")
            if not rec or rec.get("state") in (COMPLETED, ERROR):
                continue
            cfg = rec.get("config", {}).get("configs", {})
            if cfg.get(TABLE_NAME_KEY) == table and \
                    segment in cfg.get(SEGMENT_NAME_KEY, "").split(","):
                out.append(task_id)
        return out
