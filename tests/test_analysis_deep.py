"""Deep analysis tier tests: fixture corpus for the lock-order /
async-safety rule families (known-bad snippets each rule must catch,
known-good snippets that must pass WITHOUT suppressions), jaxpr kernel
contracts over the registered kernel surface, the wire-schema gate, and
the suppression-parsing / baseline-determinism edge cases (ISSUE 7
satellites)."""
import json
import os
import subprocess
import sys

import pytest

from pinot_tpu.analysis import analyze_source
from pinot_tpu.analysis.core import parse_suppressions

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVER_PATH = "pinot_tpu/server/_fixture.py"      # concurrency scope
PLAIN_PATH = "pinot_tpu/common/_fixture.py"


def rules_of(source: str, path: str = PLAIN_PATH):
    return sorted({f.rule for f in analyze_source(source, path).findings})


def findings_of(source: str, path: str = PLAIN_PATH):
    return analyze_source(source, path).findings


# ---------------------------------------------------------------------------
# known-bad corpus — each snippet must fire its rule
# ---------------------------------------------------------------------------

BAD_DEADLOCK_CYCLE = """
import threading

class Ledger:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def debit(self):
        with self._a:
            with self._b:
                pass

    def credit(self):
        with self._b:
            with self._a:
                pass
"""

BAD_CYCLE_INTERPROCEDURAL = """
import threading

class Pool:
    def __init__(self):
        self._queue_lock = threading.Lock()
        self._state_lock = threading.Lock()

    def _promote(self):
        with self._queue_lock:
            pass

    def rebalance(self):
        with self._state_lock:
            self._promote()          # state → queue ...

    def drain(self):
        with self._queue_lock:
            with self._state_lock:   # ... queue → state: cycle
                pass
"""

BAD_LOCK_ACROSS_AWAIT = """
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()

    async def refresh(self, fetch):
        with self._lock:
            data = await fetch()     # threading lock parked over await
        return data
"""

BAD_LOCK_ACROSS_BLOCKING = """
import threading
import time

class Registry:
    def __init__(self):
        self._lock = threading.Lock()

    def publish(self):
        with self._lock:
            time.sleep(1.0)          # every thread convoys behind this
"""

BAD_LOOP_BLOCKING_SLEEP = """
import time

async def handle(request):
    time.sleep(0.5)                  # stalls the whole event loop
    return request
"""

BAD_LOOP_BLOCKING_RESULT = """
async def gather(fut):
    return fut.result()              # unproven future: blocks the loop
"""

BAD_LOOP_ONLY_HELPER = """
import subprocess

def _compress(payload):
    return subprocess.run(["gzip"], input=payload)   # loop-reachable

async def respond(payload):
    return _compress(payload)
"""

BAD_CROSS_LOOP_THREADSAFE = """
import asyncio

async def dispatch(coro, loop):
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    return fut
"""

BAD_CROSS_LOOP_CREATE_TASK = """
import asyncio

def fire_and_forget(coro):
    asyncio.create_task(coro)        # no running loop in a sync caller
"""

BAD_NARROWING_DTYPE = """
import numpy as np

def doc_offsets(doc_ids, widths):
    return (doc_ids * widths).astype(np.int32)
"""


def test_bad_deadlock_cycle_fires():
    found = findings_of(BAD_DEADLOCK_CYCLE)
    assert [f.rule for f in found] == ["lock-order"]
    assert "Ledger._a" in found[0].message
    assert "Ledger._b" in found[0].message


def test_bad_interprocedural_cycle_fires():
    found = findings_of(BAD_CYCLE_INTERPROCEDURAL)
    assert "lock-order" in {f.rule for f in found}
    msg = " ".join(f.message for f in found)
    assert "Pool.rebalance → Pool._promote" in msg


def test_bad_lock_across_await_fires():
    found = findings_of(BAD_LOCK_ACROSS_AWAIT)
    assert "lock-blocking" in {f.rule for f in found}
    assert any("await" in f.message for f in found)


def test_bad_lock_across_blocking_call_fires():
    found = findings_of(BAD_LOCK_ACROSS_BLOCKING)
    assert "lock-blocking" in {f.rule for f in found}
    assert any("time.sleep" in f.message for f in found)


def test_bad_loop_blocking_sleep_fires():
    assert rules_of(BAD_LOOP_BLOCKING_SLEEP) == ["async-blocking"]


def test_bad_loop_blocking_result_fires():
    found = findings_of(BAD_LOOP_BLOCKING_RESULT)
    assert [f.rule for f in found] == ["async-blocking"]
    assert "asyncio.wait" in found[0].message   # tells you the fix


def test_bad_loop_only_helper_fires():
    found = findings_of(BAD_LOOP_ONLY_HELPER)
    assert [f.rule for f in found] == ["async-blocking"]
    assert "reachable only from the event loop" in found[0].message


def test_bad_cross_loop_threadsafe_fires():
    assert rules_of(BAD_CROSS_LOOP_THREADSAFE) == ["cross-loop"]


def test_bad_cross_loop_create_task_fires():
    assert rules_of(BAD_CROSS_LOOP_CREATE_TASK) == ["cross-loop"]


def test_bad_narrowing_dtype_fires():
    assert rules_of(BAD_NARROWING_DTYPE) == ["dtype-drift"]


# ---------------------------------------------------------------------------
# known-good corpus — must pass WITHOUT suppressions
# ---------------------------------------------------------------------------

GOOD_CONSISTENT_LOCK_ORDER = """
import threading

class Ledger:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def debit(self):
        with self._a:
            with self._b:
                pass

    def credit(self):
        with self._a:
            with self._b:
                pass
"""

GOOD_SNAPSHOT_THEN_WORK = """
import threading
import time

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def publish(self):
        with self._lock:
            snapshot = dict(self._entries)
        time.sleep(1.0)              # blocking AFTER the lock released
        return snapshot
"""

GOOD_ASYNC_AWAITS = """
import asyncio

async def handle(request, fetch):
    await asyncio.sleep(0.5)
    return await fetch(request)
"""

GOOD_DONE_SET_RESULT = """
import asyncio

async def first_winner(tasks):
    done, pending = await asyncio.wait(
        tasks, return_when=asyncio.FIRST_COMPLETED)
    for t in done:
        return t.result()            # proven complete: a value read
"""

GOOD_OFFLOADED_HELPER = """
import asyncio
import subprocess

def _compress(payload):
    return subprocess.run(["gzip"], input=payload)

async def respond(payload):
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, _compress, payload)
"""

GOOD_CROSS_LOOP_FROM_THREAD = """
import asyncio

def submit_from_watcher(coro, loop):
    return asyncio.run_coroutine_threadsafe(coro, loop)

async def schedule(coro):
    return asyncio.ensure_future(coro)
"""


def test_good_corpus_passes_without_suppressions():
    goods = [GOOD_CONSISTENT_LOCK_ORDER, GOOD_SNAPSHOT_THEN_WORK,
             GOOD_ASYNC_AWAITS, GOOD_DONE_SET_RESULT,
             GOOD_OFFLOADED_HELPER, GOOD_CROSS_LOOP_FROM_THREAD]
    assert len(goods) >= 5
    for src in goods:
        res = analyze_source(src, PLAIN_PATH)
        assert res.findings == [], [f.render() for f in res.findings]
        assert res.suppressed == []      # good BY CONSTRUCTION, not
        #                                  by suppression


def test_bad_corpus_counts():
    bads = [BAD_DEADLOCK_CYCLE, BAD_CYCLE_INTERPROCEDURAL,
            BAD_LOCK_ACROSS_AWAIT, BAD_LOCK_ACROSS_BLOCKING,
            BAD_LOOP_BLOCKING_SLEEP, BAD_LOOP_BLOCKING_RESULT,
            BAD_LOOP_ONLY_HELPER, BAD_CROSS_LOOP_THREADSAFE,
            BAD_CROSS_LOOP_CREATE_TASK, BAD_NARROWING_DTYPE]
    assert len(bads) >= 5
    for src in bads:
        assert findings_of(src), "known-bad snippet produced no finding"


# ---------------------------------------------------------------------------
# review-hardening regressions (findings from the ISSUE 7 review pass)
# ---------------------------------------------------------------------------

BAD_CLOSURE_WRITE = """
import threading

class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._pool = None
        self.done = False

    def kick(self):
        def cb():
            self.done = True       # runs later, on a pool thread,
        self._pool.submit(cb)      # with NO lock held
"""

BAD_RESULT_NAME_REUSE = """
import asyncio

async def race(fut, tasks):
    t = fut
    x = t.result()                  # NOT proven done: blocks the loop
    done, _ = await asyncio.wait(tasks)
    for t in done:
        x = t.result()              # proven done: fine
    return x
"""

GOOD_INIT_HELPER = """
import threading

class Boot:
    def __init__(self):
        self.state = "INIT"
        self._setup()               # construction happens-before
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _setup(self):
        self.state = "READY"        # init-only: not a thread path

    def _run(self):
        while True:
            self.state = "RUNNING"  # sole post-publish writer
"""

GOOD_LOOP_CALLBACK_CREATE_TASK = """
import asyncio

class Poller:
    def arm(self, loop):
        loop.call_soon(self._poke)

    def _poke(self):
        asyncio.ensure_future(self._work())   # runs ON the loop thread

    async def _work(self):
        await asyncio.sleep(0)
"""


BAD_PUBLIC_THREAD_TARGET = """
import threading

class Worker:
    def __init__(self):
        self.n = 0
        threading.Thread(target=self.run).start()

    def run(self):
        self.n += 1        # runs on the spawned thread AND any caller
"""

GOOD_CLOSURE_TAKES_OWN_LOCK = """
import threading

class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._pool = None
        self.done = False

    def kick(self):
        def cb():
            with self._lock:
                self.done = True   # guarded at CALL time by its own
        self._pool.submit(cb)      # acquisition — not a finding
"""

GOOD_DONE_CALLBACK_SAME_LOOP = """
import asyncio

class Stepper:
    def __init__(self):
        self.state = 0

    async def step(self, fut):
        self.state = 1             # event-loop thread ...
        fut.add_done_callback(self._on_done)

    def _on_done(self, fut):
        self.state = 2             # ... same event-loop thread
"""

BAD_CALL_SOON_BLOCKING = """
import time

class Poller:
    def arm(self, loop):
        loop.call_soon(self._tick)

    def _tick(self):
        time.sleep(0.1)            # runs ON the loop: blocks it
"""


def test_public_thread_target_single_method_race_fires():
    # the method carries BOTH a spawn root and its external root: one
    # writing method, two provable threads → a finding, no second
    # method required
    found = findings_of(BAD_PUBLIC_THREAD_TARGET, SERVER_PATH)
    assert [f.rule for f in found] == ["concurrency"]
    assert "spawn:run" in found[0].message
    assert "ext:run" in found[0].message


def test_closure_acquiring_its_own_lock_is_clean():
    assert rules_of(GOOD_CLOSURE_TAKES_OWN_LOCK, SERVER_PATH) == []


def test_done_callback_shares_the_loop_thread():
    # add_done_callback targets run ON the loop — same context as the
    # async writer, not a second thread root
    assert rules_of(GOOD_DONE_CALLBACK_SAME_LOOP, SERVER_PATH) == []


def test_call_soon_target_is_loop_context_for_blocking():
    found = findings_of(BAD_CALL_SOON_BLOCKING)
    assert [f.rule for f in found] == ["async-blocking"]
    assert "time.sleep" in found[0].message


def test_write_baseline_reports_reduced_vs_pruned(tmp_path):
    # two identical findings → baseline count 2; fixing ONE must report
    # a REDUCED entry (still grandfathered), never a pruned one
    bad = tmp_path / "mod.py"
    two = ("import numpy as np\n\n"
           "def f(a, b):\n"
           "    return (a * b).astype(np.int32)\n\n"
           "def g(a, b):\n"
           "    return (a * b).astype(np.int32)\n")
    bad.write_text(two)
    baseline = tmp_path / "baseline.json"
    proc = _run_cli([str(bad), "--write-baseline",
                     "--baseline", str(baseline)], str(tmp_path))
    assert proc.returncode == 0
    bad.write_text(two.replace(
        "def g(a, b):\n    return (a * b).astype(np.int32)\n",
        "def g(a, b):\n    return a\n"))
    proc = _run_cli([str(bad), "--write-baseline",
                     "--baseline", str(baseline)], str(tmp_path))
    assert proc.returncode == 0
    assert "reduced baseline entry 2 → 1" in proc.stdout
    assert "pruned" not in proc.stdout
    assert sum(json.loads(
        baseline.read_text())["findings"].values()) == 1


BAD_INIT_CLOSURE_THREAD = """
import threading

class C:
    def __init__(self):
        self.state = 0
        def run():
            while True:
                self.state += 1    # spawned from __init__: runs later
        threading.Thread(target=run).start()

    def advance(self):
        self.state = 2             # races the closure thread
"""

GOOD_SAME_NAME_DIFFERENT_CLASSES = """
import time

class A:
    def _send(self):
        time.sleep(1)              # thread-only helper of class A

    def pump(self):
        self._send()               # sync caller: NOT loop-only

class B:
    async def go(self):
        return self._send()

    def _send(self):
        return 1                   # B's loop-only _send doesn't block
"""

GOOD_SET_NAME_IS_CONSTRUCTION = """
import threading

class Descriptor:
    def __init__(self):
        self._lock = threading.Lock()
        self.name = None

    def __set_name__(self, owner, name):
        self.name = name           # class-definition time, pre-sharing
"""


BAD_INIT_HELPER_CLOSURE = """
import threading

class C:
    def __init__(self):
        self.state = 0
        self._start()

    def _start(self):                  # reachable from __init__ only
        def run():
            while True:
                self.state += 1        # ... but the closure escapes it
        threading.Thread(target=run).start()

    def advance(self):
        self.state = 2
"""

GOOD_LOOP_ONLY_CREATE_TASK = """
import asyncio

def _kick(coro):
    return asyncio.ensure_future(coro)   # called only from async code

async def main(coro):
    return _kick(coro)
"""

GOOD_INLINE_CLOSURE_UNDER_LOCK = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def update(self):
        with self._lock:
            def bump():
                self.n += 1        # defined AND invoked under the lock
            bump()
"""


GOOD_PUBLIC_SYNC_FROM_ASYNC = """
import time

class Flusher:
    async def tick(self):
        self.flush()

    def flush(self):
        time.sleep(1)       # public: callable from worker threads too
"""

GOOD_SORT_KEY_UNDER_LOCK = """
import threading

class Ranker:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0

    def rank(self, xs):
        with self._lock:
            def key(x):
                self.hits += 1     # runs inline inside the with-block
                return x
            xs.sort(key=key)
"""

BAD_SORT_KEY_ESCAPES_LOCK = GOOD_SORT_KEY_UNDER_LOCK.replace(
    "            xs.sort(key=key)", "        xs.sort(key=key)")


GOOD_TEMP_RELEASE_NO_CRASH = """
import threading

class Waiter:
    def __init__(self):
        self._lock = threading.Lock()

    def step(self):
        with self._lock:
            self._lock.release()   # temporary-release pattern
            self._lock.acquire()
"""

BAD_DONE_SET_REBOUND = """
import asyncio

async def f(tasks, futs):
    done, _ = await asyncio.wait(tasks)
    done = futs                    # rebinding voids the proof
    for t in done:
        return t.result()
"""


def test_temporary_release_does_not_crash_the_analyzer():
    res = analyze_source(GOOD_TEMP_RELEASE_NO_CRASH, SERVER_PATH)
    assert res.errors == []        # must return a result, not raise


def test_done_set_rebinding_voids_the_result_proof():
    found = findings_of(BAD_DONE_SET_REBOUND)
    assert [f.rule for f in found] == ["async-blocking"]


def test_public_sync_method_is_not_loop_only():
    # async call sites prove nothing about a PUBLIC method — it is an
    # external root, callable from worker threads where blocking is fine
    assert rules_of(GOOD_PUBLIC_SYNC_FROM_ASYNC) == []


def test_sort_key_closure_inherits_escape_site_lock():
    assert rules_of(GOOD_SORT_KEY_UNDER_LOCK, SERVER_PATH) == []


def test_sort_key_closure_escaping_without_lock_fires():
    found = findings_of(BAD_SORT_KEY_ESCAPES_LOCK, SERVER_PATH)
    assert any("Ranker.rank.<key>" in f.message for f in found), \
        [f.render() for f in found]


def test_init_helper_spawned_closure_race_fires():
    found = findings_of(BAD_INIT_HELPER_CLOSURE, SERVER_PATH)
    assert {f.rule for f in found} == {"concurrency"}
    msgs = " ".join(f.message for f in found)
    assert "_start.<run>" in msgs and "C.advance" in msgs


def test_loop_only_helper_may_create_tasks():
    assert rules_of(GOOD_LOOP_ONLY_CREATE_TASK) == []


def test_inline_closure_under_lock_is_clean():
    assert rules_of(GOOD_INLINE_CLOSURE_UNDER_LOCK, SERVER_PATH) == []


def test_init_spawned_closure_race_fires():
    found = findings_of(BAD_INIT_CLOSURE_THREAD, SERVER_PATH)
    assert {f.rule for f in found} == {"concurrency"}
    msgs = " ".join(f.message for f in found)
    assert "__init__.<run>" in msgs and "C.advance" in msgs


def test_same_named_methods_do_not_alias_across_classes():
    assert rules_of(GOOD_SAME_NAME_DIFFERENT_CLASSES) == []


def test_set_name_counts_as_construction():
    assert rules_of(GOOD_SET_NAME_IS_CONSTRUCTION, SERVER_PATH) == []


def test_closure_write_in_lock_class_fires():
    # v1 parity: a self-write inside a closure handed to a pool is
    # unguarded at CALL time regardless of locks held at def time
    found = findings_of(BAD_CLOSURE_WRITE, SERVER_PATH)
    assert "concurrency" in {f.rule for f in found}
    assert any("self.done" in f.message for f in found)


def test_result_exemption_is_flow_scoped():
    found = findings_of(BAD_RESULT_NAME_REUSE)
    assert [f.rule for f in found] == ["async-blocking"]
    assert found[0].line == 6       # the pre-wait call, not the loop's


def test_init_only_helper_is_not_a_thread_path():
    assert rules_of(GOOD_INIT_HELPER, SERVER_PATH) == []


def test_loop_callback_may_create_tasks():
    assert rules_of(GOOD_LOOP_CALLBACK_CREATE_TASK) == []


def test_rule_filter_on_deep_rule_implies_deep_tier(tmp_path):
    # without the implication this reported a false green: the deep
    # rule was accepted by validation but never executed
    proc = _run_cli(["--rule", "wire-schema"], REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "tpulint[fast+deep]" in proc.stdout


# ---------------------------------------------------------------------------
# audited broker pattern: the exact `_dispatch_hedged` shapes
# ---------------------------------------------------------------------------


def test_broker_hedge_result_pattern_is_verified_clean():
    """The audited `primary.result()` sites (broker/request_handler
    _dispatch_hedged) were rewritten into the done-set iteration form —
    the committed file must analyze clean under async-blocking."""
    path = os.path.join(REPO_ROOT, "pinot_tpu/broker/request_handler.py")
    with open(path) as fh:
        src = fh.read()
    res = analyze_source(src, "pinot_tpu/broker/request_handler.py")
    assert [f for f in res.findings if f.rule == "async-blocking"] == []
    # and not via suppression: the invariant is analyzer-verified
    assert [f for f in res.suppressed
            if f.rule == "async-blocking"] == []


# ---------------------------------------------------------------------------
# kernel contracts (jaxpr tier)
# ---------------------------------------------------------------------------


def test_registered_kernel_surface_passes_contracts():
    from pinot_tpu.analysis import contracts
    violations = contracts.check_kernel_contracts()
    assert violations == [], violations


def test_contract_grid_covers_every_kernel_family():
    from pinot_tpu.ops import kernels
    names = {c[0] for c in kernels.contract_cases()}
    for family in ("filter_pred_mix", "agg_part_sums", "group_dense",
                   "group_compacted", "group_ranked", "select_limit",
                   "select_order", "select_ordertk", "select_ordermk"):
        assert family in names, f"{family} missing from contract grid"
    assert len(kernels.CONTRACT_SHAPE_BUCKETS) >= 2


def test_callback_detector_catches_pure_callback():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from pinot_tpu.analysis import contracts

    def bad(x):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    closed = jax.make_jaxpr(bad)(jnp.zeros(4))
    assert "pure_callback" in contracts.find_callbacks(closed)


def test_retrace_identity_of_cached_builder():
    from pinot_tpu.ops import kernels
    spec = (("match_all",), (("count", "*", "sv", None),), None, None)
    k1 = kernels.build_segment_kernel(8192, *spec)
    k2 = kernels.build_segment_kernel(8192, *spec)
    assert k1 is k2


def test_wide_i64_asserts_without_x64():
    import jax
    from pinot_tpu import compat
    if jax.config.jax_enable_x64:
        pytest.skip("x64 enabled: the assertion path is unreachable")
    with pytest.raises(AssertionError, match="x64"):
        compat.wide_i64(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# wire schema
# ---------------------------------------------------------------------------


def test_committed_wire_schema_round_trips():
    from pinot_tpu.analysis import contracts
    path = os.path.join(REPO_ROOT, contracts.WIRE_SCHEMA_FILE)
    assert os.path.exists(path), "wire-schema.json not committed"
    diffs = contracts.check_wire_schema(path)
    assert diffs == [], diffs


def test_wire_schema_detects_removed_optional_key(tmp_path):
    """Removing an optional serde key (the version-skew break class)
    must fail the gate with a field-level diff naming the key."""
    from pinot_tpu.analysis import contracts
    schema = contracts.wire_schema()
    schema["instanceRequest"]["optional"] = [
        k for k in schema["instanceRequest"]["optional"]
        if k != "deadlineBudgetMs"]
    del schema["instanceRequest"]["shape"]["deadlineBudgetMs"]
    stale = tmp_path / "wire-schema.json"
    stale.write_text(json.dumps(schema))
    diffs = contracts.check_wire_schema(str(stale))
    assert any("deadlineBudgetMs" in d for d in diffs), diffs


def test_wire_schema_detects_retyped_tag(tmp_path):
    from pinot_tpu.analysis import contracts
    schema = contracts.wire_schema()
    schema["objectSerde"]["int64"] = "J"        # retyped tag byte
    stale = tmp_path / "wire-schema.json"
    stale.write_text(json.dumps(schema))
    diffs = contracts.check_wire_schema(str(stale))
    assert any("objectSerde.int64" in d for d in diffs), diffs


def test_wire_schema_missing_snapshot_is_a_finding(tmp_path):
    from pinot_tpu.analysis import contracts
    diffs = contracts.check_wire_schema(str(tmp_path / "nope.json"))
    assert diffs and "missing" in diffs[0]


# ---------------------------------------------------------------------------
# suppression parsing edge cases (satellite)
# ---------------------------------------------------------------------------


def test_suppression_multiple_rules_one_comment():
    per_line, per_file = parse_suppressions(
        "x = 1  # tpulint: disable=host-sync, retrace -- reason\n")
    assert per_line == {1: {"host-sync", "retrace"}}
    assert per_file == set()


def test_suppression_disable_all():
    per_line, _ = parse_suppressions(
        "x = 1  # tpulint: disable=all -- fixture\n")
    assert per_line == {1: {"all"}}


def test_suppression_file_level_anywhere():
    src = "x = 1\n# tpulint: disable-file=lock-blocking -- module docs\n"
    _, per_file = parse_suppressions(src)
    assert per_file == {"lock-blocking"}


def test_suppression_whitespace_variants():
    for form in ("#tpulint: disable=host-sync",
                 "#  tpulint:  disable=host-sync",
                 "# tpulint: disable=host-sync,dtype-drift"):
        per_line, _ = parse_suppressions(f"x = 1  {form}\n")
        assert "host-sync" in per_line[1], form


def test_suppression_malformed_is_ignored():
    for form in ("# tpulint: disable",          # no rules
                 "# tpulint disable=host-sync",  # missing colon
                 "# lint: disable=host-sync"):
        per_line, per_file = parse_suppressions(f"x = 1  {form}\n")
        assert per_line == {} and per_file == set(), form


def test_suppression_wrong_line_does_not_apply():
    src = ("# tpulint: disable=dtype-drift -- wrong line\n"
           "import numpy as np\n"
           "def f(a, b):\n"
           "    return (a * b).astype(np.int32)\n")
    res = analyze_source(src, PLAIN_PATH)
    assert [f.rule for f in res.findings] == ["dtype-drift"]
    assert res.suppressed == []


def test_suppression_counts_as_suppressed_not_dropped():
    src = ("import numpy as np\n"
           "def f(a, b):\n"
           "    return (a * b).astype(np.int32)"
           "  # tpulint: disable=dtype-drift -- bounded upstream\n")
    res = analyze_source(src, PLAIN_PATH)
    assert res.findings == []
    assert [f.rule for f in res.suppressed] == ["dtype-drift"]


# ---------------------------------------------------------------------------
# baseline determinism + stale pruning (satellite)
# ---------------------------------------------------------------------------


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "pinot_tpu.analysis", *args],
        cwd=cwd, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO_ROOT})


def test_write_baseline_twice_is_byte_identical(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text("import numpy as np\n\n"
                   "def f(a, b):\n"
                   "    return (a * b).astype(np.int32)\n")
    b1 = tmp_path / "b1.json"
    b2 = tmp_path / "b2.json"
    for out in (b1, b2):
        proc = _run_cli([str(bad), "--write-baseline",
                         "--baseline", str(out)], str(tmp_path))
        assert proc.returncode == 0, proc.stdout + proc.stderr
    assert b1.read_bytes() == b2.read_bytes()
    # and rewriting over an existing baseline is also byte-stable
    proc = _run_cli([str(bad), "--write-baseline",
                     "--baseline", str(b1)], str(tmp_path))
    assert proc.returncode == 0
    assert b1.read_bytes() == b2.read_bytes()


def test_stale_baseline_entries_reported_and_pruned(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text("import numpy as np\n\n"
                   "def f(a, b):\n"
                   "    return (a * b).astype(np.int32)\n")
    baseline = tmp_path / "baseline.json"
    proc = _run_cli([str(bad), "--write-baseline",
                     "--baseline", str(baseline)], str(tmp_path))
    assert proc.returncode == 0
    assert json.loads(baseline.read_text())["findings"]

    # fix the code: the grandfathered entry is now STALE
    bad.write_text("import numpy as np\n\n"
                   "def f(a, b):\n"
                   "    wide = (a.astype(np.int64) * b)\n"
                   "    return wide\n")
    # CI mode reports it and fails (grandfather list must shrink)
    proc = _run_cli([str(bad), "--strict-baseline",
                     "--baseline", str(baseline)], str(tmp_path))
    assert proc.returncode == 1
    assert "stale baseline entry" in proc.stdout
    # regenerating prunes it, says so, and leaves an empty baseline
    proc = _run_cli([str(bad), "--write-baseline",
                     "--baseline", str(baseline)], str(tmp_path))
    assert proc.returncode == 0
    assert "pruned stale baseline entry" in proc.stdout
    assert json.loads(baseline.read_text())["findings"] == {}


def test_failure_summary_groups_by_rule(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text(BAD_LOOP_BLOCKING_SLEEP + BAD_NARROWING_DTYPE)
    proc = _run_cli([str(bad), "--no-baseline"], str(tmp_path))
    assert proc.returncode == 1
    assert "new findings by rule" in proc.stderr
    assert "async-blocking" in proc.stderr
    assert "dtype-drift" in proc.stderr
    assert "fix →" in proc.stderr


@pytest.mark.slow
def test_deep_cli_green_on_repo():
    proc = _run_cli(["pinot_tpu/", "--deep", "--strict-baseline"],
                    REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "tpulint[fast+deep]" in proc.stdout
