"""Token scheduler under saturation: decay accounting, priority under
flood, starvation bounds, capacity rejection, queue deadlines.

Parity targets: tokenbucket/TokenSchedulerGroup.java:31-56 (linear-decay
token accounting), MultiLevelPriorityQueue.java:38 (priority pick + soft
limit moderation + OutOfCapacity + trimExpired), PriorityScheduler.java
(semaphore-gated scheduling loop).
"""
import threading
import time

import numpy as np
import pytest

from pinot_tpu.server.scheduler import (MultiLevelPriorityQueue,
                                        ResourceLimitPolicy,
                                        SchedulerDeadlineError,
                                        SchedulerOutOfCapacityError,
                                        TokenBucketScheduler,
                                        TokenSchedulerGroup, make_scheduler)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t

    def advance_ms(self, ms: float) -> None:
        self.t += ms / 1e3


# ---------------------------------------------------------------------------
# Token accounting (deterministic, fake clock)
# ---------------------------------------------------------------------------


def test_idle_group_tokens_converge_to_full_allotment():
    clk = FakeClock()
    g = TokenSchedulerGroup("t1", num_tokens_per_ms=4, token_lifetime_ms=100,
                            clock=clk)
    # fixed point of t = a*L*N + (1-a)*t with zero usage is L*N = 400
    g.available_tokens = 0.0
    for _ in range(40):
        clk.advance_ms(100)
        g.consume_tokens()
    assert abs(g.consume_tokens() - 400.0) < 1.0


def test_heavy_user_decays_below_idle_group():
    clk = FakeClock()
    heavy = TokenSchedulerGroup("heavy", 4, 100, clock=clk)
    light = TokenSchedulerGroup("light", 4, 100, clock=clk)
    # heavy runs 2 threads continuously across 5 quanta; light idles
    heavy.increment_threads()
    heavy.increment_threads()
    for _ in range(5):
        clk.advance_ms(100)
        heavy.consume_tokens()
        light.consume_tokens()
    assert heavy.consume_tokens() < light.consume_tokens()
    # decay formula steady state with 2 threads of 4 allotted:
    # t = 0.8*400 + 0.2*(t - 200) -> t = (320 - 40) / 0.8 = 350 minus the
    # in-quantum drain (200/quantum): strictly below light's 400
    heavy.decrement_threads()
    heavy.decrement_threads()
    # after going idle, heavy converges back up (fair chance restored)
    for _ in range(40):
        clk.advance_ms(100)
    assert abs(heavy.consume_tokens() - 400.0) < 2.0


def test_within_quantum_drain_is_linear_in_threads():
    clk = FakeClock()
    g = TokenSchedulerGroup("g", 4, 100, clock=clk)
    g.increment_threads()
    clk.advance_ms(30)          # 30ms x 1 thread
    assert abs(g.consume_tokens() - (400 - 30)) < 1e-6
    g.increment_threads()
    clk.advance_ms(20)          # +20ms x 2 threads
    assert abs(g.consume_tokens() - (400 - 30 - 40)) < 1e-6


# ---------------------------------------------------------------------------
# MultiLevelPriorityQueue pick semantics (deterministic)
# ---------------------------------------------------------------------------


def _mk_queue(clk, workers=4, soft_pct=0.3, hard_pct=0.5, max_pending=8):
    policy = ResourceLimitPolicy(workers,
                                 max_threads_per_group_pct=hard_pct,
                                 soft_threads_per_group_pct=soft_pct,
                                 max_pending_per_group=max_pending)
    return MultiLevelPriorityQueue(policy, workers, 100,
                                   query_deadline_s=30.0, clock=clk)


def test_queue_picks_group_with_more_tokens():
    clk = FakeClock()
    q = _mk_queue(clk)
    q.put("heavy", lambda: "h1")
    q.put("light", lambda: "l1")
    # burn heavy's tokens
    hg = q.group("heavy")
    hg.increment_threads()
    clk.advance_ms(250)
    hg.consume_tokens()
    hg.decrement_threads()
    ctx = q.take_next()
    assert ctx.group == "light"


def test_queue_ties_break_fcfs_by_arrival():
    clk = FakeClock()
    q = _mk_queue(clk)
    q.put("a", lambda: 1)
    clk.advance_ms(1)
    q.put("b", lambda: 2)
    # equal tokens -> earliest arrival (group a) wins
    assert q.take_next().group == "a"
    assert q.take_next().group == "b"


def test_soft_limit_moderation_prefers_lean_group():
    clk = FakeClock()
    q = _mk_queue(clk, workers=10, soft_pct=0.3, hard_pct=0.8)
    q.put("fat", lambda: 1)
    q.put("lean", lambda: 2)
    fat = q.group("fat")
    # fat has MORE tokens (lean burned some) but is past the soft limit
    lg = q.group("lean")
    lg.increment_threads()
    clk.advance_ms(150)
    lg.consume_tokens()
    lg.decrement_threads()
    fat.add_reserved(4)           # soft limit = 3, hard = 8
    assert q.take_next().group == "lean"


def test_hard_limit_blocks_scheduling_entirely():
    clk = FakeClock()
    q = _mk_queue(clk, workers=4, hard_pct=0.5)   # hard = 2
    q.put("g", lambda: 1)
    g = q.group("g")
    g.add_reserved(2)
    assert q.take_next(timeout=0.0) is None       # canSchedule false
    g.release_reserved(1)
    assert q.take_next(timeout=0.0).group == "g"


def test_out_of_capacity_needs_pending_and_reserved_at_limit():
    clk = FakeClock()
    q = _mk_queue(clk, workers=4, hard_pct=0.5, max_pending=2)
    q.put("g", lambda: 1)
    q.put("g", lambda: 2)
    # pending at limit but no reserved threads: still accepted
    q.put("g", lambda: 3)
    q.group("g").add_reserved(2)
    with pytest.raises(SchedulerOutOfCapacityError):
        q.put("g", lambda: 4)


def test_expired_queries_trimmed_with_deadline_error():
    clk = FakeClock()
    policy = ResourceLimitPolicy(4)
    q = MultiLevelPriorityQueue(policy, 4, 100, query_deadline_s=1.0,
                                clock=clk)
    ctx = q.put("g", lambda: 1)
    clk.advance_ms(1500)         # injected clock drives the deadline
    assert q.take_next(timeout=0.0) is None
    with pytest.raises(SchedulerDeadlineError):
        ctx.future.result(timeout=1)
    # a fresh query after the trim still schedules
    assert q.put("g", lambda: 2) is not None
    assert q.take_next(timeout=0.0).group == "g"


def test_starved_tenant_wins_within_bounded_picks():
    """Per-tenant fairness regression (the tenant-isolation invariant):
    one group floods the queue and burns CPU; a second group arriving
    late must win a scheduling pick within a BOUNDED number of
    take_next() calls — far fewer than the flood's backlog — because
    the flood's token decay outweighs FCFS arrival order. Fully
    deterministic: fake clock, simulated execution, no threads."""
    clk = FakeClock()
    q = _mk_queue(clk, workers=4, max_pending=128)
    for i in range(60):
        q.put("aggressor", lambda i=i: i)
    agg = q.group("aggressor")
    # the aggressor has been burning 2 workers for a while
    agg.increment_threads()
    agg.increment_threads()
    for _ in range(5):
        clk.advance_ms(100)
        agg.consume_tokens()
    # the victim's first query arrives LAST (worst case for FCFS)
    q.put("victim", lambda: "v")
    picks_until_victim = None
    for pick in range(20):
        ctx = q.take_next(timeout=0.0)
        assert ctx is not None
        if ctx.group == "victim":
            picks_until_victim = pick
            break
        # simulate the aggressor pick executing 30ms on one thread
        agg.increment_threads()
        clk.advance_ms(30)
        agg.consume_tokens()
        agg.decrement_threads()
    # bounded: the victim is scheduled within a handful of picks, not
    # behind the 60-deep aggressor backlog
    assert picks_until_victim is not None and picks_until_victim <= 5, \
        f"victim starved for {picks_until_victim} picks"
    # the aggressor keeps the rest of the machine: next pick is its own
    assert q.take_next(timeout=0.0).group == "aggressor"


# ---------------------------------------------------------------------------
# End-to-end saturation (real threads; generous bounds for slow CI)
# ---------------------------------------------------------------------------


def test_flood_two_groups_light_group_does_not_starve():
    """Flood 'heavy' with far more work than the pool; sparse 'light'
    queries must keep being scheduled promptly (the priority the token
    decay exists to provide) and the heavy flood must still progress."""
    sched = TokenBucketScheduler(num_workers=4)
    try:
        heavy_waits, light_waits = [], []
        heavy_futs = []

        def work(waits, t_submit, dur):
            def fn():
                waits.append(time.monotonic() - t_submit)
                time.sleep(dur)
                return True
            return fn

        for _ in range(80):
            heavy_futs.append(sched.submit(
                "heavy", work(heavy_waits, time.monotonic(), 0.01)))
        light_futs = []
        for _ in range(10):
            light_futs.append(sched.submit(
                "light", work(light_waits, time.monotonic(), 0.002)))
            time.sleep(0.02)
        for f in light_futs:
            assert f.result(timeout=10) is True
        # starvation bound: every light query scheduled well before the
        # heavy backlog (80 x 10ms over <=2 effective workers ~ 0.4s+)
        # could possibly drain
        assert max(light_waits) < 0.35, f"light waits: {light_waits}"
        light_p99 = float(np.percentile(light_waits, 99))
        assert light_p99 < 0.3
        for f in heavy_futs:
            assert f.result(timeout=30) is True
        # heavy saw real queueing (saturation actually happened)
        assert max(heavy_waits) > 3 * max(light_waits)
        stats = {s["name"]: s for s in sched.group_stats()}
        assert stats["heavy"]["numPending"] == 0
        assert stats["light"]["availableTokens"] >= \
            stats["heavy"]["availableTokens"] - 50
    finally:
        sched.shutdown()


def test_saturated_group_rejects_past_capacity():
    policy = ResourceLimitPolicy(2, max_threads_per_group_pct=0.5,
                                 max_pending_per_group=4)
    sched = TokenBucketScheduler(num_workers=2, policy=policy)
    try:
        gate = threading.Event()
        futs = [sched.submit("g", lambda: (gate.wait(5), True)[-1])
                for _ in range(12)]
        deadline = time.monotonic() + 5
        rejected = 0
        while time.monotonic() < deadline and rejected == 0:
            f = sched.submit("g", lambda: True)
            if f.done() and f.exception() is not None:
                assert isinstance(f.exception(),
                                  SchedulerOutOfCapacityError)
                rejected += 1
            time.sleep(0.01)
        assert rejected, "no OutOfCapacity under a full queue"
        gate.set()
        done = sum(1 for f in futs
                   if f.exception(timeout=10) is None and f.result() is True)
        assert done >= 4            # accepted ones complete after release
    finally:
        sched.shutdown()


def test_shutdown_fails_pending():
    sched = TokenBucketScheduler(num_workers=1)
    gate = threading.Event()
    futs = [sched.submit("g", lambda: gate.wait(5)) for _ in range(6)]
    sched.shutdown()
    gate.set()
    failed = sum(1 for f in futs
                 if f.exception(timeout=5) is not None)
    assert failed >= 1              # drained queries carry the error


def test_make_scheduler_tokenbucket_roundtrip():
    s = make_scheduler("tokenbucket", 2)
    try:
        assert isinstance(s, TokenBucketScheduler)
        assert s.submit("t", lambda: 41 + 1).result(timeout=5) == 42
        assert s.group_stats()[0]["name"] == "t"
    finally:
        s.shutdown()
