"""Golden-value query tests: engine vs independent numpy oracle.

The BaseQueriesTest pattern (reference:
pinot-core/src/test/.../queries/BaseQueriesTest.java) — real segments, real
plan maker + executor + broker reduce, no cluster machinery; results checked
against an oracle computed from the raw input arrays.
"""
import math
import tempfile

import numpy as np
import pytest

from fixtures import build_segment, make_columns
from oracle import Oracle

from pinot_tpu.engine import QueryEngine

N = 10_000


@pytest.fixture(scope="module")
def setup():
    tmp = tempfile.mkdtemp()
    segment, cols = build_segment(tmp, n=N, seed=7)
    engine = QueryEngine([segment])
    host_engine = QueryEngine([segment], use_device=False)
    return engine, host_engine, Oracle(cols)


def agg_value(resp, i=0):
    return resp.aggregation_results[i].value


def both_engines(setup):
    engine, host_engine, oracle = setup
    return [(engine, "device"), (host_engine, "host")], oracle


# ---------------------------------------------------------------------------


def test_count_star_no_filter(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats")
        assert agg_value(resp) == str(N), label
        assert resp.total_docs == N


def test_count_with_range_filter(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["yearID"] > 2000)
    for e, label in engines:
        resp = e.query(
            "SELECT COUNT(*) FROM baseballStats WHERE yearID > 2000")
        assert agg_value(resp) == str(oracle.count(m)), label
        assert resp.num_docs_scanned == oracle.count(m)


def test_sum_min_max_avg_with_eq_filter(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] == "BOS")
    for e, label in engines:
        resp = e.query("SELECT SUM(runs), MIN(runs), MAX(runs), AVG(runs)"
                       " FROM baseballStats WHERE teamID = 'BOS'")
        assert float(agg_value(resp, 0)) == pytest.approx(
            oracle.sum("runs", m)), label
        assert float(agg_value(resp, 1)) == oracle.min("runs", m), label
        assert float(agg_value(resp, 2)) == oracle.max("runs", m), label
        assert float(agg_value(resp, 3)) == pytest.approx(
            oracle.avg("runs", m)), label


def test_compound_and_or_filter(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: (r["yearID"] >= 1995 and r["yearID"] < 2005 and
                               (r["teamID"] == "NYA" or r["teamID"] == "BOS"
                                or r["league"] == "NL")))
    q = ("SELECT COUNT(*), SUM(hits) FROM baseballStats WHERE "
         "yearID >= 1995 AND yearID < 2005 AND "
         "(teamID = 'NYA' OR teamID = 'BOS' OR league = 'NL')")
    for e, label in engines:
        resp = e.query(q)
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.sum("hits", m)), label


def test_in_and_not_in(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] in ("NYA", "BOS", "DET"))
    m2 = oracle.mask(lambda r: r["teamID"] not in ("NYA", "BOS", "DET"))
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats WHERE teamID IN "
                       "('NYA', 'BOS', 'DET')")
        assert agg_value(resp) == str(oracle.count(m)), label
        resp = e.query("SELECT COUNT(*) FROM baseballStats WHERE teamID "
                       "NOT IN ('NYA', 'BOS', 'DET')")
        assert agg_value(resp) == str(oracle.count(m2)), label


def test_between_and_float_range(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: 0.2 <= r["average"] <= 0.35)
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), AVG(average) FROM baseballStats "
                       "WHERE average BETWEEN 0.2 AND 0.35")
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.avg("average", m), rel=1e-9), label


def test_no_dictionary_column_filter_and_agg(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["salary"] > 500_000)
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), SUM(salary), MAX(salary) FROM "
                       "baseballStats WHERE salary > 500000")
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.sum("salary", m), rel=1e-6), label
        assert float(agg_value(resp, 2)) == pytest.approx(
            oracle.max("salary", m), rel=1e-6), label


def test_eq_absent_value_empty_result(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats WHERE "
            "teamID = 'ZZZ'")
        assert agg_value(resp, 0) == "0", label
        assert resp.num_docs_scanned == 0


def test_neq_and_regexp(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] != "NYA")
    for e, label in engines:
        resp = e.query(
            "SELECT COUNT(*) FROM baseballStats WHERE teamID <> 'NYA'")
        assert agg_value(resp) == str(oracle.count(m)), label
    m2 = oracle.mask(lambda r: r["playerName"].endswith("7"))
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats WHERE "
                       "REGEXP_LIKE(playerName, '7$')")
        assert agg_value(resp) == str(oracle.count(m2)), label


def test_distinctcount_and_percentile(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["league"] == "AL")
    for e, label in engines:
        resp = e.query("SELECT DISTINCTCOUNT(playerName), PERCENTILE50(runs),"
                       " PERCENTILE95(hits) FROM baseballStats WHERE "
                       "league = 'AL'")
        assert int(agg_value(resp, 0)) == oracle.distinctcount(
            "playerName", m), label
        assert float(agg_value(resp, 1)) == oracle.percentile(
            "runs", m, 50), label
        assert float(agg_value(resp, 2)) == oracle.percentile(
            "hits", m, 95), label


def test_minmaxrange(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] == "SEA")
    for e, label in engines:
        resp = e.query("SELECT MINMAXRANGE(hits) FROM baseballStats WHERE "
                       "teamID = 'SEA'")
        assert float(agg_value(resp)) == oracle.minmaxrange("hits", m), label


def test_mv_filter_and_aggs(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: "SS" in r["position"])
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), SUM(runs) FROM baseballStats "
                       "WHERE position = 'SS'")
        assert agg_value(resp, 0) == str(oracle.count(m)), label
        assert float(agg_value(resp, 1)) == pytest.approx(
            oracle.sum("runs", m)), label
    # distinct positions among AL docs
    m2 = oracle.mask(lambda r: r["league"] == "AL")
    for e, label in engines:
        resp = e.query("SELECT DISTINCTCOUNT(position) FROM baseballStats "
                       "WHERE league = 'AL'")
        assert int(agg_value(resp)) == oracle.distinctcount(
            "position", m2), label


def test_group_by_sum(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["yearID"] >= 2010)
    expected = oracle.group_by(["teamID"], m, ("sum", "runs"))
    for e, label in engines:
        resp = e.query("SELECT SUM(runs) FROM baseballStats WHERE "
                       "yearID >= 2010 GROUP BY teamID TOP 1000")
        got = {tuple(g["group"]): float(g["value"])
               for g in resp.aggregation_results[0].group_by_result}
        assert set(got.keys()) == {(k[0],) for k in expected}, label
        for k, v in expected.items():
            assert got[(k[0],)] == pytest.approx(v), (label, k)


def test_group_by_two_dims_multiple_aggs(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: True)
    exp_count = oracle.group_by(["teamID", "league"], m, ("count", None))
    exp_avg = oracle.group_by(["teamID", "league"], m, ("avg", "hits"))
    for e, label in engines:
        resp = e.query("SELECT COUNT(*), AVG(hits) FROM baseballStats "
                       "GROUP BY teamID, league TOP 1000")
        got_count = {tuple(g["group"]): int(g["value"])
                     for g in resp.aggregation_results[0].group_by_result}
        got_avg = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[1].group_by_result}
        assert got_count == {k: v for k, v in exp_count.items()}, label
        for k, v in exp_avg.items():
            assert got_avg[k] == pytest.approx(v), (label, k)


def test_group_by_top_n_ordering(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: True)
    expected = oracle.group_by(["teamID"], m, ("sum", "hits"))
    top3 = sorted(expected.items(), key=lambda kv: -kv[1])[:3]
    for e, label in engines:
        resp = e.query(
            "SELECT SUM(hits) FROM baseballStats GROUP BY teamID TOP 3")
        got = resp.aggregation_results[0].group_by_result
        assert len(got) == 3, label
        for (key, val), g in zip(top3, got):
            assert g["group"] == [key[0]], label
            assert float(g["value"]) == pytest.approx(val), label


def test_group_by_having(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: True)
    counts = oracle.group_by(["teamID"], m, ("count", None))
    keep = {k for k, v in counts.items() if v > 640}
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats GROUP BY teamID "
                       "HAVING COUNT(*) > 640 TOP 100")
        got = {tuple(g["group"]) for g in
               resp.aggregation_results[0].group_by_result}
        assert got == keep, label


def test_selection_limit(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT teamID, runs, yearID FROM baseballStats "
                       "WHERE teamID = 'NYA' LIMIT 7")
        rows = resp.selection_results.results
        assert len(rows) == 7, label
        for row in rows:
            assert row[0] == "NYA", label
        assert resp.selection_results.columns == ["teamID", "runs", "yearID"]


def test_selection_order_by(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["teamID"] == "OAK")
    hits = np.sort(oracle.vals("hits", m))[::-1][:5]
    for e, label in engines:
        resp = e.query("SELECT hits FROM baseballStats WHERE teamID = 'OAK' "
                       "ORDER BY hits DESC LIMIT 5")
        got = [int(r[0]) for r in resp.selection_results.results]
        assert got == [int(h) for h in hits], label


def test_selection_star_and_mv_decode(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT * FROM baseballStats LIMIT 3")
        rows = resp.selection_results.results
        assert len(rows) == 3, label
        cols = resp.selection_results.columns
        pos_idx = cols.index("position")
        team_idx = cols.index("teamID")
        for i, row in enumerate(rows):
            assert row[team_idx] == setup[2].cols["teamID"][i], label
            assert row[pos_idx] == setup[2].cols["position"][i], label


def test_empty_segment_level_results_merge(setup):
    engines, oracle = both_engines(setup)
    for e, label in engines:
        resp = e.query("SELECT MIN(runs), MAX(runs) FROM baseballStats "
                       "WHERE yearID > 9999")
        assert agg_value(resp, 0) == "Infinity", label
        assert agg_value(resp, 1) == "-Infinity", label


# ---------------------------------------------------------------------------
# MV group-by + valuein (reference: DefaultGroupByExecutor.aggregateGroupByMV,
# ValueInTransformFunction)
# ---------------------------------------------------------------------------


def _mv_group_oracle(cols, mask=None, gmv="position", sv=None, metric=None):
    """COUNT (and optional SUM(metric)) per MV value (x optional SV key)."""
    out = {}
    for i, lst in enumerate(cols[gmv]):
        if mask is not None and not mask[i]:
            continue
        for v in lst:
            k = (v,) if sv is None else (v, cols[sv][i])
            e = out.setdefault(k, [0, 0.0])
            e[0] += 1
            if metric is not None:
                e[1] += float(cols[metric][i])
    return out


def test_mv_group_by_count(setup):
    engines, oracle = both_engines(setup)
    exp = _mv_group_oracle(oracle.cols)
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats "
                       "GROUP BY position TOP 1000")
        got = {tuple(g["group"]): int(float(g["value"]))
               for g in resp.aggregation_results[0].group_by_result}
        assert got == {k: v[0] for k, v in exp.items()}, label


def test_mv_group_by_with_sv_key_and_sum(setup):
    engines, oracle = both_engines(setup)
    m = oracle.mask(lambda r: r["yearID"] >= 1990)
    exp = _mv_group_oracle(oracle.cols, mask=m, sv="league", metric="hits")
    for e, label in engines:
        resp = e.query("SELECT SUM(hits), COUNT(*) FROM baseballStats "
                       "WHERE yearID >= 1990 GROUP BY position, league "
                       "TOP 1000")
        got_sum = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[0].group_by_result}
        got_cnt = {tuple(g["group"]): int(float(g["value"]))
                   for g in resp.aggregation_results[1].group_by_result}
        assert got_cnt == {k: v[0] for k, v in exp.items()}, label
        assert got_sum == {k: v[1] for k, v in exp.items()}, label


def test_valuein_group_key_and_countmv(setup):
    engines, oracle = both_engines(setup)
    full = _mv_group_oracle(oracle.cols)
    keep = {("P",), ("C",), ("SS",)}
    for e, label in engines:
        resp = e.query("SELECT COUNT(*) FROM baseballStats "
                       "GROUP BY valuein(position, 'P', 'C', 'SS') TOP 100")
        got = {tuple(g["group"]): int(float(g["value"]))
               for g in resp.aggregation_results[0].group_by_result}
        assert got == {k: v[0] for k, v in full.items() if k in keep}, label
        # non-grouped COUNTMV over the restricted value set
        resp2 = e.query("SELECT COUNTMV(valuein(position, 'P', 'C', 'SS')) "
                        "FROM baseballStats")
        exp_entries = sum(v[0] for k, v in full.items() if k in keep)
        assert int(float(agg_value(resp2))) == exp_entries, label


def test_duplicate_mv_column_group_keys(setup):
    """GROUP BY col, valuein(col, ...) over the SAME MV column: each key
    position is an independent axis of the entry cross-product (a doc
    with positions [P, C] contributes (P,P), (P,C), (C,P), (C,C) before
    the valuein restriction), matching the reference's sequential
    per-key expansion (DefaultGroupByExecutor.aggregateGroupByMV).
    Round-2 advisor finding: the device expansion used to key entry
    indexes by column NAME, collapsing the two axes to the diagonal."""
    engines, oracle = both_engines(setup)
    keep = {"P", "C", "SS"}
    exp = {}
    for lst in oracle.cols["position"]:
        for v1 in lst:
            for v2 in lst:
                if v2 in keep:
                    exp[(v1, v2)] = exp.get((v1, v2), 0) + 1
    for e, label in engines:
        resp = e.query(
            "SELECT COUNT(*) FROM baseballStats "
            "GROUP BY position, valuein(position, 'P', 'C', 'SS') "
            "TOP 1000")
        got = {tuple(g["group"]): int(float(g["value"]))
               for g in resp.aggregation_results[0].group_by_result}
        assert got == exp, label


def test_countmv_inside_group_by(setup):
    engines, oracle = both_engines(setup)
    # COUNTMV(position) grouped by league: entries per league
    exp = {}
    for i, lst in enumerate(oracle.cols["position"]):
        k = (oracle.cols["league"][i],)
        exp[k] = exp.get(k, 0) + len(lst)
    for e, label in engines:
        resp = e.query("SELECT COUNTMV(position) FROM baseballStats "
                       "GROUP BY league TOP 100")
        got = {tuple(g["group"]): int(float(g["value"]))
               for g in resp.aggregation_results[0].group_by_result}
        assert got == exp, label


def test_mv_metric_sum_in_group_by(tmp_path):
    """Numeric MV aggregation argument inside a group-by (SUMMV parity:
    each (doc, entry) contributes to the doc's group)."""
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import Schema, dimension, metric
    from pinot_tpu.common.schema import FieldSpec, FieldType
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    rng = np.random.default_rng(3)
    n = 800
    schema = Schema("mv", [dimension("k", DataType.STRING),
                           FieldSpec("scores", DataType.INT,
                                     FieldType.DIMENSION,
                                     single_value=False),
                           metric("v", DataType.INT)])
    keys = np.array(["a", "b", "c"], dtype=object)
    kcol = keys[rng.integers(0, 3, n)]
    scores = [list(rng.integers(0, 50, rng.integers(1, 4)))
              for _ in range(n)]
    cols = {"k": kcol, "scores": scores,
            "v": rng.integers(0, 100, n).astype(np.int32)}
    d = str(tmp_path / "seg")
    SegmentCreator(schema, None, segment_name="mv0").build(cols, d)
    seg = ImmutableSegmentLoader.load(d)
    exp = {}
    for k, lst in zip(kcol, scores):
        e = exp.setdefault((k,), [0.0, 0])
        e[0] += float(sum(lst))
        e[1] += len(lst)
    for use_device in (True, False):
        eng = QueryEngine([seg], use_device=use_device)
        resp = eng.query("SELECT SUMMV(scores), COUNTMV(scores) FROM mv "
                         "GROUP BY k TOP 10")
        assert not resp.exceptions, resp.exceptions
        got_sum = {tuple(g["group"]): float(g["value"])
                   for g in resp.aggregation_results[0].group_by_result}
        got_cnt = {tuple(g["group"]): int(float(g["value"]))
                   for g in resp.aggregation_results[1].group_by_result}
        assert got_sum == {k: v[0] for k, v in exp.items()}
        assert got_cnt == {k: v[1] for k, v in exp.items()}
