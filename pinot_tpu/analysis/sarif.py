"""SARIF 2.1.0 export: tpulint findings as CI annotations.

One run, one tool (`tpulint`), every rule that is registered, every
finding that the analysis produced — including suppressed ones (SARIF
`suppressions`, kind `inSource`) and the grandfathered/new split
(SARIF `baselineState`: `unchanged` vs `new`), so a CI viewer renders
exactly the gate's verdict and nothing is lost in translation. The
round-trip contract (tested): rule id, file, line, message, suppression
state, and baseline state all survive `to_sarif` -> JSON -> parse.
"""
from __future__ import annotations

import json
from typing import Dict, List

from pinot_tpu.analysis.core import Finding, all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _result(f: Finding, baseline_state: str, suppressed: bool) -> dict:
    out = {
        "ruleId": f.rule,
        "level": "error" if baseline_state == "new" and not suppressed
                 else "note",
        "baselineState": baseline_state,
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line},
            },
        }],
    }
    if suppressed:
        out["suppressions"] = [{"kind": "inSource"}]
    return out


def to_sarif(findings: List[Finding], suppressed: List[Finding],
             baseline: Dict[str, int]) -> dict:
    """`baseline` is the committed grandfather map (key -> count): per
    key the first N occurrences are `unchanged`, the rest `new` — the
    exact split the gate enforces."""
    rules = [{"id": rid,
              "shortDescription": {"text": rule.description},
              "properties": {"tier": rule.tier}}
             for rid, rule in sorted(all_rules().items())]
    seen: Dict[str, int] = {}
    results = []
    for f in sorted(findings):
        n = seen.get(f.key(), 0)
        seen[f.key()] = n + 1
        state = "unchanged" if n < baseline.get(f.key(), 0) else "new"
        results.append(_result(f, state, suppressed=False))
    for f in sorted(suppressed):
        results.append(_result(f, "unchanged", suppressed=True))
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri":
                    "docs/ANALYSIS.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def write_sarif(path: str, findings: List[Finding],
                suppressed: List[Finding],
                baseline: Dict[str, int]) -> dict:
    doc = to_sarif(findings, suppressed, baseline)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc


def parse_sarif(doc: dict) -> List[dict]:
    """Flatten a SARIF doc back to comparable finding dicts (the
    round-trip test's other half)."""
    out = []
    for run in doc.get("runs", []):
        for res in run.get("results", []):
            loc = res["locations"][0]["physicalLocation"]
            out.append({
                "rule": res["ruleId"],
                "path": loc["artifactLocation"]["uri"],
                "line": loc["region"]["startLine"],
                "message": res["message"]["text"],
                "baselineState": res.get("baselineState"),
                "suppressed": bool(res.get("suppressions")),
            })
    return out
