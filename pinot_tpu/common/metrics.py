"""Metrics registry: meters, gauges, and phase timers.

Parity: pinot-common/.../metrics/AbstractMetrics.java (typed
addMeteredTableValue / setValueOfTableGauge / addPhaseTiming over a yammer
MetricsRegistry) and the per-component subclasses BrokerMetrics /
ServerMetrics / ControllerMetrics with their Meter/Gauge/Timer enums
(BrokerMeter.java, BrokerQueryPhase.java, ServerMeter.java,
ServerQueryPhase.java). We keep one thread-safe registry per component;
metric names are plain strings (optionally suffixed with a table name the
way the reference's table-level metrics are), and timers keep a bounded
reservoir for percentiles instead of an exponentially-decaying sample.
"""
from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class Meter:
    """Monotonic event counter with a lifetime rate."""

    def __init__(self) -> None:
        self._count = 0
        self._t0 = time.monotonic()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    @property
    def count(self) -> int:
        return self._count

    def rate(self) -> float:
        """Events per second since the meter was created."""
        dt = time.monotonic() - self._t0
        return self._count / dt if dt > 0 else 0.0


class Gauge:
    """Last-value (or callable-backed) instantaneous metric."""

    def __init__(self) -> None:
        self._value: float = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        self._fn = None
        self._value = float(value)

    def set_callable(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        return self._value


class Timer:
    """Duration metric: count, total, mean, reservoir percentiles, and
    bounded log-scale histogram buckets (Prometheus exposition)."""

    RESERVOIR = 1024
    # log-scale millisecond bucket upper bounds: 0.25ms … ~131s in ×2
    # steps (20 buckets + overflow). Bounded and fixed, so exposition
    # output size and update cost are O(1) regardless of traffic.
    BUCKET_BOUNDS_MS: Tuple[float, ...] = tuple(
        0.25 * 2 ** i for i in range(20))

    def __init__(self) -> None:
        self._count = 0
        self._total_ms = 0.0
        self._samples: deque = deque(maxlen=self.RESERVOIR)
        self._buckets = [0] * (len(self.BUCKET_BOUNDS_MS) + 1)
        # percentile memo per requested tuple: ps -> (count at compute
        # time, values); a snapshot with no new updates since the last
        # one never re-runs np.percentile, and the hedge path's p95
        # probe doesn't thrash the snapshot's (50, 95, 99) entry
        self._pct_cache: Dict[Tuple[float, ...],
                              Tuple[int, List[float]]] = {}
        self._lock = threading.Lock()

    def update(self, ms: float) -> None:
        idx = bisect.bisect_left(self.BUCKET_BOUNDS_MS, ms)
        with self._lock:
            self._count += 1
            self._total_ms += ms
            self._samples.append(ms)
            self._buckets[idx] += 1

    @contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.update((time.perf_counter() - t0) * 1e3)

    @property
    def count(self) -> int:
        return self._count

    @property
    def total_ms(self) -> float:
        return self._total_ms

    @property
    def mean_ms(self) -> float:
        return self._total_ms / self._count if self._count else 0.0

    def percentile_ms(self, p: float) -> float:
        return self.percentiles_ms((p,))[0]

    def percentiles_ms(self, ps: Sequence[float]) -> List[float]:
        """All requested percentiles in ONE np.percentile batch,
        memoized on the sample count — repeated snapshot()/exposition
        reads between updates cost a dict lookup, not an array sort."""
        ps = tuple(ps)
        with self._lock:
            hit = self._pct_cache.get(ps)
            if hit is not None and hit[0] == self._count:
                return list(hit[1])
            if not self._samples:
                return [0.0] * len(ps)
            vals = [float(v) for v in
                    np.percentile(np.asarray(self._samples), ps)]
            if len(self._pct_cache) > 8:     # bounded: ps tuples are few
                self._pct_cache.clear()
            self._pct_cache[ps] = (self._count, vals)
            return list(vals)

    def bucket_counts(self) -> List[int]:
        """Per-bucket (non-cumulative) counts; the last entry is the
        overflow bucket (> BUCKET_BOUNDS_MS[-1])."""
        with self._lock:
            return list(self._buckets)


class MetricsRegistry:
    """One component's metric namespace (broker / server / controller)."""

    def __init__(self, component: str = ""):
        self.component = component
        self._meters: Dict[str, Meter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def meter(self, name: str, table: Optional[str] = None) -> Meter:
        return self._get(self._meters, Meter, name, table)

    def gauge(self, name: str, table: Optional[str] = None) -> Gauge:
        return self._get(self._gauges, Gauge, name, table)

    def timer(self, name: str, table: Optional[str] = None) -> Timer:
        return self._get(self._timers, Timer, name, table)

    def peek_timer(self, name: str,
                   table: Optional[str] = None) -> Optional[Timer]:
        """Read-only lookup that never registers a series — for probes
        keyed on unvalidated strings (e.g. request table names), where
        get-or-create would grow the registry without bound."""
        key = f"{table}.{name}" if table else name
        with self._lock:
            return self._timers.get(key)

    def _get(self, store, cls, name: str, table: Optional[str]):
        key = f"{table}.{name}" if table else name
        with self._lock:
            m = store.get(key)
            if m is None:
                m = store[key] = cls()
            return m

    SNAPSHOT_PERCENTILES = (50.0, 95.0, 99.0)

    def metric_maps(self) -> Tuple[Dict[str, Meter], Dict[str, Gauge],
                                   Dict[str, Timer]]:
        """Consistent shallow copies of the three metric maps (the
        Prometheus exposition renderer iterates these)."""
        with self._lock:
            return dict(self._meters), dict(self._gauges), \
                dict(self._timers)

    def snapshot(self) -> dict:
        """Flat JSON-able view of every registered metric.

        Timer percentiles are computed in one memoized np.percentile
        batch per timer (keyed on the update count), and the bounded
        log-scale histogram rides along as [upperBoundMs, count] pairs
        (None bound = overflow bucket)."""
        meters, gauges, timers = self.metric_maps()
        out: Dict[str, object] = {}
        for k, m in meters.items():
            out[f"meter.{k}.count"] = m.count
        for k, g in gauges.items():
            out[f"gauge.{k}"] = g.value
        bounds = list(Timer.BUCKET_BOUNDS_MS) + [None]
        for k, t in timers.items():
            out[f"timer.{k}.count"] = t.count
            out[f"timer.{k}.totalMs"] = round(t.total_ms, 3)
            out[f"timer.{k}.meanMs"] = round(t.mean_ms, 3)
            p50, p95, p99 = t.percentiles_ms(self.SNAPSHOT_PERCENTILES)
            out[f"timer.{k}.p50Ms"] = round(p50, 3)
            out[f"timer.{k}.p95Ms"] = round(p95, 3)
            out[f"timer.{k}.p99Ms"] = round(p99, 3)
            out[f"timer.{k}.buckets"] = [
                [bound, n] for bound, n in zip(bounds, t.bucket_counts())
                if n]
        return out


# -- metric name constants (parity: the reference's metric enums) ------------

class CommonGauge:
    # process-wide HBM residency metering (obs/residency.py ledger);
    # exposed by EVERY component with the kind (and per-table) label
    # riding the table-suffix convention as "<table>|<kind>"
    DEVICE_BYTES_RESIDENT = "deviceBytesResident"


class BrokerMeter:
    QUERIES = "queries"
    REQUEST_COMPILATION_EXCEPTIONS = "requestCompilationExceptions"
    RESOURCE_MISSING_EXCEPTIONS = "resourceMissingExceptions"
    QUERY_QUOTA_EXCEEDED = "queryQuotaExceeded"
    NO_SERVER_FOUND_EXCEPTIONS = "noServerFoundExceptions"
    REQUEST_DROPPED_DUE_TO_ACCESS_ERROR = "requestDroppedDueToAccessError"
    BROKER_RESPONSES_WITH_PARTIAL_SERVERS = "brokerResponsesWithPartialServers"
    DOCUMENTS_SCANNED = "documentsScanned"
    # fault-tolerance layer (global and per-server via the table suffix)
    SERVER_ERRORS = "serverErrors"
    HEDGED_REQUESTS = "hedgedRequests"
    SEGMENT_RETRIES = "segmentRetries"
    # ingress control: queries rejected at the broker, per cause via the
    # table suffix ("tableQuota" | "tenantQuota" | "serverBusy")
    QUERIES_DROPPED = "queriesDropped"
    # per-dispatch server-busy replies observed (per shed cause via the
    # table suffix) — distinct from QUERIES_DROPPED, which counts whole
    # queries the client lost; a busy reply recovered by failover is
    # telemetry only
    SERVER_BUSY_RESPONSES = "serverBusyResponses"
    # broker-level result cache (hybrid tables, freshness-bounded)
    RESULT_CACHE_HITS = "resultCacheHits"
    RESULT_CACHE_MISSES = "resultCacheMisses"
    # per-hop serde accounting: bytes of server reply payloads decoded
    # at the broker (pairs with the serverResponseDeserialization timer
    # so PROFILE artifacts can attribute serde separately from
    # transport) and bytes of InstanceRequest payloads sent
    SERVER_RESPONSE_BYTES = "serverResponseBytes"
    INSTANCE_REQUEST_BYTES = "instanceRequestBytes"


class BrokerGauge:
    # per-server (table-suffixed) fault-tolerance observability
    SERVER_HEALTH = "serverHealth"          # EWMA success score in [0, 1]
    BREAKER_STATE = "breakerState"          # 0 closed / 1 half-open / 2 open
    # seconds since the handler booted (exposition liveness probe)
    UPTIME_SECONDS = "uptimeSeconds"


class BrokerTimer:
    # per-server (table-suffixed) request latency; drives the hedge
    # threshold (p95-based) in broker/fault_tolerance.py
    SERVER_LATENCY = "serverLatency"


class BrokerQueryPhase:
    REQUEST_COMPILATION = "requestCompilation"
    AUTHORIZATION = "authorization"
    QUERY_ROUTING = "queryRouting"
    SCATTER_GATHER = "scatterGather"
    # DataTable decode of one server reply (a slice of scatterGather:
    # the serde share of the gather, metered per dispatch)
    SERVER_RESPONSE_DESERIALIZATION = "serverResponseDeserialization"
    REDUCE = "reduce"
    QUERY_TOTAL = "queryTotal"


class ServerMeter:
    QUERIES = "queries"
    QUERY_EXECUTION_EXCEPTIONS = "queryExecutionExceptions"
    DELETED_SEGMENT_COUNT = "deletedSegmentCount"
    REALTIME_ROWS_CONSUMED = "realtimeRowsConsumed"
    # queries dropped (or truncated) because the broker-propagated
    # deadline had already expired — work nobody would read
    DEADLINE_EXPIRED_QUERIES = "deadlineExpiredQueries"
    # segment integrity / cold-start recovery
    SEGMENT_DOWNLOADS = "segmentDownloads"
    SEGMENT_LOCAL_RELOADS = "segmentLocalReloads"
    SEGMENT_CRC_MISMATCHES = "segmentCrcMismatches"
    # primary-key upsert: rows that superseded an existing key / docs
    # invalidated in validDocIds bitmaps
    UPSERTED_ROWS = "upsertedRows"
    MASKED_DOCS = "maskedDocs"
    # admission control: requests shed before execution (per cause via
    # the table suffix: "overload" | "hedge" | "tenantOverQuota" |
    # "deadline" | "capacity") and requests admitted in brownout mode
    # (degraded deadline → flagged-partial results)
    REQUESTS_SHED = "requestsShed"
    BROWNOUT_QUERIES = "brownoutQueries"
    # server-side CRC-exact result cache
    RESULT_CACHE_HITS = "resultCacheHits"
    RESULT_CACHE_MISSES = "resultCacheMisses"
    # per-hop serde accounting: request payload bytes deserialized and
    # reply payload bytes serialized (the responseSerialization /
    # requestDeserialization timers' byte-volume counterparts)
    REQUEST_BYTES = "requestBytes"
    RESPONSE_BYTES = "responseBytes"
    # upsert maintenance: committed segments whose compacted rewrite was
    # remapped into the key map at swap, and key-map entries dropped
    # when a retention-deleted segment's keys were garbage-collected
    UPSERT_SEGMENTS_REMAPPED = "upsertSegmentsRemapped"
    UPSERT_KEYS_GCED = "upsertKeysGced"
    # tiered residency (server/residency_manager.py): segments promoted
    # back to HBM, segments demoted under budget pressure (per target
    # tier via the table suffix: "host" | "disk"), and queries that hit
    # a disk-tier segment and paid the artifact reload
    RESIDENCY_PROMOTIONS = "residencyPromotions"
    RESIDENCY_DEMOTIONS = "residencyDemotions"
    RESIDENCY_COLD_HITS = "residencyColdHits"
    # cross-query dispatch coalescing: kernel executions that served
    # more than one query, and queries that skipped the batching window
    # (budget too tight to survive it)
    BATCHED_DISPATCHES = "batchedDispatches"
    BATCH_BYPASS = "batchBypass"
    # single-flight result-cache dedup: identical concurrent queries
    # that waited on the leader's execution instead of their own
    SINGLE_FLIGHT_WAITS = "singleFlightWaits"
    # IVF ANN vector search: queries that requested probing (nprobe>0).
    # The probe-vs-exact-fallback split per segment rides the obs
    # profiler's path counters ("ivfProbe" / "ivfExactFallback")
    IVF_NPROBE_QUERIES = "ivfNprobeQueries"


class ServerTimer:
    # queries served per sealed batch window (a Timer so the occupancy
    # DISTRIBUTION rides the existing histogram/percentile machinery;
    # the "ms" unit suffix in the exposition reads as "queries")
    BATCH_OCCUPANCY = "batchOccupancy"


class ControllerMeter:
    # integrity scrubber (SegmentIntegrityChecker)
    CORRUPT_SEGMENTS = "corruptSegmentArtifacts"
    ORPHAN_ARTIFACTS_DELETED = "orphanArtifactsDeleted"
    ERROR_REPLICAS_REPAIRED = "errorReplicasRepaired"
    # self-healing plane (ClusterHealthMonitor / SegmentRebalancer /
    # standby failover): replica moves applied by the rebalancer,
    # consuming partitions reassigned off dead servers, and leader-lease
    # takeovers from a different (dead or deposed) controller
    REBALANCE_MOVES = "rebalanceMoves"
    PARTITION_TAKEOVERS = "partitionTakeovers"
    LEADER_FAILOVERS = "leaderFailovers"
    # maintenance plane (SegmentSwapManager / RetentionManager /
    # SwapJanitor): crash-safe segment rewrites swapped in, expired
    # segments tombstoned by retention, interrupted swaps the janitor
    # resumed from their durable intent records, and delayed-delete
    # tombstones finally reclaimed after the grace window
    SEGMENTS_COMPACTED = "segmentsCompacted"
    SEGMENTS_MERGED = "segmentsMerged"
    RETENTION_SEGMENTS_DELETED = "retentionSegmentsDeleted"
    SWAPS_RESUMED = "swapsResumed"
    TOMBSTONES_DELETED = "tombstonesDeleted"


class MinionMeter:
    # task-queue hygiene: IN_PROGRESS claims whose lease expired (the
    # claiming minion died mid-task) requeued to GENERATED, and claims
    # that exhausted their attempt budget and went ERROR
    TASK_REQUEUES = "taskRequeues"
    TASK_ATTEMPTS_EXHAUSTED = "taskAttemptsExhausted"


class ControllerGauge:
    # Σ over segments of (replicas the config wants, capped at live
    # capacity) minus (ideal-state holders that are live) — 0 when the
    # cluster is fully repaired, >0 while self-healing is in progress
    CLUSTER_REPLICATION_DEFICIT = "clusterReplicationDeficit"
    # registered tables / schemas (cheap sanity series for dashboards)
    TABLE_COUNT = "tableCount"
    SCHEMA_COUNT = "schemaCount"


class ServerQueryPhase:
    REQUEST_DESERIALIZATION = "requestDeserialization"
    SCHEDULER_WAIT = "schedulerWait"
    SEGMENT_PRUNING = "segmentPruning"
    SEGMENT_EXECUTION = "segmentExecution"
    SHARDED_EXECUTION = "shardedExecute"
    BUILD_QUERY_PLAN = "buildQueryPlan"
    QUERY_PLAN_EXECUTION = "queryPlanExecution"
    QUERY_PROCESSING = "queryProcessing"
    RESPONSE_SERIALIZATION = "responseSerialization"


class ServerGauge:
    DOCUMENT_COUNT = "documentCount"
    SEGMENT_COUNT = "segmentCount"
    LLC_PARTITION_CONSUMING = "llcPartitionConsuming"
    UPSERT_KEY_MAP_SIZE = "upsertKeyMapSize"
    # admission control queue depth (submitted minus completed)
    ADMISSION_QUEUE_DEPTH = "admissionQueueDepth"
    # tiered residency: per-tier twins of deviceBytesResident (the
    # `|tier:<tier>` registry suffix renders as a `tier` label) plus
    # the count of segments hot enough for HBM but still waiting on a
    # promotion slot — the admission brownout watermark input
    RESIDENCY_TIER_BYTES = "residencyTierBytes"
    RESIDENCY_PROMOTION_BACKLOG = "residencyPromotionBacklog"
