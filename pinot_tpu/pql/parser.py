"""PQL recursive-descent parser → BrokerRequest.

Parity: org.apache.pinot.pql.parsers.Pql2Compiler.compileToBrokerRequest
(pinot-common/.../pql/parsers/Pql2Compiler.java:63-102) and the PQL2.g4
grammar: SELECT output list (columns or aggregation calls), FROM, WHERE
predicate tree (comparison / BETWEEN / IN / NOT IN / REGEXP_LIKE / IS NULL
with AND/OR nesting), GROUP BY, HAVING, ORDER BY, TOP, LIMIT.

Comparison predicates compile to the same FilterOperator encoding the
reference uses (Pql2AstNode → FilterQueryTree): ``=`` → EQUALITY, ``<>/!=`` →
NOT, ``< <= > >=`` → one-sided RANGE, BETWEEN → two-sided inclusive RANGE.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.request import (AggregationInfo, BrokerRequest,
                                      FilterOperator, FilterQueryTree, GroupBy,
                                      HavingNode, QueryOptions, Selection,
                                      SelectionSort, VectorSimilarity)
from pinot_tpu.pql.lexer import PqlSyntaxError, TokType, Token, tokenize

# Aggregation function names the engine recognizes (PERCENTILE variants are
# matched by prefix, e.g. PERCENTILE95 / PERCENTILETDIGEST99).
AGG_PREFIXES = (
    "COUNT", "SUM", "MIN", "MAX", "AVG", "MINMAXRANGE", "DISTINCTCOUNTHLL",
    "DISTINCTCOUNTRAWHLL", "DISTINCTCOUNT", "FASTHLL", "PERCENTILEEST",
    "PERCENTILETDIGEST", "PERCENTILE",
)
_MV_SUFFIX = "MV"


def is_aggregation_function(name: str) -> bool:
    up = name.upper()
    if up.endswith(_MV_SUFFIX):
        up = up[: -len(_MV_SUFFIX)]
    for p in sorted(AGG_PREFIXES, key=len, reverse=True):
        if up.startswith(p):
            rest = up[len(p):]
            return rest == "" or rest.isdigit()
    return False


class Pql2Compiler:
    """compile(pql) -> BrokerRequest."""

    def compile(self, pql: str) -> BrokerRequest:
        return _Parser(tokenize(pql), pql).parse_query()


def compile_pql(pql: str) -> BrokerRequest:
    return Pql2Compiler().compile(pql)


class _Parser:
    def __init__(self, toks: List[Token], text: str):
        self.toks = toks
        self.text = text
        self.i = 0

    # -- token plumbing ----------------------------------------------------
    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *words: str) -> bool:
        t = self.peek()
        if t.type == TokType.KEYWORD and t.upper == words[0]:
            # multi-word keyword like GROUP BY
            for k, w in enumerate(words):
                tk = self.toks[self.i + k]
                if not (tk.type == TokType.KEYWORD and tk.upper == w):
                    return False
            self.i += len(words)
            return True
        return False

    def expect_kw(self, *words: str):
        if not self.accept_kw(*words):
            raise PqlSyntaxError(
                f"expected {' '.join(words)} at {self.peek().pos} "
                f"(got {self.peek().value!r})")

    def expect(self, ttype: TokType) -> Token:
        t = self.next()
        if t.type != ttype:
            raise PqlSyntaxError(f"expected {ttype.value} at {t.pos}, "
                                 f"got {t.value!r}")
        return t

    # -- grammar -----------------------------------------------------------
    def parse_query(self) -> BrokerRequest:
        self.expect_kw("SELECT")
        select_items = self.parse_select_list()
        self.expect_kw("FROM")
        table = self.expect(TokType.IDENT).value

        filt = None
        if self.accept_kw("WHERE"):
            filt = self.parse_predicate()

        group_by_cols: List[str] = []
        if self.accept_kw("GROUP", "BY"):
            group_by_cols = self.parse_ident_list()

        having = None
        if self.accept_kw("HAVING"):
            having = self.parse_having()

        order_by: List[SelectionSort] = []
        if self.accept_kw("ORDER", "BY"):
            order_by = self.parse_order_list()

        top_n = None
        if self.accept_kw("TOP"):
            top_n = int(self.expect(TokType.INT).value)

        offset, size = 0, None
        if self.accept_kw("LIMIT"):
            first = int(self.expect(TokType.INT).value)
            if self.peek().type == TokType.COMMA:
                self.next()
                offset, size = first, int(self.expect(TokType.INT).value)
            elif self.accept_kw("OFFSET"):
                size, offset = first, int(self.expect(TokType.INT).value)
            else:
                size = first

        options = QueryOptions()
        if self.accept_kw("OPTION"):
            self.expect(TokType.LPAREN)
            while True:
                key = self.next().value
                self.expect(TokType.OP)  # '='
                val = self.next().value
                options.options[key] = val
                if key == "timeoutMs":
                    options.timeout_ms = int(val)
                elif key == "trace":
                    options.trace = str(val).lower() in ("true", "1")
                if self.peek().type == TokType.COMMA:
                    self.next()
                    continue
                break
            self.expect(TokType.RPAREN)

        if self.peek().type != TokType.EOF:
            raise PqlSyntaxError(
                f"trailing input at {self.peek().pos}: {self.peek().value!r}")

        # -- assemble ------------------------------------------------------
        aggs = [it for it in select_items if isinstance(it, AggregationInfo)]
        cols = [it for it in select_items if isinstance(it, str)]
        vecs = [it for it in select_items if isinstance(it, VectorSimilarity)]
        if aggs and cols:
            raise PqlSyntaxError(
                "cannot mix aggregations and plain columns in SELECT "
                "(use GROUP BY for grouped output)")

        req = BrokerRequest(table_name=table, filter=filt,
                            query_options=options)
        if vecs:
            if len(vecs) > 1:
                raise PqlSyntaxError(
                    "only one VECTOR_SIMILARITY clause per query")
            if aggs or group_by_cols or having is not None or order_by:
                raise PqlSyntaxError(
                    "VECTOR_SIMILARITY cannot mix with aggregations, "
                    "GROUP BY, HAVING or ORDER BY (results are ranked "
                    "by similarity score)")
            if "*" in cols:
                raise PqlSyntaxError(
                    "VECTOR_SIMILARITY with SELECT * is not supported — "
                    "name the ride-along columns explicitly")
            if top_n is not None or size is not None:
                raise PqlSyntaxError(
                    "VECTOR_SIMILARITY takes k as its third argument; "
                    "TOP/LIMIT do not apply")
            v = vecs[0]
            req.vector = v
            req.selection = Selection(columns=cols, order_by=[],
                                      offset=0, size=v.k)
            req.limit = v.k
            return req
        if aggs:
            req.aggregations = aggs
            if group_by_cols:
                req.group_by = GroupBy(columns=group_by_cols,
                                       top_n=top_n or size or 10)
            req.having = having
            req.limit = top_n or size or 10
        else:
            if group_by_cols:
                raise PqlSyntaxError("GROUP BY requires aggregations")
            req.selection = Selection(columns=cols or ["*"],
                                      order_by=order_by, offset=offset,
                                      size=size if size is not None else 10)
            req.limit = size if size is not None else 10
        return req

    def parse_select_list(self):
        items = []
        if self.peek().type == TokType.STAR:
            self.next()
            return ["*"]
        while True:
            items.append(self.parse_select_item())
            if self.peek().type == TokType.COMMA:
                self.next()
                continue
            return items

    def parse_select_item(self):
        t = self.peek()
        if t.type == TokType.IDENT and \
                self.toks[self.i + 1].type == TokType.LPAREN:
            if t.upper == "VECTOR_SIMILARITY":
                return self.parse_vector_call()
            if is_aggregation_function(t.value):
                return self.parse_agg_call()
        if t.type == TokType.IDENT:
            return self.next().value
        raise PqlSyntaxError(f"bad select item at {t.pos}: {t.value!r}")

    def parse_vector_call(self) -> VectorSimilarity:
        """VECTOR_SIMILARITY(col, [f, f, ...], k[, 'COSINE'|'DOT'|'MIPS'])."""
        self.next()                              # VECTOR_SIMILARITY
        self.expect(TokType.LPAREN)
        col = self.expect(TokType.IDENT).value
        self.expect(TokType.COMMA)
        self.expect(TokType.LBRACKET)
        q: List[float] = []
        while self.peek().type != TokType.RBRACKET:
            t = self.next()
            if t.type not in (TokType.INT, TokType.FLOAT):
                raise PqlSyntaxError(
                    f"expected a number in the query vector at {t.pos}, "
                    f"got {t.value!r}")
            q.append(float(t.value))
            if self.peek().type == TokType.COMMA:
                self.next()
        self.expect(TokType.RBRACKET)
        if not q:
            raise PqlSyntaxError("empty query vector")
        self.expect(TokType.COMMA)
        t = self.peek()
        k = int(self.expect(TokType.INT).value)
        if k <= 0:
            raise PqlSyntaxError(f"VECTOR_SIMILARITY k must be positive "
                                 f"at {t.pos}, got {k}")
        metric = "COSINE"
        if self.peek().type == TokType.COMMA:
            self.next()
            m = self.expect(TokType.STRING).value.upper()
            if m not in ("COSINE", "DOT", "MIPS"):
                raise PqlSyntaxError(
                    f"unknown similarity metric {m!r} "
                    "(COSINE | DOT | MIPS)")
            metric = m
        self.expect(TokType.RPAREN)
        return VectorSimilarity(column=col, query=q, k=k, metric=metric)

    def parse_agg_call(self) -> AggregationInfo:
        name = self.next().upper
        self.expect(TokType.LPAREN)
        if self.peek().type == TokType.STAR:
            self.next()
            col = "*"
        else:
            col = self.parse_column_or_expression()
        self.expect(TokType.RPAREN)
        return AggregationInfo(function_name=name, column=col)

    def parse_column_or_expression(self) -> str:
        """Plain column, or a transform call like time_convert(col,'D','H')
        — returned as a canonical expression string (parity:
        TransformExpressionTree's standardized column name)."""
        t = self.expect(TokType.IDENT)
        if self.peek().type != TokType.LPAREN or \
                not expr_mod.is_transform_function(t.value):
            return t.value
        return expr_mod.to_string(self._parse_expr_call(t.value))

    def _parse_expr_call(self, fname: str):
        self.expect(TokType.LPAREN)
        args = []
        if self.peek().type != TokType.RPAREN:
            args.append(self._parse_expr_arg())
            while self.peek().type == TokType.COMMA:
                self.next()
                args.append(self._parse_expr_arg())
        self.expect(TokType.RPAREN)
        return expr_mod.Call(fname.lower(), tuple(args))

    def _parse_expr_arg(self):
        t = self.next()
        if t.type == TokType.STRING:
            return expr_mod.Lit(t.value, is_string=True)
        if t.type in (TokType.INT, TokType.FLOAT):
            return expr_mod.Lit(t.value)
        if t.type == TokType.IDENT:
            if self.peek().type == TokType.LPAREN and \
                    expr_mod.is_transform_function(t.value):
                return self._parse_expr_call(t.value)
            return expr_mod.Col(t.value)
        raise PqlSyntaxError(
            f"bad expression argument at {t.pos}: {t.value!r}")

    def parse_ident_list(self) -> List[str]:
        out = [self.parse_column_or_expression()]
        while self.peek().type == TokType.COMMA:
            self.next()
            out.append(self.parse_column_or_expression())
        return out

    def parse_order_list(self) -> List[SelectionSort]:
        out = []
        while True:
            col = self.expect(TokType.IDENT).value
            asc = True
            if self.accept_kw("ASC"):
                asc = True
            elif self.accept_kw("DESC"):
                asc = False
            out.append(SelectionSort(column=col, ascending=asc))
            if self.peek().type == TokType.COMMA:
                self.next()
                continue
            return out

    # -- WHERE predicates --------------------------------------------------
    def parse_predicate(self) -> FilterQueryTree:
        return self.parse_or()

    def parse_or(self) -> FilterQueryTree:
        left = self.parse_and()
        children = [left]
        while self.accept_kw("OR"):
            children.append(self.parse_and())
        if len(children) == 1:
            return left
        return FilterQueryTree(FilterOperator.OR, children=children)

    def parse_and(self) -> FilterQueryTree:
        left = self.parse_unary()
        children = [left]
        while self.accept_kw("AND"):
            children.append(self.parse_unary())
        if len(children) == 1:
            return left
        return FilterQueryTree(FilterOperator.AND, children=children)

    def parse_unary(self) -> FilterQueryTree:
        if self.peek().type == TokType.LPAREN:
            self.next()
            node = self.parse_or()
            self.expect(TokType.RPAREN)
            return node
        # REGEXP_LIKE(col, 'pattern')
        t = self.peek()
        if t.type == TokType.IDENT and t.upper == "REGEXP_LIKE" and \
                self.toks[self.i + 1].type == TokType.LPAREN:
            self.next(); self.next()
            col = self.expect(TokType.IDENT).value
            self.expect(TokType.COMMA)
            pat = self.expect(TokType.STRING).value
            self.expect(TokType.RPAREN)
            return FilterQueryTree(FilterOperator.REGEXP_LIKE, column=col,
                                   values=[pat])
        return self.parse_comparison()

    def parse_literal(self) -> str:
        t = self.next()
        if t.type in (TokType.STRING, TokType.INT, TokType.FLOAT,
                      TokType.IDENT):
            return t.value
        raise PqlSyntaxError(f"expected literal at {t.pos}, got {t.value!r}")

    def parse_comparison(self) -> FilterQueryTree:
        col = self.parse_column_or_expression()
        t = self.peek()
        if t.type == TokType.OP:
            op = self.next().value
            val = self.parse_literal()
            return _comparison_to_tree(col, op, val)
        negate = self.accept_kw("NOT")
        if self.accept_kw("BETWEEN"):
            lo = self.parse_literal()
            self.expect_kw("AND")
            hi = self.parse_literal()
            node = FilterQueryTree(FilterOperator.RANGE, column=col,
                                   lower=lo, upper=hi,
                                   lower_inclusive=True, upper_inclusive=True)
            if negate:
                raise PqlSyntaxError("NOT BETWEEN is not supported")
            return node
        if self.accept_kw("IN"):
            self.expect(TokType.LPAREN)
            vals = [self.parse_literal()]
            while self.peek().type == TokType.COMMA:
                self.next()
                vals.append(self.parse_literal())
            self.expect(TokType.RPAREN)
            return FilterQueryTree(
                FilterOperator.NOT_IN if negate else FilterOperator.IN,
                column=col, values=vals)
        if self.accept_kw("IS"):
            is_not = self.accept_kw("NOT")
            self.expect_kw("NULL")
            return FilterQueryTree(
                FilterOperator.IS_NOT_NULL if is_not else FilterOperator.IS_NULL,
                column=col)
        raise PqlSyntaxError(f"bad predicate near {t.pos}: {t.value!r}")

    # -- HAVING ------------------------------------------------------------
    def parse_having(self) -> HavingNode:
        return self.parse_having_or()

    def parse_having_or(self) -> HavingNode:
        children = [self.parse_having_and()]
        while self.accept_kw("OR"):
            children.append(self.parse_having_and())
        if len(children) == 1:
            return children[0]
        return HavingNode(FilterOperator.OR, children=children)

    def parse_having_and(self) -> HavingNode:
        children = [self.parse_having_unary()]
        while self.accept_kw("AND"):
            children.append(self.parse_having_unary())
        if len(children) == 1:
            return children[0]
        return HavingNode(FilterOperator.AND, children=children)

    def parse_having_unary(self) -> HavingNode:
        if self.peek().type == TokType.LPAREN:
            self.next()
            node = self.parse_having_or()
            self.expect(TokType.RPAREN)
            return node
        agg = self.parse_agg_call()
        t = self.peek()
        if t.type == TokType.OP:
            op = self.next().value
            val = self.parse_literal()
            tree = _comparison_to_tree("_", op, val)
            return HavingNode(tree.operator, agg=agg, values=tree.values,
                              lower=tree.lower, upper=tree.upper,
                              lower_inclusive=tree.lower_inclusive,
                              upper_inclusive=tree.upper_inclusive)
        if self.accept_kw("BETWEEN"):
            lo = self.parse_literal()
            self.expect_kw("AND")
            hi = self.parse_literal()
            return HavingNode(FilterOperator.RANGE, agg=agg, lower=lo,
                              upper=hi)
        if self.accept_kw("IN"):
            self.expect(TokType.LPAREN)
            vals = [self.parse_literal()]
            while self.peek().type == TokType.COMMA:
                self.next()
                vals.append(self.parse_literal())
            self.expect(TokType.RPAREN)
            return HavingNode(FilterOperator.IN, agg=agg, values=vals)
        raise PqlSyntaxError(f"bad HAVING predicate at {t.pos}")


def _comparison_to_tree(col: str, op: str, val: str) -> FilterQueryTree:
    if op == "=":
        return FilterQueryTree(FilterOperator.EQUALITY, column=col,
                               values=[val])
    if op in ("<>", "!="):
        return FilterQueryTree(FilterOperator.NOT, column=col, values=[val])
    if op == "<":
        return FilterQueryTree(FilterOperator.RANGE, column=col, upper=val,
                               upper_inclusive=False)
    if op == "<=":
        return FilterQueryTree(FilterOperator.RANGE, column=col, upper=val,
                               upper_inclusive=True)
    if op == ">":
        return FilterQueryTree(FilterOperator.RANGE, column=col, lower=val,
                               lower_inclusive=False)
    if op == ">=":
        return FilterQueryTree(FilterOperator.RANGE, column=col, lower=val,
                               lower_inclusive=True)
    raise PqlSyntaxError(f"unknown comparison operator {op!r}")
