"""End-to-end segment artifact integrity: CRC32 stamping + verification.

Parity: the reference's segment CRC story — CrcUtils.computeCrc over the
segment files at build time, the crc stamped into SegmentZKMetadata, and
SegmentFetcherAndLoader verifying every downloaded artifact before it is
served (a mismatch fails the transition and the artifact is discarded).
Here the checksum covers every artifact file EXCEPT metadata.json — the
crc is stamped into metadata.json itself, so the metadata file cannot be
part of its own checksum (the reference excludes it the same way).

The checksum is layout-honest: it folds in each member's file name, so a
missing, renamed, or extra index file changes the crc even if the byte
streams happen to collide. v1 (file-per-index) and v3 (columns.psf) are
different artifacts and carry different crcs — the crc always describes
the bytes that actually travel and land on disk.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import zlib
from typing import Optional

from pinot_tpu.segment import format as fmt

log = logging.getLogger(__name__)

_CHUNK = 1 << 20


class SegmentIntegrityError(ValueError):
    """A segment artifact's bytes do not match its recorded CRC."""


def compute_crc(seg_dir: str) -> str:
    """CRC32 over every file in the segment directory except
    metadata.json, folding in file names (sorted) so structural changes
    are detected. Returned as a decimal string (SegmentMetadata.crc)."""
    crc = 0
    for name in sorted(os.listdir(seg_dir)):
        if name == fmt.METADATA_FILE or name.endswith(".tmp"):
            # .tmp files are staging leftovers (a crash between stage
            # and rename, e.g. at integrity.stamp_rename) — never part
            # of the durable payload, so they must not poison the crc
            # of an otherwise-intact artifact on cold-start rescan
            continue
        path = os.path.join(seg_dir, name)
        if os.path.isdir(path):
            continue           # segment artifacts are flat
        crc = zlib.crc32(name.encode("utf-8"), crc)
        with open(path, "rb") as f:
            while True:
                chunk = f.read(_CHUNK)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
    return str(crc & 0xFFFFFFFF)


def stamp_crc(seg_dir: str) -> str:
    """Compute the artifact crc and stamp it into metadata.json via a
    staged write + atomic rename; returns the crc. Run at seal time
    (SegmentCreator.build) and lazily for pre-integrity artifacts
    entering the deep store. The rewrite used to be in place — a crash
    mid-write left a torn metadata.json, destroying the only copy of
    the segment's schema/index layout (surfaced by the tpulint
    `durability-order` rule; staged-rename is the repo-wide discipline,
    docs/ROBUSTNESS.md)."""
    from pinot_tpu.common.faults import crash_points
    crc = compute_crc(seg_dir)
    meta_path = os.path.join(seg_dir, fmt.METADATA_FILE)
    with open(meta_path) as f:
        meta = json.load(f)
    meta["crc"] = crc
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, default=str)
        f.flush()
        os.fsync(f.fileno())
    # seeded crash point: metadata staged but not yet published — the
    # old metadata.json is still intact and a re-run re-stamps cleanly
    crash_points.hit("integrity.stamp_rename")
    os.replace(tmp, meta_path)
    return crc


def recorded_crc(seg_dir: str) -> Optional[str]:
    """The crc stamped in the artifact's own metadata.json (None when
    the artifact predates integrity stamping or has no metadata)."""
    meta_path = os.path.join(seg_dir, fmt.METADATA_FILE)
    try:
        with open(meta_path) as f:
            return json.load(f).get("crc")
    except (OSError, ValueError):
        return None


def verify_segment(seg_dir: str,
                   expected_crc: Optional[str] = None) -> str:
    """Verify the artifact against `expected_crc` (falling back to the
    crc stamped in its metadata). Returns the actual crc; raises
    SegmentIntegrityError on mismatch. Artifacts with no recorded crc
    anywhere pass vacuously (pre-integrity segments stay loadable)."""
    actual = compute_crc(seg_dir)
    expected = expected_crc if expected_crc is not None \
        else recorded_crc(seg_dir)
    if expected is not None and str(expected) != actual:
        raise SegmentIntegrityError(
            f"segment artifact {seg_dir} crc mismatch: "
            f"expected {expected}, computed {actual}")
    return actual


def quarantine_segment(seg_dir: str, quarantine_root: str) -> str:
    """Move a corrupt artifact into `quarantine_root` (never deleted —
    kept for forensics, out of every serving path). Returns the new
    location. Collisions get a numeric suffix."""
    os.makedirs(quarantine_root, exist_ok=True)
    base = os.path.basename(os.path.normpath(seg_dir))
    dest = os.path.join(quarantine_root, base)
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = os.path.join(quarantine_root, f"{base}.{n}")
    shutil.move(seg_dir, dest)
    log.warning("quarantined corrupt segment artifact %s -> %s",
                seg_dir, dest)
    return dest
