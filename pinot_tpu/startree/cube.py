"""Star-tree analogue: pre-aggregated cubes over dictId combinations.

Parity: pinot-core/.../core/startree/v2/ — StarTreeV2BuilderConfig
(dimensionsSplitOrder, functionColumnPairs, maxLeafRecords) and the
pre-aggregation the tree encodes. The TPU-idiomatic form drops the node
tree entirely: a cube is a *columnar grouped table* — one row per distinct
dictId combination of the configured dimensions, with materialized
count/sum/min/max stats per configured metric. Queries that only touch
cube dimensions and covered metrics run over n_groups rows instead of
n_docs (OffHeapStarTree.java:35-76's O(tree) skip becomes an O(groups)
columnar scan — groups are bounded at build time, typically 1000-100000x
smaller than the segment).

The cube's dimension lanes share the parent segment's dictionaries, so
every id-domain predicate the engine can resolve against the segment
resolves identically against the cube.
"""
from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Dict, List, Optional

import numpy as np

STARTREE_META = "startree.{idx}.json"
STARTREE_DATA = "startree.{idx}.npz"
DEFAULT_MAX_GROUPS = 1 << 20


@dataclasses.dataclass
class StarTreeConfig:
    dimensions: List[str]                 # split order (all materialized)
    metrics: List[str]                    # metric columns with stats lanes
    max_groups: int = DEFAULT_MAX_GROUPS  # build refused above this

    @classmethod
    def from_json(cls, d: dict) -> "StarTreeConfig":
        metrics = []
        for pair in d.get("functionColumnPairs", d.get("metrics", [])):
            # "SUM__revenue" → revenue (the cube stores the full stat set)
            col = pair.split("__", 1)[1] if "__" in pair else pair
            if col not in metrics and col != "*":
                metrics.append(col)
        # NOTE: Pinot's maxLeafRecords is a node-SPLIT threshold, not a
        # size cap — a ported config's maxLeafRecords (default 10k) must
        # not disable cube builds, so only maxGroups/maxSize cap the build
        return cls(
            dimensions=list(d.get("dimensionsSplitOrder",
                                  d.get("dimensions", []))),
            metrics=metrics,
            max_groups=int(d.get("maxGroups",
                                 d.get("maxSize", DEFAULT_MAX_GROUPS))))

    def to_json(self) -> dict:
        return {"dimensionsSplitOrder": self.dimensions,
                "metrics": self.metrics, "maxSize": self.max_groups}


class StarTreeCube:
    """One materialized cube: dim id lanes + per-metric stat lanes."""

    def __init__(self, config: StarTreeConfig, n_groups: int,
                 dim_ids: Dict[str, np.ndarray],
                 counts: np.ndarray,
                 metric_stats: Dict[str, Dict[str, np.ndarray]]):
        self.config = config
        self.n_groups = n_groups
        self.dim_ids = dim_ids                  # col → int32 [n_groups]
        self.counts = counts                    # int64 [n_groups]
        self.metric_stats = metric_stats        # col → {sum,min,max}[n_groups]

    @property
    def dimensions(self) -> List[str]:
        return self.config.dimensions

    @property
    def metrics(self) -> List[str]:
        return self.config.metrics

    def save(self, seg_dir: str, idx: int) -> None:
        # narrow on disk (near-height cubes are ~75% of segment bytes):
        # dims to their minimal int dtype, counts to int32, min/max to
        # f32 when every value round-trips exactly (integer metrics
        # < 2^24 — the dictionary-encoded SSB case); load() upcasts back
        from pinot_tpu.segment.loader import min_id_dtype
        arrays = {"counts": self.counts.astype(np.int32)
                  if self.counts.size and self.counts.max() < 2**31
                  else self.counts}
        for d, ids in self.dim_ids.items():
            mx = int(ids.max()) if len(ids) else 0
            arrays[f"dim.{d}"] = ids.astype(min_id_dtype(mx))
        for m, stats in self.metric_stats.items():
            for k, arr in stats.items():
                if k in ("min", "max") and arr.size:
                    f32 = arr.astype(np.float32)
                    if np.array_equal(f32.astype(np.float64), arr):
                        arr = f32
                arrays[f"met.{m}.{k}"] = arr
        # data first, meta last: the .json is the commit marker, so a
        # crash mid-save never leaves a json pointing at a missing npz
        np.savez(os.path.join(seg_dir, STARTREE_DATA.format(idx=idx)),
                 **arrays)
        with open(os.path.join(seg_dir, STARTREE_META.format(idx=idx)),
                  "w") as fh:
            json.dump(self.config.to_json(), fh)

    @classmethod
    def load(cls, seg_dir, idx: int) -> "StarTreeCube":
        import io

        from pinot_tpu.segment import format as fmt
        d = fmt.open_dir(seg_dir)
        config = StarTreeConfig.from_json(json.loads(
            d.read_text(STARTREE_META.format(idx=idx))))
        data = np.load(io.BytesIO(
            d.read_bytes(STARTREE_DATA.format(idx=idx))))
        dim_ids = {d: data[f"dim.{d}"].astype(np.int32)
                   for d in config.dimensions}
        metric_stats = {
            m: {k: data[f"met.{m}.{k}"].astype(np.float64)
                for k in ("sum", "min", "max")}
            for m in config.metrics}
        counts = data["counts"].astype(np.int64)
        return cls(config, len(counts), dim_ids, counts, metric_stats)


def build_star_trees(segment, table_config) -> List[StarTreeCube]:
    """Materialize every configured cube from a loaded segment's host
    lanes. Parity: BaseSingleTreeBuilder — but a single vectorized
    group-by pass instead of a sort+split tree walk."""
    cubes: List[StarTreeCube] = []
    for raw_cfg in table_config.indexing_config.star_tree_configs or []:
        config = StarTreeConfig.from_json(raw_cfg) \
            if isinstance(raw_cfg, dict) else raw_cfg
        cube = _build_cube(segment, config)
        if cube is not None:
            cubes.append(cube)
    return cubes


def _build_cube(segment, config: StarTreeConfig
                ) -> Optional[StarTreeCube]:
    n = segment.num_docs
    if n == 0 or not config.dimensions:
        return None
    dim_lanes: Dict[str, tuple] = {}
    for d in config.dimensions:
        if not segment.has_column(d):
            return None
        ds = segment.data_source(d)
        cm = ds.metadata
        if not (cm.has_dictionary and cm.single_value):
            return None                     # MV/raw dims unsupported
        dim_lanes[d] = (ds.dict_ids, cm.cardinality)
    def _metric(ds):
        # deferred: only decoded if the cube survives the group-count
        # checks (a rejected cube must not cost O(n) per metric)
        cm = ds.metadata
        if cm.has_dictionary:
            return lambda: np.asarray(ds.dictionary.values,
                                      dtype=np.float64)[ds.dict_ids]
        return lambda: ds.raw_values.astype(np.float64)

    metric_vals: Dict[str, object] = {}
    for m in config.metrics:
        if not segment.has_column(m):
            return None
        ds = segment.data_source(m)
        cm = ds.metadata
        if not cm.single_value or not cm.data_type.is_numeric:
            return None
        metric_vals[m] = _metric(ds)
    return build_cube_from_arrays(config, dim_lanes, metric_vals)


def build_cube_from_arrays(config: StarTreeConfig,
                           dim_lanes: Dict[str, tuple],
                           metric_vals: Dict[str, np.ndarray]
                           ) -> Optional[StarTreeCube]:
    """Core cube pass over host arrays: dim_lanes maps dimension →
    (dict_ids, cardinality), metric_vals maps metric → float64 values
    (or a zero-arg callable producing them, resolved only once the cube
    passes the group-count checks). Linear-time grouping (hash factorize
    + bincount) instead of the O(n log n) unique sort; the creator calls
    this directly on its in-memory ids so sealing a segment never
    re-reads it from disk."""
    if not config.dimensions or \
            any(d not in dim_lanes for d in config.dimensions):
        return None
    cards = [dim_lanes[d][1] for d in config.dimensions]
    if np.prod([float(c) for c in cards]) >= 2**62:
        return None                         # packed key would overflow
    n = len(dim_lanes[config.dimensions[0]][0])
    if n == 0:
        return None
    from pinot_tpu import native

    lanes = [dim_lanes[d][0] for d in config.dimensions]
    key = native.packed_key(lanes, cards)
    if key is None:
        key = np.zeros(n, dtype=np.int64)
        for lane, card in zip(lanes, cards):
            key = key * card + lane

    # grouping ladder (measured at 8M rows): bounded spans take the O(n)
    # LUT factorize (0.2s); wide key spaces take ONE C-speed argsort
    # (~1s — beats both hashed grouping and ufunc.at extrema). Stats are
    # then one native pass per metric (gather fused into the run walk),
    # with bincount/reduceat numpy fallbacks.
    from pinot_tpu.utils.factorize import int_lut_factorize
    inverse = order = starts = None
    fact = int_lut_factorize(key)
    if fact is not None:
        uniq, inverse = fact
        g = len(uniq)
    else:
        order = np.argsort(key)
        sk = key[order]
        starts = np.flatnonzero(
            np.concatenate(([True], sk[1:] != sk[:-1])))
        uniq = sk[starts]
        g = len(uniq)
    if g > config.max_groups:
        return None                         # cube would not pay off

    dim_ids: Dict[str, np.ndarray] = {}
    rem = uniq.copy()
    for d, card in zip(reversed(config.dimensions), reversed(cards)):
        dim_ids[d] = (rem % card).astype(np.int32)
        rem //= card
    if starts is not None:
        counts = np.diff(np.append(starts, n)).astype(np.int64)
    else:
        counts = native.group_counts(inverse, g)
        if counts is None:
            counts = np.bincount(inverse, minlength=g).astype(np.int64)

    metric_stats: Dict[str, Dict[str, np.ndarray]] = {}
    for m in config.metrics:
        if m not in metric_vals:
            return None
        vals = metric_vals[m]
        if callable(vals):
            vals = vals()
        vals = np.asarray(vals, dtype=np.float64)
        stats = None
        if starts is not None:
            stats = native.group_stats_sorted(order, starts, n, vals)
            if stats is None:
                sv = vals[order]
                stats = (np.add.reduceat(sv, starts),
                         np.minimum.reduceat(sv, starts),
                         np.maximum.reduceat(sv, starts))
        else:
            stats = native.group_stats(inverse, vals, g)
            if stats is None:
                sums = np.bincount(inverse, weights=vals, minlength=g)
                mins = np.full(g, np.inf)
                maxs = np.full(g, -np.inf)
                np.minimum.at(mins, inverse, vals)
                np.maximum.at(maxs, inverse, vals)
                stats = (sums, mins, maxs)
        metric_stats[m] = {"sum": stats[0], "min": stats[1],
                           "max": stats[2]}
    return StarTreeCube(config, g, dim_ids, counts, metric_stats)


def _linear_unique(key: np.ndarray):
    """(sorted unique keys, inverse codes) — O(n) hash factorize with an
    np.unique fallback (pandas missing)."""
    from pinot_tpu.utils.factorize import sorted_factorize_or_unique
    return sorted_factorize_or_unique(key)


def load_star_trees(seg_dir) -> List[StarTreeCube]:
    from pinot_tpu.segment import format as fmt
    d = fmt.open_dir(seg_dir)
    cubes = []
    for meta_name in d.list(prefix="startree.", suffix=".json"):
        idx = int(meta_name.split(".")[1])
        try:
            cubes.append(StarTreeCube.load(d, idx))
        except Exception:  # noqa: BLE001 — an acceleration structure must
            # never brick the segment; skip the broken cube
            import logging
            logging.getLogger(__name__).warning(
                "skipping unloadable star-tree cube %d in %s", idx,
                d.path, exc_info=True)
    return cubes
