"""Per-table consuming-segment statistics history.

Parity: core/realtime/impl/RealtimeSegmentStatsHistory.java:49 — a
bounded, disk-persisted window of completed consuming segments' observed
stats (rows indexed, per-column cardinality, average MV count). The next
consuming segment sizes its initial allocations from the estimates, the
memory-provisioning feedback loop that keeps steady-state consumption
from paying repeated growth copies.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

MAX_ENTRIES_PER_TABLE = 10


class RealtimeSegmentStatsHistory:
    """Rolling window of segment stats, persisted as JSON."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._tables: Dict[str, List[dict]] = {}  # tpulint: disable=cache-bound -- keyed by table name (bounded by cluster tables); inner lists trimmed to max_rows
        try:
            with open(path) as fh:
                data = json.load(fh)
            if isinstance(data, dict):
                self._tables = {t: list(v) for t, v in data.items()}
        except (OSError, ValueError):
            pass                      # fresh/corrupt file: start empty

    # -- record ------------------------------------------------------------
    def add_segment_stats(self, table: str, stats: dict) -> None:
        """stats: {"numRowsIndexed": int,
        "columns": {col: {"cardinality": int, "avgMvCount": float}}}."""
        with self._lock:
            window = self._tables.setdefault(table, [])
            window.append(stats)
            del window[:-MAX_ENTRIES_PER_TABLE]
            self._save()

    def _save(self) -> None:
        tmp = f"{self.path}.tmp"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w") as fh:  # tpulint: disable=lock-blocking -- stats persist at segment-flush cadence (minutes); the lock pairs the in-memory update with its durable image
                json.dump(self._tables, fh)
            os.replace(tmp, self.path)     # atomic: never a torn file
        except OSError:
            pass                      # stats are advisory, never fatal

    # -- estimate ----------------------------------------------------------
    def estimate(self, table: str) -> Optional[dict]:
        """Allocation hint for the next consuming segment, averaged over
        the window; None with no history (callers use defaults). Only
        the row estimate drives allocations today; per-column stats stay
        raw in entries() (read by provisioning tooling)."""
        with self._lock:
            window = self._tables.get(table)
            if not window:
                return None
            rows = [int(e.get("numRowsIndexed", 0)) for e in window]
            return {"rows": int(sum(rows) / len(rows))}

    def entries(self, table: str) -> List[dict]:
        with self._lock:
            return list(self._tables.get(table, ()))
