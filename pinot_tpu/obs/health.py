"""`/debug/health`: one-scrape leak-gate rollup per process.

Every component (broker / server / controller / minion) exposes the
same small JSON via `GET /debug/health` so the soak harness — and an
operator — polls ONE endpoint per process for everything the leak
gates watch: RSS, the residency ledger (total + per-kind, which is
where exchange held-bytes live), and the summed leak-sensitive gauges
(`upsertKeyMapSize`, `admissionQueueDepth`,
`clusterReplicationDeficit`). `/metrics` stays the full-fidelity
surface; this is the curated subset whose FLATNESS over a 30-minute
run is the pass/fail signal (obs/slo.GaugeSeries).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

#: gauge base-names summed across their table-suffixed series into the
#: rollup (a gauge registered as "tbl.upsertKeyMapSize" counts toward
#: "upsertKeyMapSize")
LEAK_GAUGES = (
    "upsertKeyMapSize",
    "admissionQueueDepth",
    "clusterReplicationDeficit",
    "deviceBytesResident",
)

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") \
    else 4096


def rss_bytes() -> int:
    """Resident set size of THIS process, from /proc (zero if the
    platform has no procfs — the soak gates run on Linux)."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return 0


def _sum_gauges(metrics, base: str) -> float:
    """Sum a gauge across its global and table-suffixed series."""
    _, gauges, _ = metrics.metric_maps()
    total = 0.0
    for key, g in gauges.items():
        if key == base or key.endswith(f".{base}"):
            try:
                total += float(g.value)
            except Exception:  # noqa: BLE001 — callable gauge racing shutdown
                pass
    return total


def health_rollup(component: str, metrics=None,
                  extra: Optional[Dict[str, object]] = None) -> dict:
    """The /debug/health body. ``extra`` lets a component graft
    process-specific gauges (e.g. the broker's result-cache size)."""
    from pinot_tpu.obs.residency import LEDGER
    snap = LEDGER.snapshot()
    out: dict = {
        "component": component,
        "pid": os.getpid(),
        "rssBytes": rss_bytes(),
        "residency": {
            "totalDeviceBytesResident":
                snap.get("totalDeviceBytesResident", 0),
            "byKind": snap.get("byKind", {}),
            "entryCount": snap.get("entryCount", 0),
        },
        # exchange held-bytes ride the residency ledger under the
        # "exchange" kind; surfacing them top-level keeps the soak's
        # gauge-series wiring one key deep
        "exchangeHeldBytes":
            (snap.get("byKind") or {}).get("exchange", 0),
        "gauges": {},
    }
    if metrics is not None:
        for base in LEAK_GAUGES:
            out["gauges"][base] = _sum_gauges(metrics, base)
    if extra:
        out["gauges"].update(extra)
    return out
