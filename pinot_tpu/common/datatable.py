"""DataTable: the server→broker result wire format.

Parity: pinot-common/.../utils/DataTable.java + DataTableImplV2.java:40-263 —
version, metadata map, exceptions, schema (column names/types), row payload —
rebuilt as a tagged binary format on top of the typed object serde
(common/serde.py) instead of the reference's fixed+variable byte regions.

Three logical layouts mirror IntermediateResultsBlock's payloads:
- aggregation-only: one row, one object cell per aggregation function
- group-by: one row per group, key columns + intermediate object columns
- selection: one row per selected doc
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List

from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.common.serde import obj_from_bytes, obj_to_bytes
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock

_U32 = struct.Struct(">I")
VERSION = 1

KIND_EMPTY = 0
KIND_AGGREGATION = 1
KIND_GROUP_BY = 2
KIND_SELECTION = 3

# Structured metadata key carrying the JSON list of segments a server was
# asked for but does not host; the broker keys its one-shot re-dispatch off
# this (not off parsing exception strings, which can drift independently).
MISSING_SEGMENTS_KEY = "missingSegments"
# Human-facing exception prefix for the same condition — shared so the
# server format and the broker's partial-response surface stay in sync.
SEGMENT_MISSING_EXC_PREFIX = "SegmentMissingError:"


@dataclasses.dataclass
class DataTable:
    kind: int = KIND_EMPTY
    columns: List[str] = dataclasses.field(default_factory=list)
    rows: List[tuple] = dataclasses.field(default_factory=list)
    num_group_cols: int = 0
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)
    exceptions: List[str] = dataclasses.field(default_factory=list)

    # -- wire format -------------------------------------------------------
    def to_bytes(self) -> bytes:
        out = bytearray()
        out += _U32.pack(VERSION)
        out += bytes([self.kind])
        out += _U32.pack(self.num_group_cols)
        _w_obj(out, self.metadata)
        _w_obj(out, list(self.exceptions))
        _w_obj(out, list(self.columns))
        out += _U32.pack(len(self.rows))
        for row in self.rows:
            _w_obj(out, tuple(row))
        return bytes(out)

    @classmethod
    def from_bytes(cls, b: bytes) -> "DataTable":
        off = 0
        version = _U32.unpack_from(b, off)[0]
        off += 4
        if version != VERSION:
            raise ValueError(f"unsupported DataTable version {version}")
        kind = b[off]
        off += 1
        num_group_cols = _U32.unpack_from(b, off)[0]
        off += 4
        metadata, off = _r_obj(b, off)
        exceptions, off = _r_obj(b, off)
        columns, off = _r_obj(b, off)
        n_rows = _U32.unpack_from(b, off)[0]
        off += 4
        rows = []
        for _ in range(n_rows):
            row, off = _r_obj(b, off)
            rows.append(row)
        return cls(kind=kind, columns=list(columns), rows=rows,
                   num_group_cols=num_group_cols,
                   metadata=dict(metadata), exceptions=list(exceptions))

    # -- block conversion --------------------------------------------------
    @classmethod
    def from_block(cls, request: BrokerRequest,
                   block: IntermediateResultsBlock) -> "DataTable":
        dt = cls(metadata=block.stats.to_metadata(),
                 exceptions=list(block.exceptions))
        dt.metadata["timeUsedMs"] = f"{block.stats.time_used_ms:.3f}"
        if block.execution_path is not None:
            dt.metadata["executionPath"] = block.execution_path
        # numpy-scalar normalization happens inside serde._write_obj, so
        # rows can carry intermediates as-is
        if block.group_map is not None:
            dt.kind = KIND_GROUP_BY
            gcols = request.group_by.columns if request.group_by else []
            dt.num_group_cols = len(gcols)
            dt.columns = list(gcols) + [a.call for a in request.aggregations]
            dt.rows = [tuple(key) + tuple(inters)
                       for key, inters in block.group_map.items()]
        elif block.agg_intermediates is not None:
            dt.kind = KIND_AGGREGATION
            dt.columns = [a.call for a in request.aggregations]
            dt.rows = [tuple(block.agg_intermediates)]
        elif block.selection_rows is not None:
            dt.kind = KIND_SELECTION
            dt.columns = list(block.selection_columns or [])
            dt.rows = [tuple(row) for row in block.selection_rows]
            if block.selection_display_cols is not None:
                # trailing ORDER-BY-only columns: the broker needs the
                # display split to trim after its cross-server merge
                dt.metadata["selectionDisplayCols"] = str(
                    block.selection_display_cols)
        return dt

    def to_block(self) -> IntermediateResultsBlock:
        blk = IntermediateResultsBlock(exceptions=list(self.exceptions))
        blk.stats = _stats_from_metadata(self.metadata)
        if self.kind == KIND_GROUP_BY:
            g = self.num_group_cols
            blk.group_map = {tuple(row[:g]): list(row[g:])
                             for row in self.rows}
        elif self.kind == KIND_AGGREGATION:
            blk.agg_intermediates = list(self.rows[0]) if self.rows else None
        elif self.kind == KIND_SELECTION:
            blk.selection_rows = [tuple(r) for r in self.rows]
            blk.selection_columns = list(self.columns)
            n = self.metadata.get("selectionDisplayCols")
            if n is not None:
                blk.selection_display_cols = int(n)
        return blk


def _stats_from_metadata(md: Dict[str, str]) -> ExecutionStats:
    def gi(k):
        return int(md.get(k, "0"))

    return ExecutionStats(
        num_docs_scanned=gi("numDocsScanned"),
        num_entries_scanned_in_filter=gi("numEntriesScannedInFilter"),
        num_entries_scanned_post_filter=gi("numEntriesScannedPostFilter"),
        num_segments_processed=gi("numSegmentsProcessed"),
        num_segments_matched=gi("numSegmentsMatched"),
        total_docs=gi("totalDocs"),
        num_groups_limit_reached=md.get("numGroupsLimitReached") == "true",
        num_consuming_segments_processed=gi("numConsumingSegmentsProcessed"),
        min_consuming_freshness_ms=gi("minConsumingFreshnessTimeMs"),
        time_used_ms=float(md.get("timeUsedMs", "0")))


def _w_obj(out: bytearray, v) -> None:
    b = obj_to_bytes(v)
    out += _U32.pack(len(b))
    out += b


def _r_obj(b: bytes, off: int):
    n = _U32.unpack_from(b, off)[0]
    off += 4
    return obj_from_bytes(b[off:off + n]), off + n
