"""Broker-plane tests: routing, scatter-gather, partial responses, quota,
hybrid time-boundary split — over an embedded multi-server cluster.

Mirrors the reference's routing-builder unit tests and the ClusterTest
pattern (multi-node in one process, real serde on the wire).
"""
import tempfile

import numpy as np
import pytest

from fixtures import build_segment
from oracle import Oracle

from pinot_tpu.broker import (BalancedRandomRoutingTableBuilder,
                              LargeClusterRoutingTableBuilder,
                              BrokerRequestHandler, InProcessTransport,
                              ReplicaGroupRoutingTableBuilder,
                              RoutingManager, TcpTransport,
                              TimeBoundaryService)
from pinot_tpu.common.cluster_state import ONLINE, TableView
from pinot_tpu.server import ServerInstance

import random


def _view(table, segment_servers):
    return TableView(table, {seg: {srv: ONLINE for srv in servers}
                             for seg, servers in segment_servers.items()})


# -- routing builders -------------------------------------------------------

def test_balanced_random_builder_covers_all_segments():
    view = _view("t_OFFLINE", {
        f"seg_{i}": [f"s{i % 3}", f"s{(i + 1) % 3}"] for i in range(12)})
    tables = BalancedRandomRoutingTableBuilder(num_tables=5).build(
        view, random.Random(0))
    assert len(tables) == 5
    for rt in tables:
        routed = sorted(s for segs in rt.values() for s in segs)
        assert routed == sorted(view.segments())
        # balance: with 12 segments over 3 servers, no server > 8
        assert max(len(v) for v in rt.values()) <= 8


def test_balanced_random_builder_skips_dead_replicas():
    view = TableView("t_OFFLINE", {
        "seg_live": {"s0": ONLINE, "s1": "OFFLINE"},
        "seg_dead": {"s1": "ERROR"},
    })
    tables = BalancedRandomRoutingTableBuilder(num_tables=3).build(
        view, random.Random(0))
    for rt in tables:
        assert rt.get("s0") == ["seg_live"]
        assert "s1" not in rt


def test_large_cluster_builder_caps_servers_but_covers():
    # 10 servers, 120 segments, 4 replicas each: a 4-server subset can
    # cover everything, so fan-out stays near the target
    view = _view("t_OFFLINE", {
        f"seg_{i}": [f"s{(i + k) % 10}" for k in range(4)]
        for i in range(120)})
    tables = LargeClusterRoutingTableBuilder(
        target_num_servers=4, num_tables=6).build(view, random.Random(1))
    assert len(tables) == 6
    for rt in tables:
        routed = sorted(s for segs in rt.values() for s in segs)
        assert routed == sorted(view.segments())   # full coverage
        # bounded fan-out: near the target, below the fleet size
        assert len(rt) <= 7 < 10


def test_large_cluster_builder_skips_dead_replicas():
    view = TableView("t_OFFLINE", {
        "seg_live": {"s0": ONLINE, "s1": "OFFLINE"},
        "seg_dead": {"s1": "ERROR"},
    })
    tables = LargeClusterRoutingTableBuilder(
        target_num_servers=1, num_tables=2).build(view, random.Random(0))
    for rt in tables:
        assert rt.get("s0") == ["seg_live"]
        assert "s1" not in rt


def test_replica_group_builder_single_server_per_table():
    view = _view("t_OFFLINE",
                 {f"seg_{i}": ["s0", "s1"] for i in range(6)})
    tables = ReplicaGroupRoutingTableBuilder(num_tables=4).build(
        view, random.Random(0))
    for rt in tables:
        assert len(rt) == 1           # one replica group serves everything
        assert sorted(list(rt.values())[0]) == sorted(view.segments())


# -- embedded cluster -------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    base = tempfile.mkdtemp()
    servers = {f"server_{i}": ServerInstance(f"server_{i}")
               for i in range(2)}
    all_cols = []
    view = TableView("baseballStats_OFFLINE", {})
    for i in range(4):
        seg, cols = build_segment(f"{base}/seg{i}", n=1500, seed=80 + i,
                                  name=f"bb_{i}")
        all_cols.append(cols)
        target = f"server_{i % 2}"
        servers[target].data_manager.table(
            "baseballStats_OFFLINE", create=True).add_segment(seg)
        view.segment_states[f"bb_{i}"] = {target: ONLINE}
    merged = {k: (np.concatenate([c[k] for c in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((c[k] for c in all_cols), []))
              for k in all_cols[0]}
    routing = RoutingManager()
    routing.update_view(view)
    handler = BrokerRequestHandler(routing, InProcessTransport(servers))
    yield handler, Oracle(merged), servers
    for s in servers.values():
        s.stop()


def test_broker_aggregation_across_servers(cluster):
    handler, oracle, _ = cluster
    m = oracle.mask(lambda r: r["league"] == "NL")
    resp = handler.handle("SELECT COUNT(*), AVG(runs) FROM baseballStats "
                          "WHERE league = 'NL'")
    assert resp.aggregation_results[0].value == str(oracle.count(m))
    assert float(resp.aggregation_results[1].value) == pytest.approx(
        oracle.avg("runs", m))
    assert resp.num_servers_queried == 2
    assert resp.num_servers_responded == 2
    assert resp.num_segments_processed == 4
    assert resp.total_docs == 6000


def test_broker_group_by_reduce(cluster):
    handler, oracle, _ = cluster
    m = oracle.mask(lambda r: True)
    expected = oracle.group_by(["teamID"], m, ("sum", "hits"))
    resp = handler.handle(
        "SELECT SUM(hits) FROM baseballStats GROUP BY teamID TOP 1000")
    got = {tuple(g["group"]): float(g["value"])
           for g in resp.aggregation_results[0].group_by_result}
    assert got == {(k[0],): pytest.approx(v) for k, v in expected.items()}


def test_broker_selection_order_by(cluster):
    handler, oracle, _ = cluster
    resp = handler.handle("SELECT runs FROM baseballStats "
                          "ORDER BY runs DESC LIMIT 10")
    got = [int(r[0]) for r in resp.selection_results.results]
    m = oracle.mask(lambda r: True)
    assert got == [int(v) for v in
                   sorted(oracle.vals("runs", m), reverse=True)[:10]]


def test_broker_unknown_table(cluster):
    handler, _, _ = cluster
    resp = handler.handle("SELECT COUNT(*) FROM nothere")
    assert resp.exceptions
    assert "TableDoesNotExistError" in resp.exceptions[0]["message"]


def test_broker_bad_pql(cluster):
    handler, _, _ = cluster
    resp = handler.handle("SELEKT nope")
    assert resp.exceptions
    assert "PQLParsingError" in resp.exceptions[0]["message"]


def test_broker_quota(cluster):
    handler, _, _ = cluster
    handler.quota.set_qps_quota("baseballStats", 3)
    try:
        results = [handler.handle("SELECT COUNT(*) FROM baseballStats")
                   for _ in range(10)]
        over = [r for r in results if r.exceptions and
                "QuotaExceededError" in r.exceptions[0]["message"]]
        assert over, "quota never tripped at 10 rapid queries vs 3 qps"
    finally:
        handler.quota.set_qps_quota("baseballStats", None)


def test_broker_partial_response(cluster):
    handler, oracle, servers = cluster

    class Flaky(InProcessTransport):
        async def query(self, server, payload, timeout):
            if server == "server_1":
                raise ConnectionError("boom")
            return await super().query(server, payload, timeout)

    flaky_handler = BrokerRequestHandler(handler.routing, Flaky(servers))
    resp = flaky_handler.handle("SELECT COUNT(*) FROM baseballStats")
    assert resp.num_servers_queried == 2
    assert resp.num_servers_responded == 1
    # partial result: only server_0's 2 segments
    assert resp.num_segments_processed == 2


def test_broker_over_tcp(cluster):
    handler, oracle, servers = cluster
    endpoints = {}
    for name, inst in servers.items():
        port = inst.start(port=0)
        endpoints[name] = ("127.0.0.1", port)
    tcp_handler = BrokerRequestHandler(handler.routing,
                                       TcpTransport(endpoints))
    try:
        m = oracle.mask(lambda r: r["teamID"] == "BOS")
        resp = tcp_handler.handle(
            "SELECT SUM(runs) FROM baseballStats WHERE teamID = 'BOS'")
        assert float(resp.aggregation_results[0].value) == pytest.approx(
            oracle.sum("runs", m))
        assert resp.num_servers_responded == 2
    finally:
        tcp_handler.close()


# -- hybrid time boundary ---------------------------------------------------

def test_hybrid_time_boundary_split():
    base = tempfile.mkdtemp()
    server = ServerInstance("hybrid_server")
    # offline segment: years < 2010; "realtime" segment: years >= 2005
    # (overlap on purpose: the boundary must dedupe)
    from fixtures import make_columns, make_schema, make_table_config
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    cols_all = make_columns(4000, seed=7)
    off_mask = cols_all["yearID"] < 2010
    rt_mask = cols_all["yearID"] >= 2005

    def subset(mask, name, table, seg_dir):
        sub = {k: (np.asarray(v)[mask] if isinstance(v, np.ndarray)
                   else [x for x, m in zip(v, mask) if m])
               for k, v in cols_all.items()}
        sub["position"] = [list(p) for p in sub["position"]]
        creator = SegmentCreator(make_schema(), make_table_config(),
                                 segment_name=name)
        creator.build(sub, seg_dir)
        seg = ImmutableSegmentLoader.load(seg_dir)
        server.data_manager.table(table, create=True).add_segment(seg)
        return seg

    off_seg = subset(off_mask, "off_0", "baseballStats_OFFLINE",
                     f"{base}/off")
    subset(rt_mask, "rt_0", "baseballStats_REALTIME", f"{base}/rt")

    routing = RoutingManager()
    routing.update_view(_view("baseballStats_OFFLINE",
                              {"off_0": ["hybrid_server"]}))
    routing.update_view(_view("baseballStats_REALTIME",
                              {"rt_0": ["hybrid_server"]}))
    tb = TimeBoundaryService()
    tb.update_from_segments("baseballStats_OFFLINE", "yearID", "DAYS",
                            [off_seg.metadata.end_time])
    handler = BrokerRequestHandler(routing, InProcessTransport(
        {"hybrid_server": server}))
    handler.time_boundary = tb

    resp = handler.handle("SELECT COUNT(*) FROM baseballStats")
    # boundary = max offline end time (2009) - 1: offline <= 2008, rt > 2008
    y = cols_all["yearID"]
    expected = int((off_mask & (y <= 2008)).sum() +
                   (rt_mask & (y > 2008)).sum())
    assert resp.aggregation_results[0].value == str(expected)
    server.stop()


def test_time_boundary_only_from_served_segments():
    """The boundary must come from EV-present segments with endTime > 0 —
    a property-store segment no server serves yet must not advance it."""
    from fixtures import make_schema

    from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
    from pinot_tpu.common.cluster_state import TableView

    schema = make_schema()

    class FakeCoord:
        def watch_external_views(self, fn):
            pass

        def tables(self):
            return []

    class FakeManager:
        meta = {
            "seg_served": {"endTime": 100, "timeUnit": "DAYS"},
            "seg_unserved": {"endTime": 200, "timeUnit": "DAYS"},
            "seg_bad_end": {"endTime": -1, "timeUnit": "DAYS"},
        }

        def get_schema(self, name):
            return schema

        def segment_names(self, table):
            return list(self.meta)

        def segment_metadata(self, table, seg):
            return self.meta[seg]

    w = BrokerClusterWatcher(FakeCoord(), FakeManager())
    view = TableView("baseballStats_OFFLINE", {
        "seg_served": {"i1": "ONLINE"},
        "seg_bad_end": {"i1": "ONLINE"},
    })
    w._update_time_boundary(view)
    info = w.time_boundary.get("baseballStats_OFFLINE")
    assert info is not None and info.column == "yearID"
    assert info.value == 100 - 1  # max served end − one unit; 200 excluded


def test_time_boundary_ignores_offline_replicas():
    """A segment whose replicas are all OFFLINE in the EV is not routable,
    so it must not advance the boundary either."""
    from fixtures import make_schema

    from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
    from pinot_tpu.common.cluster_state import TableView

    schema = make_schema()

    class FakeCoord:
        def watch_external_views(self, fn):
            pass

        def tables(self):
            return []

    class FakeManager:
        meta = {
            "seg_on": {"endTime": 50, "timeUnit": "DAYS"},
            "seg_off": {"endTime": 500, "timeUnit": "DAYS"},
        }

        def get_schema(self, name):
            return schema

        def segment_names(self, table):
            return list(self.meta)

        def segment_metadata(self, table, seg):
            return self.meta[seg]

    w = BrokerClusterWatcher(FakeCoord(), FakeManager())
    view = TableView("baseballStats_OFFLINE", {
        "seg_on": {"i1": "ONLINE"},
        "seg_off": {"i1": "OFFLINE", "i2": "ERROR"},
    })
    w._update_time_boundary(view)
    info = w.time_boundary.get("baseballStats_OFFLINE")
    assert info.value == 50 - 1


def test_routing_config_selects_builder_per_table():
    from pinot_tpu.broker.routing import (RoutingManager,
                                          make_routing_builder)
    assert isinstance(make_routing_builder("largecluster",
                                           {"targetNumServers": "3"}),
                      LargeClusterRoutingTableBuilder)
    assert isinstance(make_routing_builder("ReplicaGroup"),
                      ReplicaGroupRoutingTableBuilder)
    assert make_routing_builder(None) is None
    assert make_routing_builder("bogus") is None

    rm = RoutingManager()
    view = _view("t_OFFLINE", {f"seg_{i}": ["s0", "s1"] for i in range(4)})
    rm.update_view(view)
    assert isinstance(rm.table_builder("t_OFFLINE"),
                      BalancedRandomRoutingTableBuilder)
    rm.set_table_builder("t_OFFLINE",
                         ReplicaGroupRoutingTableBuilder(num_tables=3))
    # override rebuilt the held view with the new builder
    assert len(rm._tables["t_OFFLINE"]) == 3
    assert isinstance(rm.table_builder("t_OFFLINE"),
                      ReplicaGroupRoutingTableBuilder)


def test_cluster_watcher_applies_table_routing_config(tmp_path):
    import os
    from fixtures import build_segment, make_schema, make_table_config
    from pinot_tpu.broker.routing import ReplicaGroupRoutingTableBuilder
    from pinot_tpu.common.table_config import RoutingConfig
    from pinot_tpu.tools.cluster import EmbeddedCluster

    cluster = EmbeddedCluster(str(tmp_path), num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cfg = make_table_config(
            routing_config=RoutingConfig("replicagroup"))
        cluster.add_table(cfg)
        d = str(tmp_path / "seg")
        os.makedirs(d)
        build_segment(d, n=256, seed=4, name="rt_route")
        cluster.upload_segment("baseballStats_OFFLINE", d)
        assert isinstance(
            cluster.watcher.routing.table_builder("baseballStats_OFFLINE"),
            ReplicaGroupRoutingTableBuilder)
        resp = cluster.query("SELECT COUNT(*) FROM baseballStats")
        assert resp.aggregation_results[0].value == "256"
    finally:
        cluster.stop()


def test_make_routing_builder_tolerates_bad_options():
    from pinot_tpu.broker.routing import make_routing_builder
    b = make_routing_builder("largecluster", {"targetNumServers": "abc"})
    assert isinstance(b, LargeClusterRoutingTableBuilder)
    assert b.target == 20
    b = make_routing_builder("largecluster", {"targetNumServers": "-3"})
    assert b.target == 1


def test_remove_table_clears_builder_override():
    from pinot_tpu.broker.routing import RoutingManager
    rm = RoutingManager()
    view = _view("t_OFFLINE", {"seg_0": ["s0"]})
    rm.update_view(view)
    rm.set_table_builder("t_OFFLINE", ReplicaGroupRoutingTableBuilder())
    rm.remove_table("t_OFFLINE")
    assert isinstance(rm.table_builder("t_OFFLINE"),
                      BalancedRandomRoutingTableBuilder)


def test_broker_retries_missing_segments_on_stale_routing(tmp_path):
    """A server that unloaded a segment (rebalance drop / reload bounce)
    answers with SegmentMissingError; the broker re-dispatches those
    segments to a live replica from the current view — queries stay
    correct with zero surfaced errors as long as ANY replica serves."""
    import os

    from fixtures import make_columns, make_schema, make_table_config
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.tools.cluster import EmbeddedCluster

    c = EmbeddedCluster(str(tmp_path), num_servers=2)
    try:
        cfg = make_table_config()
        cfg.segments_config.replication = 2
        c.add_schema(make_schema())
        c.add_table(cfg)
        d = os.path.join(str(tmp_path), "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "stale_seg").build(make_columns(1000, seed=12), d)
        c.upload_segment("baseballStats_OFFLINE", d)

        # simulate routing staleness: one server silently drops the
        # segment while the external view (and routing tables) still
        # advertise it
        tdm = c.servers["Server_0"].data_manager.table(
            "baseballStats_OFFLINE")
        tdm.remove_segment("stale_seg")

        hit_errors = []
        for _ in range(20):     # sampled routing hits both servers
            resp = c.query("SELECT COUNT(*) FROM baseballStats")
            if resp.exceptions:
                hit_errors.append(resp.exceptions)
            assert int(resp.aggregation_results[0].value) == 1000
        assert not hit_errors, hit_errors[:2]
    finally:
        c.stop()
