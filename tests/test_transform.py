"""Transform functions + expression filters — golden tests vs numpy.

Parity: TransformFunctionFactory (add/sub/mult/div, time_convert,
datetime_convert), ExpressionFilterOperator, transform-in-group-by
(TransformOperator.java:41). Device path: expressions evaluate over
dictionary value tables host-side while doc-scale work stays id-domain
kernels; host fallback evaluates row-domain.
"""
import tempfile

import numpy as np
import pytest

from fixtures import build_segment, build_shared_segments

from pinot_tpu.common import expression as ex
from pinot_tpu.engine import QueryEngine
from pinot_tpu.parallel import make_mesh
from pinot_tpu.pql.parser import compile_pql


# -- expression unit tests ---------------------------------------------------

def test_parse_and_canonicalize():
    e = ex.parse_expression("time_convert(yearID,'DAYS','HOURS')")
    assert ex.to_string(e) == "time_convert(yearID,'DAYS','HOURS')"
    assert ex.columns_of(e) == ["yearID"]
    e2 = ex.parse_expression("div(add(runs, hits), 2)")
    assert ex.to_string(e2) == "div(add(runs,hits),2)"
    assert ex.columns_of(e2) == ["runs", "hits"]
    with pytest.raises(ex.ExpressionError):
        ex.parse_expression("nosuchfn(a)")


def test_evaluate_arithmetic_and_time():
    a = np.array([1, 2, 3], dtype=np.int64)
    b = np.array([10, 20, 30], dtype=np.int64)
    cols = {"a": a, "b": b}
    r = ex.evaluate("add(a,b)", cols.__getitem__)
    assert list(r) == [11, 22, 33]
    r = ex.evaluate("div(mult(a,b),2)", cols.__getitem__)
    assert list(r) == [5.0, 20.0, 45.0]
    r = ex.evaluate("time_convert(a,'DAYS','HOURS')", cols.__getitem__)
    assert list(r) == [24, 48, 72]
    # datetime_convert: days → weekly buckets expressed in days
    d = np.array([0, 3, 7, 13, 14], dtype=np.int64)
    r = ex.evaluate(
        "datetime_convert(d,'1:DAYS:EPOCH','1:DAYS:EPOCH','7:DAYS')",
        {"d": d}.__getitem__)
    assert list(r) == [0, 0, 7, 7, 14]


def test_parser_expressions_in_positions():
    req = compile_pql(
        "SELECT SUM(add(runs,hits)) FROM t "
        "WHERE time_convert(yearID,'DAYS','HOURS') > 100 "
        "GROUP BY div(yearID,10)")
    assert req.aggregations[0].column == "add(runs,hits)"
    assert req.filter.column == "time_convert(yearID,'DAYS','HOURS')"
    assert req.group_by.columns == ["div(yearID,10)"]
    assert set(req.referenced_columns()) == {"runs", "hits", "yearID"}


# -- engine golden tests -----------------------------------------------------

@pytest.fixture(scope="module")
def seg():
    d = tempfile.mkdtemp()
    segment, cols = build_segment(d, n=4000, seed=13)
    return segment, cols


def _engines(segment):
    return [QueryEngine([segment], use_device=True),
            QueryEngine([segment], use_device=False)]


def test_expression_aggregation_single_column(seg):
    segment, cols = seg
    years = cols["yearID"].astype(np.int64)
    m = cols["teamID"] == "BOS"
    exp = float((years[m] * 24).sum())
    for eng in _engines(segment):
        resp = eng.query(
            "SELECT SUM(time_convert(yearID,'DAYS','HOURS')) "
            "FROM baseballStats WHERE teamID = 'BOS'")
        assert float(resp.aggregation_results[0].value) == exp


def test_expression_aggregation_multi_column_host(seg):
    segment, cols = seg
    exp = float((cols["runs"].astype(np.float64) +
                 cols["hits"].astype(np.float64)).sum())
    for eng in _engines(segment):
        resp = eng.query("SELECT SUM(add(runs,hits)) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == \
            pytest.approx(exp)


def test_expression_min_max_avg(seg):
    segment, cols = seg
    vals = cols["runs"].astype(np.float64) * 2 + 1
    for eng in _engines(segment):
        resp = eng.query(
            "SELECT MIN(add(mult(runs,2),1)), MAX(add(mult(runs,2),1)), "
            "AVG(add(mult(runs,2),1)) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == vals.min()
        assert float(resp.aggregation_results[1].value) == vals.max()
        assert float(resp.aggregation_results[2].value) == \
            pytest.approx(vals.mean())


def test_expression_filter(seg):
    segment, cols = seg
    hours = cols["yearID"].astype(np.int64) * 24
    m = (hours >= 2000 * 24) & (hours < 2010 * 24)
    exp = float(cols["runs"][m].sum())
    for eng in _engines(segment):
        resp = eng.query(
            "SELECT SUM(runs) FROM baseballStats "
            "WHERE time_convert(yearID,'DAYS','HOURS') >= 48000 AND "
            "time_convert(yearID,'DAYS','HOURS') < 48240")
        assert float(resp.aggregation_results[0].value) == exp


def test_time_bucketed_group_by(seg):
    """The canonical OLAP shape: GROUP BY a non-injective time bucket —
    collisions across source dict ids must merge exactly."""
    segment, cols = seg
    years = cols["yearID"].astype(np.int64)
    buckets = years - (years % 5)            # 5-year buckets via datetime
    runs = cols["runs"].astype(np.float64)
    expected = {}
    for b in np.unique(buckets):
        expected[int(b)] = float(runs[buckets == b].sum())
    pql = ("SELECT SUM(runs) FROM baseballStats GROUP BY "
           "datetime_convert(yearID,'1:DAYS:EPOCH','1:DAYS:EPOCH',"
           "'5:DAYS') TOP 50")
    for eng in _engines(segment):
        resp = eng.query(pql)
        got = {int(g["group"][0]): float(g["value"])
               for g in resp.aggregation_results[0].group_by_result}
        assert got == expected


def test_expression_group_by_sharded():
    base = tempfile.mkdtemp()
    segs, merged = build_shared_segments(base, n_segs=8, n=2048, seed=17)
    eng = QueryEngine(segs, mesh=make_mesh())
    years = merged["yearID"].astype(np.int64)
    buckets = years - (years % 10)
    runs = merged["runs"].astype(np.float64)
    expected = {int(b): float(runs[buckets == b].sum())
                for b in np.unique(buckets)}
    resp = eng.query(
        "SELECT SUM(runs) FROM baseballStats GROUP BY "
        "datetime_convert(yearID,'1:DAYS:EPOCH','1:DAYS:EPOCH','10:DAYS') "
        "TOP 50")
    got = {int(g["group"][0]): float(g["value"])
           for g in resp.aggregation_results[0].group_by_result}
    assert got == expected


def test_expression_distinctcount_percentile(seg):
    segment, cols = seg
    doubled = cols["runs"].astype(np.int64) * 2
    exp_distinct = len(np.unique(doubled))
    for eng in _engines(segment):
        resp = eng.query(
            "SELECT DISTINCTCOUNT(mult(runs,2)), "
            "PERCENTILE50(mult(runs,2)) FROM baseballStats")
        assert int(resp.aggregation_results[0].value) == exp_distinct
        v = sorted(doubled)
        exp_p50 = float(v[(len(v) * 50) // 100])
        assert float(resp.aggregation_results[1].value) == exp_p50


def test_percentile_over_noninjective_transform(seg):
    """Colliding transformed values must ACCUMULATE counts (a histogram
    overwrite here silently drops most of the distribution)."""
    segment, cols = seg
    years = cols["yearID"].astype(np.int64)
    buckets = np.sort(years - (years % 5))
    exp_p50 = float(buckets[(len(buckets) * 50) // 100])
    for eng in _engines(segment):
        resp = eng.query(
            "SELECT PERCENTILE50(datetime_convert(yearID,'1:DAYS:EPOCH',"
            "'1:DAYS:EPOCH','5:DAYS')) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == exp_p50


def test_expression_is_not_null(seg):
    segment, cols = seg
    for eng in _engines(segment):
        resp = eng.query(
            "SELECT COUNT(*) FROM baseballStats "
            "WHERE time_convert(yearID,'DAYS','HOURS') IS NOT NULL")
        assert int(resp.aggregation_results[0].value) == len(cols["yearID"])
        resp = eng.query(
            "SELECT COUNT(*) FROM baseballStats "
            "WHERE time_convert(yearID,'DAYS','HOURS') IS NULL")
        assert int(resp.aggregation_results[0].value) == 0


def test_time_convert_truncates_toward_zero():
    v = np.array([-25, -24, -1, 0, 1, 24, 25], dtype=np.int64)
    r = ex.evaluate("time_convert(v,'HOURS','DAYS')", {"v": v}.__getitem__)
    # Java TimeUnit.convert truncates toward zero: -25h -> -1d, -1h -> 0d
    assert list(r) == [-1, -1, 0, 0, 0, 1, 1]
