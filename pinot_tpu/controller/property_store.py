"""PropertyStore: hierarchical JSON records with watches + durability.

Parity: the ZooKeeper property store as Pinot uses it through Helix
(ZKMetadataProvider paths: /CONFIGS/TABLE, /SEGMENTS/<table>/<segment>,
ideal states, external views). In-process, thread-safe, watch callbacks on
path prefixes — the single source of truth for cluster state, exactly the
role ZK plays; a networked implementation can replace it behind the same
interface.

Durability (parity: ZK's transaction log + fuzzy snapshots): with a
`data_dir`, every mutation is journaled to an append-only JSONL
write-ahead log before the call returns, and the store periodically
writes a compacted `snapshot-<seq>.json` and truncates the WAL. On
startup the newest valid snapshot is loaded and the WAL replayed on top;
a torn final record (crash mid-append) is dropped and the file truncated
back to the last complete record, exactly like ZK discarding a torn
txn-log tail.

Two record classes never reach the journal, mirroring ZK ephemerals:
  - records written with ``ephemeral=True`` (session-scoped liveness),
  - records under ``non_durable_prefixes`` (live instances, current
    states, the controller leader lease) — session state that described
    processes which no longer exist after a restart; replaying them
    would resurrect dead peers.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

Watcher = Callable[[str, Optional[dict]], None]

#: session/liveness state and its derivatives — never journaled, never
#: replayed (the layout constants live in state_machine.py /
#: leadership.py / tenants.py; duplicated here as plain strings because
#: property_store is the layer *below* them). LIVEINSTANCES and
#: CURRENTSTATES describe processes that no longer exist after a
#: restart; EXTERNALVIEW and BROKERRESOURCE are recomputed from them on
#: the first membership event, so replaying stale copies would route
#: queries at dead servers/brokers.
DEFAULT_NON_DURABLE_PREFIXES = (
    "/LIVEINSTANCES/",
    "/CURRENTSTATES/",
    "/EXTERNALVIEW/",
    "/BROKERRESOURCE/",
    "/CONTROLLER/LEADER",
)

WAL_FILE = "wal.jsonl"
SNAPSHOT_PREFIX = "snapshot-"

#: fsync policies for the WAL: "always" = fsync every append (survives
#: power loss); "never" = flush to the OS only (survives process crash —
#: the failure model the crash-recovery tests exercise — without paying
#: an fsync per cluster-state write)
FSYNC_ALWAYS = "always"
FSYNC_NEVER = "never"


class PropertyStore:
    def __init__(self, data_dir: Optional[str] = None,
                 fsync: str = FSYNC_NEVER,
                 snapshot_every: int = 1000,
                 non_durable_prefixes: Tuple[str, ...] =
                 DEFAULT_NON_DURABLE_PREFIXES):
        """`data_dir`: enable WAL + snapshot durability under this
        directory (None = in-memory only, the test/default shape).
        `fsync`: WAL flush policy (FSYNC_ALWAYS | FSYNC_NEVER).
        `snapshot_every`: journaled mutations between compacted
        snapshots (0 disables automatic snapshots)."""
        if fsync not in (FSYNC_ALWAYS, FSYNC_NEVER):
            raise ValueError(f"unknown fsync policy {fsync!r}")
        self._data: Dict[str, dict] = {}
        self._watchers: List[tuple] = []        # (prefix, callback)
        self._lock = threading.RLock()
        # serializes external-view composition (state_machine.compose_view
        # read-compute-write cycles from coordinator + ViewComposer threads)
        self.compose_lock = threading.Lock()
        # -- durability state ----------------------------------------------
        self.data_dir = data_dir
        self._fsync = fsync
        self._snapshot_every = snapshot_every
        self._non_durable = tuple(non_durable_prefixes)
        self._ephemeral_paths: set = set()
        self._wal = None                        # open WAL file handle
        self._seq = 0                           # last journaled seq
        self._ops_since_snapshot = 0
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            self._recover()

    # -- durability --------------------------------------------------------
    def _is_durable(self, path: str) -> bool:
        if path in self._ephemeral_paths:
            return False
        return not any(path.startswith(p) or path == p.rstrip("/")
                       for p in self._non_durable)

    def _recover(self) -> None:
        """Load newest valid snapshot, replay the WAL on top, tolerate a
        torn final record, and leave the WAL open for appends."""
        snap_seq = 0
        snaps = sorted((f for f in os.listdir(self.data_dir)
                        if f.startswith(SNAPSHOT_PREFIX) and
                        f.endswith(".json")),
                       key=self._snapshot_seq, reverse=True)
        for name in snaps:
            try:
                with open(os.path.join(self.data_dir, name)) as f:
                    snap = json.load(f)
                self._data = dict(snap["data"])
                snap_seq = int(snap["seq"])
                break
            except (ValueError, KeyError, OSError):
                log.warning("discarding corrupt snapshot %s", name)
        self._seq = snap_seq
        wal_path = os.path.join(self.data_dir, WAL_FILE)
        valid_bytes = 0
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                for line in f:
                    if not line.endswith(b"\n"):
                        log.warning("dropping torn WAL tail (%d bytes)",
                                    len(line))
                        break
                    try:
                        rec = json.loads(line)
                        seq, op = rec["seq"], rec["op"]
                    except (ValueError, KeyError):
                        log.warning("dropping torn/corrupt WAL record; "
                                    "replay stops here")
                        break
                    valid_bytes += len(line)
                    if seq <= snap_seq:
                        continue        # already folded into the snapshot
                    if op == "set":
                        self._data[rec["path"]] = rec["record"]
                    elif op == "remove":
                        self._data.pop(rec["path"], None)
                    self._seq = max(self._seq, seq)
            size = os.path.getsize(wal_path)
            if valid_bytes < size:
                # truncate back to the last complete record so new
                # appends don't concatenate onto torn bytes. Seeded
                # crash point: dying DURING recovery's repair truncate
                # (the double-crash window) must leave the WAL
                # recoverable again — truncation only ever removes
                # already-rejected torn bytes, so re-running recovery
                # converges to the same state
                from pinot_tpu.common.faults import crash_points
                crash_points.hit("store.recover_truncate")
                with open(wal_path, "r+b") as f:
                    f.truncate(valid_bytes)
        self._wal = open(wal_path, "a", encoding="utf-8")

    def _journal(self, op: str, path: str,
                 blob: Optional[str] = None) -> None:
        """Append one mutation to the WAL (caller holds self._lock).
        `blob` is the record pre-serialized by the caller — parsed here
        only once the write is known to be durable, so ephemeral /
        session-state writes (current states, heartbeats, views) pay no
        extra copy."""
        if self._wal is None or not self._is_durable(path):
            return
        self._seq += 1
        entry = {"seq": self._seq, "op": op, "path": path}
        if op == "set":
            entry["record"] = json.loads(blob)
        line = json.dumps(entry) + "\n"
        from pinot_tpu.common.faults import InjectedCrash, crash_points
        crash_points.hit("store.wal_append")      # die before the append
        if crash_points.consume("store.wal_torn"):
            # die mid-append: a torn record reaches the disk — recovery
            # must drop it and truncate back to the last complete record
            self._wal.write(line[: max(1, len(line) // 2)])
            self._wal.flush()
            raise InjectedCrash("store.wal_torn")
        self._wal.write(line)
        self._wal.flush()
        if self._fsync == FSYNC_ALWAYS:
            os.fsync(self._wal.fileno())  # tpulint: disable=lock-blocking -- WAL append IS the durability design: journal order must equal mutation order, so the fsync belongs inside the lock (fsync policy gates the cost)
        self._ops_since_snapshot += 1
        if self._snapshot_every and \
                self._ops_since_snapshot >= self._snapshot_every:
            self._snapshot_locked()

    @staticmethod
    def _snapshot_seq(name: str) -> int:
        try:
            return int(name[len(SNAPSHOT_PREFIX):-len(".json")])
        except ValueError:
            return -1

    def _snapshot_locked(self) -> None:
        """Write a compacted snapshot and truncate the WAL (lock held).

        Crash-safe ordering: the snapshot is staged and atomically
        renamed BEFORE the WAL truncates; replay skips WAL records with
        seq <= snapshot seq, so a crash between the two steps only
        leaves harmless duplicates."""
        durable = {p: r for p, r in self._data.items()
                   if self._is_durable(p)}
        name = f"{SNAPSHOT_PREFIX}{self._seq}.json"
        tmp = os.path.join(self.data_dir, name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:  # tpulint: disable=lock-blocking -- compaction must atomically pair the snapshot with the WAL truncate; writers pause for the (bounded, every-N-ops) snapshot by design
            json.dump({"seq": self._seq, "data": durable}, f)
            f.flush()
            os.fsync(f.fileno())  # tpulint: disable=lock-blocking -- same snapshot-swap atomicity invariant as the open() above
        # seeded crash point: snapshot staged but not renamed — the WAL
        # is untruncated, so recovery ignores the .tmp and replays the
        # (longer) journal over the previous snapshot
        from pinot_tpu.common.faults import crash_points
        crash_points.hit("store.snapshot_rename")
        os.replace(tmp, os.path.join(self.data_dir, name))
        self._wal.close()
        self._wal = open(os.path.join(self.data_dir, WAL_FILE), "w",  # tpulint: disable=lock-blocking -- the WAL swap is part of the atomic snapshot step; a mutation slipping between truncate and reopen would be lost
                         encoding="utf-8")
        self._ops_since_snapshot = 0
        for old in os.listdir(self.data_dir):
            if old.startswith(SNAPSHOT_PREFIX) and old != name and \
                    not old.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self.data_dir, old))
                except OSError:
                    pass

    def snapshot(self) -> None:
        """Force a compacted snapshot + WAL truncation now."""
        with self._lock:
            if self._wal is not None:
                self._snapshot_locked()

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                if self._fsync == FSYNC_ALWAYS:
                    os.fsync(self._wal.fileno())  # tpulint: disable=lock-blocking -- close(): final flush must serialize against in-flight journaled mutations
                self._wal.close()
                self._wal = None

    def _mark_class(self, path: str, ephemeral: bool) -> None:
        """Latest-write-wins durability class (lock held): an ephemeral
        write shadowing a durable record journals the removal so replay
        can't resurrect the stale durable value; a durable write over a
        once-ephemeral path makes it journalable again."""
        if ephemeral:
            if path not in self._ephemeral_paths and \
                    path in self._data and self._is_durable(path):
                self._journal("remove", path, None)
            self._ephemeral_paths.add(path)
        else:
            self._ephemeral_paths.discard(path)

    # -- records -----------------------------------------------------------
    def set(self, path: str, record: dict, ephemeral: bool = False) -> None:
        """`ephemeral` binds the record to the writer's session where the
        store is networked (store_server passes it through); locally it
        only excludes the record from the durability journal."""
        blob = json.dumps(record)
        with self._lock:
            self._mark_class(path, ephemeral)
            self._data[path] = json.loads(blob)
            self._journal("set", path, blob)
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)]
        # each watcher receives its own deep-copied snapshot — never the
        # caller's still-mutable object, and never a dict shared with
        # another watcher that may mutate it (get() defensively copies;
        # the push path must too)
        for cb in watchers:
            cb(path, json.loads(blob))

    def get(self, path: str) -> Optional[dict]:
        with self._lock:
            rec = self._data.get(path)
            return json.loads(json.dumps(rec)) if rec is not None else None

    def update(self, path: str, fn: Callable[[Optional[dict]], dict]
               ) -> dict:
        """Atomic read-modify-write (single-writer ideal-state updates).
        Always a durable-class write."""
        with self._lock:
            rec = fn(self.get(path))
            blob = json.dumps(rec)
            self._mark_class(path, ephemeral=False)
            self._data[path] = json.loads(blob)
            self._journal("set", path, blob)
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)]
        for cb in watchers:
            cb(path, json.loads(blob))
        return rec

    def cas(self, path: str, expected: Optional[dict],
            record: dict, ephemeral: bool = False) -> bool:
        """Compare-and-set: apply only if the current record equals
        `expected` (None = path absent). The remote client's update()
        builds its read-modify-write loop on this."""
        blob = json.dumps(record)
        with self._lock:
            if self._data.get(path) != expected:
                return False
            self._mark_class(path, ephemeral)
            self._data[path] = json.loads(blob)
            self._journal("set", path, blob)
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)]
        for cb in watchers:
            cb(path, json.loads(blob))
        return True

    def remove(self, path: str) -> bool:
        with self._lock:
            existed = self._data.pop(path, None) is not None
            if existed:
                self._journal("remove", path, None)
            self._ephemeral_paths.discard(path)
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)] if existed else []
        for cb in watchers:
            cb(path, None)
        return existed

    def children(self, prefix: str) -> List[str]:
        """Paths directly under prefix (like ZK getChildren)."""
        if not prefix.endswith("/"):
            prefix += "/"
        with self._lock:
            out = set()
            for p in self._data:
                if p.startswith(prefix):
                    out.add(p[len(prefix):].split("/", 1)[0])
            return sorted(out)

    def list_paths(self, prefix: str) -> List[str]:
        with self._lock:
            return sorted(p for p in self._data if p.startswith(prefix))

    # -- watches -----------------------------------------------------------
    def watch(self, prefix: str, callback: Watcher) -> None:
        with self._lock:
            self._watchers.append((prefix, callback))

    def unwatch(self, callback: Watcher) -> None:
        with self._lock:
            self._watchers = [(p, cb) for p, cb in self._watchers
                              if cb is not callback]
