#!/usr/bin/env python
"""Upsert kill-restart convergence gate.

Boots an embedded cluster with a primary-key upsert table, streams rows
with heavily duplicated keys until at least one segment commits, then
KILLS the cluster (no graceful flush of in-memory upsert state) and
restarts over the same durable directories. The restarted cluster must,
within a bounded window:

- converge to the EXACT distinct-key row count and latest value per key
  (COUNT(*) / SUM over the latest rows), and
- perform ZERO topic re-reads before the key-map snapshot offset — the
  consumer resumes at the committed boundary, proving recovery came
  from the key-map snapshot + validDocIds sidecars + journal, not from
  replaying the topic from zero.

Exit code 0 on convergence, 1 otherwise. Env knobs:
  UPSERT_SMOKE_ROWS      rows published (default 800)
  UPSERT_SMOKE_WINDOW_S  convergence window after restart (default 60)
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROWS = int(os.environ.get("UPSERT_SMOKE_ROWS", "800"))
WINDOW_S = float(os.environ.get("UPSERT_SMOKE_WINDOW_S", "60"))
RT_TABLE = "baseballStats_REALTIME"
TOPIC = "upsert_smoke_topic"
FACTORY = "mem_upsert_smoke"


def wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return True
        except Exception:  # noqa: BLE001 — still converging
            pass
        time.sleep(0.1)
    print(f"FAIL: timed out waiting for {what}", file=sys.stderr)
    return False


class RecordingConsumerFactory:
    """Wraps a consumer factory, recording the smallest offset any
    partition consumer fetched from — the re-read detector."""

    def __init__(self, inner):
        self.inner = inner
        self.min_fetch = None

    def create_metadata_provider(self, cfg):
        return self.inner.create_metadata_provider(cfg)

    def create_stream_consumer(self, cfg, checkpoint=None):
        return self.inner.create_stream_consumer(cfg, checkpoint=checkpoint)

    def create_partition_consumer(self, cfg, partition):
        consumer = self.inner.create_partition_consumer(cfg, partition)
        outer = self

        class _Wrapped:
            def fetch_messages(self, start, end, timeout_ms):
                outer.min_fetch = start if outer.min_fetch is None \
                    else min(outer.min_fetch, start)
                return consumer.fetch_messages(start, end, timeout_ms)

            def close(self):
                consumer.close()

        return _Wrapped()


def main() -> int:
    import shutil

    from pinot_tpu.common.table_config import UpsertConfig
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    from pinot_tpu.tools.cluster import EmbeddedCluster
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tests"))
    from fixtures import make_schema
    from test_realtime import make_rows, rt_config

    base = tempfile.mkdtemp(prefix="pinot_tpu_upsert_smoke_")
    t0 = time.monotonic()
    stream = MemoryStream(TOPIC, num_partitions=1)
    registry.register_stream_factory(
        FACTORY, MemoryStreamConsumerFactory(stream, batch_size=50))
    cfg = rt_config(FACTORY, TOPIC, flush_rows=250)
    cfg.upsert_config = UpsertConfig(mode="FULL",
                                     primary_key_columns=["playerName"])

    cluster = EmbeddedCluster(base, num_servers=1,
                              store_dir=os.path.join(base, "store"))
    rows = make_rows(ROWS, seed=17)
    latest = {}
    for r in rows:
        latest[r["playerName"]] = r
    exp_cnt = len(latest)
    exp_sum = float(sum(r["runs"] for r in latest.values()))
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(cfg)
        for r in rows:
            stream.publish(r, partition=0)
        mgr = cluster.controller.manager

        def committed():
            return any((mgr.segment_metadata(RT_TABLE, s) or {}).get(
                "status") == "DONE"
                for s in mgr.segment_names(RT_TABLE))

        if not wait_for(committed, 40, "a committed upsert segment"):
            return 1
        if not wait_for(
                lambda: _count(cluster) == exp_cnt, 40,
                "pre-kill convergence"):
            return 1
    finally:
        cluster.stop()          # "kill": in-memory upsert state is gone
    print(f"[{time.monotonic()-t0:6.1f}s] killed cluster "
          f"(expect {exp_cnt} keys, sum {exp_sum})")

    # restart with a RECORDING consumer factory: any fetch below the
    # durable snapshot offset is a topic re-read the recovery should
    # have avoided
    recorder = RecordingConsumerFactory(
        MemoryStreamConsumerFactory(stream, batch_size=50))
    registry.register_stream_factory(FACTORY, recorder)
    part_dir = os.path.join(base, "server_work", "Server_0", "upsert",
                            RT_TABLE, "partition_0")
    snaps = [f for f in os.listdir(part_dir)
             if f.startswith("keymap-") and f.endswith(".json")]
    if not snaps:
        print("FAIL: no key-map snapshot on disk", file=sys.stderr)
        return 1
    snap_offset = json.load(open(os.path.join(
        part_dir, max(snaps, key=lambda n: int(n[7:-5])))))["offset"]

    c2 = EmbeddedCluster(base, num_servers=1,
                         store_dir=os.path.join(base, "store"))
    try:
        def converged():
            c2.controller.realtime.ensure_all_partitions_consuming()
            resp = c2.query(
                "SELECT COUNT(*), SUM(runs) FROM baseballStats")
            if resp.exceptions or not resp.aggregation_results:
                return False
            return int(resp.aggregation_results[0].value) == exp_cnt \
                and float(resp.aggregation_results[1].value) == exp_sum

        if not wait_for(converged, WINDOW_S, "post-restart convergence"):
            return 1
        print(f"[{time.monotonic()-t0:6.1f}s] restarted cluster "
              f"converged to {exp_cnt} keys")
        if recorder.min_fetch is None or recorder.min_fetch < snap_offset:
            print(f"FAIL: topic re-read below the snapshot offset "
                  f"(min fetch {recorder.min_fetch} < {snap_offset})",
                  file=sys.stderr)
            return 1
        print(f"[{time.monotonic()-t0:6.1f}s] zero topic re-reads before "
              f"snapshot offset {snap_offset} "
              f"(first fetch at {recorder.min_fetch})")
    finally:
        c2.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("PASS: upsert kill-restart converged with zero pre-snapshot "
          "topic re-reads")
    return 0


def _count(cluster):
    resp = cluster.query("SELECT COUNT(*) FROM baseballStats")
    if resp.exceptions or not resp.aggregation_results:
        return -1
    return int(resp.aggregation_results[0].value)


if __name__ == "__main__":
    sys.exit(main())
