"""Stage-2 window executor: exchanged scan blocks → window rows.

The broker's stage 1 scatters a plain selection scan (display columns +
window inputs) that every routed server publishes to the exchange; one
coordinator server fetches all blocks (its own through the in-process
registry), concatenates the columns in deterministic source order, and
runs the window kernel (ops/kernels.build_window_kernel): ONE
lax.sort by (partition codes, window-order keys, input index) + rebased
iota/cumsum. The host oracle twin here mirrors it with a stable
np.lexsort and the same int32 arithmetic, so both paths are
bit-identical by construction.

Exactness contract:
- all windows of a query share one PARTITION BY / ORDER BY (one sort =
  one deterministic output order) — typed error otherwise;
- SUM(...) OVER is INTEGER-only and the executor rejects inputs whose
  running sums could leave int32 (the dtype every backend shares);
- output rows come back ordered by (partition, window order, input
  order) — the input order is itself deterministic (blocks sorted by
  source server, scan rows in segment order).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock
from pinot_tpu.query.stages import exchange
from pinot_tpu.query.stages.errors import StageCompileError
from pinot_tpu.query.stages.join import columns_of

#: total row cap for one window evaluation (the exchanged blocks are
#: device-sorted as one array; past this, callers must narrow the WHERE)
WINDOW_CAP = 1 << 16


def scan_columns(request: BrokerRequest) -> List[str]:
    """Columns the stage-1 scan must ship: display + window inputs."""
    cols = list(request.selection.columns)
    for w in request.windows:
        for c in list(w.partition_by) + [s.column for s in w.order_by] + \
                ([w.column] if w.column else []):
            if c not in cols:
                cols.append(c)
    return cols


def _shared_window_frame(request: BrokerRequest):
    """(partition_by, order_by) shared by every window of the query."""
    w0 = request.windows[0]
    frame = (tuple(w0.partition_by),
             tuple((s.column, s.ascending) for s in w0.order_by))
    for w in request.windows[1:]:
        if (tuple(w.partition_by),
                tuple((s.column, s.ascending) for s in w.order_by)) != frame:
            raise StageCompileError(
                "all window functions of one query must share the same "
                "PARTITION BY and ORDER BY (one sort defines one "
                "deterministic output order)")
    return w0.partition_by, w0.order_by


def _factorize_i32(col) -> np.ndarray:
    arr = col if isinstance(col, np.ndarray) else \
        np.asarray(col, dtype=object)
    _uniq, inv = np.unique(arr, return_inverse=True)
    return inv.astype(np.int32)


def _int_lane(col, name: str, part: np.ndarray) -> np.ndarray:
    arr = col if isinstance(col, np.ndarray) else np.asarray(col)
    if arr.dtype.kind not in "iu":
        raise StageCompileError(
            f"SUM(...) OVER is integer-only (the int32 running-sum "
            f"exactness contract); column '{name}' decoded as "
            f"{arr.dtype}")
    if len(arr):
        # exact PER-PARTITION bound: running sums only accumulate
        # within a partition, so a query whose partitions each fit
        # int32 is safe even when the global abs-sum is not
        per_part = np.bincount(part,
                               weights=np.abs(arr.astype(np.int64)))
        if per_part.size and float(per_part.max()) >= 2 ** 31:
            raise StageCompileError(
                f"SUM({name}) OVER running sums can exceed int32 — "
                "narrow the scan (the int32 accumulator is the "
                "cross-backend exactness contract)")
    return arr.astype(np.int32)


def _host_window(part: np.ndarray, orders: List[np.ndarray],
                 sums: List[np.ndarray]):
    """Host oracle twin of kernels.build_window_kernel (same total sort
    order — stable lexsort with the input index as final tie-break —
    and the same int32 running sums)."""
    n = len(part)
    iota = np.arange(n, dtype=np.int64)
    keys = [iota] + [o for o in reversed(orders)] + [part]
    perm = np.lexsort(tuple(keys))
    sp = part[perm]
    new = np.ones(n, dtype=bool)
    new[1:] = sp[1:] != sp[:-1]
    starts = np.maximum.accumulate(np.where(new, iota, 0))
    # rank fits int32 trivially (row count is capped at WINDOW_CAP)
    rn = (iota - starts).astype(np.int32) + np.int32(1)
    run_sums = []
    for v in sums:
        sv = v[perm].astype(np.int64)
        cs = np.cumsum(sv)
        base = cs[starts] - sv[starts]
        run_sums.append((cs - base).astype(np.int32))
    return perm.astype(np.int64), rn, run_sums


def _device_window(part: np.ndarray, orders: List[np.ndarray],
                   sums: List[np.ndarray]):
    from pinot_tpu.obs.profiler import profiled_device_get
    from pinot_tpu.ops import kernels
    n = len(part)
    n_pad = kernels.pow2_bucket(max(n, 1))

    def pad(a):
        out = np.zeros(n_pad, dtype=np.int32)
        out[:n] = a
        return out

    # residency: the padded operands become jitted-kernel params (one
    # implicit upload each); account them for the dispatch's duration
    from pinot_tpu.obs import residency
    owner = f"win:{id(part)}"
    residency.LEDGER.register(
        owner, table="", segment="", kind="window",
        nbytes=4 * n_pad * (1 + len(orders) + len(sums)))
    try:
        outs = profiled_device_get(kernels.run_window_kernel(
            pad(part), tuple(pad(o) for o in orders),
            tuple(pad(v) for v in sums), n))
    finally:
        residency.LEDGER.release(owner)
    perm = np.asarray(outs["win.perm"])[:n].astype(np.int64)
    rn = np.asarray(outs["win.rn"])[:n].astype(np.int32)
    run_sums = [np.asarray(outs[f"win.sum{j}"])[:n].astype(np.int32)
                for j in range(len(sums))]
    return perm, rn, run_sums


def execute_window(request: BrokerRequest,
                   columns: Dict[str, object],
                   num_rows: int,
                   use_device: bool = True) -> IntermediateResultsBlock:
    """Window evaluation over assembled columns → selection block whose
    rows are (display cols..., window values...) in window order."""
    if num_rows > WINDOW_CAP:
        raise StageCompileError(
            f"window input has {num_rows} rows > cap {WINDOW_CAP} — "
            "narrow the WHERE filter")
    partition_by, order_by = _shared_window_frame(request)
    if num_rows:
        if partition_by:
            codes = [_factorize_i32(columns[c]) for c in partition_by]
            part = codes[0].astype(np.int64)
            for c in codes[1:]:
                part = part * (int(c.max()) + 1 if len(c) else 1) + c
            _u, inv = np.unique(part, return_inverse=True)
            part = inv.astype(np.int32)
        else:
            part = np.zeros(num_rows, dtype=np.int32)
        orders = []
        for s in order_by:
            code = _factorize_i32(columns[s.column])
            orders.append(code if s.ascending else ~code)
        sums = [_int_lane(columns[w.column], w.column, part)
                for w in request.windows if w.function == "SUM"]
        runner = _device_window if use_device else _host_window
        perm, rn, run_sums = runner(part, orders, sums)
    else:
        perm = np.zeros(0, np.int64)
        rn = np.zeros(0, np.int32)
        run_sums = [np.zeros(0, np.int32)
                    for w in request.windows if w.function == "SUM"]

    display = list(request.selection.columns)
    out_cols: List[object] = []
    for c in display:
        col = columns[c]
        if isinstance(col, np.ndarray):
            out_cols.append(col[perm])
        else:
            out_cols.append([col[i] for i in perm])
    si = 0
    for w in request.windows:
        if w.function == "ROW_NUMBER":
            out_cols.append(rn.astype(np.int64))
        else:
            out_cols.append(run_sums[si].astype(np.int64))
            si += 1

    blk = IntermediateResultsBlock()
    blk.selection_cols = out_cols
    blk.selection_columns = display + [w.result_name
                                       for w in request.windows]
    blk.stats = ExecutionStats(num_docs_scanned=num_rows,
                               num_segments_processed=0,
                               total_docs=num_rows)
    return blk


def execute_window_stage(request: BrokerRequest, sources: List[dict],
                         deadline_s: Optional[float] = None,
                         use_device: bool = True
                         ) -> IntermediateResultsBlock:
    """Coordinator entry: fetch every stage-1 block, concatenate columns
    in deterministic source order, run the window kernel."""
    ordered = sorted(sources, key=lambda s: (str(s.get("server")),
                                             str(s.get("id"))))
    blocks = exchange.fetch_blocks(ordered, deadline_s)
    names = scan_columns(request)
    col_parts: Dict[str, list] = {c: [] for c in names}
    total = 0
    for dt in blocks:
        cols = columns_of(dt)
        n = dt.num_rows()
        total += n
        for c in names:
            if c not in cols:
                raise StageCompileError(
                    f"stage-1 window block is missing column '{c}'")
            col_parts[c].append(cols[c])
    columns: Dict[str, object] = {}
    for c, parts in col_parts.items():
        if parts and all(isinstance(p, np.ndarray) for p in parts):
            columns[c] = np.concatenate(parts)
        else:
            merged: list = []
            for p in parts:
                merged.extend(list(p))
            columns[c] = merged
    return execute_window(request, columns, total, use_device=use_device)
