"""Table configuration model.

Parity: pinot-common/src/main/java/org/apache/pinot/common/config/
{TableConfig,SegmentsValidationAndRetentionConfig,IndexingConfig,
TenantConfig,TableCustomConfig}.java — same JSON shape for the subset that
drives the engine: table type, retention, indexing (inverted/no-dictionary/
bloom/star-tree/sorted), stream configs and replication.
"""
from __future__ import annotations

import dataclasses
import enum
import json
from typing import Dict, List, Optional


class TableType(enum.Enum):
    OFFLINE = "OFFLINE"
    REALTIME = "REALTIME"


@dataclasses.dataclass
class IndexingConfig:
    inverted_index_columns: List[str] = dataclasses.field(default_factory=list)
    no_dictionary_columns: List[str] = dataclasses.field(default_factory=list)
    bloom_filter_columns: List[str] = dataclasses.field(default_factory=list)
    sorted_column: Optional[str] = None
    star_tree_configs: List[dict] = dataclasses.field(default_factory=list)
    load_mode: str = "MMAP"  # MMAP | HEAP (host) — device copy is explicit
    stream_configs: Dict[str, str] = dataclasses.field(default_factory=dict)
    aggregate_metrics: bool = False
    # column → {"functionName": ..., "numPartitions": N} (parity:
    # SegmentPartitionConfig); the segment creator records each built
    # segment's observed partition ids in its metadata
    segment_partition_config: Dict[str, dict] = dataclasses.field(
        default_factory=dict)
    # "v1" (file-per-index) | "v3" (single columns.psf container with
    # per-member DEFLATE — parity: SegmentVersion + ChunkCompressor)
    segment_version: str = "v1"
    # parity: startree/hll HllConfig — {"columnsToDerive": [...],
    # "log2m": N, "suffix": "_hll"}: the creator adds a derived column of
    # per-row serialized HLLs per origin, targeted by the FASTHLL rewrite
    hll_config: Optional[dict] = None
    # VECTOR column → IVF index config: {"type": "IVF", "numCentroids",
    # "trainIterations", "seed", "trainSampleSize"} (index/ivf.py
    # defaults apply). The creator trains a per-segment codebook at
    # seal; absent columns stay exact-scan.
    vector_index_configs: Dict[str, dict] = dataclasses.field(
        default_factory=dict)

    def to_json(self) -> dict:
        return {
            "invertedIndexColumns": self.inverted_index_columns,
            "noDictionaryColumns": self.no_dictionary_columns,
            "bloomFilterColumns": self.bloom_filter_columns,
            "sortedColumn": [self.sorted_column] if self.sorted_column else [],
            "starTreeConfigs": self.star_tree_configs,
            "loadMode": self.load_mode,
            "streamConfigs": self.stream_configs,
            "aggregateMetrics": self.aggregate_metrics,
            "segmentPartitionConfig": {
                "columnPartitionMap": self.segment_partition_config},
            "segmentFormatVersion": self.segment_version,
            "hllConfig": self.hll_config,
            "vectorIndexConfigs": self.vector_index_configs,
        }

    @classmethod
    def from_json(cls, d: dict) -> "IndexingConfig":
        sorted_cols = d.get("sortedColumn") or []
        return cls(
            inverted_index_columns=d.get("invertedIndexColumns") or [],
            no_dictionary_columns=d.get("noDictionaryColumns") or [],
            bloom_filter_columns=d.get("bloomFilterColumns") or [],
            sorted_column=sorted_cols[0] if sorted_cols else None,
            star_tree_configs=d.get("starTreeConfigs") or [],
            load_mode=d.get("loadMode", "MMAP"),
            stream_configs=d.get("streamConfigs") or {},
            aggregate_metrics=d.get("aggregateMetrics", False),
            segment_partition_config=(d.get("segmentPartitionConfig") or {}
                                      ).get("columnPartitionMap", {}),
            segment_version=d.get("segmentFormatVersion", "v1"),
            hll_config=d.get("hllConfig"),
            vector_index_configs=d.get("vectorIndexConfigs") or {},
        )


@dataclasses.dataclass
class SegmentsConfig:
    """Validation + retention config.

    Parity: SegmentsValidationAndRetentionConfig.
    """
    replication: int = 1
    retention_time_unit: Optional[str] = None   # e.g. "DAYS"
    retention_time_value: Optional[int] = None
    time_column_name: Optional[str] = None
    time_type: Optional[str] = None
    segment_push_type: str = "APPEND"           # APPEND | REFRESH
    segment_push_frequency: str = "DAILY"       # DAILY | HOURLY
    segment_assignment_strategy: str = "BalanceNumSegmentAssignmentStrategy"

    def to_json(self) -> dict:
        return {
            "replication": str(self.replication),
            "retentionTimeUnit": self.retention_time_unit,
            "retentionTimeValue": (str(self.retention_time_value)
                                   if self.retention_time_value else None),
            "timeColumnName": self.time_column_name,
            "timeType": self.time_type,
            "segmentPushType": self.segment_push_type,
            "segmentPushFrequency": self.segment_push_frequency,
            "segmentAssignmentStrategy": self.segment_assignment_strategy,
        }

    @classmethod
    def from_json(cls, d: dict) -> "SegmentsConfig":
        rv = d.get("retentionTimeValue")
        return cls(
            replication=int(d.get("replication", 1)),
            retention_time_unit=d.get("retentionTimeUnit"),
            retention_time_value=int(rv) if rv else None,
            time_column_name=d.get("timeColumnName"),
            time_type=d.get("timeType"),
            segment_push_type=d.get("segmentPushType", "APPEND"),
            segment_push_frequency=d.get("segmentPushFrequency", "DAILY"),
            segment_assignment_strategy=d.get(
                "segmentAssignmentStrategy",
                "BalanceNumSegmentAssignmentStrategy"),
        )


@dataclasses.dataclass
class UpsertConfig:
    """Primary-key upsert configuration.

    Parity: the reference's later-version UpsertConfig (mode FULL: the
    latest row per primary key wins; superseded rows are masked at query
    time via per-segment validDocIds). The primary key is one or more
    schema columns; the stream must partition rows by key so one
    partition owns each key's history (the standard Pinot deployment
    assumption — the key map is per-partition).
    """
    mode: str = "NONE"                   # NONE | FULL
    primary_key_columns: List[str] = dataclasses.field(default_factory=list)
    # snapshot the key map + validDocIds at every segment seal, so a
    # restarted server converges without replaying the topic from zero
    enable_snapshot: bool = True

    @property
    def enabled(self) -> bool:
        return self.mode.upper() == "FULL"

    def to_json(self) -> dict:
        return {"mode": self.mode.upper(),
                "primaryKeyColumns": list(self.primary_key_columns),
                "enableSnapshot": self.enable_snapshot}

    @classmethod
    def from_json(cls, d: dict) -> "UpsertConfig":
        return cls(mode=str(d.get("mode", "NONE")).upper(),
                   primary_key_columns=list(d.get("primaryKeyColumns") or []),
                   enable_snapshot=bool(d.get("enableSnapshot", True)))


@dataclasses.dataclass
class TenantConfig:
    broker: str = "DefaultTenant"
    server: str = "DefaultTenant"

    def to_json(self) -> dict:
        return {"broker": self.broker, "server": self.server}

    @classmethod
    def from_json(cls, d: dict) -> "TenantConfig":
        return cls(d.get("broker", "DefaultTenant"), d.get("server", "DefaultTenant"))


@dataclasses.dataclass
class QuotaConfig:
    storage: Optional[str] = None          # e.g. "100G"
    max_queries_per_second: Optional[float] = None

    def to_json(self) -> dict:
        return {"storage": self.storage,
                "maxQueriesPerSecond": self.max_queries_per_second}

    @classmethod
    def from_json(cls, d: dict) -> "QuotaConfig":
        q = d.get("maxQueriesPerSecond")
        return cls(d.get("storage"), float(q) if q is not None else None)


@dataclasses.dataclass
class RoutingConfig:
    """Broker routing-table builder selection (parity: RoutingConfig /
    routingTableBuilderName in the reference's table config)."""
    builder_name: Optional[str] = None   # balanced | replicagroup |
    #                                      largecluster (None = broker default)
    options: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        d = {}
        if self.builder_name:
            d["routingTableBuilderName"] = self.builder_name
        if self.options:
            d["routingTableBuilderOptions"] = dict(self.options)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "RoutingConfig":
        return cls(d.get("routingTableBuilderName"),
                   dict(d.get("routingTableBuilderOptions", {})))


@dataclasses.dataclass
class TableConfig:
    table_name: str                      # raw name, without type suffix
    table_type: TableType = TableType.OFFLINE
    segments_config: SegmentsConfig = dataclasses.field(default_factory=SegmentsConfig)
    indexing_config: IndexingConfig = dataclasses.field(default_factory=IndexingConfig)
    tenant_config: TenantConfig = dataclasses.field(default_factory=TenantConfig)
    quota_config: Optional[QuotaConfig] = None
    upsert_config: Optional[UpsertConfig] = None
    routing_config: RoutingConfig = dataclasses.field(
        default_factory=RoutingConfig)
    custom_config: Dict[str, str] = dataclasses.field(default_factory=dict)
    # task type → config map for the minion plane (parity: TableTaskConfig,
    # e.g. {"ConvertToRawIndexTask": {"columnsToConvert": "a,b"}})
    task_configs: Dict[str, Dict[str, str]] = dataclasses.field(
        default_factory=dict)

    @property
    def table_name_with_type(self) -> str:
        return f"{self.table_name}_{self.table_type.value}"

    def to_json(self) -> dict:
        d = {
            "tableName": self.table_name_with_type,
            "tableType": self.table_type.value,
            "segmentsConfig": self.segments_config.to_json(),
            "tableIndexConfig": self.indexing_config.to_json(),
            "tenants": self.tenant_config.to_json(),
            "metadata": {"customConfigs": self.custom_config},
        }
        if self.task_configs:
            d["task"] = {"taskTypeConfigsMap": self.task_configs}
        if self.quota_config:
            d["quota"] = self.quota_config.to_json()
        if self.upsert_config:
            d["upsertConfig"] = self.upsert_config.to_json()
        routing = self.routing_config.to_json()
        if routing:
            d["routing"] = routing
        return d

    def to_json_str(self) -> str:
        return json.dumps(self.to_json(), indent=2)

    @classmethod
    def from_json(cls, d: dict) -> "TableConfig":
        name = d["tableName"]
        ttype = TableType(d.get("tableType", "OFFLINE").upper())
        for suffix in ("_OFFLINE", "_REALTIME"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        return cls(
            table_name=name,
            table_type=ttype,
            segments_config=SegmentsConfig.from_json(d.get("segmentsConfig", {})),
            indexing_config=IndexingConfig.from_json(d.get("tableIndexConfig", {})),
            tenant_config=TenantConfig.from_json(d.get("tenants", {})),
            quota_config=(QuotaConfig.from_json(d["quota"]) if d.get("quota")
                          else None),
            upsert_config=(UpsertConfig.from_json(d["upsertConfig"])
                           if d.get("upsertConfig") else None),
            custom_config=(d.get("metadata", {}) or {}).get("customConfigs", {}),
            routing_config=RoutingConfig.from_json(d.get("routing", {})
                                                   or {}),
            task_configs=(d.get("task", {}) or {}).get("taskTypeConfigsMap",
                                                       {}),
        )

    @classmethod
    def from_json_str(cls, s: str) -> "TableConfig":
        return cls.from_json(json.loads(s))


def raw_table_name(name: str) -> str:
    for suffix in ("_OFFLINE", "_REALTIME"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name
