"""Minion-plane background maintenance: compaction, merge, retention,
upsert GC, and the crash-safe swap protocol (ISSUE 11).

Five tiers:

1. **Task-queue leases** — a kill -9'd minion's IN_PROGRESS claim
   requeues on lease expiry (injectable clock), bounded attempts go
   ERROR, completion is fenced against requeued claims, concurrent
   claims have a single winner.
2. **Upsert remap/GC units** — a compacted artifact's shifted doc ids
   re-point the key map (attach_or_fold remap path), persistence makes
   the remap crash-safe, retention-deleted segments' keys leave the
   map.
3. **End-to-end compaction** — deadness published at seal drives the
   generator; the worker rewrites and swaps; COUNT/SUM stay exactly
   equal to the host oracle across the swap and dedup keeps working.
4. **Kill -9 at every swap crash point** — compact.staged /
   compact.pre_swap / compact.pre_delete: after recovery (janitor
   resume + task requeue) results match the oracle exactly, and no
   healthy artifact is CRC-quarantined.
5. **Merge + retention + scrubber coordination** — small segments fold
   into one through the same swap protocol; retention tombstones with
   grace; the scrubber respects open swap intents and reclaims
   tombstones only past grace.
"""
import os
import tempfile
import threading
import time

import pytest

from fixtures import make_columns, make_schema, make_table_config

from pinot_tpu.common.faults import InjectedCrash, crash_points
from pinot_tpu.common.metrics import MetricsRegistry, MinionMeter
from pinot_tpu.common.table_config import UpsertConfig
from pinot_tpu.controller.compaction import (SegmentSwapManager,
                                             SwapJanitor, TRASH_MARKER)
from pinot_tpu.controller.manager import InvalidTableConfigError
from pinot_tpu.controller.periodic import (RetentionManager,
                                           SegmentIntegrityChecker)
from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.minion import (COMPLETED, ERROR, GENERATED, IN_PROGRESS,
                              UPSERT_COMPACTION_TASK, MinionWorker,
                              PinotTaskConfig, PinotTaskManager,
                              TaskQueue)
from pinot_tpu.minion.tasks import SEGMENT_NAME_KEY, TABLE_NAME_KEY
from pinot_tpu.realtime.upsert import (PartitionUpsertMetadata,
                                       deadness_path)
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.tools.cluster import EmbeddedCluster

from test_realtime import make_rows, rt_config
from test_upsert import (RT_TABLE, _register, count_and_sum,
                         latest_by_key, upsert_rt_config, wait_until)


@pytest.fixture(autouse=True)
def _clean_crash_points():
    crash_points.clear()
    yield
    crash_points.clear()


@pytest.fixture
def work_dir():
    return tempfile.mkdtemp()


# ---------------------------------------------------------------------------
# tier 1: task-queue claim leases
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _task(seg="s0"):
    return PinotTaskConfig("PurgeTask", {TABLE_NAME_KEY: "t_OFFLINE",
                                         SEGMENT_NAME_KEY: seg})


def test_lease_expiry_requeues_then_error_after_attempts():
    clock = FakeClock()
    metrics = MetricsRegistry("minion")
    q = TaskQueue(PropertyStore(), clock=clock, lease_s=60.0,
                  max_attempts=2, metrics=metrics)
    t = _task()
    q.submit(t)
    assert q.claim("w1", ["PurgeTask"]) is not None
    # lease still live: nothing to requeue
    assert q.requeue_expired() == []
    clock.t += 61
    assert q.requeue_expired() == [t.task_id]
    assert q.task_states("PurgeTask")[t.task_id] == GENERATED
    assert metrics.meter(MinionMeter.TASK_REQUEUES).count == 1
    # second claim, second expiry: attempts exhausted -> ERROR
    assert q.claim("w2", ["PurgeTask"]) is not None
    clock.t += 61
    assert q.requeue_expired() == [t.task_id]
    rec = q.store.get(f"/TASKS/PurgeTask/{t.task_id}")
    assert rec["state"] == ERROR and "lease expired" in rec["info"]
    assert metrics.meter(
        MinionMeter.TASK_ATTEMPTS_EXHAUSTED).count == 1


def test_complete_after_requeue_is_rejected():
    clock = FakeClock()
    q = TaskQueue(PropertyStore(), clock=clock, lease_s=60.0)
    t = _task()
    q.submit(t)
    assert q.claim("w1", ["PurgeTask"]) is not None
    clock.t += 61
    q.requeue_expired()
    assert q.claim("w2", ["PurgeTask"]) is not None
    # the zombie's completion must not clobber w2's claim
    assert q.finish(t, COMPLETED, worker_id="w1") is False
    assert q.task_states("PurgeTask")[t.task_id] == IN_PROGRESS
    # the live claimant's completion lands
    assert q.finish(t, COMPLETED, worker_id="w2") is True
    assert q.task_states("PurgeTask")[t.task_id] == COMPLETED


def test_concurrent_claims_have_single_winner():
    q = TaskQueue(PropertyStore())
    t = _task()
    q.submit(t)
    winners = []
    barrier = threading.Barrier(8)

    def contend(i):
        barrier.wait()
        got = q.claim(f"w{i}", ["PurgeTask"])
        if got is not None:
            winners.append(i)

    threads = [threading.Thread(target=contend, args=(i,))
               for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(winners) == 1
    rec = q.store.get(f"/TASKS/PurgeTask/{t.task_id}")
    assert rec["worker"] == f"w{winners[0]}"
    assert rec["attempts"] == 1


def test_worker_crash_mid_execute_requeues_then_second_converges(
        work_dir):
    """kill -9 mid-task: the worker dies (InjectedCrash propagates, no
    ERROR write), the claim lease expires, the queue requeues, and a
    second worker converges the task."""
    from pinot_tpu.minion.executors import PinotTaskExecutor, \
        TaskExecutorRegistry
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        d = os.path.join(work_dir, "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "crash_seg").build(make_columns(500, seed=1), d)
        cluster.upload_segment("baseballStats_OFFLINE", d)

        ran = {"n": 0}

        class DieOnce(PinotTaskExecutor):
            task_type = "PurgeTask"

            def execute(self, task, schema, table_config, input_dirs,
                        work_dir, context):
                ran["n"] += 1
                if ran["n"] == 1:
                    raise InjectedCrash("minion kill -9")
                from pinot_tpu.minion.executors import \
                    SegmentConversionResult
                return SegmentConversionResult(input_dirs[0],
                                               "crash_seg")

        registry = TaskExecutorRegistry()
        registry.register(DieOnce())
        clock = FakeClock()
        mgr = cluster.controller.manager
        q = TaskQueue(mgr.store, clock=clock, lease_s=60.0)
        t = PinotTaskConfig("PurgeTask", {
            TABLE_NAME_KEY: "baseballStats_OFFLINE",
            SEGMENT_NAME_KEY: "crash_seg"})
        q.submit(t)
        w1 = MinionWorker(mgr, instance_id="Minion_1",
                          registry=registry,
                          work_dir=os.path.join(work_dir, "m1"))
        w1.queue = q
        with pytest.raises(InjectedCrash):
            w1.run_one()
        # the death wrote NO terminal state
        assert q.task_states("PurgeTask")[t.task_id] == IN_PROGRESS
        clock.t += 61
        assert q.requeue_expired() == [t.task_id]
        w2 = MinionWorker(mgr, instance_id="Minion_2",
                          registry=registry,
                          work_dir=os.path.join(work_dir, "m2"))
        w2.queue = q
        assert w2.run_one() == t.task_id
        assert q.task_states("PurgeTask")[t.task_id] == COMPLETED
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# tier 2: upsert remap / GC units
# ---------------------------------------------------------------------------


class _Seg:
    def __init__(self, n):
        self.num_docs = n


def _kd(keys_docs):
    return [((k,), d) for k, d in keys_docs]


def test_attach_or_fold_remaps_compacted_artifact(work_dir):
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    # seg 0: a@0 b@1 c@2 a@3  -> a@0 dead; seal
    p.apply_batch(0, _kd([("a", 0), ("b", 1), ("c", 2), ("a", 3)]), 4)
    p.seal(0, 4, 4)
    # seg 1 supersedes b -> b@1 (seg 0) dead too
    p.apply_batch(1, _kd([("b", 0)]), 5)
    assert list(p.register_consuming(0).invalid_ids(4)) == [0, 1]
    # the compacted artifact dropped docs {0, 1}: surviving order c, a
    vd = p.attach_or_fold(0, _Seg(2), lambda: [("c",), ("a",)])
    assert p._map[("c",)] == (0, 0)
    assert p._map[("a",)] == (0, 1)
    assert p._map[("b",)] == (1, 0)          # newer seg keeps b
    assert list(vd.invalid_ids(2)) == []     # both survivors live
    assert p.remapped_segments == 1
    # a key superseded AFTER compaction masks the compacted row
    p.apply_batch(1, _kd([("c", 1)]), 6)
    assert list(vd.invalid_ids(2)) == [0]
    # idempotent: re-running the remap converges to the same state
    vd2 = p.attach_or_fold(0, _Seg(2), lambda: [("c",), ("a",)])
    assert vd2 is not vd or True
    assert p._map[("c",)] == (1, 1)
    assert p._map[("a",)] == (0, 1)
    p.close()
    # persistence: a fresh instance attaches (no re-remap needed)
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r._covered[0] == 2
    folds = []
    r.attach_or_fold(0, _Seg(2), lambda: folds.append(1) or
                     [("c",), ("a",)])
    assert folds == []                        # attached, not re-derived
    assert r._map[("a",)] == (0, 1)
    r.close()


def test_remap_crash_point_then_restart_converges(work_dir):
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1), ("a", 2)]), 3)
    p.seal(0, 3, 3)
    crash_points.arm("upsert.compact_snapshot")
    with pytest.raises(InjectedCrash):
        p.attach_or_fold(0, _Seg(2), lambda: [("b",), ("a",)])
    p.close()
    # restart over the same durable state: the old snapshot still says
    # 3 covered docs, so the remap re-derives and persists this time
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    vd = r.attach_or_fold(0, _Seg(2), lambda: [("b",), ("a",)])
    assert r._map[("a",)] == (0, 1)
    assert r._map[("b",)] == (0, 0)
    assert list(vd.invalid_ids(2)) == []
    r.close()
    # and the persisted remap attaches cleanly on the NEXT restart
    r2 = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r2._covered[0] == 2
    r2.close()


def test_gc_segment_drops_keys_bitmap_and_sidecar(work_dir):
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1), ("a", 2)]), 3)
    p.seal(0, 3, 3)
    p.apply_batch(1, _kd([("c", 0)]), 4)
    sidecar = p._sidecar_path(0)
    assert os.path.exists(sidecar)
    assert p.key_map_size() == 3
    dropped = p.gc_segment(0)
    assert dropped == 2
    assert p.key_map_size() == 1              # only c remains
    assert 0 not in p._valid and 0 not in p._covered
    assert not os.path.exists(sidecar)
    assert p.gced_keys == 2
    # the shrunken map is durable: a restart does NOT resurrect the
    # dropped entries from the pre-GC snapshot
    p.close()
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r.key_map_size() == 1
    r.close()


def test_gc_crash_point_leaves_idempotent_rerun(work_dir):
    """Dying between the in-memory drop and the snapshot persist
    (upsert.gc_snapshot) resurrects the entries on restart — a bounded
    metric skew, never a correctness loss — and a re-run of the GC
    converges."""
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1), ("a", 2)]), 3)
    p.seal(0, 3, 3)
    crash_points.arm("upsert.gc_snapshot")
    with pytest.raises(InjectedCrash):
        p.gc_segment(0)
    p.close()
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r.key_map_size() == 2          # zombies: snapshot predates gc
    assert r.gc_segment(0) == 2           # idempotent re-run converges
    r.close()
    r2 = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r2.key_map_size() == 0
    r2.close()


# ---------------------------------------------------------------------------
# tier 3 + 4: end-to-end compaction, kill -9 at every swap crash point
# ---------------------------------------------------------------------------


COMPACT_CFG = {"invalidDocsThresholdPercent": "10", "minInvalidDocs": "5"}


def _compaction_cluster(work_dir, topic, rows_a=400, flush_rows=300):
    """Upsert cluster where the sealed segments carry dead rows:
    publish `rows_a` rows, then republish EVERY OTHER one (new values)
    so the sealed segments end up partially — never fully — superseded
    (a fully dead segment is retention's job, not compaction's).
    Returns (cluster, stream, all_rows)."""
    stream = _register(topic)
    cluster = EmbeddedCluster(
        work_dir, num_servers=1,
        store_dir=os.path.join(work_dir, "store"))
    cluster.add_schema(make_schema())
    cfg = upsert_rt_config(f"mem_{topic}", topic, flush_rows=flush_rows)
    cfg.task_configs = {UPSERT_COMPACTION_TASK: dict(COMPACT_CFG)}
    cluster.add_table(cfg)
    rows = make_rows(rows_a, seed=7)
    for r in rows:
        stream.publish(r, partition=0)
    again = [dict(r, runs=r["runs"] + 1000) for r in rows[::2]]
    for r in again:
        stream.publish(r, partition=0)
    return cluster, stream, rows + again


def _oracle(rows):
    latest = latest_by_key(rows)
    return len(latest), float(sum(r["runs"] for r in latest.values()))


def _wait_deadness(cluster, segment, min_invalid=5, timeout=40):
    store = cluster.controller.manager.store

    def ready():
        meta = cluster.controller.manager.segment_metadata(RT_TABLE,
                                                           segment)
        if not meta or meta.get("status") != "DONE":
            return False
        rec = store.get(deadness_path(RT_TABLE, segment))
        return rec is not None and len(rec["invalid"]) >= min_invalid
    return wait_until(ready, timeout=timeout)


def test_compaction_end_to_end_holds_exact_parity(work_dir):
    cluster, stream, rows = _compaction_cluster(work_dir, "topic_cmp_e2e")
    try:
        exp = _oracle(rows)
        assert wait_until(lambda: count_and_sum(cluster) == exp,
                          timeout=60), (count_and_sum(cluster), exp)
        seg0 = "baseballStats__0__0"
        assert _wait_deadness(cluster, seg0), "deadness never published"
        mgr = cluster.controller.manager
        before_docs = int(mgr.segment_metadata(RT_TABLE,
                                               seg0)["totalDocs"])
        tm = cluster.controller.task_manager
        ids = tm.schedule_tasks()
        assert any(i.startswith(f"Task_{UPSERT_COMPACTION_TASK}")
                   for i in ids), ids
        # scheduling again must not duplicate the open task
        assert not any(
            i.startswith(f"Task_{UPSERT_COMPACTION_TASK}")
            for i in tm.schedule_tasks())
        worker = MinionWorker(mgr, work_dir=os.path.join(work_dir, "mw"))
        done = worker.drain()
        assert done, "worker ran no tasks"
        states = worker.queue.task_states(UPSERT_COMPACTION_TASK)
        assert all(s == COMPLETED for s in states.values()), states

        # the swap shrank the artifact without changing ANY result
        after = int(mgr.segment_metadata(RT_TABLE, seg0)["totalDocs"])
        assert after < before_docs
        assert count_and_sum(cluster) == exp
        # the old artifact is a delayed-delete tombstone, not gone
        canonical = mgr.canonical_artifact_path(RT_TABLE, seg0)
        parent = os.path.dirname(canonical)
        assert any(TRASH_MARKER in n for n in os.listdir(parent))
        # stale deadness was cleared at swap
        assert mgr.store.get(deadness_path(RT_TABLE, seg0)) is None
        # dedup still works across the compacted segment: supersede a
        # key whose winner now lives in the compacted artifact
        more = [dict(rows[0], runs=5)]
        for r in more:
            stream.publish(r, partition=0)
        exp2 = _oracle(rows + more)
        assert wait_until(lambda: count_and_sum(cluster) == exp2,
                          timeout=30), (count_and_sum(cluster), exp2)
    finally:
        cluster.stop()


@pytest.mark.parametrize("point", ["compact.staged", "compact.pre_swap",
                                   "compact.pre_delete"])
def test_swap_crash_point_recovery_exact_parity(work_dir, point):
    """kill -9 the swap at each seeded crash point: queries keep exact
    COUNT/SUM parity with the host oracle through the crash, recovery
    (janitor resume + task requeue) converges to the compacted state,
    and the scrubber never quarantines a healthy artifact."""
    cluster, stream, rows = _compaction_cluster(
        work_dir, f"topic_cmp_{point.replace('.', '_')}")
    try:
        exp = _oracle(rows)
        assert wait_until(lambda: count_and_sum(cluster) == exp,
                          timeout=60), (count_and_sum(cluster), exp)
        seg0 = "baseballStats__0__0"
        assert _wait_deadness(cluster, seg0), "deadness never published"
        mgr = cluster.controller.manager
        tm = cluster.controller.task_manager
        clock = FakeClock()
        queue = TaskQueue(mgr.store, clock=clock, lease_s=60.0)
        tm.queue = queue
        assert tm.schedule_tasks()
        worker = MinionWorker(mgr, instance_id="Minion_A",
                              work_dir=os.path.join(work_dir, "mA"))
        worker.queue = queue
        crash_points.arm(point)
        with pytest.raises(InjectedCrash):
            worker.drain()
        # mid-crash: every query still exact (old or new world, never
        # a torn mix)
        assert count_and_sum(cluster) == exp
        # the scrubber must not quarantine anything mid-swap (intent
        # open or staging young)
        checker = SegmentIntegrityChecker()
        checker.run(mgr)
        assert not any(e["corrupt"] or e["missingArtifact"]
                       for e in checker.last_report.values()), \
            checker.last_report
        # recovery: janitor resumes from the durable intent (the
        # driver is provably dead here, so the live-driver age gate is
        # waived), the task queue requeues the died-with-the-minion
        # claim, a second worker converges whatever remains
        janitor = SwapJanitor(cluster.controller.swaps,
                              min_intent_age_s=0)
        janitor.run(mgr)
        clock.t += 61
        queue.requeue_expired()
        worker2 = MinionWorker(mgr, instance_id="Minion_B",
                               work_dir=os.path.join(work_dir, "mB"))
        worker2.queue = queue
        worker2.drain()
        assert count_and_sum(cluster) == exp
        # converged: compacted artifact served, no open intents
        assert cluster.controller.swaps.open_intents(RT_TABLE) == []
        states = queue.task_states(UPSERT_COMPACTION_TASK)
        assert all(s in (COMPLETED, GENERATED) for s in
                   states.values()), states
        meta = mgr.segment_metadata(RT_TABLE, seg0)
        if point != "compact.staged":
            # past `staged` the rewrite is durable: recovery rolls
            # FORWARD, so the record carries the compacted artifact
            assert meta.get("swappedFrom") == [seg0], meta
        checker.run(mgr)
        assert not any(e["corrupt"] for e in
                       checker.last_report.values()), checker.last_report
        # dedup still exact after recovery
        more = [dict(rows[0], runs=5)]
        for r in more:
            stream.publish(r, partition=0)
        exp2 = _oracle(rows + more)
        assert wait_until(lambda: count_and_sum(cluster) == exp2,
                          timeout=30), (count_and_sum(cluster), exp2)
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# tier 5: merge, retention, scrubber coordination, validation
# ---------------------------------------------------------------------------


def test_merge_end_to_end_replaces_inputs_exactly(work_dir):
    cluster = EmbeddedCluster(work_dir, num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cfg = make_table_config()
        cfg.task_configs = {"MergeRollupTask": {
            "smallSegmentDocsThreshold": "100000",
            "maxNumSegmentsPerTask": "4"}}
        cluster.add_table(cfg)
        for i in range(3):
            d = os.path.join(work_dir, f"small_{i}")
            SegmentCreator(make_schema(), make_table_config(),
                           segment_name=f"small_{i}").build(
                make_columns(400, seed=10 + i), d)
            cluster.upload_segment("baseballStats_OFFLINE", d)
        resp = cluster.query(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats")
        exp = (int(resp.aggregation_results[0].value),
               float(resp.aggregation_results[1].value))
        assert exp[0] == 1200
        mgr = cluster.controller.manager
        tm = cluster.controller.task_manager
        ids = tm.schedule_tasks()
        assert len(ids) == 1, ids
        worker = MinionWorker(mgr, work_dir=os.path.join(work_dir, "mw"))
        worker.drain()
        states = worker.queue.task_states("MergeRollupTask")
        assert all(s == COMPLETED for s in states.values()), states
        names = mgr.segment_names("baseballStats_OFFLINE")
        assert len(names) == 1 and names[0].startswith("merged_"), names
        resp = cluster.query(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats")
        got = (int(resp.aggregation_results[0].value),
               float(resp.aggregation_results[1].value))
        assert got == exp
        # inputs were tombstoned (delayed delete), not destroyed
        tdir = os.path.join(mgr.deep_store_dir, "baseballStats_OFFLINE")
        trash = [n for n in os.listdir(tdir) if TRASH_MARKER in n]
        assert len(trash) == 3, sorted(os.listdir(tdir))
        # scheduling again: the merged segment is not re-merged (one
        # segment is never a merge group)
        assert tm.schedule_tasks() == []
    finally:
        cluster.stop()


def test_merge_rollup_time_bucketing_respects_boundaries():
    """`bucketTimePeriodMs` groups merge inputs by startTime bucket so
    no merged output spans a bucket (= retention window) boundary; unset
    keeps the one-global-bundle behavior."""
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.minion.task_manager import MergeRollupTaskGenerator

    day = 86_400_000
    metas = {f"s{i}": {"status": "DONE", "totalDocs": 100,
                       "downloadPath": f"/x/s{i}",
                       "startTime": (i // 2) * day + i}
             for i in range(6)}           # buckets: day0 x2, day1 x2, day2 x2

    class StubManager:
        def segment_names(self, table):
            return sorted(metas)

        def segment_metadata(self, table, seg):
            return metas[seg]

    class StubQueue:
        def tasks_for_segment(self, ttype, table, seg):
            return []

    def gen(cfg_extra):
        cfg = TableConfig("t")
        cfg.task_configs = {"MergeRollupTask": dict(
            {"smallSegmentDocsThreshold": "1000",
             "maxNumSegmentsPerTask": "8"}, **cfg_extra)}
        return MergeRollupTaskGenerator().generate(
            "t_OFFLINE", cfg, StubManager(), StubQueue())

    # unbucketed: one global bundle of all 6
    tasks = gen({})
    assert len(tasks) == 1
    assert tasks[0].configs["segmentName"].count(",") == 5

    # bucketed by day: three 2-segment tasks, none crossing a boundary
    tasks = gen({"bucketTimePeriodMs": str(day)})
    assert len(tasks) == 3
    for t in tasks:
        batch = t.configs["segmentName"].split(",")
        buckets = {metas[s]["startTime"] // day for s in batch}
        assert len(buckets) == 1, (batch, buckets)

    # a bucket with a single small segment schedules nothing for it
    metas["s6"] = {"status": "DONE", "totalDocs": 100,
                   "downloadPath": "/x/s6", "startTime": 3 * day}
    tasks = gen({"bucketTimePeriodMs": str(day)})
    assert len(tasks) == 3
    assert all("s6" not in t.configs["segmentName"] for t in tasks)


def test_retention_tombstones_expired_and_gcs_upsert_keys(work_dir):
    topic = "topic_retention_gc"
    stream = _register(topic)
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cfg = upsert_rt_config(f"mem_{topic}", topic, flush_rows=300)
        cfg.segments_config.retention_time_unit = "DAYS"
        cfg.segments_config.retention_time_value = 5
        cluster.add_table(cfg)
        rows = make_rows(400, seed=3)
        for r in rows:
            stream.publish(r, partition=0)
        exp = _oracle(rows)
        assert wait_until(lambda: count_and_sum(cluster) == exp,
                          timeout=60), (count_and_sum(cluster), exp)
        mgr = cluster.controller.manager
        seg0 = "baseballStats__0__0"
        assert wait_until(lambda: (mgr.segment_metadata(RT_TABLE, seg0)
                                   or {}).get("status") == "DONE",
                          timeout=30)
        part = cluster.participants["Server_0"].realtime \
            .upsert_manager(RT_TABLE).partition(0)
        keys_before = part.key_map_size()
        seg0_keys = sum(1 for loc in part._map.values() if loc[0] == 0)
        assert seg0_keys > 0
        # far-future clock: everything committed is past retention,
        # but the latest sequence is protected (restart-offset anchor)
        far = int((time.time() + 10 * 86_400) * 1e3)
        RetentionManager(now_ms_fn=lambda: far).run(mgr)
        assert mgr.segment_metadata(RT_TABLE, seg0) is None
        # the artifact became a tombstone, not an immediate delete
        tdir = os.path.join(mgr.deep_store_dir, RT_TABLE)
        assert any(n.startswith(seg0 + TRASH_MARKER)
                   for n in os.listdir(tdir))
        # server-side upsert GC dropped the expired segment's keys
        assert wait_until(
            lambda: part.key_map_size() == keys_before - seg0_keys,
            timeout=10), (part.key_map_size(), keys_before, seg0_keys)
        # the consuming partition survived: new rows still ingest
        more = make_rows(50, seed=99)
        for r in more:
            stream.publish(r, partition=0)
        assert wait_until(
            lambda: count_and_sum(cluster)[0] > 0, timeout=30)
    finally:
        cluster.stop()


def test_scrubber_respects_staging_tombstones_and_intents(work_dir):
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        d = os.path.join(work_dir, "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "sc_seg").build(make_columns(300, seed=5), d)
        cluster.upload_segment("baseballStats_OFFLINE", d)
        mgr = cluster.controller.manager
        tdir = os.path.join(mgr.deep_store_dir, "baseballStats_OFFLINE")
        canonical = os.path.join(tdir, "sc_seg")
        # a staging dir covered by an OPEN intent + a trash tombstone
        staging = canonical + ".staging.swap"
        mgr.fs.copy(canonical, staging)
        trash = canonical + f"{TRASH_MARKER}123"
        mgr.fs.copy(canonical, trash)
        orphan = os.path.join(tdir, "random_leftover")
        mgr.fs.copy(canonical, orphan)
        mgr.store.set("/SWAPS/baseballStats_OFFLINE/sc_seg",
                      {"olds": ["sc_seg"], "newCrc": "x",
                       "inplace": True})
        far = time.time() + 3600
        checker = SegmentIntegrityChecker(now_fn=lambda: far)
        checker.run(mgr)
        rep = checker.last_report.get("baseballStats_OFFLINE", {})
        # the intent protects its staging AND its tombstone AND the
        # canonical artifact from the CRC sweep, at ANY age; the
        # unrelated orphan is swept
        assert os.path.isdir(staging)
        assert os.path.isdir(trash)
        assert not os.path.isdir(orphan)
        assert "sc_seg" not in rep.get("corrupt", [])
        assert rep.get("orphansDeleted") == ["random_leftover"], rep
        # intent cleared: old staging is swept, old tombstone reclaimed
        mgr.store.remove("/SWAPS/baseballStats_OFFLINE/sc_seg")
        checker2 = SegmentIntegrityChecker(now_fn=lambda: far)
        checker2.run(mgr)
        assert not os.path.isdir(staging)
        assert not os.path.isdir(trash)
        rep2 = checker2.last_report.get("baseballStats_OFFLINE", {})
        assert rep2.get("tombstonesDeleted") == [
            f"sc_seg{TRASH_MARKER}123"], rep2
        # YOUNG staging/tombstones survive even with no intent
        mgr.fs.copy(canonical, staging)
        mgr.fs.copy(canonical, trash)
        checker3 = SegmentIntegrityChecker(now_fn=time.time)
        checker3.run(mgr)
        assert os.path.isdir(staging) and os.path.isdir(trash)
    finally:
        cluster.stop()


def test_terminal_tasks_are_pruned_after_retention():
    clock = FakeClock()
    q = TaskQueue(PropertyStore(), clock=clock)
    t1, t2 = _task("s0"), _task("s1")
    q.submit(t1)
    q.submit(t2)
    q.claim("w", ["PurgeTask"])
    q.claim("w", ["PurgeTask"])
    q.finish(t1, COMPLETED, worker_id="w")
    q.finish(t2, ERROR, worker_id="w")
    assert q.prune_terminal() == []          # younger than retention
    clock.t += TaskQueue.DEFAULT_TERMINAL_RETENTION_S + 1
    assert sorted(q.prune_terminal()) == sorted([t1.task_id,
                                                 t2.task_id])
    assert q.task_states("PurgeTask") == {}
    # open tasks are never pruned
    t3 = _task("s2")
    q.submit(t3)
    clock.t += TaskQueue.DEFAULT_TERMINAL_RETENTION_S + 1
    assert q.prune_terminal() == []
    assert q.task_states("PurgeTask")[t3.task_id] == GENERATED


def test_gc_missing_reconciles_watchless_deletions(work_dir):
    """A server that was DOWN when retention deleted a segment missed
    the record-removal watch event: the boot-time reconcile
    (gc_missing against live segment records) must drop the zombie
    keys anyway."""
    p = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    p.apply_batch(0, _kd([("a", 0), ("b", 1), ("a", 2)]), 3)
    p.seal(0, 3, 3)
    p.apply_batch(1, _kd([("c", 0)]), 4)
    p.seal(1, 4, 1)
    p.close()
    # "restart": seq 0's record is gone cluster-wide; only seq 1 (and
    # the consuming seq 2) remain
    r = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r.key_map_size() == 3             # zombies restored
    assert r.gc_missing({1, 2}) == 2         # a + b lived in seq 0
    assert r.key_map_size() == 1
    r.close()
    # and the reconcile is durable
    r2 = PartitionUpsertMetadata(work_dir, RT_TABLE, 0)
    assert r2.key_map_size() == 1
    r2.close()


def test_scrubber_protects_merge_olds_via_intent(work_dir):
    """A merge swap's OPEN intent must shield its OLD segments'
    artifacts and tombstones too — mid-protocol their records are
    already pruned, so without the intent they look like ancient
    orphans and would be hard-deleted inside the rollback window."""
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        mgr = cluster.controller.manager
        tdir = os.path.join(mgr.deep_store_dir, "baseballStats_OFFLINE")
        os.makedirs(tdir, exist_ok=True)
        d = os.path.join(work_dir, "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "old_a").build(make_columns(100, seed=1), d)
        old_art = os.path.join(tdir, "old_a")
        mgr.fs.copy(d, old_art)
        old_trash = os.path.join(tdir, f"old_a{TRASH_MARKER}1")
        mgr.fs.copy(d, old_trash)
        # open merge intent referencing old_a; its record is gone
        mgr.store.set("/SWAPS/baseballStats_OFFLINE/merged_x",
                      {"olds": ["old_a"], "newCrc": "x",
                       "inplace": False})
        far = time.time() + 3600
        checker = SegmentIntegrityChecker(now_fn=lambda: far)
        checker.run(mgr)
        assert os.path.isdir(old_art), "intent must protect the old"
        assert os.path.isdir(old_trash)
        # intent resolved: both are reclaimable past grace
        mgr.store.remove("/SWAPS/baseballStats_OFFLINE/merged_x")
        checker.run(mgr)
        assert not os.path.isdir(old_art)
        assert not os.path.isdir(old_trash)
    finally:
        cluster.stop()


def test_retention_and_task_config_validation(work_dir):
    from pinot_tpu.controller.controller import Controller
    ctrl = Controller(os.path.join(work_dir, "ds"))
    mgr = ctrl.manager
    mgr.add_schema(make_schema())

    def offline(**kw):
        cfg = make_table_config()
        for k, v in kw.items():
            setattr(cfg, k, v)
        return cfg

    # retention: unit without value / bad unit / bad value
    cfg = offline()
    cfg.segments_config.retention_time_unit = "DAYS"
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(cfg)
    cfg = offline()
    cfg.segments_config.retention_time_unit = "FORTNIGHTS"
    cfg.segments_config.retention_time_value = 2
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(cfg)
    cfg = offline()
    cfg.segments_config.retention_time_unit = "DAYS"
    cfg.segments_config.retention_time_value = 0
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(cfg)
    # compaction task on a non-upsert table
    cfg = offline(task_configs={UPSERT_COMPACTION_TASK: {}})
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(cfg)
    # malformed thresholds
    cfg = offline(task_configs={"MergeRollupTask": {
        "smallSegmentDocsThreshold": "lots"}})
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(cfg)
    cfg = offline(task_configs={"MergeRollupTask": {
        "mergeType": "AVERAGE"}})
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(cfg)
    # merge on an upsert table is rejected (doc ids under the key map)
    rt = upsert_rt_config("f", "t")
    rt.task_configs = {"MergeRollupTask": {}}
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(rt)
    # upsert compaction thresholds validated
    rt = upsert_rt_config("f", "t")
    rt.task_configs = {UPSERT_COMPACTION_TASK: {
        "invalidDocsThresholdPercent": "150"}}
    with pytest.raises(InvalidTableConfigError):
        mgr.add_table(rt)
    # and the valid shapes pass
    ok = offline(task_configs={"MergeRollupTask": {
        "smallSegmentDocsThreshold": "1000", "mergeType": "ROLLUP"}})
    mgr.add_table(ok)
    ctrl.stop()


def test_swap_rejects_unknown_inputs(work_dir):
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(make_table_config())
        d = os.path.join(work_dir, "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "solo").build(make_columns(100, seed=2), d)
        swaps = SegmentSwapManager(cluster.controller.manager)
        with pytest.raises(ValueError):
            swaps.swap_segments("baseballStats_OFFLINE",
                                ["never_existed"], d)
    finally:
        cluster.stop()
