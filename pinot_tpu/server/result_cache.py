"""Server-side CRC-exact result cache.

Key = (table, canonical query fingerprint, frozen segment state), where
the segment state is the sorted tuple of every queried segment's
``(name, CRC, validDocIds version)``. Exactness falls out of PR 4's
end-to-end CRC discipline:

- an immutable segment's bytes are named by its CRC — a refreshed or
  re-built segment is a NEW crc, so a stale entry can never be served
  (invalidation is free: the key simply stops being constructed);
- an upsert invalidation bumps the segment's validDocIds version,
  which is part of the key for the same reason;
- a consuming (mutable) segment has no CRC — any request touching one
  is simply not cacheable here (the broker-level freshness-bounded
  cache covers hybrid traffic).

Values are the serialized DataTable payload from the original
execution; a hit deserializes a FRESH DataTable (no shared mutable
state with past or future queries), so cached results are bit-identical
to uncached ones on every execution path — host, device scan, or
mesh-sharded — because they ARE the original path's bytes.

Hits bypass the admission queue entirely: under overload, repetitive
dashboard traffic keeps being served from cache while the admission
controller sheds the non-repetitive excess — the graceful-degradation
valve ROADMAP item 5 asks for.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple


def segment_cache_states(segments) -> Optional[Tuple]:
    """Frozen cache-state tuple for a set of acquired segments, or None
    when any segment is uncacheable (mutable / missing CRC)."""
    states = []
    for seg in segments:
        if getattr(seg, "is_mutable", False):
            return None
        meta = getattr(seg, "metadata", None)
        crc = getattr(meta, "crc", None) if meta is not None else None
        if not crc:
            return None
        vd = getattr(seg, "valid_doc_ids", None)
        states.append((seg.segment_name, crc,
                       -1 if vd is None else int(vd.version)))
    return tuple(sorted(states))


class ServerResultCache:
    """Bounded LRU of serialized DataTable payloads."""

    def __init__(self, max_entries: int = 256,
                 max_bytes: int = 64 << 20):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._entries: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._bytes = 0
        self._gen = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @property
    def generation(self) -> int:
        """Bumped by every clear(). Capture it BEFORE executing a query
        and pass it to put(): a segment swap's clear between execution
        and store then drops the stale insert instead of letting it
        re-enter under a key the post-swap segment also constructs
        (a same-CRC reload over an evolved schema never changes the
        key again, so a raced re-insert would be served forever)."""
        with self._lock:
            return self._gen

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @staticmethod
    def key(table: str, fingerprint: str, seg_states: Tuple) -> tuple:
        return (table, fingerprint, seg_states)

    def get(self, key: tuple) -> Optional[bytes]:
        with self._lock:
            payload = self._entries.get(key)
            if payload is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return payload

    def put(self, key: tuple, payload: bytes,
            gen: Optional[int] = None) -> None:
        size = len(payload)
        if size > self.max_bytes:
            return                       # a single giant result: skip
        with self._lock:
            if gen is not None and gen != self._gen:
                return    # a clear (segment swap) raced this execution
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[key] = payload
            self._bytes += size
            while self._entries and (
                    len(self._entries) > self.max_entries or
                    self._bytes > self.max_bytes):
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= len(evicted)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._gen += 1

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses}


class SingleFlight:
    """Cold-cache dedup for IDENTICAL concurrent queries.

    N requests sharing a full result-cache key (table + canonical
    fingerprint + frozen segment states) on a cold cache are the
    degenerate batch — same literals, same everything. The first probe
    becomes the LEADER and executes; followers block (bounded) on the
    leader's completion and then RE-PROBE the cache. Correctness never
    depends on the leader: a follower whose wait times out, or whose
    re-probe still misses (leader failed, cache cleared by a segment
    swap, entry evicted), simply falls through to its own execution —
    the pre-existing behavior.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._waiters: "dict[tuple, threading.Event]" = {}

    def begin(self, key: tuple):
        """(is_leader, event). Leaders MUST call done(key) afterwards
        (any outcome); followers wait on the event then re-probe."""
        with self._lock:
            ev = self._waiters.get(key)
            if ev is not None:
                return False, ev
            ev = threading.Event()
            self._waiters[key] = ev
            return True, ev

    def done(self, key: tuple) -> None:
        """The leader finished (stored, failed, or skipped the store):
        release every follower and retire the key."""
        with self._lock:
            ev = self._waiters.pop(key, None)
        if ev is not None:
            ev.set()
