"""Batch ingestion: build one segment per input file, push to controller.

Parity: pinot-hadoop — SegmentCreationJob (one MAPPER PROCESS per input
file runs the segment build, hadoop/job/SegmentCreationJob.java) +
SegmentTarPushJob (POST artifacts to the controller). The MapReduce
mapper fleet becomes a process pool (true parallel builds — dictionary
sort + bit-packing are CPU-bound Python/numpy); the "push" is the
resource manager's segment upload (or any callable for remote push).
"""
from __future__ import annotations

import os
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

from pinot_tpu.common.schema import Schema, TimeUnit
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.tools.create_segment import create_segment_from_file


def _build_one(args) -> str:
    """One mapper: input file → segment dir (module-level so the
    process pool can pickle it)."""
    (path, fmt, schema, seg_dir, table_config, name, expressions,
     incoming_time_unit) = args
    create_segment_from_file(
        path, fmt, schema, seg_dir, table_config, segment_name=name,
        expressions=expressions, incoming_time_unit=incoming_time_unit)
    return seg_dir


def batch_build_segments(
        input_paths: Sequence[str], fmt: str, schema: Schema,
        out_base: str, table_config: Optional[TableConfig] = None,
        segment_name_prefix: Optional[str] = None,
        expressions: Optional[Dict[str, str]] = None,
        incoming_time_unit: Optional[TimeUnit] = None,
        max_workers: int = 4, use_processes: bool = True) -> List[str]:
    """Build one segment per input file in parallel; returns segment
    dirs (input order). `use_processes=False` falls back to threads
    (e.g. for non-picklable expression callables)."""
    prefix = segment_name_prefix or schema.schema_name
    jobs = [(path, fmt, schema, os.path.join(out_base, f"{prefix}_{i}"),
             table_config, f"{prefix}_{i}", expressions,
             incoming_time_unit)
            for i, path in enumerate(input_paths)]
    workers = min(max_workers, max(len(jobs), 1))
    pool = None
    if use_processes:
        try:
            # spawn, not fork: the caller may already have initialized
            # the JAX/XLA runtime (any segment load does), and forking
            # its runtime threads can deadlock the workers; jobs are
            # picklable module-level tuples so spawn is safe
            import multiprocessing
            pool = ProcessPoolExecutor(
                max_workers=workers,
                mp_context=multiprocessing.get_context("spawn"))
        except (OSError, ImportError):
            # restricted environments without process support: degrade
            # to threads rather than failing the job (worker errors
            # still propagate from pool.map below)
            pool = None
    if pool is None:
        pool = ThreadPoolExecutor(max_workers=workers)
    with pool:
        return list(pool.map(_build_one, jobs))


def push_segments(segment_dirs: Sequence[str],
                  push: Callable[[str], str]) -> List[str]:
    """Push built segments (parity: SegmentTarPushJob). `push(seg_dir)` is
    typically `lambda d: manager.add_segment(table, d)` or an HTTP upload."""
    return [push(d) for d in segment_dirs]


def preprocess_inputs(
        input_paths: Sequence[str], fmt: str, schema: Schema,
        out_base: str, partition_column: str, num_partitions: int,
        partition_function: str = "murmur",
        sort_column: Optional[str] = None,
        **reader_kw) -> List[str]:
    """Partition/sort shuffle stage BEFORE segment build.

    Parity: pinot-hadoop/.../job/SegmentPreprocessingJob.java:59 — the
    optional MR job that routes rows to one output file per partition
    (so every built segment holds exactly one partition id and the
    broker's partition pruning eliminates whole segments) and sorts rows
    within each partition (so the sorted column gets a sorted forward
    index). Emits JSON-lines files readable by the batch build; the
    table's segmentPartitionConfig must name the same function/count for
    the recorded metadata to line up with query-time hashing.
    """
    import json as _json

    from pinot_tpu.common.partition import (coerce_partition_value,
                                            make_partition_function)
    from pinot_tpu.ingestion.record_reader import make_record_reader

    fn = make_partition_function(partition_function, num_partitions)
    part_field = schema.field(partition_column) \
        if schema.has_column(partition_column) else None
    dt = part_field.data_type.np_dtype if part_field is not None else None
    sort_field = schema.field(sort_column) \
        if sort_column is not None and schema.has_column(sort_column) \
        else None
    # keyed by the RAW partition id the creator will record (the modulo
    # function yields negative ids for negative values — those must stay
    # their own partition-pure files, not alias bucket [-1])
    buckets: Dict[int, List[dict]] = {p: [] for p in range(num_partitions)}
    for path in input_paths:
        reader = make_record_reader(path, fmt, schema, **reader_kw)
        with reader:
            for row in reader:
                # hash exactly what the segment creator will record:
                # nulls become the schema default, values are typed
                # (raw reader strings would hash/sort differently and
                # split a partition across files)
                v = row.get(partition_column)
                if part_field is not None:
                    v = part_field.convert(v)
                p = fn.get_partition(coerce_partition_value(dt, v)
                                     if dt is not None else v)
                buckets.setdefault(p, []).append(dict(row))
    os.makedirs(out_base, exist_ok=True)
    out_paths: List[str] = []
    for p, rows in sorted(buckets.items()):
        if sort_column is not None:
            if sort_field is not None:
                rows.sort(key=lambda r: sort_field.convert(
                    r.get(sort_column)))
            else:
                rows.sort(key=lambda r: r.get(sort_column))
        out = os.path.join(out_base, f"part_{p}.json")
        with open(out, "w") as fh:
            for r in rows:
                fh.write(_json.dumps(r) + "\n")
        out_paths.append(out)
    return out_paths


def batch_ingest(input_paths: Sequence[str], fmt: str, schema: Schema,
                 out_base: str, table: str, manager,
                 table_config: Optional[TableConfig] = None,
                 **kw) -> List[str]:
    """Build + push in one call against a ResourceManager."""
    dirs = batch_build_segments(input_paths, fmt, schema, out_base,
                                table_config, **kw)
    return push_segments(dirs, lambda d: manager.add_segment(table, d))
