"""Shared test fixtures: a deterministic multi-type table + segment builder.

Mirrors the reference's Avro-fixture approach
(pinot-core/src/test/.../queries/*QueriesTest building real segments from
fixtures) with a seeded random table generator.
"""
from __future__ import annotations

import numpy as np

from pinot_tpu.common.datatype import DataType
from pinot_tpu.common.schema import (Schema, TimeUnit, dimension, metric,
                                     time_field)
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegmentLoader

TEAMS = ["ANA", "BAL", "BOS", "CHA", "CLE", "DET", "HOU", "KCA", "LAA",
         "MIN", "NYA", "OAK", "SEA", "TBA", "TEX", "TOR"]
LEAGUES = ["AL", "NL"]
POSITIONS = ["P", "C", "1B", "2B", "3B", "SS", "LF", "CF", "RF", "DH"]


def make_schema() -> Schema:
    return Schema("baseballStats", [
        dimension("teamID", DataType.STRING),
        dimension("league", DataType.STRING),
        dimension("playerName", DataType.STRING),
        dimension("position", DataType.STRING, single_value=False),
        metric("runs", DataType.INT),
        metric("hits", DataType.LONG),
        metric("average", DataType.DOUBLE),
        metric("salary", DataType.FLOAT),
        time_field("yearID", DataType.INT, TimeUnit.DAYS),
    ])


def make_table_config(**kw) -> TableConfig:
    idx = IndexingConfig(
        inverted_index_columns=kw.pop("inverted", ["teamID", "league"]),
        bloom_filter_columns=kw.pop("bloom", ["teamID"]),
        no_dictionary_columns=kw.pop("no_dict", ["salary"]))
    return TableConfig("baseballStats", indexing_config=idx, **kw)


def make_columns(n: int, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    return {
        "teamID": np.array(rng.choice(TEAMS, n), dtype=object),
        "league": np.array(rng.choice(LEAGUES, n), dtype=object),
        "playerName": np.array(
            [f"player_{i % 997:03d}" for i in rng.integers(0, 997, n)],
            dtype=object),
        "position": [list(rng.choice(POSITIONS, rng.integers(1, 4),
                                     replace=False)) for _ in range(n)],
        "runs": rng.integers(0, 150, n).astype(np.int32),
        "hits": rng.integers(0, 250, n).astype(np.int64),
        "average": np.round(rng.random(n), 3),
        "salary": (rng.random(n).astype(np.float32) * 1e6).round(2),
        "yearID": rng.integers(1990, 2020, n).astype(np.int32),
    }


def build_segment(tmpdir: str, n: int = 10_000, seed: int = 0,
                  name: str | None = None):
    cols = make_columns(n, seed)
    creator = SegmentCreator(make_schema(), make_table_config(),
                             segment_name=name)
    creator.build(cols, tmpdir)
    return ImmutableSegmentLoader.load(tmpdir), cols


def make_shared_columns(n: int, seed: int = 0) -> dict:
    """Columns whose first rows enumerate each value pool, so every segment
    built from them has IDENTICAL dictionaries — the shared-dictionary
    layout the mesh-sharded executor combines in the dictId domain."""
    assert n >= 1024, "need n >= 1024 to cover the value pools"
    rng = np.random.default_rng(seed)

    def pick(pool, dtype=None):
        k = len(pool)
        idx = np.concatenate([np.arange(k), rng.integers(0, k, n - k)])
        arr = np.asarray(pool)[idx]
        return arr.astype(dtype) if dtype is not None else \
            np.array(arr, dtype=object)

    players = [f"player_{i:03d}" for i in range(997)]
    avg_grid = np.round(np.arange(256) / 256.0, 4)
    positions = [[POSITIONS[i % len(POSITIONS)]] if i < len(POSITIONS)
                 else list(rng.choice(POSITIONS, rng.integers(1, 4),
                                      replace=False))
                 for i in range(n)]
    return {
        "teamID": pick(TEAMS),
        "league": pick(LEAGUES),
        "playerName": pick(players),
        "position": positions,
        "runs": pick(np.arange(150), np.int32),
        "hits": pick(np.arange(250), np.int64),
        "average": pick(avg_grid, np.float64),
        "salary": (rng.random(n).astype(np.float32) * 1e6).round(2),
        "yearID": pick(np.arange(1990, 2020), np.int32),
    }


def build_shared_segments(base: str, n_segs: int = 8, n: int = 2048,
                          seed: int = 0):
    """n_segs segments with identical dictionaries + concatenated raw cols."""
    import os
    segs, all_cols = [], []
    for i in range(n_segs):
        d = os.path.join(base, f"seg{i}")
        os.makedirs(d, exist_ok=True)
        cols = make_shared_columns(n, seed + i)
        creator = SegmentCreator(make_schema(), make_table_config(),
                                 segment_name=f"shared_{i}")
        creator.build(cols, d)
        segs.append(ImmutableSegmentLoader.load(d))
        all_cols.append(cols)
    merged = {k: (np.concatenate([c[k] for c in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((c[k] for c in all_cols), []))
              for k in all_cols[0]}
    return segs, merged
