"""QPS smoke rung for CI: the serving plane must sustain a modest
target-QPS step over the real TCP data plane with zero errors — and
must never regress below the throughput the committed r06 artifact
recorded for the PRE-zero-copy serving plane.

A regression canary, not a benchmark: it catches a reintroduced
one-in-flight-per-connection bottleneck, a serde blow-up, or a
scheduler deadlock in seconds. The honest throughput numbers come from
scripts/qps_curve.py (QPS_r*.json artifacts); docs/PERFORMANCE.md
explains how to read both.

Knee-regression gate, re-anchored at the r11 serving plane: the
committed QPS_r11.json (zero-copy columnar plane + scale-out, knee 650
QPS / ~500 sustained on the perf rig) sets the floor at a CONSERVATIVE
fraction (R11_FLOOR_FRACTION) of its max sustained rate — CI boxes are
slower and noisier than the perf rig, but the embedded smoke plane
must still clear a floor that the PRE-overhaul r06 plane (~78 QPS
sustained) could never touch. A rung offered at 2× the floor must
achieve at least the floor with zero errors.
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROWS = int(os.environ.get("QPS_SMOKE_ROWS", 4000))
SEGMENTS = int(os.environ.get("QPS_SMOKE_SEGMENTS", 2))
STEP_S = float(os.environ.get("QPS_SMOKE_STEP_S", 2.0))
# generous floor: CI boxes are noisy; the pre-mux serving plane failed
# this by an order of magnitude at equal per-query cost
MIN_ACHIEVED_FRACTION = 0.5
# conservative r11 anchor: the perf rig sustained ~500 QPS; a CI box
# running the embedded (single-process) plane must clear a quarter of
# that — well above anything the r06 plane could do (~78), so a
# serving-plane regression toward the old plane still fails loudly
R11_FLOOR_FRACTION = float(os.environ.get("QPS_SMOKE_R11_FRACTION",
                                          "0.25"))


def _r11_sustained_qps() -> float:
    """Max sustained QPS in the committed r11 scaling artifact — the
    basis of the knee-regression floor."""
    try:
        with open(os.path.join(REPO, "QPS_r11.json")) as f:
            r11 = json.load(f)
        return float(r11["max_sustained_qps"])
    except (OSError, ValueError, KeyError):
        return 500.0              # the committed r11 value, pinned


def main() -> int:
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.tools.datagen import (build_ssb_segment_dirs,
                                         ssb_schema, ssb_table_config)
    from pinot_tpu.tools.perf import QueryRunner

    floor = R11_FLOOR_FRACTION * _r11_sustained_qps()
    target = float(os.environ.get("QPS_SMOKE_TARGET", 2.0 * floor))

    base = tempfile.mkdtemp()
    dirs, _ids, _sc = build_ssb_segment_dirs(
        os.path.join(base, "segs"), ROWS, SEGMENTS, seed=7)
    cluster = EmbeddedCluster(os.path.join(base, "cluster"),
                              num_servers=2, tcp=True)
    try:
        cluster.add_schema(ssb_schema())
        cluster.add_table(ssb_table_config())
        for d in dirs:
            cluster.upload_segment("lineorder_OFFLINE", d)
        queries = ["SELECT COUNT(*) FROM lineorder",
                   "SELECT SUM(lo_revenue) FROM lineorder "
                   "WHERE lo_quantity < 25"]
        runner = QueryRunner(cluster.query, queries)
        runner.single_thread(num_times=2)      # warm plan/kernel caches
        report = runner.target_qps(qps=target, duration_s=STEP_S,
                                   num_threads=8)
        runner.close()
        out = report.to_json()
        out["r11_floor_qps"] = floor
        print(json.dumps(out, indent=1))
        ok = True
        if report.num_errors:
            print(f"FAIL: {report.num_errors} query errors",
                  file=sys.stderr)
            ok = False
        if report.qps < MIN_ACHIEVED_FRACTION * target:
            print(f"FAIL: achieved {report.qps:.1f} QPS < "
                  f"{MIN_ACHIEVED_FRACTION:.0%} of target {target:g}",
                  file=sys.stderr)
            ok = False
        if report.qps < floor:
            print(f"FAIL: achieved {report.qps:.1f} QPS < "
                  f"{R11_FLOOR_FRACTION:.0%} of the committed r11 "
                  f"sustained rate ({floor:.1f}) — the serving plane "
                  "regressed from the zero-copy r11 artifact",
                  file=sys.stderr)
            ok = False
        print("qps smoke: " + ("OK" if ok else "FAILED"))
        return 0 if ok else 1
    finally:
        cluster.stop()


if __name__ == "__main__":
    sys.exit(main())
