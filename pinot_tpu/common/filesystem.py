"""PinotFS: the deep-store filesystem abstraction.

Parity: pinot-common/.../filesystem/PinotFS.java (copy/move/delete/mkdir/
exists/listFiles + factory by URI scheme) with LocalPinotFS as the default
implementation. Segment directories are the durable artifacts; servers
fetch them from the deep store on ONLINE transitions.
"""
from __future__ import annotations

import os
import shutil
from typing import Dict, List, Type


class PinotFS:
    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> bool:
        raise NotImplementedError

    def move(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def copy(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list_files(self, path: str) -> List[str]:
        raise NotImplementedError

    def is_directory(self, path: str) -> bool:
        raise NotImplementedError


class LocalPinotFS(PinotFS):
    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> bool:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
            return True
        if os.path.exists(path):
            os.remove(path)
            return True
        return False

    def move(self, src: str, dst: str) -> bool:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        shutil.move(src, dst)
        return True

    def copy(self, src: str, dst: str) -> bool:
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            shutil.copy2(src, dst)
        return True

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def list_files(self, path: str) -> List[str]:
        return sorted(os.path.join(path, f) for f in os.listdir(path))

    def is_directory(self, path: str) -> bool:
        return os.path.isdir(path)


class HttpPinotFS(PinotFS):
    """Read-only deep-store client over the controller's /deepstore
    endpoints (parity: the reference's HTTP segment fetchers,
    pinot-common/.../segment/fetcher/ — servers without a shared
    filesystem download committed artifacts from the controller).

    Paths look like ``http://host:port/deepstore/<rel-path>``; rel-path
    is resolved by the controller strictly inside its deep-store root.
    ``copy(src, dst_local)`` downloads — a segment DIRECTORY arrives as
    the upload tar format and is unpacked at ``dst``. Mutations raise:
    the deep store's writer is the controller.
    """

    TIMEOUT_S = 30.0

    def __init__(self, tls_config=None):
        # parity: HttpsSegmentFetcher — an https deep store fetches with a
        # client SSLContext from the configured CA / verification flag
        self._ssl_ctx = tls_config.client_context() \
            if tls_config is not None else None

    def _split(self, path: str):
        marker = "/deepstore/"
        i = path.find(marker)
        if i < 0:
            raise ValueError(f"not a deep-store URI: {path!r}")
        return path[:i], path[i + len(marker):]

    def _call(self, path: str, op: str) -> bytes:
        import urllib.parse
        import urllib.request
        base, rel = self._split(path)
        url = f"{base}/deepstore/{op}?path=" + urllib.parse.quote(rel)
        ctx = self._ssl_ctx if url.startswith("https:") else None
        with urllib.request.urlopen(url, timeout=self.TIMEOUT_S,
                                    context=ctx) as resp:
            return resp.read()

    def _stat(self, path: str) -> dict:
        import json
        return json.loads(self._call(path, "stat"))

    def exists(self, path: str) -> bool:
        return bool(self._stat(path)["exists"])

    def is_directory(self, path: str) -> bool:
        return bool(self._stat(path)["isDirectory"])

    def list_files(self, path: str) -> List[str]:
        import json
        files = json.loads(self._call(path, "list"))["files"]
        return [path.rstrip("/") + "/" + f for f in files]

    def copy(self, src: str, dst: str) -> bool:
        # stat BEFORE downloading: deciding dir-vs-file after the fact
        # could disagree with the downloaded payload if the controller
        # deletes/replaces the path in between (and it saves nothing —
        # either order is two round-trips)
        is_dir = self._stat(src)["isDirectory"]
        data = self._call(src, "download")
        if is_dir:
            from pinot_tpu.common.segment_tar import unpack_segment_tar
            os.makedirs(dst, exist_ok=True)
            unpack_segment_tar(data, dst)
        else:
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            with open(dst, "wb") as f:
                f.write(data)
        return True

    def mkdir(self, path: str) -> None:
        raise PermissionError("HttpPinotFS is read-only (the deep "
                              "store's writer is the controller)")

    def delete(self, path: str) -> bool:
        raise PermissionError("HttpPinotFS is read-only")

    def move(self, src: str, dst: str) -> bool:
        raise PermissionError("HttpPinotFS is read-only")


_REGISTRY: Dict[str, Type[PinotFS]] = {"file": LocalPinotFS,
                                       "http": HttpPinotFS,
                                       "https": HttpPinotFS}


def register_fs(scheme: str, cls: Type[PinotFS]) -> None:
    _REGISTRY[scheme] = cls


def get_fs(uri: str = "file://") -> PinotFS:
    scheme = uri.split("://", 1)[0] if "://" in uri else "file"
    try:
        return _REGISTRY[scheme]()
    except KeyError:
        raise ValueError(f"no PinotFS registered for scheme '{scheme}'")
