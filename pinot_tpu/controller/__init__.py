from pinot_tpu.controller.assignment import (BalancedNumSegmentAssignment,
                                             RandomSegmentAssignment,
                                             ReplicaGroupSegmentAssignment,
                                             make_assignment)
from pinot_tpu.controller.controller import Controller
from pinot_tpu.controller.manager import ResourceManager
from pinot_tpu.controller.periodic import (PeriodicTaskScheduler,
                                           RetentionManager,
                                           SegmentIntegrityChecker,
                                           SegmentStatusChecker)
from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.controller.state_machine import (ClusterCoordinator,
                                                StateModel)

__all__ = ["BalancedNumSegmentAssignment", "RandomSegmentAssignment",
           "ReplicaGroupSegmentAssignment", "make_assignment", "Controller",
           "ResourceManager", "PeriodicTaskScheduler", "RetentionManager",
           "SegmentStatusChecker", "SegmentIntegrityChecker",
           "PropertyStore", "ClusterCoordinator", "StateModel"]
