"""HTTP client for the LLC segment-completion protocol.

Parity: the server side of SegmentCompletionProtocol — the reference's
ServerSegmentCompletionProtocolHandler POSTs segmentConsumed /
segmentStoppedConsuming / segmentCommitStart / segmentCommitEnd to the
lead controller's REST API.  This client exposes the same four-method
interface as the in-process RealtimeSegmentManager, so
RealtimeTableDataManager works unchanged in a multi-process deployment
(tools/distributed.py wires it when a controller HTTP address is given).
"""
from __future__ import annotations

import json
import urllib.parse
import urllib.request

from pinot_tpu.common.completion import CompletionResponse


class HttpSegmentCompletionClient:
    def __init__(self, controller: str, timeout: float = 60.0):
        """`controller`: host:port of the controller's HTTP API."""
        self.base = f"http://{controller}"
        self.timeout = timeout

    def _post(self, path: str, params: dict, body: bytes = None) -> dict:
        url = f"{self.base}{path}?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/octet-stream"}
            if body else {})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def segment_consumed(self, table: str, segment: str, instance: str,
                         offset: int) -> CompletionResponse:
        return CompletionResponse.from_json(self._post(
            "/segmentConsumed", {"table": table, "name": segment,
                                 "instance": instance, "offset": offset}))

    def stopped_consuming(self, table: str, segment: str, instance: str,
                          reason: str = "") -> None:
        self._post("/segmentStoppedConsuming",
                   {"table": table, "name": segment, "instance": instance,
                    "reason": reason})

    def extend_build_time(self, table: str, segment: str,
                          instance: str, extra_ms: float = 60_000.0
                          ) -> CompletionResponse:
        return CompletionResponse.from_json(self._post(
            "/segmentExtendBuildTime",
            {"table": table, "name": segment, "instance": instance,
             "extraTimeMs": str(extra_ms)}))

    def commit_start(self, table: str, segment: str, instance: str,
                     offset: int) -> CompletionResponse:
        return CompletionResponse.from_json(self._post(
            "/segmentCommitStart", {"table": table, "name": segment,
                                    "instance": instance,
                                    "offset": offset}))

    def commit_end(self, table: str, segment: str, instance: str,
                   offset: int, segment_dir: str) -> CompletionResponse:
        from pinot_tpu.controller.http_api import pack_segment_dir
        return CompletionResponse.from_json(self._post(
            "/segmentCommitEnd", {"table": table, "name": segment,
                                  "instance": instance, "offset": offset},
            body=pack_segment_dir(segment_dir)))
