"""Throughput scaling curve: SSB queries through real multi-process
clusters — N brokers × M servers behind the client's
DynamicBrokerSelector — driven by the QueryRunner perf harness in
increasingQPS mode.

Parity: pinot-tools/.../perf/QueryRunner.java targetQPS/increasingQPS and
contrib/pinot-druid-benchmark PinotThroughput — the reference's benchmark
culture records p50/p99 vs offered QPS and the saturation knee, not just
single-query latency. Writes QPS_r11.json + PROFILE_r11.json at the repo
root (override with QPS_ARTIFACT / PROFILE_ARTIFACT).

Cluster shapes (QPS_SHAPES, default "1x2,2x4,4x8" = brokers×servers):
controller, each broker and each server run as their OWN process via the
admin CLI (StartController/StartServer/StartBroker parity). The client
discovers the broker fleet from the property store through the SAME
DynamicBrokerSelector production clients use — broker processes joining
or dying re-balance the offered load with zero client reconfiguration.
QPS_MULTIPROC=0 instead runs the legacy single-process EmbeddedCluster
shape (the pre-r11 artifacts' topology).

Serving-plane config under test (exported to every spawned process and
recorded in the artifact):
- PINOT_TPU_BROKER_INLINE=1      — single-loop broker pipeline (no
  cross-thread self-pipe wakeups; ~1ms/query each on a 1-core host)
- PINOT_TPU_BROKER_CACHE_OFFLINE=1 — exact offline result cache
  (segment-lifecycle-flushed, canonical-fingerprint-keyed)
- PINOT_TPU_SHM_MIN_BYTES        — colocated replies ≥ this ride the
  shared-memory transport instead of the TCP copy

The query mix is SSB replay plus a QPS_JITTER fraction (default 0.005)
of cache-busting variants (a fresh literal per slot): those always execute
end to end — server scan, columnar serde, vectorized reduce — so every
rung measures the full path and the PROFILE phase attribution at the
knee reflects real executions, while the replayed remainder exercises
the result-cache serving path production traffic hits.

Runs on the CPU backend (the serving plane under test is broker routing +
scatter/gather + scheduler + reduce; bench.py covers the chip plane), on
purpose at a row count small enough that per-query work doesn't mask the
serving-path costs.
"""
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# HARD override: the serving-plane benchmark must not pay the test
# harness's TPU relay RTT (~90ms/dispatch) per query — that measures the
# relay, not the broker path. bench.py owns the chip-plane numbers.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
# the serving-plane configuration under test (inherited by every
# spawned broker/server process; recorded in the artifact)
os.environ.setdefault("PINOT_TPU_BROKER_INLINE", "1")
os.environ.setdefault("PINOT_TPU_BROKER_CACHE_OFFLINE", "1")
os.environ.setdefault("PINOT_TPU_SHM_MIN_BYTES", str(256 * 1024))

from pinot_tpu.tools.cluster import MultiprocCluster as _ProcCluster  # noqa: E402

ROWS = int(os.environ.get("QPS_ROWS", 2_000_000))
SEGMENTS = int(os.environ.get("QPS_SEGMENTS", 4))
STEP_S = float(os.environ.get("QPS_STEP_S", 4.0))
THREADS = int(os.environ.get("QPS_THREADS", 7))
JITTER = float(os.environ.get("QPS_JITTER", "0.005"))
MULTIPROC = os.environ.get("QPS_MULTIPROC", "1") != "0"
SHAPES = [tuple(int(x) for x in s.split("x"))
          for s in os.environ.get("QPS_SHAPES", "1x2,2x4,4x8").split(",")]
LADDER = [float(x) for x in os.environ.get(
    "QPS_LADDER", "25,50,100,200,400,500,650,800,1000").split(",")]
TABLE = "lineorder_OFFLINE"


def _http(method, url, body=None, ctype="application/json", timeout=60):
    req = urllib.request.Request(
        url, data=body, method=method,
        headers={"Content-Type": ctype} if body else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class MultiprocCluster(_ProcCluster):
    """controller + num_servers servers + num_brokers brokers, one
    process each (shared harness: pinot_tpu.tools.cluster); server
    admin APIs started so per-rung PROFILE attribution covers the
    server-side phases too. This wrapper only loads the SSB data."""

    def __init__(self, base: str, dirs, schema, table_config,
                 num_brokers: int = 1, num_servers: int = 2):
        super().__init__(base, num_brokers=num_brokers,
                         num_servers=num_servers)
        self.num_brokers = num_brokers
        self.num_servers = num_servers
        self.add_schema(schema)
        self.add_table(table_config)
        for d in dirs:
            self.upload_segment(TABLE, d)

    def await_ready(self, expected_rows: int, timeout_s: float = 300.0):
        super().await_ready("lineorder", expected_rows,
                            timeout_s=timeout_s)


class EmbeddedShape:
    """Legacy single-process shape (QPS_MULTIPROC=0): one interpreter,
    TCP data plane, HTTP broker — the pre-r11 artifacts' topology."""

    def __init__(self, base, dirs, schema, table_config, num_servers=2):
        from pinot_tpu.tools.cluster import EmbeddedCluster
        self.c = EmbeddedCluster(base, num_servers=num_servers,
                                 tcp=True, http=True)
        self.c.add_schema(schema)
        self.c.add_table(table_config)
        for d in dirs:
            self.c.upload_segment(TABLE, d)
        self.broker_ports = [self.c.broker_port]
        self.num_brokers, self.num_servers = 1, num_servers
        self.store_port = None

    def await_ready(self, *_a, **_k):
        pass

    def metrics_snapshots(self):
        return {"brokers": {"Broker_0": self.c.broker.metrics.snapshot()},
                "servers": {name: s.metrics.snapshot()
                            for name, s in self.c.servers.items()}}

    def stop(self):
        self.c.stop()


# phase attribution (VERDICT.md #1: "where does the time go") — broker
# pipeline stages + server-side stages, each summed across that plane's
# process registries
BROKER_PHASES = ("requestCompilation", "authorization", "queryRouting",
                 "scatterGather", "serverResponseDeserialization",
                 "reduce", "queryTotal")
SERVER_PHASES = ("requestDeserialization", "schedulerWait",
                 "queryProcessing", "responseSerialization")


def _phase_means(prev, cur):
    """Mean per-query milliseconds per phase over one rung window
    (delta of the cumulative timers between two snapshots, summed
    across every process of that plane)."""

    def plane_mean(prev_regs, cur_regs, phase):
        dc = dt = 0.0
        for name, cur_reg in cur_regs.items():
            prev_reg = prev_regs.get(name, {})
            dc += cur_reg.get(f"timer.{phase}.count", 0) - \
                prev_reg.get(f"timer.{phase}.count", 0)
            dt += cur_reg.get(f"timer.{phase}.totalMs", 0.0) - \
                prev_reg.get(f"timer.{phase}.totalMs", 0.0)
        return round(dt / dc, 3) if dc > 0 else None

    out = {}
    for phase in BROKER_PHASES:
        out[f"broker.{phase}"] = plane_mean(prev["brokers"],
                                            cur["brokers"], phase)
    for phase in SERVER_PHASES:
        out[f"server.{phase}"] = plane_mean(prev["servers"],
                                            cur["servers"], phase)
    return out


def _attribution_profile(phase_rungs, rungs, knee):
    """The per-phase attribution note: what dominates at the knee."""
    knee_idx = next((i for i, r in enumerate(rungs)
                     if knee is not None and r["target_qps"] == knee),
                    len(rungs) - 1)
    at_knee = phase_rungs[knee_idx] if phase_rungs else {}
    total = at_knee.get("broker.queryTotal")
    breakdown = {k: v for k, v in at_knee.items()
                 if k != "broker.queryTotal" and v is not None}
    dominant = max((k for k in breakdown if k.startswith("broker.")),
                   key=lambda k: breakdown[k], default=None)
    # scatterGather CONTAINS the server-side time: compare the server
    # queryProcessing mean (per executed query) against it to judge
    # whether compute or plumbing dominates the gather
    sg = breakdown.get("broker.scatterGather")
    qp = breakdown.get("server.queryProcessing")
    compute_ratio = round(qp / sg, 3) if sg and qp is not None else None
    note = None
    if dominant is not None:
        note = (f"at the {rungs[knee_idx]['target_qps']:g}-QPS rung "
                f"(knee={knee}), mean per-query queryTotal="
                f"{total}ms; dominant broker phase: {dominant} "
                f"({breakdown[dominant]}ms)")
        if sg is not None and qp is not None:
            note += (f" — scatterGather mean {sg}ms vs server "
                     f"queryProcessing mean {qp}ms per executed query "
                     f"(compute/gather ratio {compute_ratio})")
    return {
        "artifact": "phase_attribution_profile",
        "kneeQps": knee,
        "kneeRungOfferedQps": rungs[knee_idx]["target_qps"],
        "phaseMeansMsAtKnee": at_knee,
        "dominantBrokerPhase": dominant,
        "serverComputeOverScatterGather": compute_ratio,
        "note": note,
        "rungs": [{"offered_qps": r["target_qps"],
                   "phaseMeansMs": pm}
                  for r, pm in zip(rungs, phase_rungs)],
    }


def _query_provider(queries, rows):
    """Slot → PQL: SSB replay with a JITTER fraction of cache-busting
    variants (a literal no prior query ever used → fresh canonical
    fingerprint → full execution through scan, serde and reduce). The
    variant counter is global across rungs, so every rung's jitter
    share truly executes instead of hitting the previous rung's cache
    entries."""
    import itertools
    n = len(queries)
    period = max(1, int(round(1.0 / JITTER))) if JITTER > 0 else 0
    fresh = itertools.count(1)

    def provider(i: int) -> str:
        if period and i % period == 0:
            # literal INSIDE the lo_revenue pool range [10k, 999.9k]:
            # a literal past the segment max would min/max-prune every
            # segment and measure nothing
            lit = 10_000 + (next(fresh) * 2654435761) % 980_000
            return ("SELECT COUNT(*), SUM(lo_revenue), "
                    "SUM(lo_supplycost), AVG(lo_quantity) FROM "
                    f"lineorder WHERE lo_revenue > {lit}")
        return queries[i % n]

    return provider


def _run_shape(dirs, schema, table_config, base, num_brokers,
               num_servers, queries):
    from pinot_tpu.client.connection import connect_dynamic
    from pinot_tpu.tools.perf import QueryRunner, http_query_fn

    if MULTIPROC:
        cluster = MultiprocCluster(base, dirs, schema, table_config,
                                   num_brokers=num_brokers,
                                   num_servers=num_servers)
        shape = (f"controller + {num_brokers} broker(s) + "
                 f"{num_servers} servers, one process each "
                 "(DynamicBrokerSelector client)")
    else:
        cluster = EmbeddedShape(base, dirs, schema, table_config,
                                num_servers=num_servers)
        shape = (f"controller + broker(http) + {num_servers} servers "
                 "over TCP, single process")
    conn = None
    try:
        cluster.await_ready(ROWS)
        if MULTIPROC and cluster.store_port is not None:
            # production client path: brokers discovered (and followed)
            # from the property store via DynamicBrokerSelector
            conn = connect_dynamic("127.0.0.1", cluster.store_port)
            fn = lambda pql: conn.execute(pql)          # noqa: E731
        else:
            fn = http_query_fn(
                [f"127.0.0.1:{p}" for p in cluster.broker_ports])
        provider = _query_provider(queries, ROWS)
        runner = QueryRunner(fn, queries, query_provider=provider)

        # warm every query's plan/kernel/result caches — including the
        # jitter SHAPE (one XLA compile per filter structure; later
        # jitter literals reuse the compiled kernel)
        warm = runner.single_thread(num_times=2)
        if JITTER > 0:
            for _ in range(2):
                fn(provider(0))
            # warm the BATCHED buckets too: concurrent same-shape
            # bursts form real coalescer groups at the servers, so the
            # pow2 batch-axis buckets (2/4/8) compile here instead of
            # inside a measured rung (the single-thread warm above can
            # never overlap, so it only ever compiles batch=1 kernels)
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=48) as pool:
                for _ in range(4):
                    list(pool.map(fn, [provider(0) for _ in range(48)]))
        print(f"warm[{num_brokers}x{num_servers}]: {warm}",
              file=sys.stderr, flush=True)

        rungs, phase_rungs = [], []
        knee = None
        snap = cluster.metrics_snapshots()
        for qps in LADDER:
            r = runner.target_qps(qps=qps, duration_s=STEP_S,
                                  num_threads=THREADS)
            print(str(r), file=sys.stderr, flush=True)
            rungs.append(r.to_json())
            next_snap = cluster.metrics_snapshots()
            phase_rungs.append(_phase_means(snap, next_snap))
            snap = next_snap
            if knee is None and (r.qps < 0.9 * qps or
                                 r.missed_slots > r.num_queries // 2):
                knee = qps
                break        # saturated: higher rungs only repeat it
        runner.close()
        return {
            "brokers": num_brokers, "servers": num_servers,
            "cluster": shape,
            "warmup": warm.to_json(),
            "rungs": rungs,
            "saturation_knee_qps": knee,
            "max_sustained_qps": max(
                (r["qps"] for r in rungs
                 if r["qps"] >= 0.9 * r["target_qps"] and
                 r["missed_slots"] <= r["num_queries"] // 2),
                default=0.0),
        }, phase_rungs
    finally:
        if conn is not None:
            conn.close()
        cluster.stop()


def main() -> None:
    from bench import SSB_PQLS
    from pinot_tpu.tools.datagen import (build_ssb_segment_dirs,
                                         ssb_schema, ssb_table_config)

    t0 = time.time()
    base = tempfile.mkdtemp()
    print(f"building {ROWS} rows / {SEGMENTS} segments...",
          file=sys.stderr, flush=True)
    dirs, _ids, _sc = build_ssb_segment_dirs(
        os.path.join(base, "segs"), ROWS, SEGMENTS, seed=7, star_tree=True)
    schema = ssb_schema()
    queries = list(SSB_PQLS.values())

    shapes_out = []
    best = None
    best_phase_rungs = None
    shape_list = SHAPES if MULTIPROC else [(1, 2)]
    for num_brokers, num_servers in shape_list:
        print(f"=== shape {num_brokers} broker(s) x {num_servers} "
              "servers ===", file=sys.stderr, flush=True)
        # full replication + replica-group routing: every query's whole
        # segment set is served by ONE server per routing table (the
        # reference's replica-group builders exist exactly for this
        # fan-out reduction), so adding servers adds independent
        # replicas of the whole table instead of splitting every query
        # across every server
        from pinot_tpu.common.table_config import RoutingConfig
        tconf = ssb_table_config(star_tree=True)
        tconf.segments_config.replication = num_servers
        tconf.routing_config = RoutingConfig("replicaGroup")
        result, phase_rungs = _run_shape(
            dirs, schema, tconf,
            os.path.join(base, f"cluster_{num_brokers}x{num_servers}"),
            num_brokers, num_servers, queries)
        shapes_out.append(result)
        if best is None or result["max_sustained_qps"] > \
                best["max_sustained_qps"]:
            best = result
            best_phase_rungs = phase_rungs

    knee = max((s["saturation_knee_qps"] for s in shapes_out
                if s["saturation_knee_qps"] is not None),
               default=None)
    from pinot_tpu.server.instance import DEFAULT_BATCH_WINDOW_MS
    out = {
        "artifact": "ssb13_throughput_scaling_curve",
        "rows": ROWS, "segments": SEGMENTS,
        "shapes": shapes_out,
        "backend": "cpu (serving-plane benchmark; chip plane is "
                   "bench.py)",
        "mode": "increasingQPS (QueryRunner.java parity)",
        "step_duration_s": STEP_S,
        "client_threads": THREADS,
        "query_mix": {"replayed": "SSB 13-query set",
                      "cacheBustingFraction": JITTER},
        "serving_config": {
            "wireFormat": "DataTable v3 (zero-copy columnar)",
            "brokerInline":
                os.environ["PINOT_TPU_BROKER_INLINE"] != "0",
            "brokerOfflineResultCache":
                os.environ["PINOT_TPU_BROKER_CACHE_OFFLINE"] != "0",
            "shmMinBytes": int(os.environ["PINOT_TPU_SHM_MIN_BYTES"]),
            "batchWindowMs": float(os.environ.get(
                "PINOT_TPU_BATCH_WINDOW_MS", DEFAULT_BATCH_WINDOW_MS)),
        },
        "saturation_knee_qps": knee,
        "max_sustained_qps": max(s["max_sustained_qps"]
                                 for s in shapes_out),
        "wall_s": round(time.time() - t0, 1),
    }
    path = os.path.join(REPO,
                        os.environ.get("QPS_ARTIFACT", "QPS_r11.json"))
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    # the phase-attribution profile (obs subsystem): which pipeline
    # stage the per-query time actually goes to at the BEST shape's knee
    profile = _attribution_profile(best_phase_rungs, best["rungs"],
                                   best["saturation_knee_qps"])
    profile.update({"rows": ROWS, "segments": SEGMENTS,
                    "cluster": best["cluster"],
                    "qps_artifact": os.path.basename(path)})
    ppath = os.path.join(REPO, os.environ.get("PROFILE_ARTIFACT",
                                              "PROFILE_r11.json"))
    with open(ppath, "w") as f:
        json.dump(profile, f, indent=1)
    print(f"profile: {profile['note']}", file=sys.stderr, flush=True)
    print(json.dumps({"artifact": path,
                      "profile_artifact": ppath,
                      "saturation_knee_qps": knee,
                      "max_sustained_qps": out["max_sustained_qps"],
                      "dominant_phase_at_knee":
                          profile["dominantBrokerPhase"]}))


if __name__ == "__main__":
    main()
