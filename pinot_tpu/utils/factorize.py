"""Linear-time sorted factorize shared by the dictionary and cube builders.

np.unique is an O(n log n) argsort; a hash factorize is O(n) plus a sort of
the (tiny) unique set. pandas provides the hash table; without it the
np.unique fallback keeps behavior identical.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def sorted_factorize(arr: np.ndarray
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(sorted unique values, inverse codes) for arr, or None when the
    linear path can't run (pandas missing, or NaN-like values that
    factorize maps to the -1 sentinel — callers fall back to np.unique)."""
    try:
        import pandas as pd
    except ImportError:
        return None
    codes, uniq = pd.factorize(arr)
    if len(codes) and codes.min() < 0:          # -1 = NaN sentinel
        return None
    uniq = np.asarray(uniq)
    order = np.argsort(uniq, kind="stable")      # unique set: tiny vs n
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order), dtype=np.int64)
    return uniq[order], rank[codes]


def sorted_factorize_or_unique(arr: np.ndarray
                               ) -> Tuple[np.ndarray, np.ndarray]:
    """sorted_factorize with the canonical np.unique fallback — callers
    that don't need a custom fallback (e.g. a pre-cast step) use this so
    the fallback semantics live in one place."""
    fact = sorted_factorize(arr)
    if fact is None:
        return np.unique(arr, return_inverse=True)
    return fact
