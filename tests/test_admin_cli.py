"""Admin CLI tests: file-based commands against a live HTTP cluster.

Parity: PinotAdministrator command surface (AddSchema/AddTable/
CreateSegment/UploadSegment/PostQuery/ShowCluster/DeleteSegment).
"""
import csv
import json
import os
import tempfile

import pytest

from fixtures import make_schema, make_table_config

from pinot_tpu.tools import admin
from pinot_tpu.tools.cluster import EmbeddedCluster


@pytest.fixture(scope="module")
def http_cluster():
    base = tempfile.mkdtemp()
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=1,
                              tcp=True, http=True)
    yield cluster, base
    cluster.stop()


def _run(argv, capsys):
    rc = admin.main(argv)
    out = capsys.readouterr().out
    return rc, out


def test_admin_cli_end_to_end(http_cluster, capsys):
    cluster, base = http_cluster
    ctrl = f"127.0.0.1:{cluster.controller_port}"
    broker = f"127.0.0.1:{cluster.broker_port}"

    schema_file = os.path.join(base, "schema.json")
    with open(schema_file, "w") as f:
        json.dump(make_schema().to_json(), f)
    table_file = os.path.join(base, "table.json")
    with open(table_file, "w") as f:
        json.dump(make_table_config().to_json(), f)

    rc, _ = _run(["AddSchema", "--controller", ctrl,
                  "--schema-file", schema_file], capsys)
    assert rc == 0
    rc, _ = _run(["AddTable", "--controller", ctrl,
                  "--table-config-file", table_file], capsys)
    assert rc == 0

    # CreateSegment from a CSV file
    csv_file = os.path.join(base, "rows.csv")
    cols = ["playerName", "teamID", "league", "position", "runs", "hits",
            "average", "salary", "yearID"]
    with open(csv_file, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for i in range(100):
            w.writerow([f"p{i}", f"T{i % 5}", "AL" if i % 2 else "NL",
                        ["C", "P", "SS"], i % 50, i % 99, 0.25, 1000.5,
                        1990 + i % 20])
    out_dir = os.path.join(base, "seg_csv")
    rc, out = _run(["CreateSegment", "--input", csv_file,
                    "--format", "csv", "--schema-file", schema_file,
                    "--out-dir", out_dir, "--segment-name", "cli_0"],
                   capsys)
    assert rc == 0 and json.loads(out)["totalDocs"] == 100

    rc, _ = _run(["UploadSegment", "--controller", ctrl,
                  "--table", "baseballStats_OFFLINE",
                  "--segment-dir", out_dir], capsys)
    assert rc == 0

    rc, out = _run(["PostQuery", "--broker", broker,
                    "--query", "SELECT COUNT(*) FROM baseballStats"],
                   capsys)
    assert rc == 0
    assert json.loads(out)["aggregationResults"][0]["value"] == "100"

    rc, out = _run(["SegmentDump", "--segment-dir", out_dir], capsys)
    assert rc == 0
    dump = json.loads(out)
    assert dump["segmentName"] == "cli_0" and dump["totalDocs"] == 100
    assert dump["columns"]["teamID"]["hasDictionary"] is True

    rc, out = _run(["VerifyClusterState", "--controller", ctrl], capsys)
    assert rc == 0 and json.loads(out)["converged"] is True

    rc, out = _run(["ChangeNumReplicas", "--controller", ctrl,
                    "--table", "baseballStats_OFFLINE", "--replicas", "2"],
                   capsys)
    assert rc == 0

    rc, out = _run(["ShowCluster", "--controller", ctrl], capsys)
    assert rc == 0
    view = json.loads(out)
    assert "baseballStats_OFFLINE" in view

    rc, _ = _run(["DeleteSegment", "--controller", ctrl,
                  "--table", "baseballStats_OFFLINE",
                  "--segment", "cli_0"], capsys)
    assert rc == 0
    rc, out = _run(["PostQuery", "--broker", broker,
                    "--query", "SELECT COUNT(*) FROM baseballStats"],
                   capsys)
    resp = json.loads(out)
    # the only segment is gone: either an empty count or (when routing
    # dropped the now-segmentless table) a TableDoesNotExist error
    if resp.get("aggregationResults"):
        assert resp["aggregationResults"][0]["value"] == "0"
    else:
        assert resp.get("exceptions"), resp


def test_realtime_quickstart_command(capsys):
    """RealtimeQuickStart parity: boots, consumes the demo stream, and
    answers the sample queries."""
    from pinot_tpu.tools.admin import main
    rc = main(["RealtimeQuickstart", "--rows", "600", "--exit-after"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "consumed 600/600 rows" in out
    # the three sample queries printed real responses
    assert out.count("> SELECT") == 3
    assert "aggregationResults" in out


def test_hybrid_quickstart_command(capsys):
    """HybridQuickstart parity: offline + realtime sides merge at the
    time boundary, overlapping years deduplicated."""
    from pinot_tpu.tools.admin import main
    rc = main(["HybridQuickstart", "--rows", "400", "--exit-after"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "deduplicated at the time boundary" in out
    assert out.count("> SELECT") == 3


def test_startree_viewer_and_provisioning_helper(capsys):
    """Parity: StarTreeIndexViewer + RealtimeProvisioningHelperCommand."""
    import tempfile

    from fixtures import make_columns
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.segment.creator import SegmentCreator

    base = tempfile.mkdtemp()
    cols = make_columns(3000, seed=9)
    cfg = TableConfig("baseballStats", indexing_config=IndexingConfig(
        no_dictionary_columns=["salary"],
        star_tree_configs=[{"dimensionsSplitOrder": ["teamID", "league"],
                            "metrics": ["runs", "hits"]}]))
    seg_dir = os.path.join(base, "st_seg")
    SegmentCreator(make_schema(), cfg, segment_name="st_0").build(
        cols, seg_dir)

    rc, out = _run(["StarTreeIndexViewer", "--segment-dir", seg_dir],
                   capsys)
    assert rc == 0
    view = json.loads(out)
    assert view["totalDocs"] == 3000
    st = view["starTrees"][0]
    assert st["dimensionsSplitOrder"] == ["teamID", "league"]
    assert 0 < st["numGroups"] <= 3000
    assert st["reductionFactor"] >= 1
    assert "sum" in st["statKinds"]["runs"]

    rc, out = _run(["RealtimeProvisioningHelper",
                    "--sample-segment", seg_dir,
                    "--rows-per-hour", "100000",
                    "--num-partitions", "4", "--replication", "2",
                    "--retention-hours", "24",
                    "--num-hosts", "2,4", "--num-hours", "2,6"], capsys)
    assert rc == 0
    prov = json.loads(out)
    assert prov["bytesPerRow"] > 0
    m = prov["memoryPerHost"]
    assert set(m) == {"2hosts", "4hosts"}
    assert set(m["2hosts"]) == {"2h", "6h"}
    # fewer hosts -> more partitions/host -> more memory per host
    assert m["2hosts"]["2h"]["totalMB"] >= m["4hosts"]["2h"]["totalMB"]
    # longer flush -> bigger consuming segments
    assert m["2hosts"]["6h"]["consumingMB"] > m["2hosts"]["2h"]["consumingMB"]


def test_tenant_cli_commands(http_cluster, capsys):
    """Parity: AddTenantCommand / tenant listing over the controller
    REST, driven through the admin CLI."""
    cluster, base = http_cluster
    ctrl = f"127.0.0.1:{cluster.controller_port}"

    rc, out = _run(["AddTenant", "--controller", ctrl, "--name", "CliT",
                    "--role", "SERVER", "--instances", "Server_0"],
                   capsys)
    assert rc == 0 and "CliT" in out
    rc, out = _run(["ListTenants", "--controller", ctrl], capsys)
    assert rc == 0 and "CliT" in out
    rc, out = _run(["DeleteTenant", "--controller", ctrl,
                    "--name", "CliT"], capsys)
    assert rc == 0
    rc, out = _run(["ListTenants", "--controller", ctrl], capsys)
    assert rc == 0 and "CliT" not in out


def test_delete_table_and_backfill_commands(http_cluster, capsys):
    """Parity: DeleteTableCommand + backfill tooling (deep-store
    download → re-push refresh)."""
    cluster, base = http_cluster
    ctrl = f"127.0.0.1:{cluster.controller_port}"

    schema_file = os.path.join(base, "schema2.json")
    with open(schema_file, "w") as f:
        json.dump(make_schema().to_json(), f)
    cfg = make_table_config()
    cfg.table_name = "bfill"
    table_file = os.path.join(base, "table2.json")
    with open(table_file, "w") as f:
        json.dump(cfg.to_json(), f)
    _run(["AddSchema", "--controller", ctrl,
          "--schema-file", schema_file], capsys)
    rc, _ = _run(["AddTable", "--controller", ctrl,
                  "--table-config-file", table_file], capsys)
    assert rc == 0

    from fixtures import make_columns
    from pinot_tpu.segment.creator import SegmentCreator
    d = os.path.join(base, "bf_seg")
    SegmentCreator(make_schema(), make_table_config(),
                   "bf_seg").build(make_columns(400, seed=9), d)
    rc, _ = _run(["UploadSegment", "--controller", ctrl,
                  "--table", "bfill_OFFLINE", "--segment-dir", d], capsys)
    assert rc == 0

    # backfill with no --segment-dir: pulls from deep store, re-pushes
    rc, out = _run(["BackfillSegment", "--controller", ctrl,
                    "--table", "bfill_OFFLINE", "--segment", "bf_seg"],
                   capsys)
    assert rc == 0 and "bf_seg" in out

    rc, out = _run(["DeleteTable", "--controller", ctrl,
                    "--table", "bfill_OFFLINE"], capsys)
    assert rc == 0
    import urllib.request as _req
    with _req.urlopen(f"http://{ctrl}/tables") as r:
        tables = json.loads(r.read())["tables"]
    assert "bfill_OFFLINE" not in tables, tables
