"""Immutable segment loader: segment dir → host arrays → HBM device arrays.

Parity: pinot-core/.../indexsegment/immutable/{ImmutableSegmentImpl,
ImmutableSegmentLoader}.java + core/common/DataSource.java. Where the
reference mmaps per-index files into PinotDataBuffer (off-heap memory,
core/segment/memory/PinotDataBuffer.java:54), the TPU build's "native memory"
is HBM: each column's dictId lanes and numeric dictionary are pushed to device
once at load, padded to a lane-friendly block multiple so every query kernel
sees static shapes (SURVEY.md §7 — padded power-of-two blocks instead of mmap).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

import numpy as np

from pinot_tpu.common.datatype import DataType
from pinot_tpu.segment import format as fmt
from pinot_tpu.segment.bloom import BloomFilter
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.fwd import (mv_to_padded, read_mv_fwd, read_raw_fwd,
                                   read_sorted_fwd, read_sv_fwd,
                                   read_vec_fwd)
from pinot_tpu.segment.inverted import InvertedIndexReader
from pinot_tpu.segment.metadata import ColumnMetadata, SegmentMetadata

# Padding block == the kernel row-block so blocked reductions/matmuls tile
# evenly; 8192 = 8 x (8 x 128) VPU tiles.
from pinot_tpu.ops.kernels import BLOCK as PAD_BLOCK  # noqa: E402


def padded_size(n: int, block: int = PAD_BLOCK) -> int:
    return max(block, ((n + block - 1) // block) * block)


def min_id_dtype(max_value: int) -> np.dtype:
    """Smallest signed dtype holding ids in [0, max_value] — the single
    source of truth for id-lane narrowing (~4x less HBM/upload/filter
    bandwidth on low-cardinality columns). Kernels that mix ids with
    card-scale sentinels or bit-ops promote with .astype(int32) at the
    consumption site, sized to exactly these thresholds."""
    return np.dtype(np.int8 if max_value <= 127 else
                    np.int16 if max_value <= 32767 else np.int32)


def pad_dict_values(values: np.ndarray, np_dtype) -> np.ndarray:
    """Dictionary value table padded to the kernels' pow2 cardinality
    bucket; padding repeats the last value (kernels mask it out). The
    single convention shared by per-segment and union-dictionary lanes."""
    from pinot_tpu.ops.kernels import pow2_bucket
    if len(values) == 0:
        values = np.zeros(1, np_dtype)
    card_pad = pow2_bucket(len(values) + 1)
    return np.concatenate(
        [values, np.full(card_pad - len(values), values[-1], values.dtype)])


def vec_dim_pad(dim: int) -> int:
    """Pow2-bucketed vector width: the tree-dot kernels halve the dim
    axis pairwise, and one bucket per pow2 keeps the jit cache small.
    Padding lanes are zero — an exact no-op in every dot/norm sum."""
    from pinot_tpu.ops.kernels import pow2_bucket
    return pow2_bucket(max(dim, 1), floor=1)


def int_part_info_for(values: np.ndarray) -> tuple:
    """(n_parts, min_value) for the 7-bit bit-sliced integer sum encoding
    of a sorted integer dictionary (value = min + sum_k part_k << 7k)."""
    vals = np.asarray(values, dtype=np.int64)
    min_v = int(vals[0]) if len(vals) else 0
    max_off = (int(vals[-1]) - min_v) if len(vals) else 0
    n_parts = -(-max(1, max_off.bit_length()) // 7)
    return (n_parts, min_v)


def segment_host_bytes(seg) -> int:
    """Host-side column footprint of a (loaded or mutable) segment —
    the single accounting used by the server size/debug endpoints and
    the RealtimeProvisioningHelper. Object string arrays report their
    actual encoded payload, not 8-byte pointers."""
    def _arr_bytes(arr) -> int:
        if arr is None or not hasattr(arr, "nbytes"):
            return 0
        if getattr(arr, "dtype", None) is not None and \
                arr.dtype.kind == "O":
            return int(sum(len(str(v).encode("utf-8", "replace"))
                           for v in arr.ravel()))
        return int(arr.nbytes)

    total = 0
    for name in seg.column_names:
        ds = seg.data_source(name)
        # chunked raw columns: account the resident COMPRESSED buffer
        # without triggering the lazy full decode (the size endpoint
        # must not materialize gigabyte object arrays)
        chunks = getattr(ds, "raw_chunks", None)
        raw = getattr(ds, "_raw_values", None) \
            if chunks is not None else getattr(ds, "raw_values", None)
        if chunks is not None and raw is None:
            total += len(chunks._data)
        for arr in (getattr(ds, "dict_ids", None), raw,
                    getattr(ds, "mv_dict_ids", None),
                    getattr(ds, "vec_values", None)):
            total += _arr_bytes(arr)
        vals = getattr(getattr(ds, "dictionary", None), "values", None)
        total += _arr_bytes(vals)
    return total


def hll_tables_padded(values: np.ndarray) -> tuple:
    """(idx, rank) int32 [card_pad] HLL tables for a dictionary, padded
    to the kernels' pow2 cardinality bucket with (0, 0) — rank 0 is the
    register-max identity, so padding ids can never perturb a sketch."""
    from pinot_tpu.common.sketches import hll_tables
    from pinot_tpu.ops.kernels import pow2_bucket
    idx, rank = hll_tables(np.asarray(values))
    card_pad = pow2_bucket(len(idx) + 1)
    out_i = np.zeros(card_pad, np.int32)
    out_r = np.zeros(card_pad, np.int32)
    out_i[: len(idx)] = idx
    out_r[: len(rank)] = rank
    return out_i, out_r


def int_part_table(values: np.ndarray, n_parts: int,
                   min_v: int) -> np.ndarray:
    """[n_parts, card + 1] int8 plane table (last column = all-zero pad
    sentinel for id == cardinality row padding)."""
    off = np.asarray(values, dtype=np.int64) - min_v
    table = np.stack([(off >> (7 * k)) & 0x7F
                      for k in range(n_parts)]).astype(np.int8)
    return np.concatenate([table, np.zeros((n_parts, 1), np.int8)], axis=1)


class DataSource:
    """Column access for operators.

    Parity: core/common/DataSource.java + BlockValSet — exposes dictId forward
    index, dictionary, optional inverted/bloom index and column metadata.
    """

    @property
    def raw_values(self) -> Optional[np.ndarray]:
        if self._raw_values is None and self.raw_chunks is not None:
            with self._lane_lock:
                if self._raw_values is None:
                    self._raw_values = self.raw_chunks.decode_all()
        return self._raw_values

    @raw_values.setter
    def raw_values(self, arr) -> None:
        with self._lane_lock:
            self._raw_values = arr

    def __init__(self, metadata: ColumnMetadata, segment: "ImmutableSegment"):
        self.metadata = metadata
        self._segment = segment
        # one lock for every host-lane writer: lazy raw decode, lazy
        # HLL tables, and the residency tier's release/adopt swaps
        self._lane_lock = threading.Lock()
        self.dictionary: Optional[Dictionary] = None
        # host arrays
        self.dict_ids: Optional[np.ndarray] = None        # int32 [num_docs]
        self._raw_values: Optional[np.ndarray] = None     # no-dict columns
        # chunked raw reader (VarByteChunk parity): set for string/bytes
        # no-dictionary columns; point lookups decompress one chunk,
        # raw_values materializes lazily for scan paths
        self.raw_chunks = None
        self.mv_dict_ids: Optional[np.ndarray] = None     # int32 [docs, width]
        self.vec_values: Optional[np.ndarray] = None      # f32 [docs, dim]
        # IVF ANN index (VECTOR columns with a built index only)
        self.ivf_centroids: Optional[np.ndarray] = None   # f32 [C, dim]
        self.ivf_assignments: Optional[np.ndarray] = None  # i32 [docs]
        self.ivf_meta: Optional[dict] = None
        self.sorted_ranges: Optional[np.ndarray] = None   # [card, 2]
        self.inverted_index: Optional[InvertedIndexReader] = None
        self.bloom_filter: Optional[BloomFilter] = None
        # device arrays (lazy)
        self._dev: Dict[str, object] = {}
        self._dev_finalizer = None           # set on first device upload
        self._part_info: Optional[tuple] = None
        self._hll_tables: Optional[tuple] = None

    # -- device access -----------------------------------------------------
    def device_dict_ids(self):
        """Padded int32 dictIds on device; padding = cardinality (invalid)."""
        return self._device("dict_ids", self.host_operand("ids"))

    def device_mv_dict_ids(self):
        return self._device("mv_dict_ids", self.host_operand("mv"))

    def device_dict_values(self):
        """Numeric dictionary values on device (f64/i64 host width preserved
        when x64 is on; jax downcasts otherwise). Padded to the same pow2
        bucket the kernels use for cardinality so compiled executables are
        shared across segments with similar dictionaries; padding slots
        repeat the last value (kernels mask them out)."""
        return self._device("dict_values", self.host_operand("vals"))

    def device_raw_values(self):
        return self._device("raw_values", self.host_operand("raw"))

    def device_part_lanes(self):
        """Bit-sliced int8 part lanes [n_parts, P] for exact integer sums
        (see kernels.py 'TPU reduction strategy')."""
        return self._device("part_lanes", self.host_operand("parts"))

    def device_value_lane(self):
        """Decoded dictionary-value lane [P] for float sums."""
        return self._device("value_lane", self.host_operand("vlane"))

    def device_vec_values(self):
        """Padded [P, dim_pad] float32 embedding block on device; row
        padding is zeros (masked by the kernel's validity iota), dim
        padding is zeros (an exact no-op in the tree-dot sums)."""
        return self._device("vec_values", self.host_operand("vec"))

    def device_ivf_assign(self):
        """Narrow per-row coarse-cell lane [P] (padding rows carry the
        never-probed sentinel id numCentroids)."""
        return self._device("ivf_assign", self.host_operand("ivfa"))

    def device_ivf_centroids(self):
        """Zero-padded codebook [C_pad, dim_pad] f32."""
        return self._device("ivf_centroids", self.host_operand("ivfc"))

    def device_ivf_valid(self):
        """Centroid liveness [C_pad] bool (live count rides as a lane,
        not a param, so sharded plans stay shareable)."""
        return self._device("ivf_valid", self.host_operand("ivfv"))

    def device_hll_idx(self):
        """Per-dictId HLL register-index table [card_pad] int32 — built
        once from the dictionary values with the SAME hashing the host
        HyperLogLog uses (sketches.hll_tables), so the device register
        kernel is bit-identical to the host sketch by construction."""
        return self._device("hll_idx", self.host_operand("hllidx"))

    def device_hll_rank(self):
        """Per-dictId HLL rank table [card_pad] int32 (padding rank 0 =
        the register-max merge identity)."""
        return self._device("hll_rank", self.host_operand("hllrank"))

    def int_part_info(self) -> tuple:
        """(n_parts, min_value) for the bit-sliced integer sum encoding.

        Values are offset by min_value (so lanes are non-negative) and split
        into 7-bit slices: value = min_value + sum_k part_k << (7k).
        """
        if self._part_info is None:
            with self._lane_lock:
                if self._part_info is None:
                    self._part_info = int_part_info_for(
                        self.dictionary.values)
        return self._part_info

    def host_operand(self, kind: str) -> np.ndarray:
        """Padded host array for a lane kind ('ids'|'vals'|'raw'|'mv') —
        identical layout to the device arrays; used by the sharded executor
        to stack homogeneous segments onto a leading mesh axis."""
        if kind == "ids":
            return self._pad_ids(self.dict_ids)
        if kind == "vals":
            return pad_dict_values(self.dictionary.values,
                                   self.metadata.data_type.np_dtype)
        if kind == "raw":
            arr = self.raw_values
            p = padded_size(len(arr))
            out = np.zeros(p, dtype=arr.dtype)
            out[: len(arr)] = arr
            return out
        if kind == "mv":
            arr = self.mv_dict_ids
            p = padded_size(arr.shape[0])
            out = np.full((p, arr.shape[1]), self.metadata.cardinality,
                          dtype=np.int32)
            out[: arr.shape[0]] = arr
            return out
        if kind == "parts":
            n_parts, min_v = self.int_part_info()
            table = int_part_table(self.dictionary.values, n_parts, min_v)
            return table[:, self.host_operand("ids")]
        if kind == "vlane":
            vals = np.asarray(self.dictionary.values, dtype=np.float64)
            vals = np.concatenate([vals, [0.0]])
            return vals[self.host_operand("ids")]
        if kind == "vec":
            mat = self.vec_values
            p = padded_size(len(mat))
            dp = vec_dim_pad(self.metadata.vector_dimension)
            out = np.zeros((p, dp), dtype=np.float32)
            out[: len(mat), : mat.shape[1]] = mat
            return out
        if kind in ("ivfa", "ivfc", "ivfv"):
            from pinot_tpu.index import ivf
            c = int(self.ivf_centroids.shape[0])
            if kind == "ivfa":
                return ivf.assignment_lane(
                    self.ivf_assignments, c,
                    padded_size(len(self.ivf_assignments)))
            if kind == "ivfc":
                return ivf.centroid_lane(self.ivf_centroids)
            return ivf.validity_lane(self.ivf_assignments, c)
        if kind in ("hllidx", "hllrank"):
            if self._hll_tables is None:
                with self._lane_lock:
                    if self._hll_tables is None:
                        self._hll_tables = hll_tables_padded(
                            self.dictionary.values)
            return self._hll_tables[0 if kind == "hllidx" else 1]
        raise ValueError(kind)

    def _pad_ids(self, ids: np.ndarray) -> np.ndarray:
        p = padded_size(len(ids))
        card = self.metadata.cardinality     # padding id == cardinality
        out = np.full(p, card, dtype=min_id_dtype(card))
        out[: len(ids)] = ids
        return out

    #: _device key → residency ledger kind
    _LEDGER_KINDS = {"vec_values": "vector", "hll_idx": "hll",
                     "hll_rank": "hll", "ivf_assign": "vector",
                     "ivf_centroids": "vector", "ivf_valid": "vector"}

    def _device(self, key: str, host_array: np.ndarray):
        if key not in self._dev:
            import weakref
            from pinot_tpu.obs import residency
            seg = self._segment
            with self._lane_lock:
                if self._dev_finalizer is None:
                    # superseded frozen snapshots are freed by GC, not
                    # destroy() — the finalizer keeps the ledger truthful
                    # on that path too (release_prefix is idempotent)
                    self._dev_finalizer = weakref.finalize(
                        self, residency.LEDGER.release_prefix,
                        f"ds:{id(self)}:")
                if key not in self._dev:
                    self._dev[key] = residency.ledgered_asarray(
                        host_array,
                        owner=f"ds:{id(self)}:{key}",
                        table=seg.metadata.table_name
                        if seg is not None else "",
                        segment=seg.segment_name
                        if seg is not None else "",
                        kind=self._LEDGER_KINDS.get(key, "scan"))
        return self._dev[key]

    def release_device(self) -> None:
        """Drop every device lane and its ledger entries (segment drop/
        eviction path; re-upload after this re-registers)."""
        from pinot_tpu.obs import residency
        self._dev.clear()
        residency.LEDGER.release_prefix(f"ds:{id(self)}:")

    def device_bytes_estimate(self) -> int:
        """Bytes `warm_device` would pin in HBM for this column, from
        metadata alone (no array is materialized or uploaded) — the
        residency manager's admission charge for a not-yet-resident
        segment."""
        from pinot_tpu.ops.kernels import pow2_bucket
        cm = self.metadata
        n = cm.total_number_of_entries
        if self.dict_ids is not None or \
                (cm.has_dictionary and cm.single_value):
            total = padded_size(n) * min_id_dtype(cm.cardinality).itemsize
            if cm.data_type.is_numeric:
                total += pow2_bucket(cm.cardinality + 1) * \
                    cm.data_type.np_dtype.itemsize
            return total
        if self.vec_values is not None:
            rows = len(self.vec_values)
            total = padded_size(rows) * vec_dim_pad(
                cm.vector_dimension) * 4
            if self.ivf_centroids is not None:
                from pinot_tpu.index import ivf
                c = int(self.ivf_centroids.shape[0])
                total += padded_size(rows) * \
                    min_id_dtype(c).itemsize             # assignment lane
                total += ivf.pad_centroids(c) * \
                    (vec_dim_pad(cm.vector_dimension) * 4 + 1)  # cb + valid
            return total
        if self.raw_chunks is not None:
            return 0              # no device lane for chunked raw
        if self.raw_values is not None:
            return padded_size(len(self.raw_values)) * \
                self.raw_values.dtype.itemsize \
                if self.raw_values.dtype.kind != "O" else 0
        if self.mv_dict_ids is not None:
            return padded_size(self.mv_dict_ids.shape[0]) * \
                self.mv_dict_ids.shape[1] * 4
        if cm.has_dictionary and not cm.single_value:
            return padded_size(n) * 4
        return 0

    def release_host(self) -> None:
        """Drop the fat host-side row payloads (forward indexes, raw
        values, embeddings) for the disk residency tier. Dictionaries,
        inverted/bloom indexes and chunked-raw readers stay — they are
        dictionary-scale (or already disk-backed) and the pruner still
        needs them. `adopt_host` restores the dropped arrays from a
        freshly loaded copy of the same artifact."""
        with self._lane_lock:
            self.dict_ids = None
            self._raw_values = None
            self.mv_dict_ids = None
            self.vec_values = None
            self.ivf_assignments = None    # row-scale; codebook stays
            self._hll_tables = None

    def adopt_host(self, fresh: "DataSource") -> None:
        """Rebind host row payloads from a freshly loaded DataSource of
        the same column (disk-tier reload). Object identity of `self`
        is preserved so data-manager refs, sharded caches and in-flight
        plans keyed on the live object stay valid."""
        with self._lane_lock:
            self.dict_ids = fresh.dict_ids
            self._raw_values = fresh._raw_values
            self.mv_dict_ids = fresh.mv_dict_ids
            self.vec_values = fresh.vec_values
            self.ivf_assignments = fresh.ivf_assignments
            if fresh.raw_chunks is not None:
                self.raw_chunks = fresh.raw_chunks


class ImmutableSegment:
    """A loaded, queryable immutable segment.

    Parity: core/indexsegment/immutable/ImmutableSegmentImpl.java.
    """

    def __init__(self, metadata: SegmentMetadata,
                 data_sources: Dict[str, DataSource]):
        self.metadata = metadata
        self._data_sources = data_sources
        for ds in data_sources.values():
            if ds._segment is None:   # loader builds DataSource(cm, None)
                ds._segment = self    # backref names ledger entries
        self.star_trees = []     # pre-aggregated cubes (startree/cube.py)
        # primary-key upsert liveness bitmap (realtime/upsert.py); None
        # for non-upsert tables. Attached by the realtime data manager
        # when the committed segment swaps in / cold-start loads.
        self.valid_doc_ids = None
        self._valid_dev = None   # (bitmap version, padded device lane)
        self._valid_finalizer = None         # set on first vdoc upload

    @property
    def segment_name(self) -> str:
        return self.metadata.segment_name

    @property
    def num_docs(self) -> int:
        return self.metadata.total_docs

    @property
    def padded_docs(self) -> int:
        return padded_size(self.metadata.total_docs)

    @property
    def column_names(self):
        return list(self._data_sources.keys())

    #: parity: core/segment/virtualcolumn/VirtualColumnProviderFactory —
    #: $docId / $segmentName / $hostName are synthesized on first access
    VIRTUAL_COLUMNS = ("$docId", "$segmentName", "$hostName")

    def data_source(self, column: str) -> DataSource:
        try:
            return self._data_sources[column]
        except KeyError:
            if column in self.VIRTUAL_COLUMNS:
                ds = self._make_virtual(column)
                self._data_sources[column] = ds
                return ds
            raise KeyError(f"column '{column}' not in segment "
                           f"'{self.segment_name}'")

    def has_column(self, column: str) -> bool:
        return column in self._data_sources or \
            column in self.VIRTUAL_COLUMNS

    def _make_virtual(self, column: str) -> DataSource:
        from pinot_tpu.common.datatype import DataType
        n = self.num_docs
        if column == "$docId":
            cm = ColumnMetadata(
                name=column, data_type=DataType.INT, cardinality=n,
                bits_per_element=32, has_dictionary=False,
                min_value=0, max_value=max(n - 1, 0),
                total_number_of_entries=n)
            ds = DataSource(cm, self)
            ds.raw_values = np.arange(n, dtype=np.int32)
            return ds
        if column == "$segmentName":
            value = self.segment_name
        else:
            import socket
            value = socket.gethostname()
        cm = ColumnMetadata(
            name=column, data_type=DataType.STRING, cardinality=1,
            bits_per_element=1, sorted=True, has_dictionary=True,
            min_value=value, max_value=value, total_number_of_entries=n)
        ds = DataSource(cm, self)
        ds.dictionary = Dictionary(DataType.STRING,
                                   np.array([value], dtype=object))
        ds.dict_ids = np.zeros(n, dtype=np.int32)
        return ds

    def device_valid_lane(self):
        """Padded bool liveness lane (upsert validDocIds) on device,
        re-uploaded only when the bitmap version changes. Rows past
        num_docs pad False; the kernel ANDs with its row-validity iota
        anyway."""
        from pinot_tpu.obs import residency
        vd = self.valid_doc_ids
        ver = vd.version
        cached = self._valid_dev
        if cached is None or cached[0] != ver:
            import weakref
            host = np.zeros(self.padded_docs, dtype=bool)
            host[: self.num_docs] = vd.valid_mask(0, self.num_docs)
            if self._valid_finalizer is None:
                self._valid_finalizer = weakref.finalize(
                    self, residency.LEDGER.release,
                    f"seg:{id(self)}:vdoc")
            lane = residency.ledgered_asarray(
                host, owner=f"seg:{id(self)}:vdoc",
                table=self.metadata.table_name or "",
                segment=self.segment_name, kind="vdoc")
            cached = (ver, lane)
            self._valid_dev = cached  # tpulint: disable=concurrency -- benign racy single-slot cache: concurrent queries at worst duplicate one upload; tuple publish is atomic
        return cached[1]

    def warm_device(self, columns=None) -> None:
        """Eagerly push forward indexes + dictionaries to HBM."""
        for name in (columns or self.column_names):
            ds = self.data_source(name)
            if ds.dict_ids is not None:
                ds.device_dict_ids()
                if ds.metadata.data_type.is_numeric:
                    ds.device_dict_values()
            elif getattr(ds, "vec_values", None) is not None:
                ds.device_vec_values()
            elif ds.raw_chunks is not None:
                pass      # no device lane for string/bytes raw columns
            elif ds.raw_values is not None:
                ds.device_raw_values()
            elif ds.mv_dict_ids is not None:
                ds.device_mv_dict_ids()

    def device_bytes_estimate(self) -> int:
        """Bytes a full `warm_device` (plus the upsert vdoc lane, when
        one exists) would pin in HBM — the residency manager's
        admission charge, computed without touching the device."""
        total = sum(ds.device_bytes_estimate()
                    for ds in self._data_sources.values())
        if self.valid_doc_ids is not None:
            total += self.padded_docs        # bool lane, 1 byte/row
        return total

    def release_device_lanes(self) -> None:
        """Drop every device lane (vdoc included) and the ledger
        entries backing them, keeping host arrays intact — the
        device→host demotion step. Re-access re-uploads lazily."""
        from pinot_tpu.obs import residency
        self._valid_dev = None  # tpulint: disable=concurrency -- the residency manager drains query pins before releasing; worst case a racing reader re-uploads one lane
        residency.LEDGER.release(f"seg:{id(self)}:vdoc")
        for ds in self._data_sources.values():
            ds.release_device()

    def release_host_lanes(self, columns) -> None:
        """Drop the named columns' fat host payloads (host→disk
        demotion). Only columns the on-disk artifact can restore may be
        named — the residency manager verifies the artifact first."""
        for name in columns:
            ds = self._data_sources.get(name)
            if ds is not None:
                ds.release_host()

    def rebind_host_lanes(self, fresh: "ImmutableSegment") -> None:
        """Re-populate host payloads from a freshly loaded copy of the
        same artifact (disk-tier reload), preserving this object's
        identity so refcounted managers and caches stay valid."""
        for name, ds in self._data_sources.items():
            src = fresh._data_sources.get(name)
            if src is not None:
                ds.adopt_host(src)

    def destroy(self) -> None:
        self._valid_dev = None  # tpulint: disable=concurrency -- destroy runs after the refcounted release of the last query; worst case a racing reader re-uploads one lane
        self.release_device_lanes()


class ImmutableSegmentLoader:
    """load(segment_dir) → ImmutableSegment.

    Parity: ImmutableSegmentLoader.load (core/indexsegment/immutable/
    ImmutableSegmentLoader.java:50-81): read metadata, build a
    ColumnIndexContainer per column, wire DataSources.
    """

    @staticmethod
    def load(seg_dir: str, schema=None,
             index_loading_config=None) -> ImmutableSegment:
        """`schema`: when given, columns the schema defines but the
        segment predates are synthesized as default-value columns
        (schema evolution). `index_loading_config`: an IndexingConfig —
        inverted indexes it lists are generated at load when missing.
        Parity: core/segment/index/loader/SegmentPreProcessor.
        """
        from pinot_tpu.segment import format as fmt
        seg_dir = fmt.open_dir(seg_dir)      # v1 dir or v3 columns.psf
        meta = SegmentMetadata.load(seg_dir)
        sources: Dict[str, DataSource] = {}
        for name, cm in meta.columns.items():
            ds = DataSource(cm, None)
            if cm.data_type == DataType.VECTOR:
                ds.vec_values = read_vec_fwd(seg_dir, name)
                from pinot_tpu.index import ivf
                index = ivf.load_index(seg_dir, name)
                if index is not None:
                    ds.ivf_centroids = index.centroids
                    ds.ivf_assignments = index.assignments
                    ds.ivf_meta = index.meta
                sources[name] = ds
                continue
            if not cm.has_dictionary:
                from pinot_tpu.segment.rawchunks import (ChunkedRawReader,
                                                         has_raw_chunks)
                if has_raw_chunks(seg_dir, name):
                    ds.raw_chunks = ChunkedRawReader.open(
                        seg_dir, name,
                        is_bytes=cm.data_type == DataType.BYTES)
                else:
                    ds.raw_values = read_raw_fwd(seg_dir, name)
            else:
                ds.dictionary = Dictionary.load(seg_dir, name, cm.data_type)
                if cm.single_value:
                    ds.dict_ids = read_sv_fwd(seg_dir, name,
                                              cm.bits_per_element,
                                              meta.total_docs)
                    if cm.sorted:
                        ds.sorted_ranges = read_sorted_fwd(seg_dir, name)
                else:
                    flat, offs = read_mv_fwd(seg_dir, name)
                    ds.mv_dict_ids = mv_to_padded(flat, offs, cm.cardinality)
                if cm.has_inverted_index:
                    ds.inverted_index = InvertedIndexReader.load(
                        seg_dir, name, meta.total_docs)
                if cm.has_bloom_filter:
                    ds.bloom_filter = BloomFilter.load(seg_dir, name)
            sources[name] = ds
        # -- SegmentPreProcessor parity ---------------------------------
        if index_loading_config is not None:
            from pinot_tpu.segment.inverted import build_inverted_csr
            for name in index_loading_config.inverted_index_columns:
                ds = sources.get(name)
                if ds is None or ds.inverted_index is not None:
                    continue
                card = ds.metadata.cardinality
                if ds.dict_ids is not None:
                    docids, offsets = build_inverted_csr(
                        ds.dict_ids, np.arange(len(ds.dict_ids)), card)
                elif ds.mv_dict_ids is not None:
                    mv = ds.mv_dict_ids
                    flat = mv.reshape(-1)
                    docs = np.repeat(np.arange(mv.shape[0]), mv.shape[1])
                    keep = flat < card       # drop padding entries
                    docids, offsets = build_inverted_csr(
                        flat[keep], docs[keep], card)
                else:
                    continue                 # raw column: no dictIds
                ds.inverted_index = InvertedIndexReader(
                    docids, offsets, meta.total_docs)
                ds.metadata.has_inverted_index = True
        if schema is not None:
            for field in schema.fields:
                if field.name in sources:
                    continue
                # default column: the segment predates this schema field
                sources[field.name] = _default_column(field,
                                                      meta.total_docs)
        seg = ImmutableSegment(meta, sources)
        for ds in sources.values():
            ds._segment = seg
        from pinot_tpu.startree.cube import load_star_trees
        seg.star_trees = load_star_trees(seg_dir)
        return seg


def _default_column(field, num_docs: int) -> DataSource:
    """Constant default-value column (parity: DefaultColumnHandler +
    virtual default column providers)."""
    if field.data_type == DataType.VECTOR:
        # segments predating the vector field serve zero embeddings
        cm = ColumnMetadata(
            name=field.name, data_type=field.data_type,
            cardinality=num_docs, bits_per_element=32,
            has_dictionary=False, total_number_of_entries=num_docs,
            vector_dimension=field.vector_dimension)
        ds = DataSource(cm, None)
        ds.vec_values = np.zeros((num_docs, field.vector_dimension),
                                 np.float32)
        return ds
    default = field.default_null_value
    cm = ColumnMetadata(
        name=field.name, data_type=field.data_type, cardinality=1,
        bits_per_element=1, single_value=field.single_value, sorted=True,
        has_dictionary=True, min_value=default, max_value=default,
        total_number_of_entries=num_docs)
    ds = DataSource(cm, None)
    dtype = object if not field.data_type.is_numeric else \
        field.data_type.np_dtype
    ds.dictionary = Dictionary(field.data_type,
                               np.array([default], dtype=dtype))
    if field.single_value:
        ds.dict_ids = np.zeros(num_docs, dtype=np.int32)
    else:
        ds.mv_dict_ids = np.zeros((num_docs, 1), dtype=np.int32)
    return ds
