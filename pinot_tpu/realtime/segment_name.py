"""Low-level-consumer segment naming.

Parity: pinot-common LLCSegmentName — `{table}__{partition}__{sequence}`
(the reference appends a creation timestamp; offsets and ordering only ever
use table/partition/sequence, so the name here is the minimal deterministic
triple — nicer for tests and idempotent repair).
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True, order=True)
class LLCSegmentName:
    table: str          # raw table name (no type suffix)
    partition: int
    sequence: int

    @property
    def name(self) -> str:
        return f"{self.table}__{self.partition}__{self.sequence}"

    def next(self) -> "LLCSegmentName":
        return LLCSegmentName(self.table, self.partition, self.sequence + 1)

    @classmethod
    def parse(cls, name: str) -> "LLCSegmentName":
        parts = name.split("__")
        if len(parts) < 3:
            raise ValueError(f"not an LLC segment name: {name!r}")
        return cls(parts[0], int(parts[1]), int(parts[2]))

    @classmethod
    def is_llc(cls, name: str) -> bool:
        try:
            cls.parse(name)
            return True
        except ValueError:
            return False


def latest_llc_sequences(names) -> dict:
    """partition -> max sequence over the LLC names in `names`. The
    newest sequence per partition anchors the successor / restart-
    offset chain, so retention and merge generation must never touch
    it — shared here so both exemptions stay in sync."""
    latest: dict = {}
    for name in names:
        if not LLCSegmentName.is_llc(name):
            continue
        llc = LLCSegmentName.parse(name)
        latest[llc.partition] = max(latest.get(llc.partition, -1),
                                    llc.sequence)
    return latest
