"""async-blocking / cross-loop: event-loop discipline.

One asyncio loop thread carries every concurrent query's network waits
(broker scatter, server mux, property-store watches). A single blocking
call on that thread — `time.sleep`, `Future.result()`, a sync socket
op, a spawned subprocess, an unbatched `jax.device_get` — stalls EVERY
in-flight request, which surfaces as a latency cliff under load and is
invisible to tests that run one query at a time.

- **async-blocking** flags blocking calls inside `async def` bodies and
  inside sync functions reachable ONLY from async code in the same file
  (one-level: every local call site sits inside an `async def`, and the
  function is never handed to `run_in_executor`/a thread — those run
  off-loop by construction).

  `Future.result()` has a sanctioned non-blocking form the analyzer
  verifies instead of flagging: iterating the *done* set of an awaited
  `asyncio.wait(...)` and calling `.result()` on the loop variable —
  the future is proven complete, so `.result()` is a value read, not a
  wait. That is the broker `_finish` invariant (ISSUE 7 satellite)
  encoded as something the rule checks rather than trusts.

- **cross-loop** flags asyncio APIs used from the wrong side of the
  thread/loop boundary: `asyncio.run_coroutine_threadsafe` from inside
  a coroutine (same-loop scheduling deadlocks the await; use
  `create_task`), and module-level `asyncio.create_task`/
  `ensure_future` from a plain sync function (requires a running loop
  in THIS thread; cross-thread call sites must use
  `run_coroutine_threadsafe`).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from pinot_tpu.analysis import astutil, callgraph
from pinot_tpu.analysis.core import Finding, Rule, register

_WAIT_CALLS = {"asyncio.wait"}
_THREADSAFE = "asyncio.run_coroutine_threadsafe"
_TASK_CTORS = {"asyncio.create_task", "asyncio.ensure_future"}


def _name_bindings(fn: ast.AST, aliases) -> Dict[str, list]:
    """Every assignment binding each name in `fn` →
    [(line, is_done_set)]: is_done_set is True only when the binding is
    the done-set position of an awaited `asyncio.wait(...)` (sole
    target, or FIRST element of a tuple target). Any other assignment
    to the name is a rebinding that invalidates the proof."""
    out: Dict[str, list] = {}
    for node in astutil.walk_shallow(fn):
        if not isinstance(node, ast.Assign):
            continue
        is_wait = (isinstance(node.value, ast.Await) and
                   isinstance(node.value.value, ast.Call) and
                   astutil.resolve(node.value.value.func, aliases)
                   in _WAIT_CALLS)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.setdefault(tgt.id, []).append(
                    (node.lineno, is_wait))
            elif isinstance(tgt, ast.Tuple):
                for i, e in enumerate(tgt.elts):
                    if isinstance(e, ast.Name):
                        out.setdefault(e.id, []).append(
                            (node.lineno, is_wait and i == 0))
    return out


def _verified_result_calls(fn: ast.AST, aliases) -> Set[int]:
    """id() of every `t.result()` call PROVEN non-blocking: `t` is the
    loop variable of a `for t in done:` whose iterable's CLOSEST
    preceding binding is the done-set of an awaited `asyncio.wait(...)`
    (an intervening rebinding to anything else voids the proof), and
    the call sits inside that loop's body. Flow-scoped on purpose — the
    same name used for an unproven future elsewhere stays flagged."""
    bindings = _name_bindings(fn, aliases)
    out: Set[int] = set()
    for loop in astutil.walk_shallow(fn):
        if not (isinstance(loop, (ast.For, ast.AsyncFor)) and
                isinstance(loop.iter, ast.Name) and
                isinstance(loop.target, ast.Name)):
            continue
        before = [(ln, flag) for ln, flag in
                  bindings.get(loop.iter.id, ())
                  if ln <= loop.lineno]
        if not before:
            continue
        last_line = max(ln for ln, _flag in before)
        if not all(flag for ln, flag in before if ln == last_line):
            continue        # closest binding is not a wait done-set
        tname = loop.target.id
        for node in ast.walk(loop):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "result" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == tname:
                out.add(id(node))
    return out


def _scopes(tree: ast.Module):
    """(scope node, member functions, is_class) triples: the module
    with its top-level functions, and each class with its methods.
    Resolution is scope-local so same-named methods on different
    classes never alias (`self.m()` only reaches methods of the SAME
    class; a bare `f()` only reaches module-level functions)."""
    mod_fns = [n for n in tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    yield tree, mod_fns, False
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node, [n for n in node.body if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef))], True


def _loop_only_functions(ctx) -> Set[int]:
    """id() set of sync functions/methods that run on the event-loop
    thread: PRIVATE helpers (underscore-prefixed — a public method is
    an external root per the callgraph model, callable from any worker
    thread, so async call sites prove nothing about it) whose every
    same-SCOPE call site is inside an `async def` and which are never
    offloaded to a THREAD (run_in_executor/submit/Thread run off-loop
    by construction), plus functions registered as loop callbacks
    (call_soon*, call_later, add_done_callback), which run on the loop
    regardless of caller or visibility. Memoized on the FileContext —
    both async rules read one consistent result."""
    cached = getattr(ctx, "_loop_only", None)
    if cached is not None:
        return cached
    out: Set[int] = set()
    ctx._loop_only = out
    for scope, fns, is_class in _scopes(ctx.tree):
        offloaded = callgraph.thread_spawned_callables(scope,
                                                       ctx.aliases)
        loop_cbs = callgraph.loop_callback_callables(scope, ctx.aliases)
        sync_fns = {fn.name: fn for fn in fns
                    if isinstance(fn, ast.FunctionDef) and
                    fn.name not in offloaded and
                    (fn.name.startswith("_") or fn.name in loop_cbs)}
        called_from: Dict[str, List[bool]] = {}
        for fn in fns:
            is_async = isinstance(fn, ast.AsyncFunctionDef)
            for node in astutil.walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                ref = None
                if isinstance(node.func, ast.Name) and not is_class:
                    ref = node.func.id
                elif is_class and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self":
                    ref = node.func.attr
                if ref in sync_fns:
                    called_from.setdefault(ref, []).append(is_async)
        for name, sites in called_from.items():
            if sites and all(sites):
                out.add(id(sync_fns[name]))
        for name in loop_cbs:
            if name in sync_fns:
                out.add(id(sync_fns[name]))
    return out


@register
class AsyncBlockingRule(Rule):
    id = "async-blocking"
    description = ("blocking calls (sleep, Future.result, sync "
                   "socket/file IO, subprocess, device_get) on the "
                   "event loop: async def bodies and loop-only helpers")

    def check(self, ctx) -> Iterator[Finding]:
        loop_only = _loop_only_functions(ctx)
        for fn in astutil.iter_functions(ctx.tree):
            if isinstance(fn, ast.AsyncFunctionDef):
                yield from self._check_fn(ctx, fn, "async")
            elif id(fn) in loop_only:
                yield from self._check_fn(ctx, fn, "loop-only")

    def _check_fn(self, ctx, fn, how: str) -> Iterator[Finding]:
        verified = _verified_result_calls(fn, ctx.aliases) \
            if how == "async" else set()
        where = f"`{fn.name}`" + (
            " (reachable only from the event loop)"
            if how == "loop-only" else "")
        for node in astutil.walk_shallow(fn):
            kind = callgraph.blocking_kind(node, ctx.aliases)
            if kind is None:
                continue
            if kind == "Future.result()":
                if id(node) in verified:
                    continue     # proven complete via asyncio.wait done
                yield ctx.finding(
                    self.id, node,
                    f"{where} calls .result() on the event loop — this "
                    "blocks the whole loop unless the future is proven "
                    "done; await it, or iterate the done set of an "
                    "awaited asyncio.wait(...) so the analyzer can "
                    "verify completion")
                continue
            yield ctx.finding(
                self.id, node,
                f"{where} calls {kind} on the event loop thread — every "
                "in-flight request stalls behind it; await the async "
                "form or offload with run_in_executor")


@register
class CrossLoopRule(Rule):
    id = "cross-loop"
    description = ("asyncio APIs used from the wrong context: "
                   "run_coroutine_threadsafe inside a coroutine, "
                   "create_task from a sync function")

    def check(self, ctx) -> Iterator[Finding]:
        # sync functions that run on the loop thread anyway — loop
        # callbacks (call_soon*, add_done_callback) and helpers called
        # only from async code — may create tasks legally
        loop_cbs = callgraph.loop_callback_callables(ctx.tree,
                                                     ctx.aliases)
        loop_only = _loop_only_functions(ctx)
        for fn in astutil.iter_functions(ctx.tree):
            is_async = isinstance(fn, ast.AsyncFunctionDef)
            on_loop = is_async or fn.name in loop_cbs or \
                id(fn) in loop_only
            for node in astutil.walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = astutil.resolve(node.func, ctx.aliases)
                if callee == _THREADSAFE and is_async:
                    yield ctx.finding(
                        self.id, node,
                        f"`{fn.name}` calls run_coroutine_threadsafe "
                        "from coroutine context — scheduling onto this "
                        "same loop deadlocks the await; use "
                        "asyncio.create_task / ensure_future")
                elif callee in _TASK_CTORS and not on_loop:
                    yield ctx.finding(
                        self.id, node,
                        f"`{fn.name}` calls {callee.split('.')[-1]} from "
                        "a sync function — it requires a loop running "
                        "in THIS thread; from other threads use "
                        "asyncio.run_coroutine_threadsafe")
